"""Joint next-K-token decode with verify-and-accept (ISSUE 13,
``-m kdecode``, tier-1).

Pins the four contracts of the K-decode path:

- **verify-and-accept exactness** (PARITY.md "K-decode"): a fully
  accepted proposal block reproduces the sequential ``decode_steps``
  scan EXACTLY in tokens — and everything derived from them (completion
  text, first-int parse, scan verdicts, EOS stops, retirement points) —
  because the joint pass reuses the decode path's own per-layer
  machinery, the chunk's shared tail buffer, and the exact end-of-chunk
  fold (so int8 quantization points match too).  Logits/scores
  reproduce the sequential scan to fp32 REDUCTION-ORDER NOISE (the
  chunked-prefill equivalence class): single-query blocks are pinned
  BIT-IDENTICAL — the structural proof that the argmax chain is the
  sequential chain — while multi-query blocks may regroup summations in
  the last ulp.  At the ENGINE level, rows at any K carry identical
  discrete fields and probability fields within the fp32 rounding floor
  (the EOS-calibration |Δ| <= 2e-6 precedent).
- **rejection falls back to the unchanged step loop**: adversarial
  (random-head) proposals still yield the K=1 rows BIT-identically (the
  fallback IS the sequential code path) — a bad K-head can only cost
  wasted passes, never a wrong row — and a missing head runs
  sequentially with a one-time counter, never an error.
- **composition**: pooled-confidence retirement stays bit-reproducible
  across pool compositions at K > 1; EOS-bracket ``decode_steps_saved``
  and ``k_steps_saved`` count DISJOINT position sets (never-launched vs
  launched-jointly — no double count); strict mode holds
  (``blocked_transfers == 0``) because every K fetch happens inside the
  sanctioned consume scope.
- **pricing + plumbing**: plan_search's K axis literals are anchor-
  pinned, at least one K>1 candidate survives the full-study budget
  filter on the bench geometry, the serve coalescer key separates
  mixed-K requests, bench-diff K-tags rows so sequential and joint-K
  records never cross-compare, and the telemetry exports as a
  Prometheus histogram + per-leg labeled counters.
"""

import dataclasses
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from helpers import build_test_tokenizer, random_decoder_params  # noqa: E402
from llm_interpretation_replication_tpu.models import (  # noqa: E402
    decoder as dmod,
)
from llm_interpretation_replication_tpu.models.config import (  # noqa: E402
    DecoderConfig,
)
from llm_interpretation_replication_tpu.runtime import (  # noqa: E402
    plan as plan_mod,
)
from llm_interpretation_replication_tpu.runtime import (  # noqa: E402
    plan_search as ps,
)
from llm_interpretation_replication_tpu.runtime.engine import (  # noqa: E402
    EngineConfig,
    LegSpec,
    ScoringEngine,
)
from llm_interpretation_replication_tpu.utils import telemetry  # noqa: E402

pytestmark = pytest.mark.kdecode

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = dict(
    vocab_size=300, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, position_embedding="rotary", rotary_pct=0.25,
    max_position_embeddings=512,
)

#: discrete/derived-from-tokens fields plus the prefill-computed
#: position-0 view — IDENTICAL programs on both paths, so exact always
EXACT_FIELDS = ("scan_found", "completion", "success",
                "first_token_yes_prob", "first_token_no_prob",
                "first_token_relative_prob")
#: decode-score-derived probability fields: equal within the fp32
#: reduction-order rounding floor (the EOS-calibration 2e-6 precedent)
PROB_FIELDS = ("yes_prob", "no_prob", "relative_prob")
PROB_ATOL = 2e-6


def _prompts(n):
    return [f"Scenario {i}: does the bylaw cover bicycles in the park? "
            f"Answer:" for i in range(n)]


def _rows_equal(a_rows, b_rows):
    for a, b in zip(a_rows, b_rows):
        for f in EXACT_FIELDS:
            assert a.get(f) == b.get(f), (f, a.get(f), b.get(f))
        for f in PROB_FIELDS:
            va, vb = a.get(f), b.get(f)
            if va != va:                                 # NaN == NaN here
                assert vb != vb, (f, va, vb)
            else:
                assert vb == pytest.approx(va, abs=PROB_ATOL), (f, va, vb)
        if a.get("odds_ratio") == a.get("odds_ratio"):
            assert b.get("odds_ratio") == pytest.approx(
                a.get("odds_ratio"), rel=1e-5, abs=PROB_ATOL)
        wa, wb = a.get("weighted_confidence"), b.get("weighted_confidence")
        if wa is None:
            assert wb is None or "weighted_confidence" not in b
        else:
            assert wb == pytest.approx(wa, abs=1e-3)


def _engine(cfg=None, params=None, tok=None, **ecfg_kw):
    cfg = cfg or DecoderConfig(**TINY)
    tok = tok or build_test_tokenizer()
    params = params if params is not None else random_decoder_params(cfg)
    kw = dict(batch_size=4, buckets=(32, 64))
    kw.update(ecfg_kw)
    return ScoringEngine("falcon", cfg, params, tok,
                         engine_config=EngineConfig(**kw)), cfg, params, tok


def _prefilled(cfg, params, kv_dtype="bf16", b=3, s=8, seed=0):
    """(cache, last, lengths, target_ids) from a tiny synthetic prefill."""
    if kv_dtype != "bf16":
        cfg = dataclasses.replace(cfg, kv_cache_dtype=kv_dtype)
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, cfg.vocab_size - 10, (b, s)).astype(np.int32)
    mask = np.ones((b, s), np.int32)
    mask[1, 6:] = 0
    ids[1, 6:] = 0
    last, cache = dmod.prefill(params, cfg, jnp.asarray(ids),
                               jnp.asarray(mask), cache_len=s)
    lengths = jnp.sum(jnp.asarray(mask), axis=-1)
    tgt = jnp.asarray(np.tile([[5, 9]], (b, 1)).astype(np.int32))
    return cfg, cache, last, lengths, tgt


def _verify_chunk(params, cfg, cache, last, lengths, tgt, n, blocks,
                  proposals, eos_id=None):
    """Drive k_verify_block over one chunk in the given block sizes with
    per-position ``proposals`` [B, n]; returns (tokens, ReducedScores,
    folded cache, last logits, per-pass outs)."""
    b = int(last.shape[0])
    tail_shape = (cfg.num_layers, b, n, cfg.num_kv_heads, cfg.head_dim)
    cdt = (params["embed"]["tokens"].dtype
           if cache.k_scale is not None else cache.k.dtype)
    tk = tv = jnp.zeros(tail_shape, cdt)
    prev, done, j = last, None, 0
    toks_parts, sc_parts, outs = [], [], []
    for kb in blocks:
        out = dmod.k_verify_block(
            params, cfg, cache, tk, tv, prev, lengths, jnp.int32(0),
            jnp.int32(j), jnp.asarray(proposals[:, j:j + kb]), eos_id,
            done, tgt, with_scores="reduced", fold=(j + kb == n))
        outs.append(out)
        toks_parts.append(np.asarray(out.tokens))
        sc_parts.append(out.scores)
        prev, done, tk, tv = out.last_logits, out.done, out.tail_k, \
            out.tail_v
        j += kb
    sc = dmod.ReducedScores(*(
        np.concatenate([np.asarray(getattr(p, f)) for p in sc_parts],
                       axis=1)
        for f in dmod.ReducedScores._fields))
    return np.concatenate(toks_parts, axis=1), sc, outs[-1].cache, prev, \
        outs


# ---------------------------------------------------------------------------
# Decoder-level: the verify-and-accept bit-parity contract
# ---------------------------------------------------------------------------

class TestKVerifyBlock:
    def test_single_query_blocks_bit_identical_to_sequential(self):
        """The STRUCTURAL exactness proof: a chunk verified in
        single-query blocks sharing the chunk tail reproduces the
        sequential scan bit for bit — tokens, every reduced-score field,
        the frontier logits, AND the folded cache.  This is what makes
        the argmax chain THE sequential chain; the multi-query test
        below adds only summation regrouping on top of it."""
        cfg = DecoderConfig(**TINY)
        params = random_decoder_params(cfg, seed=3)
        cfg, cache, last, lengths, tgt = _prefilled(cfg, params)
        n = 6
        t6, s6, c6, l6, _ = dmod.decode_steps(
            params, cfg, cache, last, lengths, np.int32(0), n, None, None,
            with_scores="reduced", target_ids=tgt)
        t6 = np.asarray(t6)
        toks, sc, fc, prev, outs = _verify_chunk(
            params, cfg, cache, last, lengths, tgt, n, (1,) * n, t6)
        for out in outs:
            assert bool(np.asarray(out.accepted).all())
        assert (toks == t6).all()
        for f in dmod.ReducedScores._fields:
            assert (getattr(sc, f) == np.asarray(getattr(s6, f))).all(), f
        assert (np.asarray(prev) == np.asarray(l6)).all()
        assert (np.asarray(fc.k) == np.asarray(c6.k)).all()
        assert (np.asarray(fc.valid) == np.asarray(c6.valid)).all()
        assert (np.asarray(fc.positions) == np.asarray(c6.positions)).all()

    def test_multi_query_blocks_token_exact_scores_within_noise(self):
        """Multi-query blocks: the TRUE token chain (and acceptance) is
        exactly the sequential one, and every score statistic matches to
        fp32 reduction-order noise — the PARITY.md "K-decode" contract
        (the last-ulp regrouping a K-query pass may legitimately do)."""
        cfg = DecoderConfig(**TINY)
        params = random_decoder_params(cfg, seed=3)
        cfg, cache, last, lengths, tgt = _prefilled(cfg, params)
        n = 6
        t6, s6, c6, l6, _ = dmod.decode_steps(
            params, cfg, cache, last, lengths, np.int32(0), n, None, None,
            with_scores="reduced", target_ids=tgt)
        t6 = np.asarray(t6)
        toks, sc, fc, prev, outs = _verify_chunk(
            params, cfg, cache, last, lengths, tgt, n, (1, 3, 2), t6)
        for out in outs:
            assert bool(np.asarray(out.accepted).all())
        assert (toks == t6).all()                    # tokens: EXACT
        assert (sc.topk_ids == np.asarray(s6.topk_ids)).all()
        for f in ("topk_vals", "logz", "target_logits"):
            np.testing.assert_allclose(
                getattr(sc, f), np.asarray(getattr(s6, f)),
                rtol=1e-6, atol=1e-5, err_msg=f)
        np.testing.assert_allclose(np.asarray(prev), np.asarray(l6),
                                   rtol=1e-6, atol=1e-5)
        np.testing.assert_allclose(np.asarray(fc.k), np.asarray(c6.k),
                                   rtol=1e-5, atol=1e-5)
        assert (np.asarray(fc.valid) == np.asarray(c6.valid)).all()

    def test_int8_fold_points_match_sequential(self):
        """Fold boundaries — and therefore the int8 quantization points —
        are chunk-aligned on both paths: single-query blocks on a
        quantized cache stay BIT-identical to the sequential int8 scan
        (K-decode adds no new drift class; the tolerance vs bf16 is the
        documented kvcache one, unchanged), and multi-query blocks keep
        the same token-exact/noise contract as bf16."""
        cfg0 = DecoderConfig(**TINY)
        params = random_decoder_params(cfg0, seed=5)
        cfg, cache, last, lengths, tgt = _prefilled(cfg0, params,
                                                    kv_dtype="int8")
        assert cache.k_scale is not None
        n = 5
        t5, s5, c5, l5, _ = dmod.decode_steps(
            params, cfg, cache, last, lengths, np.int32(0), n, None, None,
            with_scores="reduced", target_ids=tgt)
        toks, sc, fc, prev, outs = _verify_chunk(
            params, cfg, cache, last, lengths, tgt, n, (1,) * n,
            np.asarray(t5))
        assert all(bool(np.asarray(o.accepted).all()) for o in outs)
        assert (toks == np.asarray(t5)).all()
        for f in dmod.ReducedScores._fields:
            assert (getattr(sc, f) == np.asarray(getattr(s5, f))).all(), f
        assert (np.asarray(fc.k) == np.asarray(c5.k)).all()
        assert (np.asarray(fc.k_scale) == np.asarray(c5.k_scale)).all()
        toks2, _, _, _, outs2 = _verify_chunk(
            params, cfg, cache, last, lengths, tgt, n, (1, 4),
            np.asarray(t5))
        assert all(bool(np.asarray(o.accepted).all()) for o in outs2)
        assert (toks2 == np.asarray(t5)).all()

    def test_mismatch_reports_prefix_and_rejects(self):
        """A wrong proposal at position 2 accepts exactly the 2-token
        prefix for that row and fails block acceptance; rows whose
        proposals all match still report full acceptance."""
        cfg = DecoderConfig(**TINY)
        params = random_decoder_params(cfg, seed=3)
        cfg, cache, last, lengths, tgt = _prefilled(cfg, params)
        n = 4
        t4, _, _, _, _ = dmod.decode_steps(
            params, cfg, cache, last, lengths, np.int32(0), n, None, None,
            with_scores=False)
        props = np.asarray(t4).copy()
        props[0, 2] = (props[0, 2] + 1) % cfg.vocab_size
        b = int(last.shape[0])
        tail = jnp.zeros((cfg.num_layers, b, n, cfg.num_kv_heads,
                          cfg.head_dim), cache.k.dtype)
        out = dmod.k_verify_block(
            params, cfg, cache, tail, tail, last, lengths, jnp.int32(0),
            jnp.int32(0), jnp.asarray(props), None, None, tgt,
            with_scores="reduced", fold=True)
        a_len = np.asarray(out.a_len)
        acc = np.asarray(out.accepted)
        assert a_len[0] == 2 and not acc[0]
        assert (a_len[1:] == n).all() and acc[1:].all()
        # the TRUE chain is immune to the bad proposal at its own position
        assert int(np.asarray(out.tokens)[0, 2]) == int(np.asarray(t4)[0, 2])

    def test_eos_frozen_chain_matches_sequential(self):
        """With an armed EOS id the verify pass's true chain freezes rows
        exactly like decode_steps (eos emitted -> eos forever), so a
        sequential-token proposal block still fully accepts."""
        cfg = DecoderConfig(**TINY)
        params = random_decoder_params(cfg, seed=3)
        cfg, cache, last, lengths, tgt = _prefilled(cfg, params)
        n = 6
        ref, _, _, _, _ = dmod.decode_steps(
            params, cfg, cache, last, lengths, np.int32(0), n, None, None,
            with_scores=False)
        # pick the token row 0 greedily emits at step 1 as the "EOS":
        # every row that ever emits it freezes from there on
        eos_id = int(np.asarray(ref)[0, 1])
        t_eos, _, _, _, d_eos = dmod.decode_steps(
            params, cfg, cache, last, lengths, np.int32(0), n, eos_id,
            None, with_scores=False)
        toks, _, _, _, outs = _verify_chunk(
            params, cfg, cache, last, lengths, tgt, n, (1, 5),
            np.asarray(t_eos), eos_id=eos_id)
        assert all(bool(np.asarray(o.accepted).all()) for o in outs)
        assert (toks == np.asarray(t_eos)).all()
        assert (np.asarray(outs[-1].done) == np.asarray(d_eos)).all()


class TestKHead:
    def test_init_and_depth(self):
        cfg = DecoderConfig(**TINY)
        head = dmod.init_k_head(cfg, 4, seed=1)
        assert head["w"].shape == (3, cfg.hidden_size, cfg.vocab_size)
        assert dmod.k_head_num_heads(head) == 3
        assert dmod.k_head_num_heads(None) == 0

    def test_distill_predicts_greedy_continuations(self):
        """Self-distillation on the evaluation prompts themselves (the
        bench's regime) interpolates the tiny geometry: proposals match
        the greedy continuation, so multi-token blocks fully accept."""
        cfg = DecoderConfig(**TINY)
        params = random_decoder_params(cfg, seed=3)
        cfg2, cache, last, lengths, tgt = _prefilled(cfg, params)
        rng = np.random.default_rng(0)
        ids = rng.integers(1, cfg.vocab_size - 10, (3, 8)).astype(np.int32)
        mask = np.ones((3, 8), np.int32)
        mask[1, 6:] = 0
        ids[1, 6:] = 0
        head = dmod.distill_k_head(params, cfg, ids, mask, k=4,
                                   gen_steps=8)
        # resident in the WEIGHTS dtype: plan.k_head_bytes prices the
        # head at the weights' width, so an fp32 copy beside bf16
        # params would pin 2x the budgeted HBM
        assert head["w"].dtype == params["embed"]["tokens"].dtype
        n = 4
        ref, _, _, _, _ = dmod.decode_steps(
            params, cfg, cache, last, lengths, np.int32(0), n, None, None,
            with_scores=False)
        # bootstrap (argmax) then a 3-token head block from its hidden
        b = 3
        tail = jnp.zeros((cfg.num_layers, b, n, cfg.num_kv_heads,
                          cfg.head_dim), cache.k.dtype)
        boot = dmod.k_verify_block(
            params, cfg, cache, tail, tail, last, lengths, jnp.int32(0),
            jnp.int32(0),
            jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None],
            None, None, tgt, with_scores="reduced", fold=False)
        props = dmod.k_propose(head, boot.last_hidden, boot.last_logits, 3)
        assert (np.asarray(props) == np.asarray(ref)[:, 1:4]).all()

    def test_propose_freezes_done_rows(self):
        cfg = DecoderConfig(**TINY)
        head = dmod.init_k_head(cfg, 3)
        hidden = jnp.ones((2, cfg.hidden_size))
        logits = jnp.ones((2, cfg.vocab_size))
        done = jnp.asarray([True, False])
        props = np.asarray(dmod.k_propose(head, hidden, logits, 3,
                                          done, 7))
        assert (props[0] == 7).all()


# ---------------------------------------------------------------------------
# Engine-level: rows at any K == the K=1 rows
# ---------------------------------------------------------------------------

class TestEngineParity:
    def _pair(self, decode_k=4, **kw):
        eng, cfg, params, tok = _engine(**kw)
        k_eng = ScoringEngine(
            "falcon", cfg, params, tok,
            engine_config=dataclasses.replace(eng.ecfg, decode_k=decode_k))
        return eng, k_eng, tok

    def test_completion_and_confidence_rows_match_k1(self):
        """Acceptance pin: at K=4 with a self-distilled head, the binary
        (50-token completions) and confidence (10-token, pooled) legs
        emit the K=1 rows — discrete fields exactly, probability fields
        at the fp32 rounding floor — while the accept path really ran
        (k_steps_saved > 0, accepted_k histogram recorded)."""
        eng, k_eng, _ = self._pair()
        prompts = _prompts(6)
        ref_b = eng.score_prompts(prompts)
        ref_c = eng.score_prompts(prompts, with_confidence=True,
                                  max_new_tokens=10)
        k_eng.distill_k_head_on(prompts)
        snap = dict(telemetry.counters())
        h0 = telemetry.hist_count("accepted_k")
        got_b = k_eng.score_prompts(prompts)
        got_c = k_eng.score_prompts(prompts, with_confidence=True,
                                    max_new_tokens=10)
        delta = telemetry.counters_since(snap)
        _rows_equal(ref_b, got_b)
        _rows_equal(ref_c, got_c)
        assert delta.get("k_blocks_proposed", 0) > 0
        assert delta.get("k_steps_saved", 0) > 0        # accepts happened
        assert telemetry.hist_count("accepted_k") > h0
        # per-leg split sums into the total
        legs = (delta.get("k_steps_saved|leg=completion", 0)
                + delta.get("k_steps_saved|leg=confidence", 0))
        assert legs == delta.get("k_steps_saved", 0)

    def test_forced_rejection_fallback_bit_identical(self):
        """Acceptance pin: ADVERSARIAL proposals (random head) force
        rejections and the fallback re-runs the unchanged sequential
        loop — rows stay bit-identical, only telemetry differs."""
        eng, k_eng, _ = self._pair()
        prompts = _prompts(6)
        ref = eng.score_prompts(prompts, with_confidence=True,
                                max_new_tokens=10)
        k_eng.k_head = dmod.init_k_head(k_eng.cfg, 4, seed=11)
        snap = dict(telemetry.counters())
        got = k_eng.score_prompts(prompts, with_confidence=True,
                                  max_new_tokens=10)
        delta = telemetry.counters_since(snap)
        _rows_equal(ref, got)
        assert delta.get("k_blocks_rejected", 0) > 0
        # an all-rejecting run did MORE work than sequential, never
        # less: no chunk completed on the K path, so zero steps-saved
        # may be claimed (the bench-record honesty rule)
        assert delta.get("k_steps_saved", 0) == 0

    def test_missing_head_runs_sequential(self):
        eng, k_eng, _ = self._pair()
        prompts = _prompts(4)
        ref = eng.score_prompts(prompts)
        snap = dict(telemetry.counters())
        got = k_eng.score_prompts(prompts)      # no head set
        delta = telemetry.counters_since(snap)
        _rows_equal(ref, got)
        assert delta.get("k_decode_head_missing", 0) == 1
        assert delta.get("k_blocks_proposed", 0) == 0
        # noted ONCE: a second call stays quiet
        k_eng.score_prompts(prompts)
        assert telemetry.counters_since(snap).get(
            "k_decode_head_missing", 0) == 1

    def test_k1_never_records_k_telemetry(self):
        eng, _, _ = self._pair()
        snap = dict(telemetry.counters())
        eng.score_prompts(_prompts(4), with_confidence=True,
                          max_new_tokens=10)
        delta = telemetry.counters_since(snap)
        assert not any(k.startswith("k_") for k in delta)

    def test_fused_two_leg_parity_across_pool_compositions(self):
        """The pooled-confidence composition contract extends to K > 1:
        different pool targets (different flush groupings) and the K=1
        reference all emit bit-identical rows on the fused two-leg
        path — acceptance is per flush batch, but BOTH outcomes of the
        accept/reject decision emit the sequential path's bits."""
        pairs = [(f"Scenario {i}: the bylaw covers bicycles.",
                  (" Answer Yes or No.", " How confident, 0-100?"))
                 for i in range(6)]
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        sample = [p + s for p, (s, _) in pairs]
        eng, cfg, params, tok = _engine()
        ref = eng.score_prefixed(pairs, legs=legs)
        rows_by_target = []
        for target in (0, 3):
            k_eng = ScoringEngine(
                "falcon", cfg, params, tok,
                engine_config=dataclasses.replace(
                    eng.ecfg, decode_k=4, phase2_pool_target=target))
            k_eng.distill_k_head_on(sample)
            rows_by_target.append(k_eng.score_prefixed(pairs, legs=legs))
        for got in rows_by_target:
            for leg_ref, leg_got in zip(ref, got):
                _rows_equal(leg_ref, leg_got)


# ---------------------------------------------------------------------------
# EOS composition: k_steps_saved and decode_steps_saved never double count
# ---------------------------------------------------------------------------

class TestEosComposition:
    def test_eos_saved_and_k_saved_are_disjoint(self):
        """``decode_steps_saved`` counts positions whose chunks were
        NEVER launched (EOS early stop); ``k_steps_saved`` counts
        positions that WERE decoded, jointly, beyond the one verify
        pass.  Disjoint by construction: their sum can never exceed the
        total decode positions, and both fire on an EOS-typical run."""
        from test_packed import _eos_boosted

        cfg = DecoderConfig(**dict(TINY, vocab_size=384))
        tok = build_test_tokenizer()
        params = random_decoder_params(cfg)
        eng = ScoringEngine(
            "falcon", cfg, params, tok,
            engine_config=EngineConfig(batch_size=8, buckets=(32, 64)))
        prompts = _prompts(6)
        targets = [["Yes", "No"]] * 6
        eos_id = bench._arm_eos_token(tok, cfg)
        boosted = _eos_boosted(eng, cfg, params, prompts, targets, eos_id)
        try:
            eng.params = boosted
            ref = eng.score_prompts(prompts, targets=targets)
            k_eng = ScoringEngine(
                "falcon", cfg, boosted, tok,
                engine_config=dataclasses.replace(eng.ecfg, decode_k=4))
            k_eng.distill_k_head_on(prompts)
            snap = dict(telemetry.counters())
            got = k_eng.score_prompts(prompts, targets=targets)
            delta = telemetry.counters_since(snap)
        finally:
            eng.params = params
            tok.eos_token_id = None
        _rows_equal(ref, got)
        gen_total = eng.ecfg.max_new_tokens
        n_rows = len(prompts)
        saved_eos = delta.get("decode_steps_saved", 0)
        saved_k = delta.get("k_steps_saved", 0)
        assert saved_eos > 0                      # EOS early stop engaged
        assert saved_k > 0                        # joint blocks accepted
        assert saved_eos + saved_k <= gen_total * n_rows


# ---------------------------------------------------------------------------
# Strict mode
# ---------------------------------------------------------------------------

class TestStrictMode:
    def test_strict_k_decode_sweep_no_blocked_transfers(self):
        """Every K-path fetch (accept flags, chunk tokens, retirement
        reads) happens inside the sanctioned consume scope, so a
        strict-mode K-decode sweep holds ``blocked_transfers == 0`` —
        and its rows still match the K=1 strict rows."""
        from llm_interpretation_replication_tpu.runtime import strict

        pairs = [(f"Scenario {i}: the bylaw covers bicycles.",
                  (" Answer Yes or No.", " How confident, 0-100?"))
                 for i in range(4)]
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        eng, cfg, params, tok = _engine()
        ref = eng.score_prefixed(pairs, legs=legs)
        k_eng = ScoringEngine(
            "falcon", cfg, params, tok,
            engine_config=dataclasses.replace(eng.ecfg, decode_k=4))
        k_eng.distill_k_head_on([p + s for p, (s, _) in pairs])
        strict.activate()
        try:
            snap = telemetry.counters()
            got = k_eng.score_prefixed(pairs, legs=legs)
            delta = telemetry.counters_since(snap)
            assert delta.get(strict.BLOCKED_COUNTER, 0) == 0
            assert delta.get("k_blocks_proposed", 0) > 0
        finally:
            strict.deactivate()
        for leg_ref, leg_got in zip(ref, got):
            _rows_equal(leg_ref, leg_got)


# ---------------------------------------------------------------------------
# Telemetry export (obs/metrics satellite)
# ---------------------------------------------------------------------------

class TestTelemetryExport:
    def test_prometheus_hist_and_leg_counters(self):
        """``accepted_k`` exports as a Prometheus ``histogram`` family and
        the per-leg ``k_steps_saved|leg=...`` twins surface as labeled
        series of ONE counter family (the README counter-table rows)."""
        from llm_interpretation_replication_tpu.obs import metrics

        eng, cfg, params, tok = _engine()
        k_eng = ScoringEngine(
            "falcon", cfg, params, tok,
            engine_config=dataclasses.replace(eng.ecfg, decode_k=4))
        prompts = _prompts(4)
        k_eng.distill_k_head_on(prompts)
        k_eng.score_prompts(prompts, with_confidence=True,
                            max_new_tokens=10)
        text = metrics.prometheus_text()
        assert "# TYPE llm_interp_accepted_k histogram" in text
        assert "llm_interp_accepted_k_bucket" in text
        assert "llm_interp_k_blocks_proposed" in text
        assert 'llm_interp_k_steps_saved{leg="confidence"}' in text


# ---------------------------------------------------------------------------
# plan / plan_search: the priced K axis
# ---------------------------------------------------------------------------

class TestPlanSearchKAxis:
    def _falcon(self):
        from llm_interpretation_replication_tpu.models.config import (
            BENCH_GEOMETRIES,
        )

        return DecoderConfig(**BENCH_GEOMETRIES["falcon-7b"])

    def test_coefficient_literals_pinned(self):
        """The PR-5/PR-8 anchor discipline: coefficients are literals a
        recalibration must change deliberately, test-first."""
        assert ps.K_ACCEPT_PRIOR == 0.9
        assert ps.K_DECODE_SHARE == 0.55
        assert ps.DEFAULT_DECODE_KS == (1, 2, 4, 8)

    def test_speedup_formula(self):
        assert ps.k_decode_speedup(1) == 1.0
        p = ps.K_ACCEPT_PRIOR
        for k in (2, 4, 8):
            pb = p ** (k - 1)
            assert ps.k_decode_speedup(k) == pytest.approx(
                k / (pb + (1 - pb) * (1 + k)))
        # the non-monotone shape IS the reason the axis is priced: at the
        # 0.9 prior K=4 beats both K=2 and K=8
        assert ps.k_decode_speedup(4) > ps.k_decode_speedup(2)
        assert ps.k_decode_speedup(4) > ps.k_decode_speedup(8)

    def test_k_head_bytes_and_need_terms(self):
        f7 = self._falcon()
        assert plan_mod.k_head_bytes(f7, 1) == 0
        assert plan_mod.k_head_bytes(f7, 4) == \
            3 * f7.hidden_size * f7.vocab_size * 2
        wb = plan_mod.weight_bytes(f7, "int8")
        base = plan_mod.full_study_need_terms(f7, wb, "xla", 320, 256)
        assert "k_head" not in base          # default: every old pin holds
        terms = plan_mod.full_study_need_terms(f7, wb, "xla", 320, 256,
                                               decode_k=4)
        assert terms["k_head"] == plan_mod.k_head_bytes(f7, 4)
        # the K-head shards like a second lm_head: over tp (and pp)
        d1 = ps.sharded_need_bytes(terms, f7, 1, 1, 1)
        d2 = ps.sharded_need_bytes(terms, f7, 1, 2, 1)
        assert d1 - ps.sharded_need_bytes(base, f7, 1, 1, 1) == \
            terms["k_head"]
        assert d2 < d1

    def test_pricing_applies_amdahl_over_decode_share(self):
        f7 = self._falcon()
        base = ps.predicted_rows_per_s(f7, 1, 1, 320, workload="full")
        k4 = ps.predicted_rows_per_s(f7, 1, 1, 320, workload="full",
                                     decode_k=4)
        s = ps.k_decode_speedup(4)
        assert k4 == pytest.approx(
            base / (1 - ps.K_DECODE_SHARE + ps.K_DECODE_SHARE / s))
        # binary/packed workloads never price the axis
        assert ps.predicted_rows_per_s(
            f7, 1, 1, 320, workload="binary", decode_k=4) == \
            ps.predicted_rows_per_s(f7, 1, 1, 320, workload="binary")

    def test_k_gt1_candidate_survives_full_study_budget(self):
        """Acceptance criterion: the full-study search on the bench
        geometry keeps at least one K>1 candidate inside the budget —
        and records the axis on every candidate row."""
        f7 = self._falcon()
        ranked = ps.search_plans(f7, "int8", 1, seq=256, workload="full")
        fit_k = [c for c in ranked if c.fits and c.decode_k > 1]
        assert fit_k, "no K>1 candidate fits the full-study budget"
        assert all("decode_k" in c.as_record() for c in ranked[:4])
        # at the 0.9 prior the K axis WINS the search outright
        chosen = ps.chosen_plan(ranked)
        assert chosen is not None and chosen.decode_k > 1

    def test_binary_and_packed_collapse_the_axis(self):
        f7 = self._falcon()
        for workload in ("binary", "packed"):
            ranked = ps.search_plans(f7, "int8", 1, seq=256,
                                     workload=workload)
            assert all(c.decode_k == 1 for c in ranked)


# ---------------------------------------------------------------------------
# serve: mixed-K requests never share an engine call
# ---------------------------------------------------------------------------

class TestServeDecodeK:
    def test_compat_key_resolves_engine_default_and_override(self):
        from llm_interpretation_replication_tpu.serve import coalescer
        from llm_interpretation_replication_tpu.serve.request import (
            ScoreRequest,
        )

        eng, _, _, _ = _engine(decode_k=4)
        base = coalescer.compat_key(eng, ScoreRequest(prompt="p"), None)
        inherit = coalescer.compat_key(
            eng, ScoreRequest(prompt="q", decode_k=4), None)
        override = coalescer.compat_key(
            eng, ScoreRequest(prompt="r", decode_k=1), None)
        assert base == inherit          # None inherits the engine's K
        assert override != base         # explicit K=1 is its own group
        with pytest.raises(ValueError, match="decode_k"):
            ScoreRequest(prompt="p", decode_k=0).validate()

    def test_mixed_k_requests_never_share_an_engine_call(self):
        from test_serve import FAST, RecordingEngine

        from llm_interpretation_replication_tpu.serve import (
            Scheduler,
            SchedulerConfig,
        )
        from llm_interpretation_replication_tpu.serve.request import (
            ScoreRequest,
        )

        eng = RecordingEngine()
        sched = Scheduler(eng, SchedulerConfig(max_batch=16, **FAST))
        futs = [sched.submit(ScoreRequest(
            prompt=f"q{i}", decode_k=(2 if i % 2 else 1)))
            for i in range(8)]
        with sched:
            rows = [f.result(timeout=30) for f in futs]
        assert all(r["success"] for r in rows)
        assert len(eng.call_log) == 2
        assert sorted(len(c["prompts"]) for c in eng.call_log) == [4, 4]


# ---------------------------------------------------------------------------
# obs/benchdiff: K-tagged workload alignment + k_decode flattening
# ---------------------------------------------------------------------------

class TestBenchDiffDecodeK:
    def _rec(self, label, metric, value, **extra):
        rec = {"label": label, "metric": metric, "value": value,
               "unit": "rows/sec"}
        rec.update(extra)
        return rec

    def test_shape_tag_only_above_one(self):
        from llm_interpretation_replication_tpu.obs.benchdiff import (
            _shape_tags,
        )

        assert _shape_tags("full-study rows (joint decode-k 4)") == ["k4"]
        assert _shape_tags("full-study rows (joint decode-k 1)") == []
        assert _shape_tags("full-study rows, no-EOS worst case") == []

    def test_mixed_k_records_report_new_gone(self):
        """A K-tagged headline never cross-compares with the sequential
        one: the K row reads ``new``, the legacy row ``gone`` — no
        verdict is computed across workload shapes (the ISSUE-11/10
        alignment discipline)."""
        from llm_interpretation_replication_tpu.obs.benchdiff import (
            diff_records,
        )

        legacy = self._rec("r05", "full-study rows/sec/chip (no-EOS "
                           "worst case)", 31.64)
        ktagged = self._rec("r06", "full-study rows/sec/chip (no-EOS "
                            "worst case, joint decode-k 4)", 45.0)
        diff = diff_records([legacy, ktagged])
        verdicts = {r["key"]: r["verdict"] for r in diff["metrics"]}
        assert verdicts["headline"] == "gone"
        assert verdicts["headline@k4"] == "new"
        assert not diff["regressions"]
        # same-shape records still align and judge
        diff2 = diff_records([ktagged, dict(ktagged, value=30.0,
                                            label="r07")])
        assert diff2["metrics"][0]["verdict"] == "REGRESSION"

    def test_k_decode_block_flattens_top_level_and_nested(self):
        from llm_interpretation_replication_tpu.obs.benchdiff import (
            flatten_metrics,
        )

        block = {"decode_k": 4, "predicted_k": 4,
                 "accepted_k_mean": 3.2, "k_reject_rate": 0.12,
                 "k_steps_saved": {"total": 900, "confidence": 400,
                                   "completion": 500}}
        top = self._rec("r06", "full-study rows (joint decode-k 4)",
                        45.0, k_decode=block)
        flat = flatten_metrics(top)
        assert flat["k-decode steps-saved (confidence)"]["value"] == 400
        assert flat["k-decode steps-saved (completion)"]["value"] == 500
        assert flat["k-decode accepted-k mean"]["value"] == 3.2
        assert flat["k-decode reject rate"]["value"] == 0.12
        nested = self._rec("r06", "sweep prompts/sec", 120.0,
                           secondary=[self._rec(
                               "x", "full-study rows (joint decode-k 4)",
                               45.0, k_decode=block)])
        flat2 = flatten_metrics(nested)
        assert flat2["k-decode reject rate"]["value"] == 0.12


# ---------------------------------------------------------------------------
# bench wiring
# ---------------------------------------------------------------------------

class TestBenchWiring:
    def test_k_decode_block_builder(self):
        import argparse

        telemetry.record_hist("accepted_k", 4)
        ns = argparse.Namespace(
            decode_k=4, predicted_k=4,
            context_counters={
                "k_blocks_proposed": 100, "k_blocks_rejected": 10,
                "k_steps_saved": 900,
                "k_steps_saved|leg=confidence": 400,
                "k_steps_saved|leg=completion": 500},
            k_hist={"counts": {telemetry.hist_bucket_index(4): 25},
                    "count": 25, "sum": 100.0})
        block = bench._k_decode_block(ns)
        assert block["decode_k"] == 4 and block["predicted_k"] == 4
        assert block["k_reject_rate"] == 0.1
        assert block["k_steps_saved"] == {
            "total": 900, "confidence": 400, "completion": 500}
        assert block["accepted_k_mean"] == 4.0
        # keys are the recovered INTEGER accepted-K values, not the log
        # histogram's geometric bucket bounds
        assert block["accepted_k_hist"] == {"4": 25}
        assert bench._k_decode_block(
            argparse.Namespace(decode_k=1)) is None
        json.dumps(block)       # record-serializable

    def test_bench_sweep_full_k_decode_end_to_end(self, tmp_path):
        """The whole bench wiring, executed: a tiny --mode sweep-full run
        at decode_k=4 distills the K-head, runs both legs through the K
        path, and lands a k_decode block (accepted-K histogram scoped to
        the measured repeats, per-leg steps saved, reject rate) plus the
        K-tagged metric text in the record."""
        import argparse

        import jax

        scenarios = [{
            "original_main": "Is soup a beverage?",
            "response_format": "Answer only 'Yes' or 'No'.",
            "confidence_format": "How confident are you (0-100)?",
            "target_tokens": ["Yes", "No"],
            "rephrasings": [f"Is soup number {i} a beverage?"
                            for i in range(6)],
        }]
        corpus = tmp_path / "perturbations.json"
        corpus.write_text(json.dumps(scenarios))
        cfg = DecoderConfig(**dict(
            TINY, parallel_residual=True, qkv_bias=True, out_bias=True,
            mlp_bias=True))
        params = bench.init_params(cfg, jax.random.PRNGKey(0),
                                   jnp.float32)
        args = argparse.Namespace(
            model="tiny", quant="none", sweep_batch=8, sweep_rows=0,
            sweep_repeats=1, pool_target=0, pipeline_depth=2,
            checkpoint_every=100, sweep_out=str(tmp_path / "out.xlsx"),
            decided_frac=0.9, perturbations=str(corpus), mode="sweep-full",
            warmup=False, fuse_prefix=True, eos_mode="none",
            eos_brackets=False, decode_k=4)
        rps, rate, out = bench.run_sweep_full_mode(args, cfg, params)
        assert rps > 0 and np.isfinite(rps)
        record = bench._full_study_record(args, rps, rate)
        assert ", joint decode-k 4" in record["metric"]
        block = record["k_decode"]
        assert block["decode_k"] == 4
        assert block["k_blocks_proposed"] > 0
        assert sum(block["accepted_k_hist"].values()) == \
            block["k_blocks_proposed"]
        # integer K labels, within the engine's possible range
        assert all(0 <= int(kk) <= 4 for kk in block["accepted_k_hist"])
        assert block["k_steps_saved"]["total"] == \
            (block["k_steps_saved"]["confidence"]
             + block["k_steps_saved"]["completion"])
        assert record["context"]["decode_k"] == 4
        json.dumps(record)

    def test_bench_source_wires_decode_k(self):
        """Source pins (the child-forwarding test style): the flag
        exists, the sweep-full engine receives it, the K-head distills
        before warmup and re-distills on the EOS bracket's params, the
        plan search applies the chosen K, and the record attaches the
        block."""
        src = open(os.path.join(REPO_ROOT, "bench.py"),
                   encoding="utf-8").read()
        assert '"--decode-k"' in src
        assert 'decode_k=getattr(args, "decode_k", 1) or 1' in src
        # the definition plus its two call sites (post-calibration and
        # the EOS bracket's re-distill)
        assert src.count("_distill_bench_k_head(") == 3
        assert "args.decode_k = best.decode_k" in src
        assert "child.decode_k = best.decode_k" in src
        assert 'record["k_decode"] = k_block' in src

    def test_cli_source_wires_decode_k(self):
        from llm_interpretation_replication_tpu.config import RunConfig

        assert RunConfig().decode_k == 1
        path = os.path.join(
            REPO_ROOT, "llm_interpretation_replication_tpu",
            "__main__.py")
        src = open(path, encoding="utf-8").read()
        assert '"--decode-k"' in src
        assert "distill_k_head_on" in src
        assert "decode_k=getattr(rc, \"decode_k\", 1)" in src
