"""T5 enc-dec parity vs HF torch (the T0/tk-instruct scoring leg)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from llm_interpretation_replication_tpu.models import config as mcfg  # noqa: E402
from llm_interpretation_replication_tpu.models import convert as mconvert  # noqa: E402
from llm_interpretation_replication_tpu.models import t5 as t5m  # noqa: E402

VOCAB = 96


def _tiny(gated: bool, tied: bool):
    from transformers import T5Config, T5ForConditionalGeneration

    hf_config = T5Config(
        vocab_size=VOCAB, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=32,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=tied, decoder_start_token_id=0, eos_token_id=1,
        pad_token_id=0,
    )
    torch.manual_seed(11 if gated else 13)
    model = T5ForConditionalGeneration(hf_config).eval()
    return hf_config, model


def _convert(hf_config, model):
    fam, cfg = mcfg.from_hf_config(hf_config)
    assert fam == "t5"
    params = mconvert.convert(
        "t5", mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    return cfg, params


@pytest.mark.parametrize("gated,tied", [(True, False), (False, True)])
def test_t5_forward_parity(gated, tied):
    hf_config, model = _tiny(gated, tied)
    cfg, params = _convert(hf_config, model)
    rng = np.random.default_rng(3)
    enc_ids = rng.integers(2, VOCAB, size=(2, 10)).astype(np.int32)
    enc_mask = np.ones_like(enc_ids)
    enc_mask[1, 7:] = 0
    enc_ids[1, 7:] = 0
    dec_ids = np.concatenate(
        [np.zeros((2, 1), np.int32), rng.integers(2, VOCAB, size=(2, 4)).astype(np.int32)],
        axis=1,
    )
    with torch.no_grad():
        hf_logits = model(
            input_ids=torch.tensor(enc_ids),
            attention_mask=torch.tensor(enc_mask),
            decoder_input_ids=torch.tensor(dec_ids),
        ).logits.float().numpy()
    ours = np.asarray(
        t5m.forward(params, cfg, jnp.asarray(enc_ids), jnp.asarray(enc_mask), jnp.asarray(dec_ids))
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=1e-3)


def test_t5_greedy_decode_matches_hf_generate():
    hf_config, model = _tiny(True, False)
    cfg, params = _convert(hf_config, model)
    rng = np.random.default_rng(5)
    enc_ids = rng.integers(2, VOCAB, size=(1, 9)).astype(np.int32)
    enc_mask = np.ones_like(enc_ids)
    steps = 5
    with torch.no_grad():
        out = model.generate(
            torch.tensor(enc_ids), attention_mask=torch.tensor(enc_mask),
            max_new_tokens=steps, min_new_tokens=steps, do_sample=False,
            output_scores=True, return_dict_in_generate=True,
        )
    hf_tokens = out.sequences[0, 1:].numpy()  # drop decoder_start
    hf_scores = np.stack([s[0].float().numpy() for s in out.scores])
    tokens, scores = t5m.greedy_decode(
        params, cfg, jnp.asarray(enc_ids), jnp.asarray(enc_mask), num_steps=steps
    )
    np.testing.assert_array_equal(np.asarray(tokens)[0][: len(hf_tokens)], hf_tokens)
    # HF applies min_new_tokens processing to scores (-inf on eos); compare the
    # raw distributions only where HF didn't post-process.
    ours = np.asarray(scores)[0]
    finite = np.isfinite(hf_scores)
    np.testing.assert_allclose(ours[finite], hf_scores[finite], atol=2e-3, rtol=1e-3)
