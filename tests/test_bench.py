"""Control-flow tests for bench.py's shared-chip OOM resilience.

The e2e sweep's batch-320 operating point sits near the HBM edge and the
real chip is shared: a co-tenant's allocation can RESOURCE_EXHAUST a
repeat that ran clean three times (observed 2026-07).  The driver records
the bench's single JSON line every round, so a mid-repeat OOM must never
sink the whole record: with an earlier successful repeat the failed one
is skipped (best-of over successes); with none, the batch steps down once
and the repeat retries.  These tests drive run_sweep_mode on a tiny CPU
model with a fault-injected engine to pin both branches.
"""

import argparse
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from llm_interpretation_replication_tpu.models.decoder import (  # noqa: E402
    DecoderConfig,
)
from llm_interpretation_replication_tpu.runtime.engine import (  # noqa: E402
    ScoringEngine,
)

TINY = dict(
    vocab_size=300, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, parallel_residual=True, qkv_bias=True,
    out_bias=True, mlp_bias=True, position_embedding="rotary",
    rotary_pct=0.25, max_position_embeddings=512,
)


def _scenarios_file(tmp_path, rephrasings=6):
    scenarios = [{
        "original_main": "Is soup a beverage?",
        "response_format": "Answer only 'Yes' or 'No'.",
        "confidence_format": "How confident are you (0-100)?",
        "target_tokens": ["Yes", "No"],
        "rephrasings": [f"Is soup number {i} a beverage?"
                        for i in range(rephrasings)],
    }]
    path = tmp_path / "perturbations.json"
    path.write_text(json.dumps(scenarios))
    return str(path)


def _args(tmp_path, batch):
    return argparse.Namespace(
        model="tiny", quant="none", sweep_batch=batch, sweep_rows=0,
        sweep_repeats=2, pool_target=0, pipeline_depth=2,
        checkpoint_every=100, sweep_out=str(tmp_path / "out.xlsx"),
        decided_frac=0.9, perturbations=_scenarios_file(tmp_path),
    )


def _fault_injector(monkeypatch, fail_on_calls):
    """Make ScoringEngine.score_prompts raise a fake RESOURCE_EXHAUSTED on
    the given full-sweep call numbers (1-based), delegating otherwise."""
    real = ScoringEngine.score_prompts
    state = {"calls": 0}

    def wrapper(self, prompts, **kw):
        state["calls"] += 1
        if state["calls"] in fail_on_calls:
            raise RuntimeError("RESOURCE_EXHAUSTED: TPU backend error (fake)")
        return real(self, prompts, **kw)

    monkeypatch.setattr(ScoringEngine, "score_prompts", wrapper)
    return state


def test_is_oom_matches_every_spelling():
    for s in ("RESOURCE_EXHAUSTED: TPU backend error",
              "jax.errors.JaxRuntimeError: ResourceExhausted",
              "Resource exhausted: Out of memory allocating 1 bytes"):
        assert bench._is_oom(RuntimeError(s)), s
    assert not bench._is_oom(ValueError("shape mismatch"))


def test_sweep_oom_with_prior_success_keeps_best(tmp_path, monkeypatch):
    cfg = DecoderConfig(**TINY)
    params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    args = _args(tmp_path, batch=8)
    state = _fault_injector(monkeypatch, fail_on_calls={2})
    pps, rate, out = bench.run_sweep_mode(args, cfg, params)
    assert state["calls"] == 2          # repeat 1 failed and was skipped
    assert pps > 0 and np.isfinite(pps)
    assert args.sweep_batch == 8        # no fallback: a repeat had succeeded
    assert os.path.exists(out)


def test_sweep_oom_without_success_steps_batch_down(tmp_path, monkeypatch):
    cfg = DecoderConfig(**TINY)
    params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    args = _args(tmp_path, batch=320)
    state = _fault_injector(monkeypatch, fail_on_calls={1})
    pps, rate, out = bench.run_sweep_mode(args, cfg, params)
    # first call OOM'd with no prior success -> batch fell back to 256 and
    # the repeat retried; both budgeted repeats then completed
    assert args.sweep_batch == 256
    assert state["calls"] == 3
    assert pps > 0 and np.isfinite(pps)


@pytest.mark.faults
def test_sweep_oom_steps_through_measured_ladder(tmp_path, monkeypatch,
                                                capsys):
    """A 384 sweep that OOMs lands on 320 (a fully-measured operating
    point) before falling to 256 — the shared MEASURED_SWEEP_LADDER in
    runtime/faults.py, not a flat jump — and every skip/retry message
    carries the truncated error text as a diagnostic trail."""
    cfg = DecoderConfig(**TINY)
    params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    args = _args(tmp_path, batch=384)
    state = _fault_injector(monkeypatch, fail_on_calls={1, 2})
    pps, rate, out = bench.run_sweep_mode(args, cfg, params)
    # 384 -> 320 (call 1 OOM) -> 256 (call 2 OOM); both repeats then ran
    assert args.sweep_batch == 256
    assert state["calls"] == 4
    assert pps > 0 and np.isfinite(pps)
    err = capsys.readouterr().err
    assert "falling back to 320" in err
    assert "falling back to 256" in err
    assert "TPU backend error (fake)" in err  # misclassification stays auditable


def test_sweep_oom_at_floor_reraises(tmp_path, monkeypatch):
    cfg = DecoderConfig(**TINY)
    params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    args = _args(tmp_path, batch=256)
    args.sweep_repeats = 1
    _fault_injector(monkeypatch, fail_on_calls={1})
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        bench.run_sweep_mode(args, cfg, params)


def test_sweep_full_oom_steps_batch_down_and_keeps_workbook(tmp_path,
                                                           monkeypatch):
    """The full-study mode shares _sweep_oom_action (step -32, floor 192)
    and must return the last SUCCESSFUL repeat's workbook path even though
    every repeat re-measures from scratch."""
    cfg = DecoderConfig(**TINY)
    params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    args = _args(tmp_path, batch=320)
    args.sweep_out = None               # per-repeat tmpdirs: successes stay
    args.warmup = False                 # keep the call accounting exact
    # the fused sweep shell scores both legs through ONE score_prefixed
    # call per chunk; inject the repeat-level OOM there
    real = ScoringEngine.score_prefixed
    state = {"calls": 0}

    def wrapper(self, pairs, **kw):
        state["calls"] += 1
        if state["calls"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: TPU backend error (fake)")
        return real(self, pairs, **kw)

    monkeypatch.setattr(ScoringEngine, "score_prefixed", wrapper)
    rps, rate, out = bench.run_sweep_full_mode(args, cfg, params)
    assert args.sweep_batch == 288      # one -32 step, not a flat 256
    # ONE fused call per repeat (binary + confidence legs together):
    # failed attempt (1) + retried repeat 0 (2) + repeat 1 (3)
    assert state["calls"] == 3
    assert rps > 0 and np.isfinite(rps)
    assert out and os.path.exists(out)
    # warm-vs-cold repeat report rides along for the JSON record
    assert len(args.repeat_times) == 2


def test_full_study_secondary_runs_in_process(tmp_path):
    """ISSUE 12: the full-study companion row is produced by an
    in-process run over a FRESH engine (the sweep engine was closed by
    run_sweep_mode) on a shallow-copied namespace — the parent's
    operating point is never mutated by the secondary's."""
    cfg = DecoderConfig(**TINY)
    params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    args = _args(tmp_path, batch=8)
    args.mode = "sweep"
    args.sweep_repeats = 1
    args.warmup = False
    args.fuse_prefix = True
    args.eos_mode = "none"
    args.eos_brackets = False
    args.full_kv_dtype = "bf16"
    args.full_prefill_chunk = 0
    args.profile = None
    args.plan_search = False
    entry = bench._full_study_secondary(args, cfg, TINY, params)
    assert entry["unit"] == "rows/sec"
    assert entry["value"] > 0 and np.isfinite(entry["value"])
    assert "full-study" in entry["metric"]
    assert "context" in entry            # its OWN operating context
    # the secondary ran sweep-full on ITS copy; the parent keeps its mode
    assert args.mode == "sweep"
    assert args.sweep_out == str(tmp_path / "out.xlsx")


def test_full_study_secondary_is_in_process_no_subprocess():
    """Satellite (ISSUE 12): the full-study secondary runs IN-PROCESS.
    The r05-era fresh-subprocess isolation is deleted — verified engine
    teardown (ScoringEngine.close) is the fix that workaround stood in
    for — so bench.py must (a) no longer re-exec itself for the
    sweep-full companion, (b) close the sweep engine before the
    full-study leg builds a fresh one, and (c) still keep the serving-
    harness flags out of the full-study leg (the ISSUE-11 decision: the
    secondary measures the row contract, not the serving harness).  A
    future editor reintroducing the subprocess must consciously break
    this pin."""
    bench_src = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")).read()
    # the serving-harness flags still exist on the parent argparse
    # surface and still ride the parent sweep mode's offline rows only
    for flag in ("--serve-load", "--serve-load-rates",
                 "--serve-load-duration", "--serve-load-seed",
                 "--serve-load-replicas"):
        assert f'"{flag}"' in bench_src, flag
    # the subprocess isolation is gone...
    assert "import subprocess" not in bench_src
    # ...replaced by the in-process secondary over a torn-down engine
    assert "_full_study_secondary(" in bench_src
    assert "engine.close(release_params=False)" in bench_src
    # the full-study leg never measures the serving harness
    secondary = bench_src[bench_src.index("def _full_study_secondary"):]
    secondary = secondary[:secondary.index("\ndef ")]
    assert "serve_load" not in secondary
    assert "rate_sweep" not in secondary


def test_non_oom_errors_propagate(tmp_path, monkeypatch):
    cfg = DecoderConfig(**TINY)
    params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    args = _args(tmp_path, batch=320)

    def boom(self, prompts, **kw):
        raise ValueError("something unrelated")

    monkeypatch.setattr(ScoringEngine, "score_prompts", boom)
    with pytest.raises(ValueError, match="unrelated"):
        bench.run_sweep_mode(args, cfg, params)


class TestServeLoadRolesSpec:
    """--serve-load-roles parsing (ISSUE 20): both roles required, fail
    fast on anything a roster can't mean."""

    def test_parse_roles_spec(self):
        assert bench._parse_roles_spec("prefill:2,decode:1") == {
            "prefill": 2, "decode": 1}
        assert bench._parse_roles_spec(" decode:1 , prefill:1 ") == {
            "decode": 1, "prefill": 1}

    def test_rejects_incomplete_or_unknown_rosters(self):
        for bad in ("prefill:2", "decode:3", "draft:1,decode:1",
                    "prefill:0,decode:1", "prefill:1,decode:0", ""):
            with pytest.raises(ValueError):
                bench._parse_roles_spec(bad)
