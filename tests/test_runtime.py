"""Runtime tests: bucketing, checkpoint loading from disk, the scoring engine
end-to-end with a tiny model (single-device and data-parallel mesh), and the
sharded train step on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax.numpy as jnp

from helpers import build_test_tokenizer
from llm_interpretation_replication_tpu.runtime import (
    batches_for_prompts,
    bucket_for,
    make_optimizer,
    init_train_state,
    make_train_step,
    ScoringEngine,
    EngineConfig,
)


class TestBucketing:
    def test_bucket_for(self):
        assert bucket_for(1) == 64
        assert bucket_for(64) == 64
        assert bucket_for(65) == 80      # perturbation-corpus hot zone
        assert bucket_for(100) == 112
        assert bucket_for(130) == 144
        assert bucket_for(150) == 160    # step-16 through the whole hot zone
        assert bucket_for(430) == 432    # 100q few-shot hot zone
        with pytest.raises(ValueError):
            bucket_for(99999)

    def test_batches_fixed_shapes_and_padding(self):
        encoded = [[1] * n for n in (5, 70, 8, 100, 3, 200)]
        batches = list(batches_for_prompts(encoded, batch_size=2, pad_id=0))
        # buckets: 64 -> [5,8,3] (2 batches), 96 -> [70], 112 -> [100],
        # 256 -> [200]
        shapes = sorted({(b.token_ids.shape, b.bucket_len) for b in batches})
        assert ((2, 64), 64) in [(s, bl) for s, bl in shapes]
        covered = sorted(int(i) for b in batches for i in b.indices if i >= 0)
        assert covered == [0, 1, 2, 3, 4, 5]
        for b in batches:
            assert b.token_ids.shape == (2, b.bucket_len)
            # pad rows duplicate row 0
            for r in range(len(b.indices)):
                if b.indices[r] < 0:
                    np.testing.assert_array_equal(b.token_ids[r], b.token_ids[0])

    def test_tiny_buckets_merge_upward(self):
        """A near-empty bucket must not cost its own XLA compile: fewer than
        min_bucket_rows prompts merge into the next occupied larger bucket
        (cascading); the largest occupied bucket never merges."""
        # 20 prompts at ~100 tokens (112 bucket), 1 stray at 70 (96), 1 at
        # 130 (144): with batch_size 16, min rows = 2 -> 96 and 112?  96 has
        # 1 < 2 -> merges into 112; 144 is largest occupied -> stays.
        encoded = [[1] * 100] * 20 + [[1] * 70] + [[1] * 130]
        batches = list(batches_for_prompts(encoded, batch_size=16, pad_id=0))
        lens = sorted({b.bucket_len for b in batches})
        assert lens == [112, 144]
        covered = sorted(int(i) for b in batches for i in b.indices if i >= 0)
        assert covered == list(range(22))
        # cascade: two tiny buckets in a row both ride up (batch 32 ->
        # min rows 4; the merged 96+112 pair is still under threshold)
        encoded = [[1] * 70] + [[1] * 100] + [[1] * 130] * 20
        batches = list(batches_for_prompts(encoded, batch_size=32, pad_id=0))
        assert sorted({b.bucket_len for b in batches}) == [144]
        # disable via min_bucket_rows=1: every occupied bucket kept
        batches = list(batches_for_prompts(encoded, batch_size=32, pad_id=0,
                                           min_bucket_rows=1))
        assert sorted({b.bucket_len for b in batches}) == [80, 112, 144]

    def test_length_sorted_batches(self):
        """Global length-sorted mode: batches are consecutive runs of the
        sorted lengths, each padded to ITS OWN max's bucket, one partial
        batch total, and every prompt index covered exactly once."""
        rng = np.random.default_rng(0)
        lens = rng.integers(60, 204, size=37)
        encoded = [[1] * int(n) for n in lens]
        batches = list(batches_for_prompts(encoded, batch_size=8, pad_id=0,
                                           length_sorted=True))
        assert len(batches) == 5  # ceil(37/8): exactly one partial batch
        covered = sorted(int(i) for b in batches for i in b.indices if i >= 0)
        assert covered == list(range(37))
        prev_max = 0
        for b in batches:
            real = b.indices >= 0
            row_lens = b.attention_mask.sum(axis=1)[real]
            # each batch pads to the bucket of its own longest prompt...
            assert b.bucket_len == bucket_for(int(row_lens.max()))
            # ...and batches come out in ascending length order
            assert int(row_lens.max()) >= prev_max
            prev_max = int(row_lens.max())
            assert b.token_ids.shape == (8, b.bucket_len)
        # padding is never worse than bucket-grouped for the same menu
        sorted_tokens = sum(8 * b.bucket_len for b in batches)
        grouped_tokens = sum(
            b.token_ids.shape[0] * b.bucket_len
            for b in batches_for_prompts(encoded, batch_size=8, pad_id=0,
                                         min_bucket_rows=1))
        assert sorted_tokens <= grouped_tokens


def _tiny_engine(mesh=None, batch_size=4):
    torch = pytest.importorskip("torch")
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    from llm_interpretation_replication_tpu.models import config as mcfg
    from llm_interpretation_replication_tpu.models import convert as mconvert

    tok = build_test_tokenizer()
    vocab = tok.backend_tokenizer.get_vocab_size() if hasattr(tok, "backend_tokenizer") else 300
    hf_config = GPTNeoXConfig(
        vocab_size=max(vocab, 300), hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=256,
    )
    torch.manual_seed(31)
    model = GPTNeoXForCausalLM(hf_config).eval()
    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    if mesh is not None:
        from llm_interpretation_replication_tpu.parallel import shard_params

        params = shard_params(params, mesh)
    eng = ScoringEngine(
        fam, cfg, params, tok, mesh=mesh,
        engine_config=EngineConfig(batch_size=batch_size, buckets=(32, 64)),
    )
    return eng, model, tok


class TestScoringEngine:
    def test_rows_contract_and_determinism(self):
        eng, _, _ = _tiny_engine()
        prompts = [
            "Is a tweet a publication? Answer: Yes",
            "Is soup a beverage?",
            "The quick brown fox",
        ]
        rows = eng.score_prompts(prompts)
        assert len(rows) == 3
        for row in rows:
            assert set(row) >= {
                "yes_prob", "no_prob", "relative_prob", "odds_ratio",
                "completion", "success",
            }
            assert row["success"]
            assert 0.0 <= row["relative_prob"] <= 1.0
        rows2 = eng.score_prompts(prompts)
        for a, b in zip(rows, rows2):
            assert a["relative_prob"] == b["relative_prob"]

    def test_data_parallel_matches_single_device(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.parallel import make_mesh

        prompts = [f"prompt number {i} about soup" for i in range(8)]
        eng_single, _, _ = _tiny_engine(mesh=None, batch_size=8)
        rows_single = eng_single.score_prompts(prompts)
        mesh = make_mesh(data=8, model=1, seq=1)
        eng_dp, _, _ = _tiny_engine(mesh=mesh, batch_size=8)
        rows_dp = eng_dp.score_prompts(prompts)
        for a, b in zip(rows_single, rows_dp):
            np.testing.assert_allclose(a["relative_prob"], b["relative_prob"], atol=1e-5)

    def test_tensor_parallel_matches_single_device(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.parallel import make_mesh

        prompts = ["soup is a beverage maybe", "tweets are publications"]
        eng_single, _, _ = _tiny_engine(mesh=None, batch_size=2)
        rows_single = eng_single.score_prompts(prompts)
        mesh = make_mesh(data=2, model=4, seq=1)
        eng_tp, _, _ = _tiny_engine(mesh=mesh, batch_size=2)
        rows_tp = eng_tp.score_prompts(prompts)
        for a, b in zip(rows_single, rows_tp):
            np.testing.assert_allclose(a["relative_prob"], b["relative_prob"], atol=1e-5)

    def test_pipelined_matches_unpipelined(self):
        """pipeline_depth > 1 overlaps host work with device compute; results
        must be identical to the serial depth-1 loop (order and values)."""
        import dataclasses as dc

        eng, _, _ = _tiny_engine(batch_size=2)
        prompts = [f"prompt {i} about soup and tweets" for i in range(7)]
        rows_piped = eng.score_prompts(prompts, with_confidence=True)
        eng.ecfg = dc.replace(eng.ecfg, pipeline_depth=1)
        rows_serial = eng.score_prompts(prompts, with_confidence=True)
        assert [r["relative_prob"] for r in rows_piped] == [
            r["relative_prob"] for r in rows_serial
        ]
        assert [r["completion"] for r in rows_piped] == [
            r["completion"] for r in rows_serial
        ]
        eng.ecfg = dc.replace(eng.ecfg, pipeline_depth=4)  # deeper than #batches
        fast_deep = eng.first_token_relative_prob(prompts)
        eng.ecfg = dc.replace(eng.ecfg, pipeline_depth=1)
        fast_serial = eng.first_token_relative_prob(prompts)
        np.testing.assert_array_equal(fast_deep, fast_serial)

    def test_completions_match_hf_generate_50_tokens(self):
        """The completion column must be the reference's full
        ``generate(max_new_tokens=50)`` text, truncated at 100 chars — not a
        10-token prefix (run_base_vs_instruct_100q.py:337-346,379)."""
        import torch

        eng, model, tok = _tiny_engine()
        assert eng.ecfg.max_new_tokens == 50
        prompts = [
            "Is a tweet a publication? Answer: Yes",
            "Is soup a beverage?",
            "The quick brown fox jumps over",
        ]
        rows = eng.score_prompts(prompts)
        for prompt, row in zip(prompts, rows):
            ids = tok(prompt, return_tensors="pt").input_ids
            with torch.no_grad():
                out = model.generate(
                    ids, max_new_tokens=50, do_sample=False,
                    pad_token_id=tok.pad_token_id or 0,
                    eos_token_id=tok.eos_token_id,
                )
            ref = tok.decode(
                out[0][ids.shape[1]:], skip_special_tokens=True
            ).strip()[:100]
            assert row["completion"] == ref, (prompt, row["completion"], ref)

    def test_reduced_scores_match_full_score_branch(self, monkeypatch):
        """The completions path defaults to ReducedScores (top-19 + logsumexp
        + target logits stacked in-scan) instead of the [B, steps, V] fp32
        buffer; forcing the full-score branch (top_k above the kept
        candidates) must yield identical rows — probabilities, completions,
        and the confidence leg."""
        from llm_interpretation_replication_tpu.models import decoder as dmod

        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"prompt {i} about soup and tweets" for i in range(6)]
        rows_reduced = eng.score_prompts(prompts, with_confidence=True)
        monkeypatch.setattr(dmod, "REDUCED_TOPK", 0)  # force full scores
        rows_full = eng.score_prompts(prompts, with_confidence=True)
        for a, b in zip(rows_reduced, rows_full):
            assert a["completion"] == b["completion"]
            assert a["success"] == b["success"]
            for f in ("yes_prob", "no_prob", "relative_prob",
                      "weighted_confidence"):
                np.testing.assert_allclose(a[f], b[f], rtol=1e-5, atol=1e-7,
                                           err_msg=f)

    def test_two_phase_matches_full_decode_probs(self):
        """decode_completions=False takes the early-exit subset path; its
        probabilities must equal the completions path (which scores every
        row) and stay within the reference scan semantics."""
        import dataclasses as dc

        eng, _, _ = _tiny_engine()
        prompts = [f"prompt {i} about soup, tweets and vehicles" for i in range(5)]
        rows_full = eng.score_prompts(prompts)
        eng.ecfg = dc.replace(eng.ecfg, decode_completions=False)
        rows_fast = eng.score_prompts(prompts)
        for a, b in zip(rows_full, rows_fast):
            np.testing.assert_allclose(a["yes_prob"], b["yes_prob"], rtol=1e-5)
            np.testing.assert_allclose(a["no_prob"], b["no_prob"], rtol=1e-5)
            np.testing.assert_allclose(
                a["relative_prob"], b["relative_prob"], rtol=1e-5
            )
            assert a["scan_found"] == b["scan_found"]
            assert b["completion"] == ""

    def test_two_phase_gather_path_on_dp_mesh(self, eight_cpu_devices):
        """The phase-2 subset GATHER (undecided rows pulled out of a SHARDED
        prefill cache, m < batch) must work across the data mesh and agree
        with the single-device full-decode result.  batch 16 on dp=8 with
        few prompts forces m=8 < 16, the gather branch."""
        import dataclasses as dc

        from llm_interpretation_replication_tpu.parallel import make_mesh

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.runtime import batching
        from llm_interpretation_replication_tpu.scoring import yes_no as yn

        prompts = [f"prompt number {i} about soup" for i in range(5)]
        eng_single, _, _ = _tiny_engine(mesh=None, batch_size=16)
        rows_single = eng_single.score_prompts(prompts)

        # guard against vacuity: at least one prompt must be UNDECIDED at
        # position 0, otherwise phase 2 (the gather under test) never runs
        yes_id, no_id = eng_single.target_ids(("Yes", "No"))[:2]
        batch = next(batching.batches_for_prompts(
            batching.encode_prompts(eng_single.tokenizer, prompts), 16,
            eng_single.ecfg.buckets,
            pad_id=eng_single.tokenizer.pad_token_id or 0,
        ))
        last = dmod.forward_last_logits(
            eng_single.params, eng_single.cfg,
            jnp.asarray(batch.token_ids), jnp.asarray(batch.attention_mask),
        )
        hit = np.asarray(yn.first_token_scan(
            last, yes_id, no_id, top_k=eng_single.ecfg.top_k)[4])
        n_undecided = int((~hit & (batch.indices >= 0)).sum())
        assert n_undecided >= 1, "fixture decided every row at position 0"

        mesh = make_mesh(data=8, model=1, seq=1)
        eng_dp, _, _ = _tiny_engine(mesh=mesh, batch_size=16)
        eng_dp.ecfg = dc.replace(eng_dp.ecfg, decode_completions=False)
        rows_dp = eng_dp.score_prompts(prompts)
        for a, b in zip(rows_single, rows_dp):
            np.testing.assert_allclose(
                a["relative_prob"], b["relative_prob"], atol=1e-5
            )
            assert a["scan_found"] == b["scan_found"]

    def test_bf16_escape_hatch_routing(self):
        """The only-working bf16 7B configuration (PARITY.md 'bf16
        fallback') is a LIBRARY decision, not a bench special case, and
        must not silently regress: on falcon-7b geometry with a 16 GB HBM
        budget, quant='none' routes to the Pallas flash kernel with the
        batch clamped to 64 (dense would exceed the budget at ANY sweep
        batch), while the int8 default keeps dense attention at batch 192
        (the measured 38 p/s headline config)."""
        from llm_interpretation_replication_tpu.models.config import DecoderConfig
        from llm_interpretation_replication_tpu.runtime import resolve_scoring_plan

        falcon7b = DecoderConfig(
            vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71,
            num_kv_heads=1, intermediate_size=18176, parallel_residual=True,
            shared_layernorm=True, qkv_bias=False, out_bias=False,
            mlp_bias=False, position_embedding="rotary",
            tie_word_embeddings=True, max_position_embeddings=2048,
        )
        # bf16: dense infeasible, flash escape hatch at batch 64
        plan = resolve_scoring_plan(falcon7b, "none", 192, 432)
        assert not plan.fits_dense
        assert plan.attention_impl == "flash"
        assert plan.batch == 64
        # ... even when the caller asks for a batch dense couldn't hold
        plan64 = resolve_scoring_plan(falcon7b, "none", 64, 432)
        assert not plan64.fits_dense and plan64.attention_impl == "flash"
        # int8 default: dense fits at the headline operating point
        plan_i8 = resolve_scoring_plan(falcon7b, "int8", 192, 432)
        assert plan_i8.fits_dense
        assert plan_i8.attention_impl == "xla" and plan_i8.batch == 192
        # weights dominate: the estimate must see ~13 GiB of bf16 weights
        assert 12 * 2**30 < plan.weight_bytes < 15 * 2**30
        # tiny models never trigger the hatch
        small = DecoderConfig(
            vocab_size=50304, hidden_size=2048, num_layers=16, num_heads=16,
            intermediate_size=8192, parallel_residual=True, qkv_bias=True,
            out_bias=True, mlp_bias=True, position_embedding="rotary",
            rotary_pct=0.25, max_position_embeddings=2048,
        )
        plan_s = resolve_scoring_plan(small, "none", 192, 432)
        assert plan_s.fits_dense and plan_s.attention_impl == "xla"
        # explicit flash request keeps a batch that fits (no pow2 clamp)
        plan_f = resolve_scoring_plan(small, "int8", 192, 432,
                                      requested_impl="flash")
        assert plan_f.attention_impl == "flash" and plan_f.batch == 192
        # a chip too small for even the weights clamps to the floor batch
        plan_t = resolve_scoring_plan(falcon7b, "none", 192, 432,
                                      hbm_bytes=8 << 30)
        assert not plan_t.fits_dense and plan_t.batch == 1

        # FULL-STUDY planning (completions + confidence): the pinned KV
        # caches shrink the sweep batch.  v5e 10k-corpus anchors with the
        # ReducedScores engine (r5): int8 falcon-7b at the 256-token worst
        # bucket fits at batch 224 (31.4 rows/s warm, the measured
        # optimum); 240 thrashes the allocator (14.1 rows/s warm) and 256
        # OOMs mid-sweep, so both clamp to 224; 192 fits and must NOT
        # clamp; the binary-leg plan at 256 stays unclamped.
        from llm_interpretation_replication_tpu.runtime.plan import (
            resolve_full_sweep_plan,
        )

        full = resolve_full_sweep_plan(falcon7b, "int8", 256, 256,
                                       pipeline_depth=2)
        assert full.batch == 224 and full.attention_impl == "xla"
        full240 = resolve_full_sweep_plan(falcon7b, "int8", 240, 256,
                                          pipeline_depth=2)
        assert full240.batch == 224
        full224 = resolve_full_sweep_plan(falcon7b, "int8", 224, 256,
                                          pipeline_depth=2)
        assert full224.batch == 224
        full192 = resolve_full_sweep_plan(falcon7b, "int8", 192, 256,
                                          pipeline_depth=2)
        assert full192.batch == 192
        binary = resolve_scoring_plan(falcon7b, "int8", 256, 256)
        assert binary.batch == 256
        # bf16 full-study: still routed to the flash escape hatch
        full_bf = resolve_full_sweep_plan(falcon7b, "none", 256, 256,
                                          pipeline_depth=2)
        assert full_bf.attention_impl == "flash" and full_bf.batch <= 64

    def test_per_call_max_new_tokens_override(self):
        """score_prompts(max_new_tokens=N) caps generation for ONE call (the
        sweep's confidence leg uses the API legs' 10-token contract) without
        touching the engine config or the scored scan: same probabilities,
        the capped completion is a prefix of the full one, and the floor is
        the scan steps."""
        import torch

        eng, model, tok = _tiny_engine()
        assert eng._gen_plan() == (10, 50)
        assert eng._gen_plan(10) == (10, 10)
        assert eng._gen_plan(1) == (10, 10)   # never below the scored scan
        prompts = ["The quick brown fox jumps over", "Is soup a beverage?"]
        full = eng.score_prompts(prompts)
        capped = eng.score_prompts(prompts, max_new_tokens=10)
        assert eng.ecfg.max_new_tokens == 50  # config untouched
        for prompt, f, c in zip(prompts, full, capped):
            np.testing.assert_allclose(c["relative_prob"],
                                       f["relative_prob"], rtol=1e-6)
            ids = tok(prompt, return_tensors="pt").input_ids
            with torch.no_grad():
                out = model.generate(
                    ids, max_new_tokens=10, do_sample=False,
                    pad_token_id=tok.pad_token_id or 0,
                    eos_token_id=tok.eos_token_id,
                )
            ref = tok.decode(out[0][ids.shape[1]:],
                             skip_special_tokens=True).strip()[:100]
            assert c["completion"] == ref, (prompt, c["completion"], ref)

    def test_pool_crosses_buckets_via_quantized_cache_len(self):
        """Undecided slices from DIFFERENT length buckets pool together
        under one quantized cache length (_pool_len): the prefill pads the
        slice with inert invalid slots (engine._prefill_select out_len), so
        a mixed-bucket sweep produces the same per-prompt numbers as the
        per-batch decode — and the pool really does hold ONE key."""
        import dataclasses as dc

        from llm_interpretation_replication_tpu.runtime import engine as emod

        eng, _, _ = _tiny_engine(batch_size=8)
        # Two distinct buckets (32 and 64) with length-sorted batching OFF,
        # so batches from both buckets are emitted and pool separately-keyed
        # slices unless the quantized key merges them.
        prompts = ([f"short {i}?" for i in range(8)]
                   + [f"longer prompt {i} crossing the bucket line {i}"
                      for i in range(8)])
        eng.ecfg = dc.replace(eng.ecfg, decode_completions=False,
                              phase2_pool=False, length_sorted_batches=False)
        rows_direct = eng.score_prompts(prompts)
        keys_seen = []
        orig_add = emod._Phase2Pool.add

        def spy_add(self, pool_len, *a, **k):
            keys_seen.append(pool_len)
            return orig_add(self, pool_len, *a, **k)

        emod._Phase2Pool.add = spy_add
        try:
            eng.ecfg = dc.replace(eng.ecfg, phase2_pool=True,
                                  phase2_pool_target=64)  # only flush_all
            rows_pooled = eng.score_prompts(prompts)
        finally:
            emod._Phase2Pool.add = orig_add
        assert all(r["success"] for r in rows_pooled)
        for a, b in zip(rows_direct, rows_pooled):
            np.testing.assert_allclose(a["relative_prob"], b["relative_prob"],
                                       rtol=1e-5)
        # both buckets' slices arrived under the SAME quantized pool key
        assert keys_seen and len(set(keys_seen)) == 1, keys_seen
        assert set(keys_seen) == {emod._pool_len(64)}

    def test_phase2_pool_matches_per_batch_decode(self):
        """Cross-batch pooling of undecided rows (one scored decode per
        ~pool_target rows instead of one per prefill batch) must be invisible
        in the results: same probabilities, same scan_found, every prompt
        emitted — including a mid-sweep flush, the end-of-sweep flush_all,
        and blank filler rows padding the pooled slice to a menu size."""
        import dataclasses as dc

        eng, _, _ = _tiny_engine(batch_size=16)
        # 40 prompts -> 3 batches of 16; undecided rows pool across batches.
        prompts = [f"prompt {i} about soup, tweets and vehicles" for i in range(40)]
        eng.ecfg = dc.replace(
            eng.ecfg, decode_completions=False, phase2_pool=False
        )
        rows_direct = eng.score_prompts(prompts)
        # targets: flush every batch / mid-sweep / only at flush_all; the
        # last case also squeezes phase2_pool_max_bytes so the HBM cap path
        # (early flush of the biggest bucket) is exercised and identical
        for target, max_bytes in ((1, 512 << 20), (8, 512 << 20),
                                  (16, 512 << 20), (64, 512 << 20),
                                  (64, 1)):
            eng.ecfg = dc.replace(
                eng.ecfg, phase2_pool=True, phase2_pool_target=target,
                phase2_pool_max_bytes=max_bytes,
            )
            rows_pooled = eng.score_prompts(prompts)
            assert all(r["success"] for r in rows_pooled)
            for a, b in zip(rows_direct, rows_pooled):
                np.testing.assert_allclose(
                    a["relative_prob"], b["relative_prob"], rtol=1e-5
                )
                np.testing.assert_allclose(a["yes_prob"], b["yes_prob"], rtol=1e-5)
                assert a["scan_found"] == b["scan_found"]

    def test_per_row_targets_match_per_group_calls(self):
        """One call with PER-PROMPT target pairs (cross-scenario batching)
        must reproduce separate per-scenario calls exactly, across the fast
        path, the two-phase path (incl. pooled flushes mixing scenarios),
        and the completions path."""
        import dataclasses as dc

        eng, _, _ = _tiny_engine(batch_size=8)
        prompts_a = [f"is item {i} a publication maybe" for i in range(6)]
        prompts_b = [f"does thing {i} count as soup" for i in range(5)]
        pairs = [("Yes", "No")] * len(prompts_a) + [("No", "Yes")] * len(prompts_b)
        mixed = prompts_a + prompts_b

        rows_a = eng.score_prompts(prompts_a, targets=("Yes", "No"))
        rows_b = eng.score_prompts(prompts_b, targets=("No", "Yes"))
        rows_mixed = eng.score_prompts(mixed, targets=pairs)
        for a, b in zip(rows_a + rows_b, rows_mixed):
            assert a["yes_prob"] == b["yes_prob"]
            assert a["relative_prob"] == b["relative_prob"]
            assert a["completion"] == b["completion"]

        eng.ecfg = dc.replace(eng.ecfg, decode_completions=False,
                              phase2_pool_target=16)
        fast_a = eng.first_token_relative_prob(prompts_a, targets=("Yes", "No"))
        fast_b = eng.first_token_relative_prob(prompts_b, targets=("No", "Yes"))
        fast_mixed = eng.first_token_relative_prob(mixed, targets=pairs)
        np.testing.assert_array_equal(np.vstack([fast_a, fast_b]), fast_mixed)

        two_a = eng.score_prompts(prompts_a, targets=("Yes", "No"))
        two_b = eng.score_prompts(prompts_b, targets=("No", "Yes"))
        two_mixed = eng.score_prompts(mixed, targets=pairs)
        for a, b in zip(two_a + two_b, two_mixed):
            np.testing.assert_allclose(a["relative_prob"], b["relative_prob"],
                                       rtol=1e-6)
            assert a["scan_found"] == b["scan_found"]
        with pytest.raises(ValueError, match="per-prompt targets"):
            eng.score_prompts(mixed, targets=pairs[:-1])

    def test_rows_carry_fused_first_token_probs(self):
        """Every score_prompts row carries first_token_{yes,no,relative}_prob
        — the top-20-filtered position-0 view the perturbation sweep's
        binary leg previously paid a second full forward for — and the
        values equal first_token_relative_prob's, on the completions path
        AND the pooled two-phase path (incl. flush-emitted rows)."""
        import dataclasses as dc

        eng, _, _ = _tiny_engine(batch_size=16)
        prompts = [f"prompt {i} about soup, tweets and vehicles" for i in range(20)]
        fast = eng.first_token_relative_prob(prompts, top_filter=20)
        for pooled in (False, True):
            eng.ecfg = dc.replace(eng.ecfg, decode_completions=not pooled,
                                  phase2_pool_target=16)
            rows = eng.score_prompts(prompts)
            for i, row in enumerate(rows):
                np.testing.assert_allclose(
                    row["first_token_yes_prob"], fast[i, 0], rtol=1e-6)
                np.testing.assert_allclose(
                    row["first_token_no_prob"], fast[i, 1], rtol=1e-6)
                np.testing.assert_allclose(
                    row["first_token_relative_prob"], fast[i, 2], rtol=1e-6)

    def test_prefill_select_slice_contract(self):
        """_prefill_select's contract: slice rows 0..count-1 are EXACTLY the
        undecided real rows (set equality — order is the sort's business),
        batch padding rows sort as decided, and the slice caches agree with
        a full prefill gather for those rows."""
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.runtime import batching
        from llm_interpretation_replication_tpu.runtime.engine import (
            _prefill_select,
        )
        from llm_interpretation_replication_tpu.scoring import yes_no as yn

        eng, _, _ = _tiny_engine(batch_size=8)
        prompts = [f"prompt {i} about soup and tweets" for i in range(5)]
        batch = next(batching.batches_for_prompts(
            batching.encode_prompts(eng.tokenizer, prompts), 8,
            eng.ecfg.buckets, pad_id=eng.tokenizer.pad_token_id or 0,
        ))
        yes_id, no_id = eng.target_ids(("Yes", "No"))[:2]
        ids = jnp.asarray(batch.token_ids)
        mask = jnp.asarray(batch.attention_mask)
        row_y = jnp.full((8,), yes_id, jnp.int32)
        row_n = jnp.full((8,), no_id, jnp.int32)
        scan0, first3, sel, sub, last_s, len_s = _prefill_select(
            eng.params, eng.cfg, ids, mask,
            jnp.asarray(batch.indices >= 0), row_y, row_n,
            cache_len=batch.bucket_len, slice_m=8, top_k=eng.ecfg.top_k,
        )
        hit = np.asarray(scan0[4])
        valid = batch.indices >= 0
        undecided = set(np.flatnonzero(~hit & valid).tolist())
        sel_np = np.asarray(sel)
        count = len(undecided)
        assert set(sel_np[:count].tolist()) == undecided
        # padding rows (invalid) never appear before real decided rows run out
        assert all(valid[r] for r in sel_np[:int(valid.sum())])
        # slice caches equal a gather of the same rows from a full prefill
        last_full, cache = dmod.prefill(
            eng.params, eng.cfg, ids, mask, cache_len=batch.bucket_len)
        np.testing.assert_allclose(
            np.asarray(sub.k), np.asarray(cache.k[:, sel_np]), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(last_s), np.asarray(last_full[sel_np]), rtol=1e-6)
        # the selected rows' scan values equal the full-batch scan's
        full_scan = yn.first_token_scan(last_full, yes_id, no_id,
                                        top_k=eng.ecfg.top_k)
        np.testing.assert_allclose(np.asarray(scan0[2]),
                                   np.asarray(full_scan[2]), rtol=1e-6)

    def test_chunked_scan_matches_single_chunk(self):
        """scan_chunk must be invisible in the results: the early exit may
        only fire when every real row is resolved (hit or actual EOS), so a
        2-step chunking and a single 10-step chunk agree row-for-row."""
        import dataclasses as dc

        eng, _, _ = _tiny_engine()
        eng.ecfg = dc.replace(eng.ecfg, decode_completions=False)
        prompts = [f"prompt {i} about soup, tweets, Yes and No" for i in range(6)]
        eng.ecfg = dc.replace(eng.ecfg, scan_chunk=10)
        rows_one = eng.score_prompts(prompts)
        eng.ecfg = dc.replace(eng.ecfg, scan_chunk=2)
        rows_chunked = eng.score_prompts(prompts)
        for a, b in zip(rows_one, rows_chunked):
            assert a["scan_found"] == b["scan_found"]
            np.testing.assert_allclose(
                a["relative_prob"], b["relative_prob"], rtol=1e-5
            )
            np.testing.assert_allclose(a["yes_prob"], b["yes_prob"], rtol=1e-5)

    def test_first_token_fast_path_matches_scan_position0(self):
        eng, _, _ = _tiny_engine()
        prompts = ["Is soup a beverage?"]
        fast = eng.first_token_relative_prob(prompts)
        rows = eng.score_prompts(prompts)
        # fast path == position-0 probabilities of the scan when the scan
        # found its hit at position 0
        if rows[0]["scan_found"]:
            pass  # positions may differ; only compare when scan hit pos 0
        np.testing.assert_allclose(fast[0, 0] + fast[0, 1] > 0, True)
        assert 0.0 <= fast[0, 2] <= 1.0


class TestLoader:
    def test_remote_code_family_config_loads_without_code(self, tmp_path):
        """Qwen v1 / Baichuan configs must load from raw config.json — their
        model_types are unknown to transformers, so AutoConfig would either
        raise or demand trust_remote_code (executing repo code)."""
        import json

        from llm_interpretation_replication_tpu.models.config import from_hf_config
        from llm_interpretation_replication_tpu.runtime.loader import load_hf_config

        snap = tmp_path / "qwen"
        snap.mkdir()
        (snap / "config.json").write_text(json.dumps({
            "model_type": "qwen", "vocab_size": 151936, "hidden_size": 4096,
            "num_hidden_layers": 32, "num_attention_heads": 32,
            "kv_channels": 128, "intermediate_size": 22016,
            "seq_length": 8192, "layer_norm_epsilon": 1e-6,
            "tie_word_embeddings": False,
        }))
        fam, cfg = from_hf_config(load_hf_config(str(snap)))
        assert fam == "qwen" and cfg.intermediate_size == 11008

        # T5 snapshots carry only feed_forward_proj; the derived
        # dense_act_fn / is_gated_act attrs must be synthesized
        (snap / "config.json").write_text(json.dumps({
            "model_type": "t5", "vocab_size": 32128, "d_model": 512,
            "num_layers": 8, "num_decoder_layers": 8, "num_heads": 6,
            "d_kv": 64, "d_ff": 1024, "relative_attention_num_buckets": 32,
            "layer_norm_epsilon": 1e-6, "feed_forward_proj": "gated-gelu",
            "decoder_start_token_id": 0, "tie_word_embeddings": False,
        }))
        fam, cfg = from_hf_config(load_hf_config(str(snap)))
        assert fam == "t5" and cfg.feed_forward_proj == "gated-gelu"

    def test_load_from_saved_snapshot(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.runtime import load_model

        hf_config = GPTNeoXConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64,
        )
        torch.manual_seed(41)
        model = GPTNeoXForCausalLM(hf_config).eval()
        snap = tmp_path / "snap"
        model.save_pretrained(snap, safe_serialization=True)
        fam, cfg, params = load_model(str(snap), dtype=jnp.float32)
        assert fam == "neox"
        ids = np.arange(1, 9, dtype=np.int32)[None, :]
        mask = np.ones_like(ids)
        ours = dmod.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask))
        with torch.no_grad():
            theirs = model(torch.tensor(ids)).logits.float().numpy()
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-3, rtol=1e-3)

    def test_load_int8_quantized(self, tmp_path):
        """quant='int8' loads int8 weights + fp32 scales and stays close to
        the torch reference logits (w8a8 error budget)."""
        torch = pytest.importorskip("torch")
        from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.runtime import load_model

        hf_config = GPTNeoXConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64,
        )
        torch.manual_seed(41)
        model = GPTNeoXForCausalLM(hf_config).eval()
        snap = tmp_path / "snap"
        model.save_pretrained(snap, safe_serialization=True)
        fam, cfg, params = load_model(str(snap), dtype=jnp.float32, quant="int8")
        attn = params["layers"]["attn"]
        assert attn["wq"].dtype == jnp.int8
        assert attn["wq_qscale"].dtype == jnp.float32
        ids = np.arange(1, 9, dtype=np.int32)[None, :]
        mask = np.ones_like(ids)
        ours = np.asarray(dmod.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask)))
        with torch.no_grad():
            theirs = model(torch.tensor(ids)).logits.float().numpy()
        corr = np.corrcoef(ours.ravel(), theirs.ravel())[0, 1]
        assert corr > 0.999, corr

    def test_unquantized_dense_scale_warning(self, tmp_path, monkeypatch):
        """Loading unquantized weights past the single-chip dense-attention
        budget warns (bf16 7B + dense S×T scores cannot share 16 GB HBM —
        PARITY.md bf16 note); int8 or flash loads stay silent."""
        import warnings

        import torch
        from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

        from llm_interpretation_replication_tpu.runtime import loader as loader_mod

        hf_config = GPTNeoXConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64,
        )
        torch.manual_seed(41)
        snap = tmp_path / "snap"
        GPTNeoXForCausalLM(hf_config).save_pretrained(snap, safe_serialization=True)
        monkeypatch.setattr(loader_mod, "DENSE_BF16_WARN_BYTES", 0)
        with pytest.warns(UserWarning, match="dense attention"):
            loader_mod.load_model(str(snap), dtype=jnp.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("error")          # no warning allowed
            loader_mod.load_model(str(snap), dtype=jnp.float32, quant="int8")
            loader_mod.load_model(str(snap), dtype=jnp.float32,
                                  attention_impl="flash")

    def test_load_int8_t5_falls_back_to_bf16(self, tmp_path):
        """A global --quant int8 must not abort mixed sweeps: T5 loads warn
        and fall back instead of raising."""
        torch = pytest.importorskip("torch")
        from transformers import T5Config, T5ForConditionalGeneration

        from llm_interpretation_replication_tpu.runtime import load_model

        hf_config = T5Config(
            vocab_size=128, d_model=32, num_layers=2, num_heads=4,
            d_ff=64, d_kv=8, decoder_start_token_id=0,
        )
        torch.manual_seed(7)
        model = T5ForConditionalGeneration(hf_config).eval()
        snap = tmp_path / "snap"
        model.save_pretrained(snap, safe_serialization=True)
        with pytest.warns(UserWarning, match="int8 quantization unsupported"):
            fam, cfg, params = load_model(str(snap), dtype=jnp.float32, quant="int8")
        assert fam == "t5"
        import jax

        leaves = jax.tree_util.tree_leaves(params)
        assert all(leaf.dtype != jnp.int8 for leaf in leaves)

    def test_load_int8_sharded_on_mesh(self, tmp_path, eight_cpu_devices):
        """int8 params place on a dp×tp mesh: weights sharded over model axis,
        column-scale sharded with them, and the forward still runs."""
        torch = pytest.importorskip("torch")
        from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.parallel import make_mesh
        from llm_interpretation_replication_tpu.runtime import load_model

        hf_config = GPTNeoXConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64,
        )
        torch.manual_seed(41)
        model = GPTNeoXForCausalLM(hf_config).eval()
        snap = tmp_path / "snap"
        model.save_pretrained(snap, safe_serialization=True)
        mesh = make_mesh(data=2, model=4)
        fam, cfg, params = load_model(
            str(snap), dtype=jnp.float32, mesh=mesh, quant="int8"
        )
        attn = params["layers"]["attn"]
        assert attn["wq"].dtype == jnp.int8
        # column-sharded weight: local shard is 1/4 of the output dim
        shard = attn["wq"].addressable_shards[0].data
        assert shard.shape[-1] == attn["wq"].shape[-1] // 4
        ids = np.arange(1, 9, dtype=np.int32)[None, :].repeat(2, axis=0)
        mask = np.ones_like(ids)
        ours = np.asarray(dmod.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask)))
        with torch.no_grad():
            theirs = model(torch.tensor(ids)).logits.float().numpy()
        corr = np.corrcoef(ours.ravel(), theirs.ravel())[0, 1]
        assert corr > 0.999, corr


class TestTrainStep:
    def test_loss_decreases_sharded(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.models.config import DecoderConfig
        from llm_interpretation_replication_tpu.parallel import make_mesh, shard_params

        rng = np.random.default_rng(0)
        cfg = DecoderConfig(
            vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
            intermediate_size=32, position_embedding="rotary",
            norm_type="rmsnorm", qkv_bias=False, out_bias=False,
            mlp_bias=False, mlp_type="gated", activation="silu",
        )
        L, H, ND, F, V = 2, 16, 16, 32, 64

        def init(*shape):
            return (rng.standard_normal(shape) * 0.05).astype(np.float32)

        params = {
            "embed": {"tokens": init(V, H)},
            "layers": {
                "ln1": {"scale": np.ones((L, H), np.float32)},
                "ln2": {"scale": np.ones((L, H), np.float32)},
                "attn": {"wq": init(L, H, ND), "wk": init(L, H, ND),
                         "wv": init(L, H, ND), "wo": init(L, ND, H)},
                "mlp": {"wg": init(L, H, F), "wi": init(L, H, F), "wo": init(L, F, H)},
            },
            "final_ln": {"scale": np.ones(H, np.float32)},
        }
        mesh = make_mesh(data=4, model=2, seq=1)
        params = shard_params(params, mesh)
        opt = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=50)
        state = init_train_state(params, opt)
        step = make_train_step(cfg, opt, mesh=mesh, donate=False)
        ids = rng.integers(1, V, size=(8, 16)).astype(np.int32)
        mask = np.ones_like(ids)
        losses = []
        for _ in range(8):
            state, loss = step(state, jnp.asarray(ids), jnp.asarray(mask))
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses


class TestLoaderAttentionImpl:
    def test_attention_impl_override_and_alibi_degrade(self, tmp_path):
        """load_model(attention_impl=...) overrides the config; explicit
        'flash' on an ALiBi family degrades to dense with a warning instead
        of crashing a mixed-roster sweep."""
        import warnings

        import torch
        from transformers import BloomConfig, BloomForCausalLM
        from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

        from llm_interpretation_replication_tpu.runtime.loader import load_model

        neox_dir = tmp_path / "neox"
        torch.manual_seed(3)
        GPTNeoXForCausalLM(GPTNeoXConfig(
            vocab_size=64, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=32,
        )).save_pretrained(neox_dir, safe_serialization=True)
        _, cfg, _ = load_model(str(neox_dir), attention_impl="auto")
        assert cfg.attention_impl == "auto"
        assert not cfg.use_flash_attention(432)
        assert cfg.use_flash_attention(2048)

        bloom_dir = tmp_path / "bloom"
        BloomForCausalLM(BloomConfig(
            vocab_size=64, hidden_size=16, n_layer=1, n_head=2,
        )).save_pretrained(bloom_dir, safe_serialization=True)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            _, cfg_b, _ = load_model(str(bloom_dir), attention_impl="flash")
        assert cfg_b.attention_impl == "xla"
        assert any("causal+padding" in str(w.message) for w in caught)
        # 'auto' on ALiBi needs no warning: the resolver just stays dense
        _, cfg_b2, _ = load_model(str(bloom_dir), attention_impl="auto")
        assert not cfg_b2.use_flash_attention(4096)
