"""serve/ continuous-batching scheduler: typed request/future surface,
admission-policy edges (deadline, priority, backpressure), coalescing
compatibility (GenerationPlan keys, length buckets), replay parity with
the offline score_prompts path (bit-identical rows, strict-mode clean),
idempotent shutdown (PrefixCachePool / HostPrefetcher double-close), and
the stdlib JSONL CLI driver."""

import io
import json
import time

import numpy as np
import pytest

from test_runtime import _tiny_engine
from test_sweeps import FakeEngine

from llm_interpretation_replication_tpu.runtime.batching import HostPrefetcher
from llm_interpretation_replication_tpu.serve import (
    DeadlineExceeded,
    QueueFull,
    Scheduler,
    SchedulerClosed,
    SchedulerConfig,
    ScoreFuture,
    ScoreRequest,
)
from llm_interpretation_replication_tpu.serve import coalescer
from llm_interpretation_replication_tpu.serve import cli as serve_cli
from llm_interpretation_replication_tpu.serve.replay import replay
from llm_interpretation_replication_tpu.utils import telemetry

pytestmark = pytest.mark.serve

FAST = dict(max_wait_s=0.01)


class RecordingEngine(FakeEngine):
    """FakeEngine that logs every micro-batch launch's composition."""

    def __init__(self):
        super().__init__("rec/model")
        self.call_log = []

    def score_prompts(self, prompts, targets=("Yes", "No"),
                      with_confidence=False, max_new_tokens=None):
        self.call_log.append({
            "prompts": list(prompts),
            "with_confidence": with_confidence,
            "max_new_tokens": max_new_tokens,
        })
        return super().score_prompts(prompts, targets, with_confidence,
                                     max_new_tokens)


# ---------------------------------------------------------------------------
# Request / future surface
# ---------------------------------------------------------------------------

class TestRequestSurface:
    def test_request_validation(self):
        with pytest.raises(ValueError, match="exactly one"):
            ScoreRequest().validate()
        with pytest.raises(ValueError, match="exactly one"):
            ScoreRequest(prompt="p", prefix="a", suffix="b").validate()
        with pytest.raises(ValueError, match="together"):
            ScoreRequest(prefix="a").validate()
        with pytest.raises(ValueError, match="pair"):
            ScoreRequest(prompt="p", targets=("Yes",)).validate()
        ScoreRequest(prompt="p").validate()
        ScoreRequest(prefix="a", suffix="b").validate()

    def test_future_timeout_and_exception(self):
        f = ScoreFuture()
        with pytest.raises(TimeoutError):
            f.result(timeout=0.01)
        f._set_exception(DeadlineExceeded("late"))
        assert f.done()
        with pytest.raises(DeadlineExceeded):
            f.result()
        assert isinstance(f.exception(), DeadlineExceeded)


# ---------------------------------------------------------------------------
# Admission policy edges
# ---------------------------------------------------------------------------

class TestAdmissionPolicy:
    def test_full_queue_typed_backpressure(self):
        sched = Scheduler(RecordingEngine(),
                          SchedulerConfig(queue_capacity=3, **FAST))
        futs = [sched.submit(ScoreRequest(prompt=f"q{i}")) for i in range(3)]
        snap = telemetry.counters()
        with pytest.raises(QueueFull):
            sched.submit(ScoreRequest(prompt="overflow"))
        assert telemetry.counters_since(snap)["serve_rejected_full"] == 1
        # never started: close rejects the queued work with a TYPED error
        sched.close()
        for f in futs:
            assert isinstance(f.exception(timeout=5), SchedulerClosed)

    def test_priority_ordering_under_full_queue(self):
        """Higher priority launches first; FIFO within a level — asserted
        on the queue's own pop order with the queue at capacity."""
        sched = Scheduler(RecordingEngine(),
                          SchedulerConfig(queue_capacity=6, **FAST))
        prios = [0, 5, 1, 5, 0, 3]
        for i, p in enumerate(prios):
            sched.submit(ScoreRequest(prompt=f"q{i}", priority=p))
        group, expired = sched.queue.pop_group(max_batch=6, max_wait_s=0)
        assert expired == []
        assert [t.request.priority for t in group] == [5, 5, 3, 1, 0, 0]
        # FIFO within a priority level: seq (admission order) ascending
        assert [t.seq for t in group] == [2, 4, 6, 3, 1, 5]
        sched.close()

    def test_deadline_expired_rejected_typed_not_dropped(self):
        eng = RecordingEngine()
        snap = telemetry.counters()
        with Scheduler(eng, SchedulerConfig(**FAST)) as sched:
            late = sched.submit(ScoreRequest(prompt="too-late",
                                             timeout_s=0.0))
            ok = sched.submit(ScoreRequest(prompt="on-time"))
            assert ok.result(timeout=30)["success"]
            err = late.exception(timeout=30)
        assert isinstance(err, DeadlineExceeded)   # typed, never silent
        assert telemetry.counters_since(snap)["serve_rejected_deadline"] == 1
        launched = [p for c in eng.call_log for p in c["prompts"]]
        assert "too-late" not in launched

    def test_submit_after_close_raises_and_close_is_idempotent(self):
        sched = Scheduler(RecordingEngine(), SchedulerConfig(**FAST))
        sched.start()
        sched.close()
        sched.close()   # safe double-close
        with pytest.raises(SchedulerClosed):
            sched.submit(ScoreRequest(prompt="late"))


# ---------------------------------------------------------------------------
# Coalescing compatibility
# ---------------------------------------------------------------------------

class TestCoalescing:
    def test_never_mixes_incompatible_plan_or_leg_keys(self):
        """One micro-batch = one (GenerationPlan key, with_confidence)
        combination: requests differing in max_new_tokens or
        with_confidence launch as separate engine calls."""
        eng = RecordingEngine()
        sched = Scheduler(eng, SchedulerConfig(max_batch=16, **FAST))
        futs = []
        for i in range(9):
            futs.append(sched.submit(ScoreRequest(
                prompt=f"q{i}",
                with_confidence=(i % 3 == 2),
                max_new_tokens=10 if i % 3 == 1 else None)))
        with sched:
            rows = [f.result(timeout=30) for f in futs]
        assert all(r["success"] for r in rows)
        assert len(eng.call_log) == 3
        for call in eng.call_log:
            assert len(call["prompts"]) == 3   # each group fully coalesced
        combos = {(c["with_confidence"], c["max_new_tokens"])
                  for c in eng.call_log}
        assert combos == {(False, None), (False, 10), (True, None)}

    def test_compat_key_tracks_engine_plan_cache_and_buckets(self):
        """The key is the engine's own GenerationPlan cache key plus the
        length bucket: distinct caps → distinct keys (the binary/
        confidence legs never share a micro-batch), and prompts landing
        in different length buckets never share a shape."""
        eng, _, _ = _tiny_engine(batch_size=4)
        short = ScoreRequest(prompt="short one")
        capped = ScoreRequest(prompt="short one", max_new_tokens=10)
        long = ScoreRequest(prompt="much longer prompt " * 12)
        conf = ScoreRequest(prompt="short one", with_confidence=True)
        enc = {id(r): coalescer.encode_request(eng, r)
               for r in (short, capped, long, conf)}
        key = {id(r): coalescer.compat_key(eng, r, enc[id(r)])
               for r in (short, capped, long, conf)}
        assert key[id(short)] != key[id(capped)]     # plan cache key differs
        assert key[id(short)] != key[id(long)]       # bucket differs
        assert key[id(short)] != key[id(conf)]       # leg differs
        # identical knobs + same bucket coalesce
        twin = ScoreRequest(prompt="short two")
        assert coalescer.compat_key(
            eng, twin, coalescer.encode_request(eng, twin)) == key[id(short)]

    def test_prefixed_requests_ride_score_prefixed(self):
        eng, _, _ = _tiny_engine(batch_size=4)
        telemetry.clear_counters()
        with Scheduler(eng, SchedulerConfig(max_batch=4, **FAST)) as sched:
            futs = [sched.submit(ScoreRequest(
                prefix=f"Is item {i} a thing?",
                suffix=" Answer Yes or No.")) for i in range(5)]
            rows = [f.result(timeout=300) for f in futs]
        assert all(r["success"] for r in rows)
        assert eng.last_prefix_pool is not None
        assert eng.last_prefix_pool.consistent
        assert telemetry.counter("prefix_miss") > 0


# ---------------------------------------------------------------------------
# Replay parity — the acceptance contract
# ---------------------------------------------------------------------------

class TestReplayParity:
    def test_rows_bit_identical_to_offline_path(self):
        """Routing a sweep workload through the scheduler yields
        row-identical results to the offline score_prompts path, across
        multiple coalesced micro-batches."""
        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is thing {i} a stuff?" for i in range(10)]
        report = replay(eng, prompts)     # require_parity raises on skew
        assert report["rows"] == 10
        assert report["mismatched_rows"] == 0
        assert report["serve_batches"] >= 2   # really went through coalescing
        assert report["serve_batch_rows"] == 10
        offline = eng.score_prompts(prompts)
        assert report["serve_rows"] == offline   # bit-identical, not approx

    def test_per_row_targets_parity(self):
        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is item {i} a thing?" for i in range(6)]
        targets = [("Yes", "No") if i % 2 else ("No", "Yes")
                   for i in range(6)]
        report = replay(eng, prompts, targets=targets)
        assert report["mismatched_rows"] == 0

    def test_strict_mode_serve_launches_stay_clean(self):
        """Acceptance: the transfer guard stays armed around
        scheduler-driven launches — a replay under strict mode completes
        with blocked_transfers == 0."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is thing {i} a stuff?" for i in range(6)]
        eng.score_prompts(prompts)   # warm outside the strict window
        strict.activate(sentry=False)
        try:
            report = replay(eng, prompts)
        finally:
            strict.deactivate()
        assert report["mismatched_rows"] == 0
        assert report["blocked_transfers"] == 0

    def test_parity_failure_is_loud(self):
        """A skewed row fails the replay with a named mismatch, never a
        silent pass."""
        from llm_interpretation_replication_tpu.serve import ServeError

        eng = RecordingEngine()
        prompts = [f"q{i}" for i in range(4)]
        offline = eng.score_prompts(prompts)
        offline[2] = dict(offline[2], yes_prob=0.123456)   # poison one row
        with pytest.raises(ServeError, match="row 2"):
            replay(eng, prompts, offline_rows=offline, offline_s=1.0)


# ---------------------------------------------------------------------------
# Shutdown path: idempotent closes (satellite)
# ---------------------------------------------------------------------------

class TestIdempotentCloses:
    def test_prefix_cache_pool_double_close(self):
        from llm_interpretation_replication_tpu.runtime.engine import (
            PrefixCachePool,
        )

        pool = PrefixCachePool()
        pool.acquire(128, 4)
        snap = telemetry.counters()
        pool.close()
        assert pool.leaked == 1 and pool.live_bytes == 0
        first = telemetry.counters_since(snap).get("prefix_pool_leaked", 0)
        assert first == 1
        pool.close()   # double-close: no re-count, no state churn
        pool.close()
        assert pool.leaked == 1
        assert telemetry.counters_since(snap).get(
            "prefix_pool_leaked", 0) == 1

    def test_host_prefetcher_double_close(self):
        hp = HostPrefetcher(range(100), lambda i: i)
        it = iter(hp)
        assert next(it) == 0
        hp.close()
        assert hp.closed
        hp.close()   # idempotent: drain loop + __exit__ both close
        hp.close()
        assert not hp._thread.is_alive()

    def test_scheduler_close_sweeps_engine_pool(self):
        """The scheduler's shutdown closes the engine's last prefix pool
        AGAIN after the engine's own per-call close — the double-close
        the idempotence contract exists for."""
        eng, _, _ = _tiny_engine(batch_size=4)
        sched = Scheduler(eng, SchedulerConfig(max_batch=4, **FAST))
        with sched:
            f = sched.submit(ScoreRequest(prefix="Is soup a thing?",
                                          suffix=" Answer Yes or No."))
            assert f.result(timeout=300)["success"]
        assert eng.last_prefix_pool.closed   # swept twice, still consistent
        assert eng.last_prefix_pool.consistent


# ---------------------------------------------------------------------------
# JSONL CLI driver (stdlib-only)
# ---------------------------------------------------------------------------

class TestJsonlDriver:
    def test_roundtrip_order_and_typed_errors(self):
        eng = RecordingEngine()
        lines = "\n".join([
            json.dumps({"prompt": "Is a tweet a publication?"}),
            json.dumps({"prompt": "Is soup a beverage?",
                        "targets": ["Yes", "No"], "priority": 3}),
            json.dumps({"bogus_field": 1}),
            json.dumps({"prompt": "third", "timeout_s": 0.0}),
        ]) + "\n"
        out = io.StringIO()
        summary = serve_cli.run_jsonl_driver(
            eng, io.StringIO(lines), out,
            SchedulerConfig(**FAST))
        assert summary == {"requests": 4, "errors": 2}
        rows = [json.loads(l) for l in out.getvalue().splitlines()]
        assert [r["id"] for r in rows] == [0, 1, 2, 3]   # input order
        assert rows[0]["success"] and rows[1]["success"]
        assert rows[2]["error_type"] == "ValueError"
        assert rows[3]["error_type"] == "DeadlineExceeded"

    def test_replay_cli_builds_sweep_workload(self, tmp_path):
        scenarios = [
            {"original_main": f"Is thing {s} a stuff?",
             "response_format": "Answer only 'Yes' or 'No'.",
             "target_tokens": ["Yes", "No"] if s == 0 else ["No", "Yes"],
             "rephrasings": [f"Is thing {s} variant {i} a stuff?"
                             for i in range(3)]}
            for s in range(2)
        ]
        path = tmp_path / "perturbations.json"
        path.write_text(json.dumps(scenarios))
        report = serve_cli.run_replay(FakeEngine("fake/model-7b"),
                                      str(path), max_rephrasings=2,
                                      config=SchedulerConfig(**FAST))
        assert report["rows"] == 4
        assert report["mismatched_rows"] == 0
        assert "serve_rows" not in report   # CLI report stays JSON-light


# ---------------------------------------------------------------------------
# Telemetry distributions
# ---------------------------------------------------------------------------

class TestServeTelemetry:
    def test_latency_and_depth_samples_recorded(self):
        telemetry.clear_samples()
        eng = RecordingEngine()
        with Scheduler(eng, SchedulerConfig(**FAST)) as sched:
            futs = [sched.submit(ScoreRequest(prompt=f"q{i}"))
                    for i in range(5)]
            [f.result(timeout=30) for f in futs]
        assert telemetry.sample_count("serve_queue_depth") == 5
        assert telemetry.sample_count("serve_latency_ms") == 5
        pcts = telemetry.sample_percentiles("serve_latency_ms")
        assert set(pcts) == {"p50", "p90", "p99"}
        assert pcts["p50"] <= pcts["p99"]

    def test_sample_ring_is_bounded(self):
        telemetry.clear_samples()
        for i in range(5000):
            telemetry.record_sample("serve_test_ring", float(i))
        assert telemetry.sample_count("serve_test_ring") == 4096
        assert telemetry.sample_total("serve_test_ring") == 5000
        # the window keeps the most recent observations
        assert telemetry.sample_percentiles("serve_test_ring")["p99"] > 4900

    def test_percentiles_scope_to_a_phase_via_last(self):
        """Regression: a later phase's percentiles must not mix in an
        earlier phase's samples — snapshot sample_total, diff, and pass
        the delta as ``last``."""
        telemetry.clear_samples()
        for _ in range(10):
            telemetry.record_sample("serve_phase_ring", 1.0)
        before = telemetry.sample_total("serve_phase_ring")
        for _ in range(5):
            telemetry.record_sample("serve_phase_ring", 1000.0)
        last = telemetry.sample_total("serve_phase_ring") - before
        scoped = telemetry.sample_percentiles("serve_phase_ring", last=last)
        assert scoped["p50"] == 1000.0       # only the new phase
        mixed = telemetry.sample_percentiles("serve_phase_ring")
        assert mixed["p50"] == 1.0           # whole window still available
        assert telemetry.sample_percentiles("serve_phase_ring", last=0) == {}


# ---------------------------------------------------------------------------
# Failure-path regressions (review findings)
# ---------------------------------------------------------------------------

class TestFailurePaths:
    def test_replay_closes_scheduler_when_a_future_fails(self):
        """Regression: a failed micro-batch must not leak the scheduler
        loop thread past replay() — close() runs in a finally."""
        import threading

        class BoomEngine:
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                raise ValueError("boom")

        offline = [{"yes_prob": 1.0, "success": True}] * 3
        with pytest.raises(ValueError, match="boom"):
            replay(BoomEngine(), ["a", "b", "c"], offline_rows=offline,
                   offline_s=1.0)
        time.sleep(0.2)
        assert not any(t.name == "serve-scheduler" and t.is_alive()
                       for t in threading.enumerate())

    def test_jsonl_driver_answers_backpressure_instead_of_crashing(self):
        """Regression: QueueFull during the driver's submit loop becomes
        that line's typed error answer; every other line is still served
        and answered."""
        import threading

        gate = threading.Event()

        class SlowEngine(RecordingEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                gate.wait(timeout=30)
                return super().score_prompts(prompts, targets,
                                             with_confidence,
                                             max_new_tokens)

        lines = "".join(json.dumps({"prompt": f"q{i}"}) + "\n"
                        for i in range(6))
        out = io.StringIO()
        threading.Timer(0.5, gate.set).start()
        summary = serve_cli.run_jsonl_driver(
            SlowEngine(), io.StringIO(lines), out,
            SchedulerConfig(queue_capacity=2, max_batch=1, **FAST))
        rows = [json.loads(l) for l in out.getvalue().splitlines()]
        assert summary["requests"] == 6          # every line answered
        assert [r["id"] for r in rows] == list(range(6))
        rejected = [r for r in rows if r.get("error_type") == "QueueFull"]
        served = [r for r in rows if r.get("success")]
        assert rejected and served               # backpressure hit, no crash
        assert len(rejected) + len(served) == 6
