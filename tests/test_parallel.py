"""Parallel-layer tests on the virtual 8-device CPU mesh."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from llm_interpretation_replication_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    make_mesh,
    mesh_shape_for,
    param_specs,
    pipeline_apply,
    pipeline_decoder_forward,
    ring_attention_sharded,
    shard_params,
    split_stage_params,
)


def _dense_attention(q, k, v, mask, causal):
    d = q.shape[-1]
    scores = np.einsum("bsnd,btnd->bnst", q, k) / np.sqrt(d)
    bias = np.where(mask[:, None, None, :], 0.0, -1e9)
    if causal:
        s = q.shape[1]
        causal_m = np.tril(np.ones((s, s), bool))
        bias = bias + np.where(causal_m[None, None], 0.0, -1e9)
    scores = scores + bias
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    return np.einsum("bnst,btnd->bsnd", probs, v)


class TestMesh:
    def test_make_mesh_shapes(self, eight_cpu_devices):
        mesh = make_mesh(model=2, seq=2)
        assert mesh.shape == {
            DATA_AXIS: 2, PIPE_AXIS: 1, MODEL_AXIS: 2, SEQ_AXIS: 2
        }
        mesh = make_mesh()
        assert mesh.shape[DATA_AXIS] == 8
        mesh = make_mesh(pipe=4, model=2)
        assert mesh.shape[PIPE_AXIS] == 4 and mesh.shape[DATA_AXIS] == 1

    def test_bad_shape_raises(self, eight_cpu_devices):
        with pytest.raises(ValueError):
            make_mesh(data=3, model=2, seq=2)

    def test_mesh_shape_for(self):
        assert mesh_shape_for(8, want_model=4) == (2, 4, 1)
        assert mesh_shape_for(8, want_model=16) == (1, 8, 1)
        assert mesh_shape_for(6, want_model=4) == (3, 2, 1)


class TestShardParams:
    def test_tp_sharding_placement(self, eight_cpu_devices):
        mesh = make_mesh(model=4, seq=1)  # data=2, model=4
        L, H, ND, F, V = 2, 8, 16, 32, 64
        params = {
            "embed": {"tokens": np.zeros((V, H), np.float32)},
            "layers": {
                "ln1": {"scale": np.ones((L, H), np.float32), "bias": np.zeros((L, H), np.float32)},
                "attn": {
                    "wq": np.zeros((L, H, ND), np.float32),
                    "wk": np.zeros((L, H, ND), np.float32),
                    "wv": np.zeros((L, H, ND), np.float32),
                    "wo": np.zeros((L, ND, H), np.float32),
                },
                "mlp": {
                    "wi": np.zeros((L, H, F), np.float32),
                    "wo": np.zeros((L, F, H), np.float32),
                },
            },
            "final_ln": {"scale": np.ones(H, np.float32)},
            "lm_head": np.zeros((H, V), np.float32),
        }
        sharded = shard_params(params, mesh)
        wq = sharded["layers"]["attn"]["wq"]
        # column-sharded over model axis: local shard holds ND/4 columns
        assert wq.sharding.spec == P(None, None, MODEL_AXIS)
        assert wq.addressable_shards[0].data.shape == (L, H, ND // 4)
        wo = sharded["layers"]["attn"]["wo"]
        assert wo.addressable_shards[0].data.shape == (L, ND // 4, H)
        ln = sharded["layers"]["ln1"]["scale"]
        assert ln.addressable_shards[0].data.shape == (L, H)  # replicated

    def test_specs_cover_all_leaves(self):
        params = {
            "embed": {"tokens": 0, "pos": 0},
            "layers": {"attn": {"wq": 0, "bq": 0}, "mlp": {"wg": 0}},
            "final_ln": {"scale": 0},
            "unknown_extra": {"leaf": 0},
        }
        specs = param_specs(params)
        assert specs["layers"]["attn"]["wq"] == P(None, None, MODEL_AXIS)
        assert specs["unknown_extra"]["leaf"] == P()  # fallback replicated


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, eight_cpu_devices, causal):
        mesh = make_mesh(data=2, model=1, seq=4)
        rng = np.random.default_rng(0)
        B, S, N, D = 2, 16, 4, 8
        q = rng.standard_normal((B, S, N, D)).astype(np.float32)
        k = rng.standard_normal((B, S, N, D)).astype(np.float32)
        v = rng.standard_normal((B, S, N, D)).astype(np.float32)
        mask = np.ones((B, S), bool)
        mask[1, 12:] = False
        with jax.default_matmul_precision("highest"):
            out = ring_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=causal,
            )
        expected = _dense_attention(q, k, v, mask, causal)
        real = mask[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(out) * real, expected * real, atol=2e-5, rtol=1e-4
        )

    def test_model_axis_sharded_heads(self, eight_cpu_devices):
        mesh = make_mesh(data=2, model=2, seq=2)
        rng = np.random.default_rng(1)
        B, S, N, D = 2, 8, 4, 4
        q, k, v = (rng.standard_normal((B, S, N, D)).astype(np.float32) for _ in range(3))
        mask = np.ones((B, S), bool)
        with jax.default_matmul_precision("highest"):
            out = ring_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=True,
            )
        expected = _dense_attention(q, k, v, mask, True)
        np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5, rtol=1e-4)


class TestUlyssesAttention:
    """All-to-all sequence parallelism (parallel/ulysses.py) — the second
    long-context strategy next to the ring."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, eight_cpu_devices, causal):
        from llm_interpretation_replication_tpu.parallel import (
            ulysses_attention_sharded,
        )

        mesh = make_mesh(data=2, model=1, seq=4)
        rng = np.random.default_rng(3)
        B, S, N, D = 2, 16, 4, 8
        q = rng.standard_normal((B, S, N, D)).astype(np.float32)
        k = rng.standard_normal((B, S, N, D)).astype(np.float32)
        v = rng.standard_normal((B, S, N, D)).astype(np.float32)
        mask = np.ones((B, S), bool)
        mask[1, 11:] = False
        with jax.default_matmul_precision("highest"):
            out = ulysses_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=causal,
            )
        expected = _dense_attention(q, k, v, mask, causal)
        real = mask[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(out) * real, expected * real, atol=2e-5, rtol=1e-4
        )

    def test_composes_with_model_axis(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.parallel import (
            ulysses_attention_sharded,
        )

        mesh = make_mesh(data=2, model=2, seq=2)
        rng = np.random.default_rng(4)
        B, S, N, D = 2, 8, 4, 4
        q, k, v = (rng.standard_normal((B, S, N, D)).astype(np.float32) for _ in range(3))
        mask = np.ones((B, S), bool)
        with jax.default_matmul_precision("highest"):
            out = ulysses_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=True,
            )
        expected = _dense_attention(q, k, v, mask, True)
        np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5, rtol=1e-4)

    def test_agrees_with_ring(self, eight_cpu_devices):
        """Both SP strategies must produce identical attention outputs."""
        from llm_interpretation_replication_tpu.parallel import (
            ulysses_attention_sharded,
        )

        mesh = make_mesh(data=1, model=1, seq=8)
        rng = np.random.default_rng(5)
        B, S, N, D = 1, 32, 8, 4
        q, k, v = (rng.standard_normal((B, S, N, D)).astype(np.float32) for _ in range(3))
        mask = np.ones((B, S), bool)
        mask[0, 29:] = False
        with jax.default_matmul_precision("highest"):
            ring = ring_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=True,
            )
            uly = ulysses_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=True,
            )
        real = mask[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(uly) * real, np.asarray(ring) * real, atol=2e-5, rtol=1e-4
        )


class TestPipeline:
    """GPipe-style pipeline over the ``pipe`` mesh axis (parallel/pipeline.py)."""

    def test_split_stage_params(self):
        tree = {"w": jnp.zeros((8, 3, 5)), "b": jnp.zeros((8, 5))}
        staged = split_stage_params(tree, 4)
        assert staged["w"].shape == (4, 2, 3, 5)
        assert staged["b"].shape == (4, 2, 5)
        with pytest.raises(ValueError):
            split_stage_params({"w": jnp.zeros((6, 2))}, 4)

    def test_apply_matches_sequential(self, eight_cpu_devices):
        """4-stage pipeline of affine stages == running the stages in order."""
        mesh = make_mesh(data=2, pipe=4)
        rng = np.random.default_rng(0)
        scales = jnp.asarray(rng.standard_normal((4, 1)) + 2.0, jnp.float32)
        xs = jnp.asarray(rng.standard_normal((3, 4, 6)), jnp.float32)  # [M, mb, F]
        out = pipeline_apply(lambda p, x: x * p[0] + 1.0, scales, xs, mesh)
        expect = np.asarray(xs)
        for s in np.asarray(scales)[:, 0]:
            expect = expect * s + 1.0
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)

    def test_single_microbatch(self, eight_cpu_devices):
        mesh = make_mesh(data=1, pipe=8)
        scales = jnp.ones((8, 1), jnp.float32) * 1.5
        xs = jnp.ones((1, 2, 3), jnp.float32)
        out = pipeline_apply(lambda p, x: x * p[0], scales, xs, mesh)
        np.testing.assert_allclose(np.asarray(out), 1.5 ** 8, rtol=1e-5)

    def test_decoder_forward_parity(self, eight_cpu_devices):
        """Pipelined decoder trunk == plain decoder.forward, dp×pp×tp mesh,
        with ragged (right-padded) rows."""
        from helpers import random_decoder_params

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.models.config import DecoderConfig

        mesh = make_mesh(data=1, pipe=4, model=2)
        cfg = DecoderConfig(
            vocab_size=96, hidden_size=16, num_layers=4, num_heads=4,
            intermediate_size=32, position_embedding="rotary",
            tie_word_embeddings=True, max_position_embeddings=32,
        )
        params = random_decoder_params(cfg, seed=1)
        rng = np.random.default_rng(2)
        ids = jnp.asarray(rng.integers(1, 96, (4, 12)), jnp.int32)
        mask = np.ones((4, 12), np.int32)
        mask[1, 9:] = 0
        mask[3, 5:] = 0
        mask = jnp.asarray(mask)
        ref = np.asarray(dmod.forward(params, cfg, ids, mask))
        got = np.asarray(
            pipeline_decoder_forward(params, cfg, ids, mask, mesh, n_microbatches=2)
        )
        np.testing.assert_allclose(got, ref, atol=2e-4, rtol=1e-4)

    def test_decoder_flash_config_parity(self, eight_cpu_devices):
        """attention_impl='flash' routes through the kernel dispatcher inside
        pipeline stages (dense equivalent on CPU) with identical outputs."""
        import dataclasses

        from helpers import random_decoder_params

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.models.config import DecoderConfig

        mesh = make_mesh(data=1, pipe=4, model=2)
        cfg = DecoderConfig(
            vocab_size=96, hidden_size=16, num_layers=4, num_heads=4,
            intermediate_size=32, position_embedding="rotary",
            tie_word_embeddings=True, max_position_embeddings=32,
        )
        params = random_decoder_params(cfg, seed=1)
        rng = np.random.default_rng(4)
        ids = jnp.asarray(rng.integers(1, 96, (4, 12)), jnp.int32)
        mask = np.ones((4, 12), np.int32)
        mask[2, 7:] = 0
        mask = jnp.asarray(mask)
        ref = np.asarray(dmod.forward(params, cfg, ids, mask))
        flash_cfg = dataclasses.replace(cfg, attention_impl="flash")
        got = np.asarray(
            pipeline_decoder_forward(params, flash_cfg, ids, mask, mesh, n_microbatches=2)
        )
        valid = np.asarray(mask, bool)
        np.testing.assert_allclose(got[valid], ref[valid], atol=2e-4, rtol=1e-4)

    def test_apply_inside_outer_jit(self, eight_cpu_devices):
        """pipeline_apply composes under a caller's jit (no nested-jit need)."""
        mesh = make_mesh(data=2, pipe=4)
        scales = jnp.asarray([[2.0], [2.0], [2.0], [2.0]], jnp.float32)
        xs = jnp.ones((2, 2, 3), jnp.float32)

        @jax.jit
        def step(p, x):
            return pipeline_apply(lambda sp, y: y * sp[0], p, x, mesh).sum()

        np.testing.assert_allclose(float(step(scales, xs)), 16.0 * 12, rtol=1e-6)

    def test_grad_through_pipeline(self, eight_cpu_devices):
        """Autodiff crosses the scan+ppermute ring: d(loss)/d(stage params)."""
        mesh = make_mesh(data=2, pipe=4)
        scales = jnp.asarray([[1.0], [2.0], [3.0], [4.0]], jnp.float32)
        xs = jnp.asarray(
            np.random.default_rng(3).standard_normal((2, 2, 5)), jnp.float32
        )

        def loss(p):
            return pipeline_apply(lambda sp, x: x * sp[0], p, xs, mesh).sum()

        g = np.asarray(jax.grad(loss)(scales))
        total = float(np.asarray(xs).sum())
        expect = np.array([[24.0 / s] for s in [1.0, 2.0, 3.0, 4.0]]) * total
        np.testing.assert_allclose(g, expect, rtol=1e-5)

    def test_indivisible_microbatches_raise(self, eight_cpu_devices):
        from helpers import random_decoder_params

        from llm_interpretation_replication_tpu.models.config import DecoderConfig

        mesh = make_mesh(data=1, pipe=4, model=2)
        cfg = DecoderConfig(
            vocab_size=96, hidden_size=16, num_layers=4, num_heads=4,
            intermediate_size=32, position_embedding="rotary",
            tie_word_embeddings=True, max_position_embeddings=32,
        )
        params = random_decoder_params(cfg, seed=0)
        ids = jnp.ones((3, 8), jnp.int32)
        mask = jnp.ones((3, 8), jnp.int32)
        with pytest.raises(ValueError, match="microbatch"):
            pipeline_decoder_forward(params, cfg, ids, mask, mesh, n_microbatches=2)


class TestT5Sharding:
    def test_t5_tp_sharded_forward_matches_unsharded(self, eight_cpu_devices):
        """T5 enc-dec forward with kind='t5' TP sharding on the 8-device mesh
        must match the unsharded forward — the loader shards T0/tk-instruct
        checkpoints this way (runtime/loader.py:180) but nothing else ran the
        sharded enc-dec path end-to-end."""
        pytest.importorskip("torch")
        import torch
        from transformers import T5Config, T5ForConditionalGeneration

        from llm_interpretation_replication_tpu.models import config as mcfg
        from llm_interpretation_replication_tpu.models import convert as mconvert
        from llm_interpretation_replication_tpu.models import t5 as t5m

        hf_config = T5Config(
            vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
            num_decoder_layers=2, num_heads=4,
            relative_attention_num_buckets=8,
            relative_attention_max_distance=32,
            feed_forward_proj="gated-gelu", tie_word_embeddings=False,
            decoder_start_token_id=0, eos_token_id=1, pad_token_id=0,
        )
        torch.manual_seed(21)
        model = T5ForConditionalGeneration(hf_config).eval()
        fam, cfg = mcfg.from_hf_config(hf_config)
        params = mconvert.convert(
            "t5", mconvert.getter_from_torch_state_dict(model.state_dict()),
            cfg, dtype=jnp.float32,
        )
        rng = np.random.default_rng(5)
        enc_ids = jnp.asarray(rng.integers(2, 96, (4, 10)), jnp.int32)
        enc_mask = jnp.ones((4, 10), jnp.int32)
        dec_ids = jnp.zeros((4, 1), jnp.int32)

        base = np.asarray(t5m.forward(params, cfg, enc_ids, enc_mask, dec_ids))

        mesh = make_mesh(data=2, model=4)
        sharded_params = shard_params(params, mesh, kind="t5")
        from jax.sharding import NamedSharding, PartitionSpec as P

        enc_ids_s = jax.device_put(enc_ids, NamedSharding(mesh, P("data")))
        enc_mask_s = jax.device_put(enc_mask, NamedSharding(mesh, P("data")))
        dec_ids_s = jax.device_put(dec_ids, NamedSharding(mesh, P("data")))
        sharded = np.asarray(
            t5m.forward(sharded_params, cfg, enc_ids_s, enc_mask_s, dec_ids_s)
        )
        np.testing.assert_allclose(sharded, base, atol=2e-5, rtol=1e-4)

        # first-decoder-token scoring (the T0/tk-instruct leg) agrees too
        tokens, scores = t5m.greedy_decode(
            sharded_params, cfg, enc_ids_s, enc_mask_s, num_steps=3
        )
        tokens_b, scores_b = t5m.greedy_decode(
            params, cfg, enc_ids, enc_mask, num_steps=3
        )
        np.testing.assert_array_equal(np.asarray(tokens), np.asarray(tokens_b))
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(scores_b), atol=2e-4, rtol=1e-3
        )


WORKER_SCRIPT = '''
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]; repo = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")   # axon plugin force-sets axon,cpu
from llm_interpretation_replication_tpu.parallel.mesh import initialize_distributed
assert initialize_distributed(f"127.0.0.1:{port}", 2, pid)
import numpy as np
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 2
assert jax.device_count() == 4
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("boot")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()).reshape(4), ("data",))
local = jnp.arange(2, dtype=jnp.float32) + 10 * pid
garr = multihost_utils.host_local_array_to_global_array(local, mesh, P("data"))
out = jax.jit(jnp.sum, in_shardings=NamedSharding(mesh, P("data")),
              out_shardings=NamedSharding(mesh, P()))(garr)
val = float(np.asarray(out.addressable_data(0)))
assert val == 22.0, val                      # 0+1 + 10+11 across processes
print(f"WORKER{pid} OK {val}")
'''


class TestDistributedBootstrap:
    def test_two_process_initialize_and_collective(self, tmp_path):
        """initialize_distributed beyond the no-op: two REAL processes join a
        coordinator on localhost (the jax.distributed path a TPU-pod slice
        takes via JAX_COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID), see a
        4-device global mesh from 2 local devices each, and a cross-process
        psum over the data axis returns the global sum on both hosts."""
        import socket
        import subprocess
        import sys

        script = tmp_path / "dist_worker.py"
        script.write_text(WORKER_SCRIPT)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def run_once():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            procs = [
                subprocess.Popen(
                    [sys.executable, str(script), str(i), str(port), repo],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                )
                for i in range(2)
            ]
            outs = []
            try:
                for p in procs:
                    out, _ = p.communicate(timeout=240)
                    outs.append((p.returncode, out))
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
            return outs

        outs = run_once()
        if any(rc != 0 and "Failed to connect" in out for rc, out in outs):
            # ephemeral-port TOCTOU: something else grabbed the port between
            # the probe bind and the coordinator bind — retry on a fresh one
            outs = run_once()
        for i, (rc, out) in enumerate(outs):
            assert rc == 0, f"worker {i} failed:\n{out[-2000:]}"
            assert f"WORKER{i} OK 22.0" in out


class TestCarveSlices:
    """parallel.mesh.carve_slices — the per-replica pod partition of the
    disaggregated fleet (ISSUE 20)."""

    def test_equal_slices_partition_contiguously(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.parallel.mesh import (
            carve_slices,
        )

        slices = carve_slices(2)
        assert [len(s) for s in slices] == [4, 4]
        flat = [d for s in slices for d in s]
        assert flat == list(eight_cpu_devices)     # contiguous, disjoint

    def test_heterogeneous_counts(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.parallel.mesh import (
            carve_slices,
        )

        slices = carve_slices(counts=(4, 2, 2))
        assert [len(s) for s in slices] == [4, 2, 2]
        assert [d for s in slices for d in s] == list(eight_cpu_devices)
        with pytest.raises(ValueError):
            carve_slices(counts=(4, 2))            # doesn't sum to 8
        with pytest.raises(ValueError):
            carve_slices(counts=(4, 0, 4))         # empty slice

    def test_indivisible_needs_counts(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.parallel.mesh import (
            carve_slices,
        )

        with pytest.raises(ValueError):
            carve_slices(3)
        with pytest.raises(ValueError):
            carve_slices(0)

    def test_fewer_devices_than_slices_degenerates_to_shared(
            self, eight_cpu_devices):
        """The CPU-harness shape: more replicas than devices — every
        slice is the FULL device list (shared placement; replica health
        reports it so nobody mistakes it for real disaggregation)."""
        from llm_interpretation_replication_tpu.parallel.mesh import (
            carve_slices,
        )

        slices = carve_slices(16)
        assert len(slices) == 16
        assert all(s == tuple(eight_cpu_devices) for s in slices)
        one = eight_cpu_devices[:1]
        assert carve_slices(2, devices=one) == (tuple(one), tuple(one))

    def test_explicit_device_subset(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.parallel.mesh import (
            carve_slices,
        )

        slices = carve_slices(2, devices=eight_cpu_devices[:4])
        assert [len(s) for s in slices] == [2, 2]
        assert [d for s in slices for d in s] == list(
            eight_cpu_devices[:4])
