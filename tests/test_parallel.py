"""Parallel-layer tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from llm_interpretation_replication_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    make_mesh,
    mesh_shape_for,
    param_specs,
    ring_attention_sharded,
    shard_params,
)


def _dense_attention(q, k, v, mask, causal):
    d = q.shape[-1]
    scores = np.einsum("bsnd,btnd->bnst", q, k) / np.sqrt(d)
    bias = np.where(mask[:, None, None, :], 0.0, -1e9)
    if causal:
        s = q.shape[1]
        causal_m = np.tril(np.ones((s, s), bool))
        bias = bias + np.where(causal_m[None, None], 0.0, -1e9)
    scores = scores + bias
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    return np.einsum("bnst,btnd->bsnd", probs, v)


class TestMesh:
    def test_make_mesh_shapes(self, eight_cpu_devices):
        mesh = make_mesh(model=2, seq=2)
        assert mesh.shape == {DATA_AXIS: 2, MODEL_AXIS: 2, SEQ_AXIS: 2}
        mesh = make_mesh()
        assert mesh.shape[DATA_AXIS] == 8

    def test_bad_shape_raises(self, eight_cpu_devices):
        with pytest.raises(ValueError):
            make_mesh(data=3, model=2, seq=2)

    def test_mesh_shape_for(self):
        assert mesh_shape_for(8, want_model=4) == (2, 4, 1)
        assert mesh_shape_for(8, want_model=16) == (1, 8, 1)
        assert mesh_shape_for(6, want_model=4) == (3, 2, 1)


class TestShardParams:
    def test_tp_sharding_placement(self, eight_cpu_devices):
        mesh = make_mesh(model=4, seq=1)  # data=2, model=4
        L, H, ND, F, V = 2, 8, 16, 32, 64
        params = {
            "embed": {"tokens": np.zeros((V, H), np.float32)},
            "layers": {
                "ln1": {"scale": np.ones((L, H), np.float32), "bias": np.zeros((L, H), np.float32)},
                "attn": {
                    "wq": np.zeros((L, H, ND), np.float32),
                    "wk": np.zeros((L, H, ND), np.float32),
                    "wv": np.zeros((L, H, ND), np.float32),
                    "wo": np.zeros((L, ND, H), np.float32),
                },
                "mlp": {
                    "wi": np.zeros((L, H, F), np.float32),
                    "wo": np.zeros((L, F, H), np.float32),
                },
            },
            "final_ln": {"scale": np.ones(H, np.float32)},
            "lm_head": np.zeros((H, V), np.float32),
        }
        sharded = shard_params(params, mesh)
        wq = sharded["layers"]["attn"]["wq"]
        # column-sharded over model axis: local shard holds ND/4 columns
        assert wq.sharding.spec == P(None, None, MODEL_AXIS)
        assert wq.addressable_shards[0].data.shape == (L, H, ND // 4)
        wo = sharded["layers"]["attn"]["wo"]
        assert wo.addressable_shards[0].data.shape == (L, ND // 4, H)
        ln = sharded["layers"]["ln1"]["scale"]
        assert ln.addressable_shards[0].data.shape == (L, H)  # replicated

    def test_specs_cover_all_leaves(self):
        params = {
            "embed": {"tokens": 0, "pos": 0},
            "layers": {"attn": {"wq": 0, "bq": 0}, "mlp": {"wg": 0}},
            "final_ln": {"scale": 0},
            "unknown_extra": {"leaf": 0},
        }
        specs = param_specs(params)
        assert specs["layers"]["attn"]["wq"] == P(None, None, MODEL_AXIS)
        assert specs["unknown_extra"]["leaf"] == P()  # fallback replicated


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, eight_cpu_devices, causal):
        mesh = make_mesh(data=2, model=1, seq=4)
        rng = np.random.default_rng(0)
        B, S, N, D = 2, 16, 4, 8
        q = rng.standard_normal((B, S, N, D)).astype(np.float32)
        k = rng.standard_normal((B, S, N, D)).astype(np.float32)
        v = rng.standard_normal((B, S, N, D)).astype(np.float32)
        mask = np.ones((B, S), bool)
        mask[1, 12:] = False
        with jax.default_matmul_precision("highest"):
            out = ring_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=causal,
            )
        expected = _dense_attention(q, k, v, mask, causal)
        real = mask[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(out) * real, expected * real, atol=2e-5, rtol=1e-4
        )

    def test_model_axis_sharded_heads(self, eight_cpu_devices):
        mesh = make_mesh(data=2, model=2, seq=2)
        rng = np.random.default_rng(1)
        B, S, N, D = 2, 8, 4, 4
        q, k, v = (rng.standard_normal((B, S, N, D)).astype(np.float32) for _ in range(3))
        mask = np.ones((B, S), bool)
        with jax.default_matmul_precision("highest"):
            out = ring_attention_sharded(
                mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(mask), causal=True,
            )
        expected = _dense_attention(q, k, v, mask, True)
        np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5, rtol=1e-4)
