"""Pooled confidence-leg decode + streamed completion caches (ISSUE 7,
``-m pooledconf``, tier-1).

Pins the four contracts of the leg-parameterized ``_Phase2Pool``:

- **pooled == per-batch at bf16**: the confidence scores the sweep
  consumes — ``weighted_confidence`` (positions 0-2) and the completion's
  first-integer parse — are BIT-IDENTICAL between the default pooled path
  and the r5 per-batch decode (``pooled_confidence=False``), on both the
  plain and the fused two-leg path; the binary leg is untouched
  bit-for-bit.  The pooled completion is a prefix of the per-batch text
  (full equality when no row retires early).
- **int8 KV stays within the documented tolerance** (PARITY.md: the
  kvcache contract extends to the pooled path).
- **early-exit retirement ≡ the full 10-step decode on decided rows**:
  rows forced to retire at the minimum step still emit the exact
  weighted confidence and first-integer value the full decode emits,
  while ``conf_steps_saved`` / ``completion_cache_bytes_freed`` prove
  steps were actually skipped and caches actually streamed.  Retirement
  is a pure function of each row's own tokens, so results are identical
  across batch shapes / pool compositions (the serve-replay contract).
- **strict mode holds**: a pooled-confidence sweep under the transfer
  guard keeps ``blocked_transfers == 0`` (every pool fetch happens inside
  the sanctioned consume scope).
"""

import dataclasses
import itertools

import numpy as np
import pytest

from test_runtime import _tiny_engine

from llm_interpretation_replication_tpu.runtime import engine as emod
from llm_interpretation_replication_tpu.runtime.engine import (
    LegSpec,
    ScoringEngine,
)
from llm_interpretation_replication_tpu.scoring.confidence import (
    extract_first_int,
    first_int_stable,
)
from llm_interpretation_replication_tpu.utils import telemetry

pytestmark = pytest.mark.pooledconf

EXACT_FIELDS = ("first_token_yes_prob", "first_token_no_prob",
                "first_token_relative_prob")
PROB_FIELDS = ("yes_prob", "no_prob", "relative_prob")
INT8_KV_ATOL = 0.05          # the PARITY.md kvcache tolerance

CONF_PROMPTS = [f"How confident are you about rule {i}, 0-100?"
                for i in range(16)]
PAIRS = [(f"Scenario {i}: the bylaw covers bicycles in the park.",
          (" Answer Yes or No.", " How confident, 0-100?"))
         for i in range(6)]
LEGS = [LegSpec("binary"),
        LegSpec("confidence", with_confidence=True, max_new_tokens=10)]


def _clone(eng, tok, **kw):
    return ScoringEngine(eng.family, eng.cfg, eng.params, tok,
                         engine_config=dataclasses.replace(eng.ecfg, **kw))


def _clean_cut(pool, toks, k):
    """Test-predicate guard mirroring the real retirement rule's one hard
    invariant: never retire on a window whose decode ends mid-character
    (U+FFFD tail) — the prefix/parse contracts only hold for clean cuts."""
    text = pool.engine.tokenizer.decode(
        [int(t) for t in toks[:k]], skip_special_tokens=True)
    return not text.endswith("�")


def _assert_conf_scores_equal(pooled_row, batch_row):
    """The pooled-confidence equivalence contract (PARITY.md): weighted
    confidence and first-integer parse bit-identical; completion a prefix;
    position-0 fields untouched."""
    assert pooled_row["weighted_confidence"] == \
        batch_row["weighted_confidence"]
    assert extract_first_int(pooled_row["completion"]) == \
        extract_first_int(batch_row["completion"])
    assert batch_row["completion"].startswith(pooled_row["completion"])
    for f in EXACT_FIELDS:
        assert pooled_row[f] == batch_row[f], f


class TestPooledConfParity:
    def test_plain_path_bf16_bit_parity(self):
        eng, _, tok = _tiny_engine(batch_size=4)
        telemetry.clear_counters()
        pooled = _clone(eng, tok)       # pooled_confidence defaults ON
        rows_p = pooled.score_prompts(CONF_PROMPTS, with_confidence=True,
                                      max_new_tokens=10)
        assert telemetry.counter("pooled_conf_rows") >= len(CONF_PROMPTS)
        rows_b = _clone(eng, tok, pooled_confidence=False).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        for a, b in zip(rows_p, rows_b):
            assert a["success"] and b["success"]
            _assert_conf_scores_equal(a, b)
            # no row retires on this model (garbage completions carry no
            # terminated integer): the full completion text is identical,
            # and the scan fields agree to reduction-order noise — the
            # pooled decode's chunk boundaries (3/5/2 vs one 10-step
            # chunk) split the two-block softmax sums differently past
            # position 2, the same tolerance class the chunked-prefill
            # equivalence pins (PARITY.md)
            for f in PROB_FIELDS:
                np.testing.assert_allclose(a[f], b[f], rtol=2e-5,
                                           atol=1e-9, err_msg=f)
            assert a["completion"] == b["completion"]

    def test_fused_two_leg_bf16_and_binary_leg_untouched(self):
        eng, _, tok = _tiny_engine(batch_size=4)
        telemetry.clear_counters()
        rows_p = _clone(eng, tok).score_prefixed(PAIRS, legs=LEGS)
        assert telemetry.counter("pooled_conf_rows") >= len(PAIRS)
        rows_b = _clone(eng, tok, pooled_confidence=False).score_prefixed(
            PAIRS, legs=LEGS)
        # binary leg: the pool must not perturb it in any way
        for a, b in zip(rows_p[0], rows_b[0]):
            for f in PROB_FIELDS + EXACT_FIELDS + ("odds_ratio",
                                                   "completion"):
                assert a[f] == b[f], f
        for a, b in zip(rows_p[1], rows_b[1]):
            _assert_conf_scores_equal(a, b)

    def test_int8_kv_within_documented_tolerance(self):
        eng, _, tok = _tiny_engine(batch_size=4)
        rows_bf = _clone(eng, tok, pooled_confidence=False).score_prompts(
            CONF_PROMPTS[:9], with_confidence=True, max_new_tokens=10)
        rows_i8 = _clone(eng, tok, kv_dtype="int8").score_prompts(
            CONF_PROMPTS[:9], with_confidence=True, max_new_tokens=10)
        for a, b in zip(rows_i8, rows_bf):
            assert a["success"]
            for f in PROB_FIELDS:
                assert abs(a[f] - b[f]) <= INT8_KV_ATOL, (f, a[f], b[f])
        # pooled-int8 vs per-batch-int8: same dequantized cache values in,
        # the pooled scores must track the per-batch ones within the same
        # bound (they are bit-identical on this harness; the tolerance
        # absorbs backend reduction-order variation at real shapes)
        rows_i8b = _clone(eng, tok, kv_dtype="int8",
                          pooled_confidence=False).score_prompts(
            CONF_PROMPTS[:9], with_confidence=True, max_new_tokens=10)
        for a, b in zip(rows_i8, rows_i8b):
            wa, wb = a["weighted_confidence"], b["weighted_confidence"]
            assert (wa is None) == (wb is None)
            if wa is not None:
                assert abs(wa - wb) <= INT8_KV_ATOL, (wa, wb)

    def test_pool_composition_never_changes_a_row(self):
        """Retirement (and therefore every emitted field) is a function
        of each row's own tokens: scoring the same prompts at different
        batch sizes — different pool compositions and flush shapes — must
        emit identical confidence rows (the serve-replay contract)."""
        eng, _, tok = _tiny_engine(batch_size=4)
        small = _clone(eng, tok).score_prompts(
            CONF_PROMPTS[:9], with_confidence=True, max_new_tokens=10)
        big = _clone(eng, tok, batch_size=16).score_prompts(
            CONF_PROMPTS[:9], with_confidence=True, max_new_tokens=10)
        for a, b in zip(small, big):
            assert a["weighted_confidence"] == b["weighted_confidence"]
            assert a["completion"] == b["completion"]


class TestEarlyExitRetirement:
    def test_forced_retirement_matches_full_decode_and_saves_steps(self):
        """Early-exit retirement ≡ the full 10-step decode on decided
        rows: rows retired at the minimum step (positions 0-2 decoded)
        emit the exact weighted confidence and first-integer value of the
        full decode, and the skipped steps land in ``conf_steps_saved``."""
        eng, _, tok = _tiny_engine(batch_size=8)
        rows_b = _clone(eng, tok, pooled_confidence=False).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        orig = emod._Phase2Pool._conf_retired_at
        emod._Phase2Pool._conf_retired_at = \
            lambda self, toks, k: _clean_cut(self, toks, k)
        telemetry.clear_counters()
        try:
            rows_p = _clone(eng, tok).score_prompts(
                CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        finally:
            emod._Phase2Pool._conf_retired_at = orig
        c = telemetry.counters()
        assert c.get("conf_steps_saved", 0) > 0
        assert c.get("pooled_conf_retired_rows", 0) > 0
        for a, b in zip(rows_p, rows_b):
            _assert_conf_scores_equal(a, b)

    def test_staggered_retirement_streams_caches_per_chunk(self):
        """Rows retiring at different steps compact the pooled cache
        between chunks: retired rows' K/V slices free mid-flush
        (``completion_cache_bytes_freed``) and the SURVIVING rows' score
        math stays correct through the gathers — the weighted confidence
        (positions 0-2, recorded before any compaction and independent
        of where the text is cut) must match the full decode per row,
        proving the row mapping never skews.  The predicate here retires
        on a fixed cadence regardless of text (the real predicate's
        clean-cut rule is pinned separately), so only the text-dependent
        fields are exempt from comparison."""
        eng, _, tok = _tiny_engine(batch_size=16)
        rows_b = _clone(eng, tok, pooled_confidence=False).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        counter = itertools.count()
        orig = emod._Phase2Pool._conf_retired_at
        emod._Phase2Pool._conf_retired_at = \
            lambda self, toks, k: next(counter) % 3 == 0
        telemetry.clear_counters()
        try:
            rows_p = _clone(eng, tok).score_prompts(
                CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        finally:
            emod._Phase2Pool._conf_retired_at = orig
        c = telemetry.counters()
        assert c.get("completion_cache_bytes_freed", 0) > 0
        assert c.get("conf_steps_saved", 0) > 0
        for a, b in zip(rows_p, rows_b):
            assert a["weighted_confidence"] == b["weighted_confidence"]
            for f in EXACT_FIELDS:
                assert a[f] == b[f], f

    def test_natural_retirement_on_digit_completions(self):
        """A row whose greedy completion carries a terminated integer
        retires through the REAL predicate (no monkeypatch): feed the
        retirement check token streams that decode to digit answers."""
        eng, _, tok = _tiny_engine(batch_size=4)
        pool = emod._Phase2Pool(eng, steps=10, eos_id=None, target=4,
                                results=[None] * 4, confidence=True)
        # token ids whose decoded text is a digit answer + terminator
        ids_85 = tok("85 okay", add_special_tokens=False)["input_ids"]
        assert pool._conf_retired_at(np.asarray(ids_85), len(ids_85))
        # a TRAILING integer is not stable (the next token could extend it)
        ids_8 = tok("about 8", add_special_tokens=False)["input_ids"]
        assert not pool._conf_retired_at(np.asarray(ids_8), len(ids_8))
        # EOS freezes the completion regardless
        pool_eos = emod._Phase2Pool(eng, steps=10, eos_id=7, target=4,
                                    results=[None] * 4, confidence=True)
        assert pool_eos._conf_retired_at(np.asarray([5, 7, 3]), 3)


class TestFirstIntStable:
    @pytest.mark.parametrize("text,stable", [
        ("", False),
        ("no digits at all", False),
        ("85", False),              # could extend to 850
        ("I am 85", False),         # trailing integer
        ("85 percent", True),       # terminated
        ("85%", True),              # boundary char terminates
        ("about 40, maybe", True),
        ("x85x", False),            # \b never matches inside a word
    ])
    def test_cases(self, text, stable):
        assert first_int_stable(text) is stable

    def test_stability_is_append_proof(self):
        """The predicate's whole contract: once stable, NO appended text
        can change extract_first_int."""
        base = "confidence: 85 "
        assert first_int_stable(base)
        v = extract_first_int(base)
        for tail in ("9", "99", " 12", "x", ".5", "000"):
            assert extract_first_int(base + tail) == v, tail


class TestStrictAndConfig:
    def test_strict_pooled_confidence_sweep_no_blocked_transfers(self):
        """Acceptance: every pool fetch (chunk tokens, retirement reads)
        happens inside the sanctioned consume scope, so a strict-mode
        pooled-confidence sweep holds ``blocked_transfers == 0``."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng, _, tok = _tiny_engine(batch_size=4)
        pooled = _clone(eng, tok, kv_dtype="int8", prefill_chunk=16)
        strict.activate()
        try:
            snap = telemetry.counters()
            rows = pooled.score_prefixed(PAIRS, legs=LEGS)
            delta = telemetry.counters_since(snap)
            assert delta.get(strict.BLOCKED_COUNTER, 0) == 0
            assert delta.get("pooled_conf_rows", 0) >= len(PAIRS)
            assert all(r["success"] for leg in rows for r in leg)
        finally:
            strict.deactivate()

    def test_per_batch_path_reachable_via_config(self):
        """Acceptance: ``pooled_confidence=False`` keeps the r5 per-batch
        decode — no pooled-confidence counters fire."""
        eng, _, tok = _tiny_engine(batch_size=4)
        telemetry.clear_counters()
        rows = _clone(eng, tok, pooled_confidence=False).score_prompts(
            CONF_PROMPTS[:6], with_confidence=True, max_new_tokens=10)
        assert telemetry.counter("pooled_conf_rows") == 0
        assert all(r["success"] for r in rows)

    def test_oversized_cap_keeps_per_batch_path(self):
        """A confidence leg whose completion cap exceeds the scored scan
        (gen_total > steps) cannot ride the pool (the pooled decode IS
        the completion) and must fall back per batch."""
        eng, _, tok = _tiny_engine(batch_size=4)
        telemetry.clear_counters()
        rows = _clone(eng, tok).score_prompts(
            CONF_PROMPTS[:6], with_confidence=True, max_new_tokens=20)
        assert telemetry.counter("pooled_conf_rows") == 0
        assert all(r["success"] for r in rows)

    def test_pooled_decode_spans_carry_the_confidence_leg(self):
        """Satellite: ``pooled_decode`` phase totals attribute the two
        legs separately — the confidence pool tags its flush spans with
        its own leg, next to the binary pool's."""
        from llm_interpretation_replication_tpu.obs import tracer as obs

        eng, _, tok = _tiny_engine(batch_size=4)
        tracer = obs.get_tracer()
        tracer.reset()
        obs.enable()
        try:
            _clone(eng, tok).score_prefixed(PAIRS, legs=LEGS)
            _clone(eng, tok, decode_completions=False).score_prompts(
                ["Is item one a vehicle?"] * 6)
            totals = obs.phase_totals(by_leg=True)
        finally:
            obs.disable()
            tracer.reset()
        assert "confidence" in totals.get("pooled_decode", {})
        assert "binary" in totals.get("pooled_decode", {})
