"""Base-vs-instruct figure builders (paper Figures 7-8).

Rebuild of analyze_results_base_versus_instruct.py: pair base/instruct rows on
prompt, drop zero-probability rows (:46-52), per-family difference strips and
a family × prompt difference heatmap.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np
import pandas as pd

from ..viz import figures


def process_model_pair(df: pd.DataFrame, base_model: str, instruct_model: str,
                       value_col: str = "relative_prob") -> pd.DataFrame:
    """Paired frame with instruct−base differences; zero-prob rows dropped."""
    base = df[df["model"] == base_model]
    inst = df[df["model"] == instruct_model]
    merged = pd.merge(
        base[["prompt", value_col, "yes_prob", "no_prob"]],
        inst[["prompt", value_col, "yes_prob", "no_prob"]],
        on="prompt", suffixes=("_base", "_instruct"),
    )
    # the reference drops rows where both target probabilities are zero
    keep = ~(
        ((merged["yes_prob_base"] == 0) & (merged["no_prob_base"] == 0))
        | ((merged["yes_prob_instruct"] == 0) & (merged["no_prob_instruct"] == 0))
    )
    merged = merged[keep].copy()
    merged["diff"] = merged[f"{value_col}_instruct"] - merged[f"{value_col}_base"]
    return merged


def base_vs_instruct_figures(
    df: pd.DataFrame,
    output_dir: str,
    value_col: str = "relative_prob",
) -> Dict[str, str]:
    """Per-family difference strips + a family×prompt heatmap.

    Expects the model_comparison_results.csv schema (model, model_family,
    base_or_instruct, prompt, yes_prob, no_prob, <value_col>).
    """
    os.makedirs(output_dir, exist_ok=True)
    paths: Dict[str, str] = {}
    diffs_by_family: Dict[str, np.ndarray] = {}
    heat_rows = []
    heat_families = []
    prompts: Optional[list] = None
    for family in df["model_family"].unique():
        fam = df[df["model_family"] == family]
        base_models = fam[fam["base_or_instruct"] == "base"]["model"].unique()
        inst_models = fam[fam["base_or_instruct"] == "instruct"]["model"].unique()
        if not len(base_models) or not len(inst_models):
            continue
        merged = process_model_pair(fam, base_models[0], inst_models[0], value_col)
        if not len(merged):
            continue
        diffs_by_family[family] = merged["diff"].to_numpy()
        if prompts is None:
            prompts = merged["prompt"].tolist()
        aligned = merged.set_index("prompt")["diff"].reindex(prompts)
        heat_rows.append(aligned.to_numpy(dtype=float))
        heat_families.append(family)
    if diffs_by_family:
        paths["difference_strips"] = figures.jitter_strip_panels(
            diffs_by_family, "Instruct − base relative-probability differences",
            os.path.join(output_dir, "base_vs_instruct_diffs.png"),
            ylabel="Δ relative probability", ylim=(-1, 1),
        )
    if heat_rows and prompts:
        labels = [f"q{i + 1}" for i in range(len(prompts))]
        paths["heatmap"] = figures.mae_heatmap(
            np.vstack(heat_rows), heat_families, labels,
            "Instruct − base differences by prompt",
            os.path.join(output_dir, "base_vs_instruct_heatmap.png"),
        )
    return paths
