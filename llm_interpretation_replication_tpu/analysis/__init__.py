from .base_vs_instruct_figs import base_vs_instruct_figures, process_model_pair
from .closed_source_eval import (
    calculate_correlations,
    compare_with_human_data,
    evaluate_all_models,
    write_report,
)
from .combined_confidence import ModelConfidenceAnalyzer, run_combined_analysis
from .irrelevant_eval import (
    analyze_results,
    build_vendor_evaluators,
    consistency_statistics,
    create_stacked_visualization,
    process_scenario_perturbations,
    run_irrelevant_evaluation,
    save_results,
    summary_frame,
    write_outputs,
)
from .model_comparison import (
    cross_experiment_kappa,
    difference_strip_plot,
    model_comparison_report,
)
from .perturbation_report import add_relative_prob, analyze_model, analyze_workbook
from .similarity_report import similarity_report
