"""Inter-model comparison reports + figures.

Rebuild of model_comparison_graph.py (pairwise correlation engine + heatmap +
distribution + reference-model difference strip) and
calculate_cohens_kappa.py (cross-experiment kappa merge), consuming the
instruct-sweep CSV schema.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np
import pandas as pd

from ..stats.correlations import (
    correlation_summary_bootstrap,
    pairwise_correlations,
    pairwise_kappa,
    pivot_model_values,
)
from ..viz import figures


def difference_strip_plot(df: pd.DataFrame, reference_model: str, output_path: str,
                          value_col: str = "relative_prob") -> Optional[str]:
    """Per-model distribution of (model − reference) differences per prompt
    (model_comparison_graph.py:33-205, Baichuan-referenced in the paper)."""
    pivot = pivot_model_values(df, value_col=value_col)
    if reference_model not in pivot.columns:
        return None
    import matplotlib.pyplot as plt

    others = [m for m in pivot.columns if m != reference_model]
    rng = np.random.default_rng(42)
    fig, ax = plt.subplots(figsize=(max(8, 1.6 * len(others)), 6))
    for i, model in enumerate(others):
        diffs = (pivot[model] - pivot[reference_model]).dropna().to_numpy()
        x = i + rng.uniform(-0.18, 0.18, diffs.size)
        ax.scatter(x, diffs, s=10, alpha=0.4)
        ax.plot([i - 0.3, i + 0.3], [np.mean(diffs)] * 2, color="black", lw=2)
    ax.axhline(0.0, color="grey", linestyle=":")
    ax.set_xticks(range(len(others)))
    ax.set_xticklabels([m.split("/")[-1] for m in others], rotation=30, ha="right")
    ax.set_ylabel(f"{value_col} − {reference_model.split('/')[-1]}")
    ax.set_title("Per-prompt differences vs reference model")
    os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
    fig.savefig(output_path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return output_path


def model_comparison_report(
    df: pd.DataFrame,
    output_dir: str,
    value_col: str = "relative_prob",
    n_bootstrap: int = 1000,
    seed: int = 42,
    reference_model: Optional[str] = None,
    make_figures: bool = True,
) -> Dict:
    """All pairwise correlations + bootstrap summary + kappa + figures."""
    os.makedirs(output_dir, exist_ok=True)
    pivot = pivot_model_values(df, value_col=value_col)
    corr_df = pairwise_correlations(pivot)
    summary = correlation_summary_bootstrap(pivot, n_bootstrap=n_bootstrap, seed=seed)
    kappa = pairwise_kappa(pivot, n_bootstrap=n_bootstrap, seed=seed)
    corr_df.to_csv(os.path.join(output_dir, "pairwise_correlations.csv"), index=False)
    report = {"pairwise": corr_df, "summary": summary, "kappa": kappa}
    if make_figures and len(pivot.columns) >= 2:
        labels = [m.split("/")[-1] for m in pivot.columns]
        mat = pivot.corr(method="pearson").to_numpy()
        report["heatmap"] = figures.correlation_heatmap(
            mat, labels, "Inter-model Pearson correlations",
            os.path.join(output_dir, "correlation_heatmap.png"),
        )
        if summary["values"]:
            report["distribution"] = figures.correlation_distribution(
                summary["values"], "Pairwise correlation distribution",
                os.path.join(output_dir, "correlation_distribution.png"),
            )
        if reference_model:
            report["difference_strip"] = difference_strip_plot(
                df, reference_model,
                os.path.join(output_dir, "difference_strip.png"), value_col,
            )
    import json

    with open(os.path.join(output_dir, "correlation_summary.json"), "w") as f:
        json.dump(
            {"summary": {k: v for k, v in summary.items() if k != "values"},
             "mean_kappa": kappa["mean_kappa"],
             "mean_kappa_ci": kappa["mean_kappa_ci"]},
            f, indent=2, default=float,
        )
    return report


def cross_experiment_kappa(
    frames: Sequence[pd.DataFrame],
    value_col: str = "relative_prob",
    threshold: float = 0.5,
    n_bootstrap: int = 1000,
    seed: int = 42,
) -> Dict:
    """Merge multiple experiment frames (same schema) into one prompts×models
    pivot and compute aggregate kappa (calculate_cohens_kappa.py)."""
    merged = pd.concat(list(frames), ignore_index=True)
    pivot = pivot_model_values(merged, value_col=value_col)
    return pairwise_kappa(pivot, threshold=threshold, n_bootstrap=n_bootstrap, seed=seed)
