"""Irrelevant-perturbation evaluation (3,400 insertions × 3 frontier models).

Rebuild of evaluate_irrelevant_perturbations.py:372-1297: evaluate the
original + every perturbed scenario (response leg + confidence leg per
triple, temperature 0.7) with ``extract_final_number`` parsing for
thinking-model outputs, resume via a processed-triple checkpoint + JSON
progress heartbeat, per-scenario/model consistency + confidence statistics
(pinned bit-exact against the reference's recorded summary.csv), violin
plots, and Excel/CSV/JSON outputs.  Vendor clients are injected (evaluator
callables ``(prompt) -> reply text``) so local models and tests plug in the
same way.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..scoring.confidence import extract_final_number
from ..utils.checkpoint import ProcessedSet
from ..utils.logging import Progress, SessionLogger
from ..utils.xlsx import write_xlsx
from ..viz import figures

Evaluator = Callable[[str], str]  # perturbed scenario text -> model reply text

RESULT_COLUMNS = [
    "model", "scenario_name", "perturbation_id", "irrelevant_statement",
    "position_index", "position_description", "response", "confidence",
    "confidence_raw_response",
]


def response_prompt(scenario: Dict, text: str) -> str:
    """``{text}\n\n{response_format}`` (evaluate_irrelevant_perturbations
    :407, :470)."""
    return f"{text}\n\n{scenario['response_format']}"


def confidence_prompt(scenario: Dict, text: str) -> str:
    return f"{text}\n\n{scenario['confidence_format']}"


def process_scenario_perturbations(
    evaluators: Dict[str, Evaluator],
    scenarios: Sequence[Dict],
    output_dir: str,
    include_original: bool = True,
    max_per_scenario: Optional[int] = None,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    """Evaluate every (model, scenario, perturbation) triple with resume."""
    log = log or SessionLogger()
    os.makedirs(output_dir, exist_ok=True)
    processed = ProcessedSet(os.path.join(output_dir, "processed_triples.json"))
    rows_path = os.path.join(output_dir, "raw_results.csv")
    if os.path.exists(rows_path):
        prior = pd.read_csv(rows_path)
        if "response_text" in prior.columns and "response" not in prior.columns:
            # pre-rename checkpoint: the old single-leg sweep stored only the
            # confidence reply.  Keep it under its new name; the response leg
            # for those rows is genuinely absent (NaN), which
            # consistency_statistics excludes rather than counting as
            # disagreement.
            prior = prior.rename(columns={"response_text": "confidence_raw_response"})
        rows: List[Dict] = prior.to_dict("records")
    else:
        rows = []
    total = sum(
        (len(s["perturbations_with_irrelevant"][:max_per_scenario])
         if max_per_scenario else len(s["perturbations_with_irrelevant"]))
        + (1 if include_original else 0)
        for s in scenarios
    ) * len(evaluators)
    progress = Progress(total, path=os.path.join(output_dir, "progress.json"))

    def run_one(model: str, evaluate: Evaluator, scenario: Dict, pid, text: str, extra: Dict):
        key = (model, scenario["scenario_name"], pid)
        if key in processed:
            return
        # two legs per triple, like the reference: the yes/no-style response
        # prompt, then the 0-100 confidence prompt (:407-470).  Each leg
        # fails independently so a broken confidence call can't clobber a
        # good response (and vice versa); the sweep continues either way.
        try:
            response = evaluate(response_prompt(scenario, text))
        except Exception as err:
            response = f"ERROR: {str(err)[:100]}"
        try:
            reply = evaluate(confidence_prompt(scenario, text))
            confidence = extract_final_number(reply)
        except Exception as err:
            reply, confidence = f"ERROR: {str(err)[:100]}", None
        rows.append(
            {
                "model": model,
                "scenario_name": scenario["scenario_name"],
                "perturbation_id": pid,
                "response": str(response)[:500],
                "confidence": confidence,
                "confidence_raw_response": str(reply)[:500],
                **extra,
            }
        )
        processed.add(key, flush=False)
        progress.update(1, model=model, scenario=scenario["scenario_name"])

    for model, evaluate in evaluators.items():
        for scenario in scenarios:
            perturbations = scenario["perturbations_with_irrelevant"]
            if max_per_scenario:
                perturbations = perturbations[:max_per_scenario]
            if include_original:
                run_one(model, evaluate, scenario, "original", scenario["original_main"],
                        {"irrelevant_statement": "", "position_index": -1,
                         "position_description": "original"})
            for p in perturbations:
                run_one(
                    model, evaluate, scenario, p["perturbation_id"], p["perturbed_text"],
                    {
                        "irrelevant_statement": p["irrelevant_statement"],
                        "position_index": p["position_index"],
                        "position_description": p["position_description"],
                    },
                )
            processed.flush()
            pd.DataFrame(rows).to_csv(rows_path, index=False)
            log(f"{model} / {scenario['scenario_name']}: checkpointed ({len(rows)} rows)")
    df = pd.DataFrame(rows, columns=RESULT_COLUMNS)
    df.to_csv(rows_path, index=False)
    return df


def consistency_statistics(df: pd.DataFrame) -> pd.DataFrame:
    """Per (model, scenario) consistency + confidence statistics, matching
    evaluate_irrelevant_perturbations.analyze_results (:503-618) exactly
    (pinned against the recorded ``summary.csv`` in
    tests/test_published_regression.py): response consistency vs the
    original, pooled original+perturbed confidence stats (pandas ddof=1 std,
    2.5/97.5 percentiles), and the perturbed-only leg; plus our ``ci_width``
    convenience column."""
    records = []
    for (model, scenario), sub in df.groupby(["model", "scenario_name"]):
        pert = sub[sub["perturbation_id"] != "original"]
        orig = sub[sub["perturbation_id"] == "original"]
        vals_all = pd.to_numeric(sub["confidence"], errors="coerce").dropna()
        vals_pert = pd.to_numeric(pert["confidence"], errors="coerce").dropna()
        def usable(series: pd.Series) -> pd.Series:
            # a response is usable when present and not a one-leg ERROR
            # sentinel (run_one records those to keep the sweep alive)
            s = series.dropna()
            return s[~s.astype(str).str.startswith("ERROR:")]

        orig_resp, orig_conf = None, np.nan
        if len(orig):
            orig_conf = pd.to_numeric(orig["confidence"], errors="coerce").iloc[0]
            orig_usable = usable(orig["response"])
            if len(orig_usable):
                orig_resp = orig_usable.iloc[0]
        if orig_resp is None and len(pert):
            # missing (or errored) original: synthesize the reference's
            # fallback — the modal perturbed response + mean perturbed
            # confidence (:522-542)
            modes = usable(pert["response"]).mode()
            if len(modes):
                orig_resp = modes.iloc[0]
            if pd.isna(orig_conf):
                orig_conf = float(vals_pert.mean()) if vals_pert.size else np.nan
        # rows whose response leg is missing or errored (legacy checkpoints,
        # one-leg failures) are excluded from the consistency denominator
        # instead of silently counting as disagreement.  No perturbations at
        # all -> trivially consistent (reference :565); perturbations exist
        # but none measurable -> NaN, not a fabricated perfect score.
        pert_resp = usable(pert["response"])
        if len(pert_resp) and orig_resp is not None:
            consistency = float((pert_resp == orig_resp).mean())
        elif len(pert) == 0:
            consistency = 1.0
        else:
            consistency = float("nan")
        rec = {
            "model": model,
            "scenario_name": scenario,
            "consistency": consistency,
            "original_confidence": float(orig_conf) if pd.notna(orig_conf) else np.nan,
            "original_response": orig_resp,
            "num_perturbations": int(len(pert)),
            "num_total_samples": int(len(sub)),
            "n_samples": int(vals_all.size),
        }
        if vals_all.size:
            p = np.percentile(vals_all, [2.5, 97.5])
            rec.update(
                mean_all_confidence=float(vals_all.mean()),
                std_all_confidence=float(vals_all.std()),
                median_all_confidence=float(vals_all.median()),
                ci_lower_95=float(p[0]), ci_upper_95=float(p[1]),
                ci_width=float(p[1] - p[0]),
            )
        if vals_pert.size:
            rec.update(
                mean_perturbed_confidence=float(vals_pert.mean()),
                std_perturbed_confidence=float(vals_pert.std()),
            )
        records.append(rec)
    return pd.DataFrame(records)


def write_outputs(df: pd.DataFrame, stats: pd.DataFrame, output_dir: str,
                  make_figures: bool = True) -> Dict[str, str]:
    os.makedirs(output_dir, exist_ok=True)
    paths = {
        "csv": os.path.join(output_dir, "raw_results.csv"),
        "xlsx": os.path.join(output_dir, "results.xlsx"),
        "stats_csv": os.path.join(output_dir, "consistency_stats.csv"),
        "stats_json": os.path.join(output_dir, "consistency_stats.json"),
    }
    df.to_csv(paths["csv"], index=False)
    write_xlsx(df, paths["xlsx"])
    stats.to_csv(paths["stats_csv"], index=False)
    with open(paths["stats_json"], "w") as f:
        json.dump(stats.to_dict("records"), f, indent=2, default=float)
    if make_figures:
        for model in df["model"].unique():
            sub = df[(df["model"] == model) & (df["perturbation_id"] != "original")]
            groups = {
                scenario: pd.to_numeric(g["confidence"], errors="coerce").dropna().tolist()
                for scenario, g in sub.groupby("scenario_name")
            }
            path = figures.violin_by_group(
                groups, f"{model} — confidence across irrelevant insertions",
                os.path.join(output_dir, f"violin_{str(model).replace('/', '--')}.png"),
            )
            if path:
                paths[f"violin_{model}"] = path
    return paths
