"""Irrelevant-perturbation evaluation (3,400 insertions × 3 frontier models).

Rebuild of evaluate_irrelevant_perturbations.py:372-1297: evaluate the
original + every perturbed scenario (response leg + confidence leg per
triple, temperature 0.7) with ``extract_final_number`` parsing for
thinking-model outputs, resume via a processed-triple checkpoint + JSON
progress heartbeat, per-scenario/model consistency + confidence statistics
(pinned bit-exact against the reference's recorded summary.csv), violin
plots, and Excel/CSV/JSON outputs.  Vendor clients are injected (evaluator
callables ``(prompt) -> reply text``) so local models and tests plug in the
same way.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..scoring.confidence import extract_final_number
from ..utils.checkpoint import ProcessedSet
from ..utils.logging import Progress, SessionLogger
from ..utils.xlsx import write_xlsx
from ..viz import figures

Evaluator = Callable[[str], str]  # perturbed scenario text -> model reply text

RESULT_COLUMNS = [
    "model", "scenario_name", "perturbation_id", "irrelevant_statement",
    "position_index", "position_description", "response", "confidence",
    "confidence_raw_response", "is_original", "response_prompt",
    "confidence_prompt",
]

DELAY_BETWEEN_REQUESTS = 0.1  # reference :62


from ..utils.strict_json import nan_to_null as _nan_to_null  # noqa: E402
# non-finite stats (all-error groups, single-sample std) must not become
# bare NaN tokens that jq/JSON.parse reject — shared strict-JSON sanitizer


def build_vendor_evaluators(
    gpt_client=None,
    claude_client=None,
    gemini_client=None,
    models: Optional[Dict[str, Dict]] = None,
    sleep: Optional[Callable[[float], None]] = None,
    delay: float = DELAY_BETWEEN_REQUESTS,
) -> Dict[str, Evaluator]:
    """``{"gpt"|"claude"|"gemini": prompt -> reply text}`` over the vendor
    clients, with each vendor's quirks preserved:

    - GPT: plain chat completion, no logprobs (reference :295-314)
    - Claude: create_message at the study temperature (:316-334)
    - Gemini: safety thresholds BLOCK_NONE and ``max_output_tokens``
      deliberately UNSET — setting it triggered empty-reply truncation on
      gemini-2.5-pro (:336-369 and the client's own bug-dodge note)

    Only vendors whose client is provided get an evaluator.  ``models``
    defaults to the study roster asset (temperature 0.7, 500-token replies,
    reference :41-57).  ``sleep`` adds the reference's inter-request pacing
    (:62); omit it in tests.
    """
    from ..config import irrelevant_eval_models

    models = models or irrelevant_eval_models()
    evaluators: Dict[str, Evaluator] = {}

    def paced(fn: Evaluator) -> Evaluator:
        if sleep is None:
            return fn

        def wrapped(prompt: str) -> str:
            out = fn(prompt)
            sleep(delay)
            return out

        return wrapped

    # each vendor's spec is bound as a default argument: a shared closure
    # variable would be rebound to the LAST vendor's spec by the time the
    # evaluators run, sending e.g. the Gemini model name to OpenAI
    if gpt_client is not None:

        def eval_gpt(prompt: str, spec=models["gpt"]) -> str:
            resp = gpt_client.chat_completion(
                spec["name"], [{"role": "user", "content": prompt}],
                temperature=spec["temperature"], max_tokens=spec["max_tokens"],
                logprobs=False,
            )
            return resp["choices"][0]["message"]["content"].strip()

        evaluators["gpt"] = paced(eval_gpt)
    if claude_client is not None:

        def eval_claude(prompt: str, spec=models["claude"]) -> str:
            msg = claude_client.create_message(
                spec["name"], [{"role": "user", "content": prompt}],
                max_tokens=spec["max_tokens"], temperature=spec["temperature"],
            )
            return claude_client.text_of(msg)

        evaluators["claude"] = paced(eval_claude)
    if gemini_client is not None:

        def eval_gemini(prompt: str, spec=models["gemini"]) -> str:
            resp = gemini_client.generate_content(
                spec["name"], prompt, temperature=spec["temperature"],
            )
            return gemini_client.text_of(resp)

        evaluators["gemini"] = paced(eval_gemini)
    return evaluators


def response_prompt(scenario: Dict, text: str) -> str:
    """``{text}\n\n{response_format}`` (evaluate_irrelevant_perturbations
    :407, :470)."""
    return f"{text}\n\n{scenario['response_format']}"


def confidence_prompt(scenario: Dict, text: str) -> str:
    return f"{text}\n\n{scenario['confidence_format']}"


def process_scenario_perturbations(
    evaluators: Dict[str, Evaluator],
    scenarios: Sequence[Dict],
    output_dir: str,
    include_original: bool = True,
    max_per_scenario: Optional[int] = None,
    limit_per_model: Optional[Dict[str, int]] = None,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    """Evaluate every (model, scenario, perturbation) triple with resume.

    ``limit_per_model`` caps NEW evaluations per model for this run (the
    reference's test-mode distribution, evaluate_irrelevant_perturbations.py
    :1138-1146, 1188-1223); already-processed triples don't count against it,
    and a scenario may be cut mid-way to honor the cap exactly."""
    log = log or SessionLogger()
    os.makedirs(output_dir, exist_ok=True)
    processed = ProcessedSet(os.path.join(output_dir, "processed_triples.json"))
    rows_path = os.path.join(output_dir, "raw_results.csv")
    if os.path.exists(rows_path):
        prior = pd.read_csv(rows_path)
        if "response_text" in prior.columns and "response" not in prior.columns:
            # pre-rename checkpoint: the old single-leg sweep stored only the
            # confidence reply.  Keep it under its new name; the response leg
            # for those rows is genuinely absent (NaN), which
            # consistency_statistics excludes rather than counting as
            # disagreement.
            prior = prior.rename(columns={"response_text": "confidence_raw_response"})
        rows: List[Dict] = prior.to_dict("records")
        # Seed the processed-set from the loaded rows: a kill between the
        # rows-CSV rename and the processed-set flush would otherwise make
        # run_one re-evaluate (and re-append) the last scenario's triples,
        # double-counting them in every downstream statistic.  Numeric pids
        # round-trip through the mixed-type CSV column as strings — restore
        # them so the keys match run_one's int pids.
        for r in rows:
            pid = r["perturbation_id"]
            if isinstance(pid, str) and pid.isdigit():
                pid = int(pid)
            elif isinstance(pid, float):
                pid = int(pid)
            processed.add((r["model"], r["scenario_name"], pid), flush=False)
    else:
        rows = []
    total = sum(
        (len(s["perturbations_with_irrelevant"][:max_per_scenario])
         if max_per_scenario else len(s["perturbations_with_irrelevant"]))
        + (1 if include_original else 0)
        for s in scenarios
    ) * len(evaluators)
    progress = Progress(total, path=os.path.join(output_dir, "progress.json"))

    def run_one(model: str, evaluate: Evaluator, scenario: Dict, pid, text: str, extra: Dict) -> bool:
        key = (model, scenario["scenario_name"], pid)
        if key in processed:
            return False
        # two legs per triple, like the reference: the yes/no-style response
        # prompt, then the 0-100 confidence prompt (:407-470).  Each leg
        # fails independently so a broken confidence call can't clobber a
        # good response (and vice versa); the sweep continues either way.
        r_prompt = response_prompt(scenario, text)
        c_prompt = confidence_prompt(scenario, text)
        try:
            response = evaluate(r_prompt)
        except Exception as err:
            response = f"ERROR: {str(err)[:100]}"
        try:
            reply = evaluate(c_prompt)
            confidence = extract_final_number(reply)
        except Exception as err:
            reply, confidence = f"ERROR: {str(err)[:100]}", None
        rows.append(
            {
                "model": model,
                "scenario_name": scenario["scenario_name"],
                "perturbation_id": pid,
                "response": str(response)[:500],
                "confidence": confidence,
                "confidence_raw_response": str(reply)[:500],
                "is_original": pid == "original",
                "response_prompt": r_prompt,
                "confidence_prompt": c_prompt,
                **extra,
            }
        )
        processed.add(key, flush=False)
        progress.update(1, model=model, scenario=scenario["scenario_name"])
        return True

    for model, evaluate in evaluators.items():
        budget = (limit_per_model or {}).get(model, float("inf"))
        for scenario in scenarios:
            if budget <= 0:
                log(f"{model}: reached evaluation limit, moving on")
                break
            perturbations = scenario["perturbations_with_irrelevant"]
            if max_per_scenario:
                perturbations = perturbations[:max_per_scenario]
            if include_original:
                budget -= run_one(
                    model, evaluate, scenario, "original", scenario["original_main"],
                    {"irrelevant_statement": "", "position_index": -1,
                     "position_description": "original"})
            for p in perturbations:
                if budget <= 0:
                    break
                budget -= run_one(
                    model, evaluate, scenario, p["perturbation_id"], p["perturbed_text"],
                    {
                        "irrelevant_statement": p["irrelevant_statement"],
                        "position_index": p["position_index"],
                        "position_description": p["position_description"],
                    },
                )
            # Rows first (atomic rename), processed-set second: a kill in
            # between re-evaluates at most one scenario on resume instead of
            # permanently dropping paid evaluations marked done but unsaved.
            tmp = rows_path + ".tmp"
            pd.DataFrame(rows).to_csv(tmp, index=False)
            os.replace(tmp, rows_path)
            processed.flush()
            log(f"{model} / {scenario['scenario_name']}: checkpointed ({len(rows)} rows)")
    df = pd.DataFrame(rows, columns=RESULT_COLUMNS)
    df.to_csv(rows_path, index=False)
    return df


def _usable(series: pd.Series) -> pd.Series:
    """Responses that are present and not a one-leg ERROR sentinel (run_one
    records those to keep the sweep alive)."""
    s = series.dropna()
    return s[~s.astype(str).str.startswith("ERROR:")]


def _original_reference(orig: pd.DataFrame, pert: pd.DataFrame):
    """(original_response, original_confidence) with the reference's missing-
    original fallback — modal perturbed response + mean perturbed confidence
    (evaluate_irrelevant_perturbations.py:522-542)."""
    orig_resp, orig_conf = None, np.nan
    if len(orig):
        orig_conf = pd.to_numeric(orig["confidence"], errors="coerce").iloc[0]
        orig_usable = _usable(orig["response"])
        if len(orig_usable):
            orig_resp = orig_usable.iloc[0]
    if orig_resp is None and len(pert):
        modes = _usable(pert["response"]).mode()
        if len(modes):
            orig_resp = modes.iloc[0]
        if pd.isna(orig_conf):
            vals_pert = pd.to_numeric(pert["confidence"], errors="coerce").dropna()
            orig_conf = float(vals_pert.mean()) if vals_pert.size else np.nan
    return orig_resp, orig_conf


def _consistency(pert: pd.DataFrame, orig_resp) -> float:
    """Share of usable perturbed responses equal to the original's.  No
    perturbations at all -> trivially consistent (reference :565);
    perturbations exist but none measurable -> NaN, not a fabricated
    perfect score."""
    pert_resp = _usable(pert["response"])
    if len(pert_resp) and orig_resp is not None:
        return float((pert_resp == orig_resp).mean())
    if len(pert) == 0:
        return 1.0
    return float("nan")


def consistency_statistics(df: pd.DataFrame) -> pd.DataFrame:
    """Per (model, scenario) consistency + confidence statistics, matching
    evaluate_irrelevant_perturbations.analyze_results (:503-618) exactly
    (pinned against the recorded ``summary.csv`` in
    tests/test_published_regression.py): response consistency vs the
    original, pooled original+perturbed confidence stats (pandas ddof=1 std,
    2.5/97.5 percentiles), and the perturbed-only leg; plus our ``ci_width``
    convenience column."""
    records = []
    for (model, scenario), sub in df.groupby(["model", "scenario_name"]):
        pert = sub[sub["perturbation_id"] != "original"]
        orig = sub[sub["perturbation_id"] == "original"]
        vals_all = pd.to_numeric(sub["confidence"], errors="coerce").dropna()
        vals_pert = pd.to_numeric(pert["confidence"], errors="coerce").dropna()
        orig_resp, orig_conf = _original_reference(orig, pert)
        # rows whose response leg is missing or errored (legacy checkpoints,
        # one-leg failures) are excluded from the consistency denominator
        # instead of silently counting as disagreement.
        consistency = _consistency(pert, orig_resp)
        rec = {
            "model": model,
            "scenario_name": scenario,
            "consistency": consistency,
            "original_confidence": float(orig_conf) if pd.notna(orig_conf) else np.nan,
            "original_response": orig_resp,
            "num_perturbations": int(len(pert)),
            "num_total_samples": int(len(sub)),
            "n_samples": int(vals_all.size),
        }
        if vals_all.size:
            p = np.percentile(vals_all, [2.5, 97.5])
            rec.update(
                mean_all_confidence=float(vals_all.mean()),
                std_all_confidence=float(vals_all.std()),
                median_all_confidence=float(vals_all.median()),
                ci_lower_95=float(p[0]), ci_upper_95=float(p[1]),
                ci_width=float(p[1] - p[0]),
            )
        if vals_pert.size:
            rec.update(
                mean_perturbed_confidence=float(vals_pert.mean()),
                std_perturbed_confidence=float(vals_pert.std()),
            )
        records.append(rec)
    return pd.DataFrame(records)


def analyze_results(df: pd.DataFrame) -> Dict:
    """Nested ``{scenario: {model: {...}}}`` analysis — the reference's
    ``analysis.json`` shape (evaluate_irrelevant_perturbations.py:503-618):
    consistency, confidence_stats (pooled + perturbed-only), per-position
    consistency, the original's prompts/raw reply, and the raw confidence
    values the violin plots draw from."""
    analysis: Dict = {}
    for (model, scenario), sub in df.groupby(["model", "scenario_name"]):
        pert = sub[sub["perturbation_id"] != "original"]
        orig = sub[sub["perturbation_id"] == "original"]
        vals_all = pd.to_numeric(sub["confidence"], errors="coerce").dropna()
        vals_pert = pd.to_numeric(pert["confidence"], errors="coerce").dropna()
        if vals_all.size == 0:
            continue                       # reference :556: nothing to analyze
        orig_resp, orig_conf = _original_reference(orig, pert)

        confidence_stats = {
            "original_confidence": float(orig_conf) if pd.notna(orig_conf) else None,
            "mean_all_confidence": float(vals_all.mean()),
            "std_all_confidence": float(vals_all.std()),
            "median_all_confidence": float(vals_all.median()),
            "ci_lower_95": float(np.percentile(vals_all, 2.5)),
            "ci_upper_95": float(np.percentile(vals_all, 97.5)),
            "min_confidence": float(vals_all.min()),
            "max_confidence": float(vals_all.max()),
            "n_samples": int(vals_all.size),
        }
        if vals_pert.size:
            confidence_stats.update(
                mean_perturbed_confidence=float(vals_pert.mean()),
                std_perturbed_confidence=float(vals_pert.std()),
                median_perturbed_confidence=float(vals_pert.median()),
                perturbed_ci_lower_95=float(np.percentile(vals_pert, 2.5)),
                perturbed_ci_upper_95=float(np.percentile(vals_pert, 97.5)),
            )

        position_consistency = {}
        if len(pert) and orig_resp is not None:
            for pos_idx in pert["position_index"].dropna().unique():
                pos = pert[pert["position_index"] == pos_idx]
                desc = pos["position_description"].iloc[0] if len(pos) else str(pos_idx)
                pos_resp = _usable(pos["response"])
                if len(pos_resp):
                    position_consistency[f"{int(pos_idx)}_{desc}"] = float(
                        (pos_resp == orig_resp).mean()
                    )

        def _orig_field(col: str) -> str:
            if len(orig) and col in orig.columns and pd.notna(orig[col].iloc[0]):
                return str(orig[col].iloc[0])
            return "N/A - Original missing"

        analysis.setdefault(scenario, {})[model] = {
            "consistency": _consistency(pert, orig_resp),
            "confidence_stats": confidence_stats,
            "position_consistency": position_consistency,
            "num_perturbations": int(len(pert)),
            "num_total_samples": int(len(sub)),
            "original_response": orig_resp,
            "original_response_prompt": _orig_field("response_prompt"),
            "original_confidence_prompt": _orig_field("confidence_prompt"),
            "original_confidence_raw_response": _orig_field("confidence_raw_response"),
            "confidence_values": [float(v) for v in vals_all],
        }
    return analysis


def summary_frame(analysis: Dict) -> pd.DataFrame:
    """The reference's ``summary.csv`` row set (:640-656)."""
    records = []
    for scenario, per_model in analysis.items():
        for model, a in per_model.items():
            cs = a["confidence_stats"]
            records.append({
                "scenario": scenario,
                "model": model,
                "consistency": a["consistency"],
                "original_confidence": cs.get("original_confidence"),
                "mean_all_confidence": cs.get("mean_all_confidence"),
                "std_all_confidence": cs.get("std_all_confidence"),
                "median_all_confidence": cs.get("median_all_confidence"),
                "ci_lower_95": cs.get("ci_lower_95"),
                "ci_upper_95": cs.get("ci_upper_95"),
                "n_samples": cs.get("n_samples"),
                "mean_perturbed_confidence": cs.get("mean_perturbed_confidence"),
                "std_perturbed_confidence": cs.get("std_perturbed_confidence"),
                "original_response": a["original_response"],
                "num_perturbations": a.get("num_perturbations", 0),
                "num_total_samples": a.get("num_total_samples", 0),
            })
    return pd.DataFrame(records)


def position_frame(analysis: Dict) -> pd.DataFrame:
    """Long-form per-position consistency (the Position Analysis sheet's
    source, :663-673)."""
    records = [
        {"scenario": scenario, "model": model, "position": position,
         "consistency": consistency}
        for scenario, per_model in analysis.items()
        for model, a in per_model.items()
        for position, consistency in a["position_consistency"].items()
    ]
    return pd.DataFrame(records, columns=["scenario", "model", "position",
                                          "consistency"])


MODEL_DISPLAY_NAMES = {  # reference :848-853
    "gpt": "GPT-4.1", "claude": "Claude Opus 4.1", "gemini": "Gemini 2.5 Pro",
}


def create_stacked_visualization(analysis: Dict, output_dir: str) -> Optional[str]:
    """``three_model_stacked_visualization.png`` — vertically stacked violin
    panels, one per model in gpt/claude/gemini order (:803-941)."""
    scenarios = sorted(analysis)
    present = [m for m in MODEL_DISPLAY_NAMES
               if any(m in analysis[s] for s in scenarios)]
    if not present:
        return None
    values = {
        MODEL_DISPLAY_NAMES[m]: {
            s: analysis[s][m]["confidence_values"]
            for s in scenarios if m in analysis[s]
        }
        for m in present
    }
    return figures.stacked_violin_panels(
        values, os.path.join(output_dir, "three_model_stacked_visualization.png"),
        group_order=scenarios,
    )


def summary_report_text(analysis: Dict) -> str:
    """The human-readable ``summary_report.txt`` (:726-765)."""
    lines = ["IRRELEVANT STATEMENT PERTURBATION ANALYSIS", "=" * 60, ""]
    for scenario, per_model in analysis.items():
        lines += ["", scenario, "-" * 40]
        for model, a in per_model.items():
            cs = a["confidence_stats"]
            lines += [
                "", f"{model}:",
                f"  Consistency: {a['consistency']:.2%}",
                f"  Original Response: {a['original_response']}",
                f"  Number of Samples: {cs.get('n_samples', 'N/A')}",
                "", "  Confidence Statistics:",
                "    Original: "
                f"{'N/A' if cs.get('original_confidence') is None else cs['original_confidence']}",
                f"    Mean (all): {cs.get('mean_all_confidence', 0):.1f}",
                f"    Std Dev (all): {cs.get('std_all_confidence', 0):.1f}",
                f"    Median (all): {cs.get('median_all_confidence', 0):.1f}",
                f"    95% CI: [{cs.get('ci_lower_95', 0):.1f}, "
                f"{cs.get('ci_upper_95', 0):.1f}]",
            ]
            if "mean_perturbed_confidence" in cs:
                lines += [
                    f"    Mean (perturbed only): {cs['mean_perturbed_confidence']:.1f}",
                    f"    Std Dev (perturbed only): {cs['std_perturbed_confidence']:.1f}",
                ]
            lines.append("\n  Position Consistency:")
            for position, consistency in a["position_consistency"].items():
                lines.append(f"    {position}: {consistency:.2%}")
    return "\n".join(lines) + "\n"


def detailed_prompts_text(df: pd.DataFrame, per_scenario: int = 5) -> str:
    """``detailed_prompts.txt`` — first few full prompt/response examples per
    scenario (:767-800)."""
    lines = ["DETAILED PROMPTS USED IN EVALUATION", "=" * 60, ""]
    counts: Dict[str, int] = {}
    seen = set()
    for _, row in df.iterrows():
        key = (row["scenario_name"], row["perturbation_id"])
        if key in seen:
            continue
        seen.add(key)
        n = counts.get(row["scenario_name"], 0)
        if n >= per_scenario:
            continue
        counts[row["scenario_name"]] = n + 1
        lines += [
            "", f"Scenario: {row['scenario_name']}",
            f"Perturbation ID: {row['perturbation_id']}",
        ]
        # original rows reloaded from a resume CSV carry NaN (truthy!) here
        if pd.notna(row.get("irrelevant_statement")) and row.get("irrelevant_statement"):
            lines.append(f"Irrelevant Statement: {row['irrelevant_statement']}")
        def text(col):
            # NaN-guarded like irrelevant_statement above: rows resumed from
            # pre-prompt-column checkpoints reindex to NaN, not missing
            val = row.get(col, "")
            return "" if pd.isna(val) else str(val)

        lines += [
            f"Model: {row['model']}", "-" * 40,
            "", "RESPONSE PROMPT:", text("response_prompt"),
            "", "CONFIDENCE PROMPT:", text("confidence_prompt"),
            "", f"Model Response: {row['response']}",
            f"Model Confidence: {row['confidence']}",
            f"Raw Confidence Response: {row['confidence_raw_response']}",
            "=" * 60,
        ]
        if counts[row["scenario_name"]] == per_scenario:
            lines.append(
                f"\n[Showing first {per_scenario} perturbations for "
                f"{row['scenario_name']}. Full data in raw_results.csv]"
            )
    return "\n".join(lines) + "\n"


def save_results(df: pd.DataFrame, analysis: Dict, output_dir: str,
                 make_figures: bool = True) -> Dict[str, str]:
    """The reference's full artifact set (:620-800): raw_results.csv,
    summary.csv, the three-sheet results_analysis.xlsx, analysis.json,
    summary_report.txt, detailed_prompts.txt, and the stacked violin
    visualization."""
    from ..utils.xlsx import write_xlsx_sheets

    os.makedirs(output_dir, exist_ok=True)
    summary = summary_frame(analysis)
    positions = position_frame(analysis)
    paths = {
        "csv": os.path.join(output_dir, "raw_results.csv"),
        "summary_csv": os.path.join(output_dir, "summary.csv"),
        "xlsx": os.path.join(output_dir, "results_analysis.xlsx"),
        "analysis_json": os.path.join(output_dir, "analysis.json"),
        "report": os.path.join(output_dir, "summary_report.txt"),
        "prompts": os.path.join(output_dir, "detailed_prompts.txt"),
    }
    df.to_csv(paths["csv"], index=False)
    summary.to_csv(paths["summary_csv"], index=False)
    sheets = {"Raw Results": df, "Summary": summary}
    if len(positions):
        sheets["Position Analysis"] = (
            positions.pivot_table(index=["scenario", "model"],
                                  columns="position", values="consistency")
            .reset_index()
        )
    write_xlsx_sheets(sheets, paths["xlsx"])
    with open(paths["analysis_json"], "w", encoding="utf-8") as f:
        # strict JSON: NaN/inf stats (all-error groups, single-sample std)
        # become null, not bare NaN tokens that non-Python consumers reject
        json.dump(_nan_to_null(analysis), f, indent=2, default=float)
    with open(paths["report"], "w", encoding="utf-8") as f:
        f.write(summary_report_text(analysis))
    with open(paths["prompts"], "w", encoding="utf-8") as f:
        f.write(detailed_prompts_text(df))
    if make_figures:
        fig = create_stacked_visualization(analysis, output_dir)
        if fig:
            paths["figure"] = fig
    return paths


def run_irrelevant_evaluation(
    evaluators: Dict[str, Evaluator],
    scenarios: Sequence[Dict],
    output_dir: str,
    limit_total: Optional[int] = None,
    make_figures: bool = True,
    log: Optional[SessionLogger] = None,
) -> Dict[str, str]:
    """End-to-end study leg: evaluate (with resume), analyze, save everything.

    ``limit_total`` is the reference's test-mode budget, split evenly across
    the models with the remainder going to the first ones (:1138-1146)."""
    log = log or SessionLogger()
    limit_per_model = None
    if limit_total is not None:
        n = len(evaluators)
        per, rem = divmod(limit_total, n)
        limit_per_model = {
            m: per + (1 if i < rem else 0) for i, m in enumerate(evaluators)
        }
        log(f"test mode: {limit_total} evaluations split as {limit_per_model}")
    df = process_scenario_perturbations(
        evaluators, scenarios, output_dir,
        limit_per_model=limit_per_model, log=log,
    )
    analysis = analyze_results(df)
    return save_results(df, analysis, output_dir, make_figures=make_figures)


def write_outputs(df: pd.DataFrame, stats: pd.DataFrame, output_dir: str,
                  make_figures: bool = True) -> Dict[str, str]:
    os.makedirs(output_dir, exist_ok=True)
    paths = {
        "csv": os.path.join(output_dir, "raw_results.csv"),
        "xlsx": os.path.join(output_dir, "results.xlsx"),
        "stats_csv": os.path.join(output_dir, "consistency_stats.csv"),
        "stats_json": os.path.join(output_dir, "consistency_stats.json"),
    }
    df.to_csv(paths["csv"], index=False)
    write_xlsx(df, paths["xlsx"])
    stats.to_csv(paths["stats_csv"], index=False)
    with open(paths["stats_json"], "w") as f:
        json.dump(stats.to_dict("records"), f, indent=2, default=float)
    if make_figures:
        for model in df["model"].unique():
            sub = df[(df["model"] == model) & (df["perturbation_id"] != "original")]
            groups = {
                scenario: pd.to_numeric(g["confidence"], errors="coerce").dropna().tolist()
                for scenario, g in sub.groupby("scenario_name")
            }
            path = figures.violin_by_group(
                groups, f"{model} — confidence across irrelevant insertions",
                os.path.join(output_dir, f"violin_{str(model).replace('/', '--')}.png"),
            )
            if path:
                paths[f"violin_{model}"] = path
    return paths
