"""Irrelevant-perturbation evaluation (3,400 insertions × 3 frontier models).

Rebuild of evaluate_irrelevant_perturbations.py:372-1297: evaluate the
original + every perturbed scenario at temperature 0.7 with
``extract_final_number`` parsing for thinking-model outputs, resume via a
processed-triple checkpoint + JSON progress heartbeat, per-scenario/model
consistency statistics (mean/std/95% interval width), violin plots, and
Excel/CSV/JSON outputs.  Vendor clients are injected (evaluator callables
``(scenario_text) -> response_text``) so local models and tests plug in the
same way.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..scoring.confidence import extract_final_number
from ..utils.checkpoint import ProcessedSet
from ..utils.logging import Progress, SessionLogger
from ..utils.xlsx import write_xlsx
from ..viz import figures

Evaluator = Callable[[str], str]  # perturbed scenario text -> model reply text

RESULT_COLUMNS = [
    "model", "scenario_name", "perturbation_id", "irrelevant_statement",
    "position_index", "position_description", "response_text", "confidence",
]


def confidence_prompt(scenario: Dict, text: str) -> str:
    return f"{text} {scenario['confidence_format']}"


def process_scenario_perturbations(
    evaluators: Dict[str, Evaluator],
    scenarios: Sequence[Dict],
    output_dir: str,
    include_original: bool = True,
    max_per_scenario: Optional[int] = None,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    """Evaluate every (model, scenario, perturbation) triple with resume."""
    log = log or SessionLogger()
    os.makedirs(output_dir, exist_ok=True)
    processed = ProcessedSet(os.path.join(output_dir, "processed_triples.json"))
    rows_path = os.path.join(output_dir, "raw_results.csv")
    rows: List[Dict] = (
        pd.read_csv(rows_path).to_dict("records") if os.path.exists(rows_path) else []
    )
    total = sum(
        (len(s["perturbations_with_irrelevant"][:max_per_scenario])
         if max_per_scenario else len(s["perturbations_with_irrelevant"]))
        + (1 if include_original else 0)
        for s in scenarios
    ) * len(evaluators)
    progress = Progress(total, path=os.path.join(output_dir, "progress.json"))

    def run_one(model: str, evaluate: Evaluator, scenario: Dict, pid, text: str, extra: Dict):
        key = (model, scenario["scenario_name"], pid)
        if key in processed:
            return
        try:
            reply = evaluate(confidence_prompt(scenario, text))
            confidence = extract_final_number(reply)
        except Exception as err:  # keep the sweep alive past broken calls
            reply, confidence = f"ERROR: {str(err)[:100]}", None
        rows.append(
            {
                "model": model,
                "scenario_name": scenario["scenario_name"],
                "perturbation_id": pid,
                "response_text": str(reply)[:500],
                "confidence": confidence,
                **extra,
            }
        )
        processed.add(key, flush=False)
        progress.update(1, model=model, scenario=scenario["scenario_name"])

    for model, evaluate in evaluators.items():
        for scenario in scenarios:
            perturbations = scenario["perturbations_with_irrelevant"]
            if max_per_scenario:
                perturbations = perturbations[:max_per_scenario]
            if include_original:
                run_one(model, evaluate, scenario, "original", scenario["original_main"],
                        {"irrelevant_statement": "", "position_index": -1,
                         "position_description": "original"})
            for p in perturbations:
                run_one(
                    model, evaluate, scenario, p["perturbation_id"], p["perturbed_text"],
                    {
                        "irrelevant_statement": p["irrelevant_statement"],
                        "position_index": p["position_index"],
                        "position_description": p["position_description"],
                    },
                )
            processed.flush()
            pd.DataFrame(rows).to_csv(rows_path, index=False)
            log(f"{model} / {scenario['scenario_name']}: checkpointed ({len(rows)} rows)")
    df = pd.DataFrame(rows, columns=RESULT_COLUMNS)
    df.to_csv(rows_path, index=False)
    return df


def consistency_statistics(df: pd.DataFrame) -> pd.DataFrame:
    """Per (model, scenario): mean/std/95% interval width of confidence over
    the perturbations; the original-scenario value for reference."""
    records = []
    for (model, scenario), sub in df.groupby(["model", "scenario_name"]):
        pert = sub[sub["perturbation_id"] != "original"]
        vals = pd.to_numeric(pert["confidence"], errors="coerce").dropna().to_numpy()
        orig = sub[sub["perturbation_id"] == "original"]
        orig_conf = (
            pd.to_numeric(orig["confidence"], errors="coerce").iloc[0]
            if len(orig)
            else np.nan
        )
        rec = {
            "model": model,
            "scenario_name": scenario,
            "n": int(vals.size),
            "original_confidence": float(orig_conf) if pd.notna(orig_conf) else np.nan,
        }
        if vals.size:
            p = np.percentile(vals, [2.5, 97.5])
            rec.update(
                mean=float(vals.mean()), std=float(vals.std()),
                p2_5=float(p[0]), p97_5=float(p[1]),
                ci_width=float(p[1] - p[0]),
            )
        records.append(rec)
    return pd.DataFrame(records)


def write_outputs(df: pd.DataFrame, stats: pd.DataFrame, output_dir: str,
                  make_figures: bool = True) -> Dict[str, str]:
    os.makedirs(output_dir, exist_ok=True)
    paths = {
        "csv": os.path.join(output_dir, "raw_results.csv"),
        "xlsx": os.path.join(output_dir, "results.xlsx"),
        "stats_csv": os.path.join(output_dir, "consistency_stats.csv"),
        "stats_json": os.path.join(output_dir, "consistency_stats.json"),
    }
    df.to_csv(paths["csv"], index=False)
    write_xlsx(df, paths["xlsx"])
    stats.to_csv(paths["stats_csv"], index=False)
    with open(paths["stats_json"], "w") as f:
        json.dump(stats.to_dict("records"), f, indent=2, default=float)
    if make_figures:
        for model in df["model"].unique():
            sub = df[(df["model"] == model) & (df["perturbation_id"] != "original")]
            groups = {
                scenario: pd.to_numeric(g["confidence"], errors="coerce").dropna().tolist()
                for scenario, g in sub.groupby("scenario_name")
            }
            path = figures.violin_by_group(
                groups, f"{model} — confidence across irrelevant insertions",
                os.path.join(output_dir, f"violin_{str(model).replace('/', '--')}.png"),
            )
            if path:
                paths[f"violin_{model}"] = path
    return paths
