"""Prompt-similarity report: validate rephrasings against originals.

Rebuild of calculate_prompt_similarity.py:209-343: run the similarity engine
over every scenario of perturbations.json and write the
``original_vs_rephrasings_similarity.xlsx`` summary workbook.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import pandas as pd

from ..stats.similarity import calculate_all_similarities
from ..utils.xlsx import write_xlsx


def similarity_report(
    perturbation_records: Sequence[Dict],
    output_dir: str,
    max_rephrasings: Optional[int] = None,
    embedding_model=None,
) -> pd.DataFrame:
    """Per-scenario similarity summary -> Excel + per-pair CSVs."""
    os.makedirs(output_dir, exist_ok=True)
    summary_rows: List[Dict] = []
    for idx, record in enumerate(perturbation_records):
        rephrasings = record["rephrasings"]
        if max_rephrasings:
            rephrasings = rephrasings[:max_rephrasings]
        if not rephrasings:
            continue
        result = calculate_all_similarities(
            record["original_main"], rephrasings, embedding_model=embedding_model
        )
        pd.DataFrame(result["original_vs_rephrasings"]).to_csv(
            os.path.join(output_dir, f"scenario_{idx + 1}_original_vs_rephrasings.csv"),
            index=False,
        )
        for metric, stats in result["summary_stats"].items():
            summary_rows.append(
                {
                    "scenario": idx + 1,
                    "metric": metric,
                    "n_rephrasings": len(rephrasings),
                    **{f"orig_{k}": v for k, v in stats["original_vs_rephrasings"].items()},
                    **{f"pair_{k}": v for k, v in stats["pairwise_rephrasings"].items()},
                }
            )
    summary = pd.DataFrame(summary_rows)
    write_xlsx(summary, os.path.join(output_dir, "original_vs_rephrasings_similarity.xlsx"))
    return summary
