"""Prompt-similarity report: validate rephrasings against originals.

Rebuild of calculate_prompt_similarity.py:209-343: run the similarity engine
over every scenario of perturbations.json and write the
``original_vs_rephrasings_similarity.xlsx`` summary workbook.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import pandas as pd

from ..stats.similarity import calculate_all_similarities
from ..utils.xlsx import write_xlsx


def load_embedding_model(name: str = "all-MiniLM-L6-v2", log=print):
    """Optional sentence-transformers embedding model, gated exactly like
    the reference (calculate_prompt_similarity.py:26-32, 221-231): None
    when the package is missing or the model cannot load (e.g. zero-egress
    environments), with a warning — the report then runs without the
    ``embedding_cosine_similarity`` column, never fails."""
    try:
        from sentence_transformers import SentenceTransformer
    except ImportError:
        log("Warning: sentence-transformers not available. "
            "Embedding similarity will be skipped.")
        return None
    import socket

    prev_timeout = socket.getdefaulttimeout()
    try:
        # Zero-egress environments HANG on the hub download rather than
        # erroring (even under huggingface_hub's own 10 s request
        # timeouts, which cover the HTTP layer but not every socket the
        # load opens); a socket-level timeout turns that into the
        # reference's warn-and-continue path within seconds instead of
        # minutes.  setdefaulttimeout is PROCESS-GLOBAL: for the duration
        # of this load, sockets opened by other threads inherit the 10 s
        # timeout too.  Every caller of this loader (the `similarity
        # --embeddings` CLI leg and similarity_report) is single-threaded,
        # so nothing else opens sockets while it runs; the previous value
        # is restored on exit either way.
        socket.setdefaulttimeout(10.0)
        log(f"Loading embedding model: {name}")
        return SentenceTransformer(name)
    except Exception as err:
        log(f"Warning: Could not load embedding model: {err}")
        log("Continuing without embedding similarity...")
        return None
    finally:
        socket.setdefaulttimeout(prev_timeout)


def similarity_report(
    perturbation_records: Sequence[Dict],
    output_dir: str,
    max_rephrasings: Optional[int] = None,
    embedding_model=None,
) -> pd.DataFrame:
    """Per-scenario similarity summary -> Excel + per-pair CSVs."""
    os.makedirs(output_dir, exist_ok=True)
    summary_rows: List[Dict] = []
    for idx, record in enumerate(perturbation_records):
        rephrasings = record["rephrasings"]
        if max_rephrasings:
            rephrasings = rephrasings[:max_rephrasings]
        if not rephrasings:
            continue
        result = calculate_all_similarities(
            record["original_main"], rephrasings, embedding_model=embedding_model
        )
        pd.DataFrame(result["original_vs_rephrasings"]).to_csv(
            os.path.join(output_dir, f"scenario_{idx + 1}_original_vs_rephrasings.csv"),
            index=False,
        )
        for metric, stats in result["summary_stats"].items():
            summary_rows.append(
                {
                    "scenario": idx + 1,
                    "metric": metric,
                    "n_rephrasings": len(rephrasings),
                    **{f"orig_{k}": v for k, v in stats["original_vs_rephrasings"].items()},
                    **{f"pair_{k}": v for k, v in stats["pairwise_rephrasings"].items()},
                }
            )
    summary = pd.DataFrame(summary_rows)
    write_xlsx(summary, os.path.join(output_dir, "original_vs_rephrasings_similarity.xlsx"))
    return summary
