"""Per-model perturbation statistics report.

Rebuild of analyze_perturbation_results.py's ``analyze_model`` orchestration
(:1719-1960) + the main-entry split by ``Model`` column (:1963-2026): per
scenario — relative probability from Token_1/Token_2, summary stats, KS/AD
normality, the clipped-normal Monte-Carlo fit, QQ/histogram/model-overlay
figures, LaTeX tables; then the combined jitter panels, Cohen's kappa between
scenario pairs, and the output/confidence compliance audits.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..stats.compliance import check_confidence_compliance, check_output_compliance
from ..stats.correlations import cohens_kappa
from ..stats.normality import normality_tests
from ..stats.truncated import fit_clipped_normal
from ..viz import figures, latex


def add_relative_prob(df: pd.DataFrame) -> pd.DataFrame:
    """Relative_Prob = T1/(T1+T2) with non-finite guard (:1737-1760)."""
    df = df.copy()
    t1 = pd.to_numeric(df["Token_1_Prob"], errors="coerce")
    t2 = pd.to_numeric(df["Token_2_Prob"], errors="coerce")
    total = t1 + t2
    df["Relative_Prob"] = np.where(total > 0, t1 / total.replace(0, np.nan), np.nan)
    return df


def analyze_model(
    df: pd.DataFrame,
    model_name: str,
    scenarios: Sequence[Dict],
    output_dir: str,
    n_simulations: int = 100_000,
    seed: int = 42,
    make_figures: bool = True,
) -> Dict:
    """Full per-model report; returns a dict of all computed statistics and
    writes figures/tables under ``output_dir``."""
    os.makedirs(output_dir, exist_ok=True)
    df = add_relative_prob(df)
    report: Dict = {"model": model_name, "scenarios": []}
    latex_tables: List[str] = []

    prob_panels: Dict[str, Sequence[float]] = {}
    conf_panels: Dict[str, Sequence[float]] = {}

    for idx, scenario in enumerate(scenarios):
        sub = df[df["Original Main Part"] == scenario["original_main"]]
        if len(sub) < 2:
            report["scenarios"].append({"scenario": idx + 1, "skipped": True, "n": len(sub)})
            continue
        probs = sub["Relative_Prob"].to_numpy(dtype=float)
        conf = pd.to_numeric(sub.get("Weighted Confidence"), errors="coerce").to_numpy(dtype=float)
        name = f"Scenario {idx + 1}"
        prob_panels[name] = probs
        conf_panels[name] = conf

        rec: Dict = {"scenario": idx + 1, "n": int(np.isfinite(probs).sum())}
        finite = probs[np.isfinite(probs)]
        if finite.size:
            p = np.percentile(finite, [2.5, 97.5])
            rec["summary"] = {
                "mean": float(finite.mean()),
                # ddof=1 to match the reference's pandas describe() stats
                # (analyze_perturbation_results.py:1789-1845); single-sample
                # std is NaN, like pandas
                "std": float(finite.std(ddof=1)) if finite.size > 1 else float("nan"),
                "median": float(np.median(finite)),
                "p2_5": float(p[0]),
                "p97_5": float(p[1]),
                "ci_width": float(p[1] - p[0]),
            }
        rec["normality"] = normality_tests(probs, label=name)
        trunc, simulated = fit_clipped_normal(probs, n_simulations=n_simulations, seed=seed)
        rec["truncated_normal"] = trunc
        # confidence rescaled /100 gets the same treatment (:1867-1909)
        conf01 = conf / 100.0
        rec["confidence_normality"] = normality_tests(conf01, label=f"{name} confidence")
        conf_trunc, conf_sim = fit_clipped_normal(conf01, n_simulations=n_simulations, seed=seed)
        rec["confidence_truncated_normal"] = conf_trunc

        latex_tables.append(
            latex.summary_stats_table(
                probs, f"{model_name}-s{idx + 1}",
                f"{model_name} — scenario {idx + 1} relative probability",
            )
        )

        if make_figures:
            base = os.path.join(output_dir, f"scenario_{idx + 1}")
            figures.probability_histogram(probs, f"{model_name} — {name}", base + "_prob_hist.png")
            figures.probability_histogram(
                conf, f"{model_name} — {name} confidence", base + "_conf_hist.png",
                xlabel="Weighted confidence",
            )
            figures.qq_plot(probs, f"{model_name} — {name}", base + "_qq.png")
            if trunc.get("fit") == "ok" and len(simulated):
                figures.truncated_model_plot(
                    probs, simulated, f"{model_name} — {name} clipped-normal",
                    base + "_truncated.png", ks_statistic=trunc.get("ks_stat"),
                )
        report["scenarios"].append(rec)

    if make_figures and prob_panels:
        figures.jitter_strip_panels(
            prob_panels, f"{model_name} — relative probability by scenario",
            os.path.join(output_dir, "combined_probability.png"),
        )
        figures.jitter_strip_panels(
            conf_panels, f"{model_name} — weighted confidence by scenario",
            os.path.join(output_dir, "combined_confidence.png"),
            ylabel="Weighted confidence", ylim=(0, 100),
        )

    # Cohen's kappa between binary (>= 0.5) judgments of scenario pairs (:1095-1190)
    kappas = {}
    names = list(prob_panels)
    for i in range(len(names)):
        for j in range(i + 1, len(names)):
            a = np.asarray(prob_panels[names[i]], float)
            b = np.asarray(prob_panels[names[j]], float)
            n = min(a.size, b.size)
            ok = np.isfinite(a[:n]) & np.isfinite(b[:n])
            if ok.sum() >= 3:
                kappas[f"{names[i]} vs {names[j]}"] = cohens_kappa(
                    (a[:n][ok] >= 0.5).astype(int), (b[:n][ok] >= 0.5).astype(int)
                )
    report["scenario_pair_kappa"] = kappas

    compliance = check_output_compliance(df)
    conf_compliance = check_confidence_compliance(df)
    report["compliance"] = compliance.to_dict("records")
    report["confidence_compliance"] = conf_compliance.to_dict("records")
    if len(compliance):
        latex_tables.append(latex.compliance_table(compliance))
    if len(conf_compliance):
        latex_tables.append(latex.confidence_compliance_table(conf_compliance))

    with open(os.path.join(output_dir, "tables.tex"), "w") as f:
        f.write(latex.standalone_document(latex_tables, title=f"{model_name} perturbation analysis"))
    return report


def analyze_workbook(
    df: pd.DataFrame,
    scenarios: Sequence[Dict],
    output_root: str,
    **kwargs,
) -> Dict[str, Dict]:
    """Split a multi-model workbook by ``Model`` and report each (:1963-2026)."""
    out = {}
    for model_name in df["Model"].unique():
        model_dir = os.path.join(output_root, str(model_name).replace("/", "--"))
        out[model_name] = analyze_model(
            df[df["Model"] == model_name], model_name, scenarios, model_dir, **kwargs
        )
    return out
