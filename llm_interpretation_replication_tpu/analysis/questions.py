"""Question-list loaders for the ordinary-meaning evaluation.

Rebuilds evaluate_closed_source_models.py:51-81 (first 50 prompts of the
instruct CSV + 50 parsed out of the survey-2 Qualtrics headers) and
extract_survey2_questions.py (header extraction incl. attention-check skip).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import pandas as pd


def question_from_header(text) -> Optional[str]:
    """Question text out of a Qualtrics column header: the last ``' - '``
    segment when it ends with '?' (shared by every survey-header consumer)."""
    if not isinstance(text, str) or " - " not in text:
        return None
    question = text.split(" - ")[-1].strip()
    return question if question.endswith("?") else None


def extract_survey2_questions(survey_csv: str) -> Tuple[List[str], Dict[str, str]]:
    """Unique questions (and their columns) from a Qualtrics header row,
    skipping the *_8 attention checks."""
    df = pd.read_csv(survey_csv)
    headers = df.iloc[0]
    questions: List[str] = []
    question_to_col: Dict[str, str] = {}
    for col in df.columns:
        if col.startswith("Q") and "_" in col and not col.endswith("_8"):
            text = headers[col]
            if pd.notna(text) and isinstance(text, str) and " - " in text:
                question = text.split(" - ")[-1].strip()
                if question not in questions:
                    questions.append(question)
                    question_to_col[question] = col
    return questions, question_to_col


def load_ordinary_meaning_questions(
    instruct_csv: str,
    survey2_csv: str,
    n_part1: int = 50,
    n_part2: int = 50,
) -> List[str]:
    """First ``n_part1`` unique prompts of the instruct comparison CSV + the
    first ``n_part2`` questions parsed from the survey-2 headers (the
    reference's marker filter: columns containing 'Left = No, Right = Yes')."""
    df1 = pd.read_csv(instruct_csv)
    questions: List[str] = list(df1["prompt"].unique()[:n_part1])
    survey2 = pd.read_csv(survey2_csv, skiprows=1)
    part2: List[str] = []
    for col in survey2.columns:
        if "Left = No, Right = Yes" in col:
            q = question_from_header(col)
            if q is not None and q not in part2:
                part2.append(q)
    questions.extend(part2[:n_part2])
    return questions


def write_question_list(questions: List[str], path: str) -> None:
    with open(path, "w") as f:
        for q in questions:
            f.write(q + "\n")


def load_human_survey_means(
    part1_csv: str,
    part2_csv: str,
    return_full: bool = False,
):
    """Pooled per-question human means from BOTH survey parts, 0-1 scale
    (evaluate_closed_source_models.py:83-159).

    Unlike the preregistered survey pipeline (survey/pipeline.py), this loader
    applies NO exclusions — the closed-source comparison pools every numeric
    response under each 'Left = No, Right = Yes' column, exactly as the
    reference does; questions appearing in both parts pool across parts.
    With ``return_full`` also returns question -> list of responses.
    """
    import numpy as np

    responses: Dict[str, List[float]] = {}
    for path in (part1_csv, part2_csv):
        df = pd.read_csv(path, skiprows=1)
        for col in df.columns:
            if "Left = No, Right = Yes" not in col:
                continue
            question = question_from_header(col)
            if question is None:
                continue
            values = pd.to_numeric(df[col], errors="coerce").dropna()
            if len(values):
                responses.setdefault(question, []).extend((values / 100.0).tolist())
    means = {q: float(np.mean(v)) for q, v in responses.items()}
    if return_full:
        return means, responses
    return means
