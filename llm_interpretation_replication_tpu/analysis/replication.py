"""One-command replication verifier.

Recomputes every headline table of the paper through THIS framework's
statistics pipeline and diffs each number against the published values with
CI-overlap PASS/FAIL verdicts — the harness the per-piece commands
(``run-100q``, ``analyze-mae-100q``, ``model-comparison``, ``analyze-survey``)
compose into but never judged before.

Published targets are transcribed from the paper sources mirrored in
BASELINE.md:

- Table 3 (MAE vs human mean) / Table 4 (MAE differences vs baselines):
  ``/root/reference/main.tex:375-417``
- Table 5 (base→instruct MAE): ``/root/reference/main.tex:432-446``
- Appendix inter-LLM correlations: ``main_online_appendix.tex:517-533``
- Appendix cross-prompt correlations: ``main_online_appendix.tex:582-621``

Two operating modes per check:

- **Recorded-artifact mode** (always available when ``/root/reference`` is
  mounted): feed the reference's committed result artifacts through our
  statistics stack — verifies the downstream pipeline reproduces the paper.
- **Snapshot mode** (``snapshots=`` / ``--snapshots``): additionally run the
  Table-5 sweep with real local HF checkpoints through the TPU engine first
  (run_base_vs_instruct_100q.py:514-599's role), then judge its output
  against the published Table 5.  Without snapshots that check reports
  SKIP — the raw reference CSV for Table 5 was never published
  (``.MISSING_LARGE_BLOBS``), so there is nothing to replay offline.

Verdict rule: a metric PASSES when the recomputed point estimate lands
inside the published 95% CI, the published point lands inside the recomputed
CI, or the two CIs overlap (statistical parity per SURVEY.md §7 — bf16/int8
arithmetic makes bitwise parity the wrong bar); where the paper publishes
only a point value, equality to the paper's printed precision is required.
Significance calls (ns/*/**/***) must match categorically.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

# --------------------------------------------------------------------------
# Published values (rounded exactly as printed in the paper)
# --------------------------------------------------------------------------

TABLE3_MAE = {
    # model -> (mae, ci_lo, ci_hi)   main.tex:375-395
    "Equanimity": (0.175, 0.154, 0.196),
    "Normal": (0.172, 0.147, 0.198),
    "GPT": (0.197, 0.171, 0.224),
    "Claude": (0.229, 0.201, 0.258),
    "Gemini": (0.241, 0.216, 0.268),
}

TABLE4_DIFFS = {
    # (model, baseline) -> (diff, significance)   main.tex:396-417
    ("GPT", "Equanimity"): (0.022, "ns"),
    ("GPT", "Normal"): (0.027, "ns"),
    ("Claude", "Equanimity"): (0.054, "**"),
    ("Claude", "Normal"): (0.059, "***"),
    ("Gemini", "Equanimity"): (0.067, "***"),
    ("Gemini", "Normal"): (0.072, "***"),
}

TABLE5_FAMILIES = {
    # family -> (base_mae, (lo,hi), instruct_mae, (lo,hi), diff, (lo,hi), sig)
    # main.tex:432-446
    "Falcon": (0.333, (0.299, 0.370), 0.468, (0.427, 0.506),
               0.135, (0.082, 0.188), "***"),
    "StableLM": (0.369, (0.329, 0.407), 0.341, (0.304, 0.378),
                 -0.030, (-0.084, 0.024), "ns"),
    "RedPajama": (0.313, (0.230, 0.386), 0.437, (0.320, 0.543),
                  0.122, (-0.010, 0.254), "*"),
}

APPENDIX_INTER_LLM = {
    # main_online_appendix.tex:517-533
    "mean_rho": (0.051, (-0.015, 0.126)),
    "median_rho": (0.045, (-0.065, 0.147)),
    "std_rho": (0.220, (0.209, 0.327)),
}

APPENDIX_CROSS_PROMPT = {
    # main_online_appendix.tex:582-621
    "human": (0.285, (0.238, 0.314)),
    "llm": (0.052, (-0.003, 0.155)),
    "difference": (0.212, (0.126, 0.292)),
}

SIG_LEVELS = (("***", 0.01), ("**", 0.05), ("*", 0.10))


def significance_category(p: float) -> str:
    """Star category from the p-value AT THE PAPER'S PRINTED PRECISION
    (3 decimals): the paper stars Claude-vs-Equanimity ** at recorded
    p=0.0098 because it prints p=0.010 — the stars follow the rounded
    value, not the raw bootstrap estimate."""
    p = round(p, 3)
    for stars, level in SIG_LEVELS:
        if p < level:
            return stars
    return "ns"


def _ci_overlap(a_lo, a_hi, b_lo, b_hi) -> bool:
    return a_lo <= b_hi and b_lo <= a_hi


def _check(table: str, metric: str, published, published_ci,
           computed, computed_ci=None, extra: str = "") -> Dict:
    """One verdict row.  PASS when point-in-CI either direction or the CIs
    overlap; point-only targets require match at printed precision."""
    if computed is None or (isinstance(computed, float) and np.isnan(computed)):
        verdict = "FAIL"
        detail = "no computed value"
    elif published_ci is None and computed_ci is None:
        decimals = max(len(str(published).split(".")[-1]), 1)
        verdict = "PASS" if round(computed, decimals) == published else "FAIL"
        detail = f"point match at {decimals} decimals"
    else:
        plo, phi = published_ci if published_ci else (published, published)
        clo, chi = computed_ci if computed_ci else (computed, computed)
        ok = (plo <= computed <= phi) or (clo <= published <= chi) \
            or _ci_overlap(plo, phi, clo, chi)
        verdict = "PASS" if ok else "FAIL"
        detail = "CI overlap"
    if extra:
        detail = f"{detail}; {extra}"
    return {
        "table": table, "metric": metric,
        "published": published, "published_ci": published_ci,
        "computed": None if computed is None else float(computed),
        "computed_ci": computed_ci, "verdict": verdict, "detail": detail,
    }


def _skip(table: str, metric: str, reason: str) -> Dict:
    return {"table": table, "metric": metric, "published": None,
            "published_ci": None, "computed": None, "computed_ci": None,
            "verdict": "SKIP", "detail": reason}


# --------------------------------------------------------------------------
# Checks
# --------------------------------------------------------------------------

def check_tables_3_4(results_csv: str, survey1: str, survey2: str,
                     n_bootstrap: int = 10_000) -> List[Dict]:
    """Tables 3-4 through analysis/closed_source_eval.compare_with_human_data
    (the path regression-pinned bit-exactly in test_published_regression)."""
    from .closed_source_eval import compare_with_human_data
    from .questions import load_human_survey_means

    if not os.path.exists(results_csv):
        return [_skip("Table 3", "all", f"missing {results_csv}"),
                _skip("Table 4", "all", f"missing {results_csv}")]
    df = pd.read_csv(results_csv)
    human_means = load_human_survey_means(survey1, survey2)
    human_std = float(np.std(list(human_means.values())))
    cmp = compare_with_human_data(df, human_means, human_std=human_std,
                                  n_bootstrap=n_bootstrap, seed=42)
    rows = []
    for model, (mae, lo, hi) in TABLE3_MAE.items():
        got = cmp["mae"].get(model)
        rows.append(_check(
            "Table 3", f"MAE {model}", mae, (lo, hi),
            got and got["mae"],
            got and (got["ci_lower"], got["ci_upper"]),
        ))
    for (model, baseline), (diff, sig) in TABLE4_DIFFS.items():
        got = (cmp.get("differences", {}).get(model) or {}).get(baseline)
        if not got:
            rows.append(_check("Table 4", f"{model} vs {baseline}", diff,
                               None, None))
            continue
        got_sig = significance_category(got["p_value"])
        row = _check(
            "Table 4", f"MAE diff {model} vs {baseline}", diff, None,
            got["diff"], (got["ci_lower"], got["ci_upper"]),
            extra=f"significance {got_sig} (published {sig})",
        )
        if row["verdict"] == "PASS" and got_sig != sig:
            row["verdict"] = "FAIL"
        rows.append(row)
    return rows


def check_table5(results_100q_csv: Optional[str], survey1: str,
                 survey2: str) -> List[Dict]:
    """Table 5 through survey/mae_100q.analyze_families.  ``results_100q_csv``
    comes from a real run-100q sweep (snapshot mode) — the reference never
    committed its own raw CSV, so without one this reports SKIP."""
    if not results_100q_csv or not os.path.exists(results_100q_csv):
        return [_skip("Table 5", f"{fam} base->instruct",
                      "requires --snapshots (or --results-100q from a "
                      "finished run-100q sweep); raw reference CSV "
                      "unpublished")
                for fam in TABLE5_FAMILIES]
    from ..__main__ import _mae_100q_families

    res, _meta = _mae_100q_families(results_100q_csv, [survey1, survey2])
    rows = []
    for fam, (bm, bci, im, ici, diff, dci, sig) in TABLE5_FAMILIES.items():
        got = res.get(fam)
        if not got or got.get("excluded"):
            rows.append(_check("Table 5", f"{fam} base->instruct", diff, dci,
                               None,
                               extra=got and got.get("reason", "excluded")))
            continue
        got_sig = significance_category(got["p_value"])
        for name, pub, pci, val, ci in (
            ("base MAE", bm, bci, got["base_mae"], None),
            ("instruct MAE", im, ici, got["instruct_mae"], None),
            ("diff", diff, dci, got["observed_diff"],
             (got["ci_lower"], got["ci_upper"])),
        ):
            row = _check("Table 5", f"{fam} {name}", pub, pci, val, ci)
            if name == "diff" and row["verdict"] == "PASS" and got_sig != sig:
                row["verdict"] = "FAIL"
                row["detail"] += f"; significance {got_sig} != published {sig}"
            rows.append(row)
    return rows


def check_appendix_inter_llm(instruct_csv: str,
                             n_bootstrap: int = 1000) -> List[Dict]:
    """Online-appendix inter-LLM correlation summary through
    stats/correlations (28 non-degenerate pairs)."""
    from ..stats.correlations import (
        correlation_summary_bootstrap,
        pivot_model_values,
    )

    if not os.path.exists(instruct_csv):
        return [_skip("Appendix inter-LLM", "all", f"missing {instruct_csv}")]
    pivot = pivot_model_values(pd.read_csv(instruct_csv))
    summary = correlation_summary_bootstrap(pivot, n_bootstrap=n_bootstrap,
                                            seed=42)
    return [
        _check("Appendix inter-LLM", "mean pairwise rho",
               *APPENDIX_INTER_LLM["mean_rho"],
               summary["mean"], tuple(summary["mean_ci"]),
               extra=f"{summary['n_pairs']} pairs"),
        _check("Appendix inter-LLM", "median pairwise rho",
               *APPENDIX_INTER_LLM["median_rho"],
               summary["median"], tuple(summary["median_ci"])),
        _check("Appendix inter-LLM", "std of pairwise rho",
               *APPENDIX_INTER_LLM["std_rho"],
               summary["std"], tuple(summary["std_ci"])),
    ]


def check_appendix_cross_prompt(survey_csvs: List[str], llm_csv: str,
                                n_bootstrap: int = 200) -> List[Dict]:
    """Online-appendix human-vs-LLM cross-prompt correlations through
    survey/pipeline (exclusions + 10-question groups + bootstrap)."""
    from ..survey.pipeline import (
        apply_exclusion_criteria,
        cross_prompt_difference_ci,
        human_cross_prompt_correlations,
        llm_cross_prompt_correlations,
        load_and_clean_survey_data,
        match_survey_to_llm_questions,
    )

    if not all(os.path.exists(p) for p in survey_csvs + [llm_csv]):
        return [_skip("Appendix cross-prompt", "all", "missing inputs")]
    df, cols = load_and_clean_survey_data(survey_csvs)
    df, _ = apply_exclusion_criteria(df, cols)
    llm_df = pd.read_csv(llm_csv)
    _, mapping = match_survey_to_llm_questions(llm_df, survey_csvs)
    hum = human_cross_prompt_correlations(df, cols, n_bootstrap=n_bootstrap,
                                          seed=42)
    llm = llm_cross_prompt_correlations(llm_df, mapping,
                                        n_bootstrap=n_bootstrap, seed=42)
    diff = cross_prompt_difference_ci(hum, llm, n_bootstrap=n_bootstrap,
                                      seed=42)
    return [
        _check("Appendix cross-prompt", "human mean correlation",
               *APPENDIX_CROSS_PROMPT["human"],
               hum["mean_correlation"],
               (hum["ci_lower"], hum["ci_upper"])),
        _check("Appendix cross-prompt", "LLM mean correlation",
               *APPENDIX_CROSS_PROMPT["llm"],
               llm["mean_correlation"],
               (llm["ci_lower"], llm["ci_upper"])),
        _check("Appendix cross-prompt", "human - LLM difference",
               *APPENDIX_CROSS_PROMPT["difference"],
               diff["difference"],
               (diff["ci_lower"], diff["ci_upper"])),
    ]


# --------------------------------------------------------------------------
# Orchestration
# --------------------------------------------------------------------------

def run_snapshot_sweep(run_config, output_dir: str) -> str:
    """Snapshot mode: run the real Table-5 sweep (run-100q) with local HF
    checkpoints through the TPU engine; returns the results CSV path."""
    from ..sweeps import run_sweep
    from ..__main__ import _engine_factory

    os.makedirs(output_dir, exist_ok=True)
    results_csv = os.path.join(output_dir, "base_vs_instruct_100q_results.csv")

    run_sweep(
        _engine_factory(run_config),
        checkpoint_path=os.path.join(
            output_dir, "base_vs_instruct_100q_checkpoint.json"),
        results_csv=results_csv,
    )
    return results_csv


def verify_replication(
    reference_root: str = "/root/reference",
    results_100q_csv: Optional[str] = None,
    n_bootstrap: int = 10_000,
    cross_prompt_bootstrap: int = 200,
) -> Dict:
    """Run every check against the recorded artifacts under
    ``reference_root`` (plus ``results_100q_csv`` for Table 5 when a sweep
    output exists).  Returns {"checks": [...], "n_pass", "n_fail", "n_skip",
    "ok"} — ``ok`` is True when nothing FAILED (SKIPs don't fail the run)."""
    ref = reference_root
    checks: List[Dict] = []
    checks += check_tables_3_4(
        f"{ref}/results/closed_source_evaluation/closed_source_evaluation_results.csv",
        f"{ref}/data/word_meaning_survey_results.csv",
        f"{ref}/data/word_meaning_survey_results_part_2.csv",
        n_bootstrap=n_bootstrap,
    )
    checks += check_table5(
        results_100q_csv,
        f"{ref}/data/word_meaning_survey_results.csv",
        f"{ref}/data/word_meaning_survey_results_part_2.csv",
    )
    checks += check_appendix_inter_llm(
        f"{ref}/data/instruct_model_comparison_results.csv")
    checks += check_appendix_cross_prompt(
        [f"{ref}/data/word_meaning_survey_results.csv",
         f"{ref}/data/word_meaning_survey_results_part_2.csv"],
        f"{ref}/data/instruct_model_comparison_results_combined.csv",
        n_bootstrap=cross_prompt_bootstrap,
    )
    n_pass = sum(c["verdict"] == "PASS" for c in checks)
    n_fail = sum(c["verdict"] == "FAIL" for c in checks)
    n_skip = sum(c["verdict"] == "SKIP" for c in checks)
    return {"checks": checks, "n_pass": n_pass, "n_fail": n_fail,
            "n_skip": n_skip, "ok": n_fail == 0}


def format_report(result: Dict) -> str:
    """Human-readable per-table PASS/FAIL report."""
    lines = ["REPLICATION VERIFICATION", "=" * 60]
    current = None
    for c in result["checks"]:
        if c["table"] != current:
            current = c["table"]
            lines.append("")
            lines.append(current)
            lines.append("-" * len(current))
        pub = c["published"]
        ci = c["published_ci"]
        pub_s = "" if pub is None else (
            f" published {pub}" + (f" [{ci[0]}, {ci[1]}]" if ci else ""))
        got = c["computed"]
        got_ci = c["computed_ci"]
        got_s = "" if got is None else (
            f" computed {got:.3f}"
            + (f" [{got_ci[0]:.3f}, {got_ci[1]:.3f}]" if got_ci else ""))
        lines.append(f"[{c['verdict']:4s}] {c['metric']}:{pub_s}{got_s}"
                     + (f"  ({c['detail']})" if c["verdict"] != "PASS" else ""))
    lines.append("")
    lines.append(f"{result['n_pass']} PASS, {result['n_fail']} FAIL, "
                 f"{result['n_skip']} SKIP -> "
                 + ("REPLICATION OK" if result["ok"] else "REPLICATION FAILED"))
    return "\n".join(lines)
