"""Three-model confidence combiner + stacked visualization.

Rebuild of combine_model_confidence_analysis.py's ``ModelConfidenceAnalyzer``
(:23-610), run_three_model_analysis.py / run_combined_confidence_analysis.py
wiring, and the stacked Figure-5/6 builders
(create_three_model_stacked_visualization.py, create_combined_visualization.py).
"""

from __future__ import annotations

import os
from itertools import combinations
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd
from scipy.stats import pearsonr, spearmanr

from ..viz import figures, latex


class ModelConfidenceAnalyzer:
    """Joins per-model perturbation-sweep confidence frames on
    (Original Main Part, Rephrased Main Part) and computes cross-model
    statistics."""

    def __init__(self, frames: Dict[str, pd.DataFrame],
                 confidence_col: str = "Confidence Value"):
        self.confidence_col = confidence_col
        self.frames = frames
        self.combined = self._combine()

    def _combine(self) -> pd.DataFrame:
        keys = ["Original Main Part", "Rephrased Main Part"]
        combined: Optional[pd.DataFrame] = None
        for model, df in self.frames.items():
            # the reference combiner reads 'Confidence Value' unconditionally
            # (combine_model_confidence_analysis.py:52-55); fall back to the
            # weighted column only when a frame lacks it
            col = (self.confidence_col if self.confidence_col in df.columns
                   else "Weighted Confidence")
            sub = df[keys + [col]].copy()
            sub[f"confidence_{model}"] = pd.to_numeric(sub[col], errors="coerce")
            sub = sub.drop(columns=[col])
            sub = sub.drop_duplicates(subset=keys)
            combined = sub if combined is None else combined.merge(sub, on=keys, how="outer")
        return combined if combined is not None else pd.DataFrame()

    @property
    def models(self) -> List[str]:
        return list(self.frames)

    def summary_stats(self) -> pd.DataFrame:
        records = []
        for scenario, sub in self.combined.groupby("Original Main Part"):
            for model in self.models:
                vals = sub[f"confidence_{model}"].dropna().to_numpy(dtype=float)
                if not vals.size:
                    continue
                p = np.percentile(vals, [2.5, 97.5])
                records.append(
                    {
                        "scenario": scenario[:60],
                        "model": model,
                        "n": int(vals.size),
                        "mean": float(vals.mean()),
                        # ddof=1: the reference's pandas .std() convention
                        # (pinned against per_prompt_statistics.csv); a
                        # single sample has no ddof-1 std, like pandas
                        "std": float(vals.std(ddof=1)) if vals.size > 1 else float("nan"),
                        "p2_5": float(p[0]),
                        "p97_5": float(p[1]),
                        "ci_width": float(p[1] - p[0]),
                    }
                )
        return pd.DataFrame(records)

    def cross_model_correlations(self) -> pd.DataFrame:
        rows = []
        for a, b in combinations(self.models, 2):
            sub = self.combined[[f"confidence_{a}", f"confidence_{b}"]].dropna()
            if len(sub) < 3:
                continue
            pr, pp = pearsonr(sub.iloc[:, 0], sub.iloc[:, 1])
            sr, sp = spearmanr(sub.iloc[:, 0], sub.iloc[:, 1])
            rows.append(
                {
                    "model_1": a, "model_2": b, "n": len(sub),
                    "pearson_r": float(pr), "pearson_p": float(pp),
                    "spearman_r": float(sr), "spearman_p": float(sp),
                }
            )
        return pd.DataFrame(rows)

    def latex_summary(self) -> str:
        stats = self.summary_stats()
        lines = [
            "\\begin{tabular}{llrrrr}",
            "\\hline",
            "Scenario & Model & N & Mean & Std & CI width \\\\",
            "\\hline",
        ]
        for _, row in stats.iterrows():
            lines.append(
                f"{row['scenario'][:30]}... & {row['model']} & {row['n']} & "
                f"{row['mean']:.1f} & {row['std']:.1f} & {row['ci_width']:.1f} \\\\"
            )
        lines += ["\\hline", "\\end{tabular}"]
        return "\n".join(lines)

    def stacked_visualization(self, output_path: str, scenarios: Optional[Sequence[str]] = None):
        """One jitter-strip panel per model, stacked (Fig. 5/6 style)."""
        import matplotlib.pyplot as plt

        scenario_keys = scenarios or list(self.combined["Original Main Part"].unique())
        fig, axes = plt.subplots(
            len(self.models), 1,
            figsize=(max(8, 2.0 * len(scenario_keys)), 4 * len(self.models)),
            squeeze=False,
        )
        rng = np.random.default_rng(42)
        for ax_row, model in zip(axes, self.models):
            ax = ax_row[0]
            for i, scenario in enumerate(scenario_keys):
                vals = self.combined[self.combined["Original Main Part"] == scenario][
                    f"confidence_{model}"
                ].dropna().to_numpy(dtype=float)
                if not vals.size:
                    continue
                x = i + rng.uniform(-0.18, 0.18, vals.size)
                ax.scatter(x, vals, s=6, alpha=0.25)
                mean = vals.mean()
                lo, hi = np.percentile(vals, [2.5, 97.5])
                ax.errorbar([i], [mean], yerr=[[mean - lo], [hi - mean]], fmt="o",
                            color="black", capsize=5, zorder=5)
            ax.set_title(model)
            ax.set_ylim(0, 100)
            ax.set_xticks(range(len(scenario_keys)))
            ax.set_xticklabels([f"S{i + 1}" for i in range(len(scenario_keys))])
            ax.set_ylabel("Confidence")
        fig.tight_layout()
        os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
        fig.savefig(output_path, dpi=150, bbox_inches="tight")
        plt.close(fig)
        return output_path


def run_combined_analysis(frames: Dict[str, pd.DataFrame], output_dir: str,
                          confidence_col: str = "Confidence Value") -> Dict:
    os.makedirs(output_dir, exist_ok=True)
    analyzer = ModelConfidenceAnalyzer(frames, confidence_col=confidence_col)
    stats = analyzer.summary_stats()
    corr = analyzer.cross_model_correlations()
    stats.to_csv(os.path.join(output_dir, "combined_confidence_stats.csv"), index=False)
    corr.to_csv(os.path.join(output_dir, "cross_model_correlations.csv"), index=False)
    with open(os.path.join(output_dir, "combined_tables.tex"), "w") as f:
        f.write(analyzer.latex_summary())
    fig_path = analyzer.stacked_visualization(
        os.path.join(output_dir, "stacked_confidence.png")
    )
    return {
        "stats": stats,
        "correlations": corr,
        "figure": fig_path,
        "combined": analyzer.combined,
    }
