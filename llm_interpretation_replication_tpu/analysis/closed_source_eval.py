"""Ordinary-meaning evaluation of frontier API models (100 questions).

Rebuild of evaluate_closed_source_models.py:602-2110: per question run the
GPT/Gemini/Claude evaluators (binary + confidence) plus the random baseline,
cache every response with completeness checking and partial re-runs, write the
per-question results CSV (§2.8 schema), then compute correlations, MAE vs the
human survey with bootstrap CIs, the Always-50 and N(μ,σ) baselines, MAE
difference p-values, LaTeX tables, and the heatmap/error-strip figures.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd
from scipy.stats import pearsonr, spearmanr

from ..api_backends.cache import ResponseCache
from ..api_backends.evaluators import (
    evaluate_claude,
    evaluate_gemini_binary,
    evaluate_gemini_confidence,
    evaluate_gpt_binary,
    evaluate_gpt_confidence,
    evaluate_random_baseline,
)
from ..stats.bootstrap import bootstrap_mae, bootstrap_mae_difference
from ..viz import figures, latex

#: per-vendor pause after EACH API call (reference GPT_DELAY/GEMINI_DELAY/
#: CLAUDE_DELAY, evaluate_closed_source_models.py:39-41); single source for
#: both the evaluation loop and the orchestrator's wall-time estimate.
DEFAULT_SLEEPS = {"gpt": 0.5, "gemini": 6.0, "claude": 1.0}

#: evaluator names in the human comparison (order = report row order)
MODEL_NAMES = ("GPT", "Gemini", "Claude", "Random")

RESULT_COLUMNS = [
    "question",
    "gpt_response", "gpt_yes_prob", "gpt_no_prob", "gpt_relative_prob",
    "gpt_confidence", "gpt_weighted_confidence",
    "gemini_response", "gemini_yes_prob", "gemini_no_prob", "gemini_relative_prob",
    "gemini_confidence", "gemini_weighted_confidence",
    "claude_response", "claude_confidence",
    "random_response", "random_relative_prob", "random_confidence",
]


def evaluate_all_models(
    questions: Sequence[str],
    gpt_client=None, gpt_model: str = "gpt-4-0125-preview",
    gemini_client=None, gemini_model: str = "gemini-2.0-flash-exp",
    claude_client=None, claude_model: str = "claude-opus-4-1-20250805",
    cache: Optional[ResponseCache] = None,
    rng: Optional[np.random.Generator] = None,
    intermediate_csv: Optional[str] = None,
    intermediate_every: int = 10,
    sleep: Callable[[float], None] = lambda s: None,
    sleeps: Dict[str, float] = None,
) -> pd.DataFrame:
    """The per-question evaluation loop with cache + partial re-runs."""
    # NOTE: explicit None check — an empty ResponseCache is falsy (__len__==0)
    cache = ResponseCache() if cache is None else cache
    rng = np.random.default_rng(42) if rng is None else rng
    sleeps = sleeps or DEFAULT_SLEEPS
    rows: List[Dict] = []
    for qi, question in enumerate(questions):
        record = dict(cache.get(question) or {})
        missing = cache.missing_evaluators(question)
        if "gpt" in missing and gpt_client is not None:
            b = evaluate_gpt_binary(gpt_client, gpt_model, question)
            sleep(sleeps["gpt"])
            c = evaluate_gpt_confidence(gpt_client, gpt_model, question)
            record.update(
                gpt_response=b["response"], gpt_yes_prob=b["yes_prob"],
                gpt_no_prob=b["no_prob"], gpt_relative_prob=b["relative_prob"],
                gpt_confidence=c["confidence"],
                gpt_weighted_confidence=c["weighted_confidence"],
            )
            sleep(sleeps["gpt"])
        if "gemini" in missing and gemini_client is not None:
            b = evaluate_gemini_binary(gemini_client, gemini_model, question)
            sleep(sleeps["gemini"])
            c = evaluate_gemini_confidence(gemini_client, gemini_model, question)
            record.update(
                gemini_response=b["response"], gemini_yes_prob=b["yes_prob"],
                gemini_no_prob=b["no_prob"], gemini_relative_prob=b["relative_prob"],
                gemini_confidence=c["confidence"],
                gemini_weighted_confidence=c["weighted_confidence"],
            )
            sleep(sleeps["gemini"])
        if "claude" in missing and claude_client is not None:
            c = evaluate_claude(claude_client, claude_model, question,
                                sleep=sleep, delay=sleeps["claude"])
            record.update(claude_response=c["response"], claude_confidence=c["confidence"])
            sleep(sleeps["claude"])
        if "random" in missing:
            r = evaluate_random_baseline(rng)
            record.update(
                random_response=r["response"],
                random_relative_prob=r["relative_prob"],
                random_confidence=r["confidence"],
            )
        cache.put(question, record)
        rows.append({"question": question, **record})
        if intermediate_csv and (qi + 1) % intermediate_every == 0:
            pd.DataFrame(rows).to_csv(intermediate_csv, index=False)
    df = pd.DataFrame(rows)
    for col in RESULT_COLUMNS:
        if col not in df.columns:
            df[col] = np.nan
    return df[RESULT_COLUMNS]


def calculate_correlations(df: pd.DataFrame) -> Dict:
    """Pairwise correlations between model relative probabilities /
    confidences (reference :788-816)."""
    out: Dict = {}
    pairs = [
        ("gpt_relative_prob", "gemini_relative_prob"),
        ("gpt_confidence", "gemini_confidence"),
        ("gpt_confidence", "claude_confidence"),
        ("gemini_confidence", "claude_confidence"),
    ]
    for a, b in pairs:
        if a not in df.columns or b not in df.columns:
            continue
        sub = df[[a, b]].apply(pd.to_numeric, errors="coerce").dropna()
        if len(sub) < 3:
            continue
        pr, pp = pearsonr(sub[a], sub[b])
        sr, sp = spearmanr(sub[a], sub[b])
        out[f"{a}__{b}"] = {
            "pearson": float(pr), "pearson_p": float(pp),
            "spearman": float(sr), "spearman_p": float(sp), "n": len(sub),
        }
    return out


def compare_with_human_data(
    df: pd.DataFrame,
    human_means: Dict[str, float],          # question text -> mean in [0,1]
    human_std: Optional[float] = None,
    n_bootstrap: int = 10_000,
    seed: int = 42,
) -> Dict:
    """MAE vs human mean + baselines + paired difference tests, mirroring
    evaluate_closed_source_models.py:985-1135 exactly (regression-pinned to
    the paper's Table 3/4 in tests/test_published_regression.py):

    - model prediction = verbalized WEIGHTED confidence / 100 for GPT/Gemini
      (fallback to plain confidence when weighted is NaN); plain
      confidence / 100 for Claude and the random evaluator (:1024-1035);
    - questions match by SUBSTRING in either direction, first hit in dict
      order (:1016-1018);
    - top-level Equanimity (always-0.5) and N(mu,sigma) baselines run over
      ALL survey questions (:917-983); per-model difference tests re-derive
      both baselines over that model's matched questions only (:1060-1099);
    - the Normal baseline replays the reference's legacy global-seed RNG
      (np.random.seed(43), N(mu*100, sigma*100), clip to [0,100], /100) so
      its draws are bit-identical;
    - mu/sigma come from ALL question means (sigma overridable via
      ``human_std``).
    """
    def match(question: str) -> Optional[float]:
        for hq, hv in human_means.items():
            if question in hq or hq in question:
                return hv
        return None

    def model_value(row, name: str):
        key = name.lower()
        if name in ("Claude", "Random"):
            return pd.to_numeric(pd.Series([row.get(f"{key}_confidence")]),
                                 errors="coerce").iloc[0]
        v = pd.to_numeric(pd.Series([row.get(f"{key}_weighted_confidence")]),
                          errors="coerce").iloc[0]
        if pd.isna(v):
            v = pd.to_numeric(pd.Series([row.get(f"{key}_confidence")]),
                              errors="coerce").iloc[0]
        return v

    errors: Dict[str, List[float]] = {}
    pairs: Dict[str, List[tuple]] = {}   # name -> [(prediction, human mean)]
    paired_h: Dict[str, List[float]] = {}
    # df-row-aligned error matrix for per-question figures: one slot per
    # matched row per model, NaN when that model had no parseable value (the
    # stats vectors above skip instead — they must stay dense for bootstrap)
    errors_aligned: Dict[str, List[float]] = {n: [] for n in MODEL_NAMES}
    matched_questions: List[str] = []
    for _, row in df.iterrows():
        h = match(str(row["question"]))
        if h is None:
            continue
        matched_questions.append(str(row["question"]))
        for name in MODEL_NAMES:
            v = model_value(row, name)
            if pd.notna(v):
                pred = float(v) / 100.0
                errors.setdefault(name, []).append(abs(pred - h))
                pairs.setdefault(name, []).append((pred, h))
                paired_h.setdefault(name, []).append(h)
                errors_aligned[name].append(abs(pred - h))
            else:
                errors_aligned[name].append(float("nan"))

    all_h = list(human_means.values())
    mu = float(np.mean(all_h)) if all_h else 0.5
    sigma = float(human_std) if human_std is not None else float(np.std(all_h))

    def normal_draws(count: int) -> List[float]:
        # legacy global-RNG replay: np.random.seed(43) + sequential normals
        legacy = np.random.RandomState(43)
        return [
            float(np.clip(legacy.normal(mu * 100, sigma * 100), 0, 100) / 100.0)
            for _ in range(count)
        ]

    errors["Equanimity"] = [abs(0.5 - h) for h in all_h]
    if all_h:
        errors["Normal"] = [abs(d - h) for d, h in zip(normal_draws(len(all_h)), all_h)]

    results: Dict = {"mae": {}, "differences": {}}
    for name, errs in errors.items():
        mean, lo, hi = bootstrap_mae(errs, n_bootstrap=n_bootstrap, seed=seed)
        record = {"mae": mean, "ci_lower": lo, "ci_upper": hi, "n": len(errs)}
        pred_h = pairs.get(name, [])
        if len(pred_h) >= 3 and np.std([p for p, _ in pred_h]) > 0 and np.std(
            [hh for _, hh in pred_h]
        ) > 0:
            r, p = pearsonr([p for p, _ in pred_h], [hh for _, hh in pred_h])
            record.update(correlation=float(r), p_value=float(p),
                          n_matched=len(pred_h))
        results["mae"][name] = record
    if "Normal" in results["mae"] and all_h:
        results["mae"]["Normal"].update(human_mean=mu, human_std=sigma)

    for name in ("GPT", "Claude", "Gemini"):
        if name not in errors:
            continue
        hs = paired_h[name]
        baselines = {"Equanimity": [abs(0.5 - h) for h in hs],
                     "Normal": [abs(d - h) for d, h in zip(normal_draws(len(hs)), hs)]}
        if "Random" in errors:
            baselines["Random"] = errors["Random"]
        diffs = {}
        for baseline, base_errs in baselines.items():
            d, lo, hi, p = bootstrap_mae_difference(
                errors[name], base_errs, n_bootstrap=n_bootstrap, seed=seed
            )
            diffs[baseline] = {"diff": d, "ci_lower": lo, "ci_upper": hi, "p_value": p}
        results["differences"][name] = diffs
    results["errors"] = errors
    results["errors_aligned"] = {
        k: v for k, v in errors_aligned.items() if np.isfinite(v).any()
    }
    results["matched_questions"] = matched_questions
    return results


def write_report(
    df: pd.DataFrame,
    comparisons: Dict,
    correlations: Dict,
    output_dir: str,
) -> Dict[str, str]:
    """CSV + LaTeX tables + heatmap/error-strip figures."""
    os.makedirs(output_dir, exist_ok=True)
    paths = {}
    csv_path = os.path.join(output_dir, "closed_source_evaluation_results.csv")
    df.to_csv(csv_path, index=False)
    paths["csv"] = csv_path
    tex = latex.mae_results_tables(comparisons["mae"], comparisons["differences"])
    tex_path = os.path.join(output_dir, "mae_results_tables.tex")
    with open(tex_path, "w") as f:
        f.write(tex)
    paths["latex"] = tex_path
    # Per-question figures use the NaN-padded df-row-aligned matrix so every
    # column is the same question for every model (the dense stats vectors
    # shift when a model skips a question; the all-questions baselines are
    # in human_means order and excluded from these figures entirely).
    errors = comparisons.get("errors_aligned") or {
        k: v for k, v in comparisons.get("errors", {}).items() if k in MODEL_NAMES
    }
    if errors:
        paths["error_strip"] = figures.per_question_error_strip(
            errors, "Per-question absolute error vs human mean",
            os.path.join(output_dir, "per_question_errors.png"),
        )
        names = [n for n in errors if len(errors[n])]
        if names:
            width = min(len(errors[n]) for n in names)
            mat = np.array([list(errors[n])[:width] for n in names])
            paths["heatmap"] = figures.mae_heatmap(
                mat, names, [f"q{i + 1}" for i in range(width)],
                "Absolute error heatmap", os.path.join(output_dir, "mae_heatmap.png"),
            )
    mae = comparisons.get("mae", {})
    if mae:
        dashboard_input = {
            "models": {k: v for k, v in mae.items()
                       if k in ("GPT", "Gemini", "Claude")},
            "baselines": {
                key: mae[name]
                for key, name in (("always_50", "Equanimity"), ("normal_human", "Normal"))
                if name in mae
            },
        }
        paths["dashboard"] = figures.model_comparison_dashboard(
            df, correlations, dashboard_input,
            os.path.join(output_dir, "model_comparison_plots.png"),
        )
        paths["mae_comparison"] = figures.mae_comparison_bar(
            dashboard_input, os.path.join(output_dir, "mae_comparison.png"),
        )
    import json

    with open(os.path.join(output_dir, "correlations.json"), "w") as f:
        json.dump(correlations, f, indent=2)
    with open(os.path.join(output_dir, "human_comparisons.json"), "w") as f:
        json.dump(
            {k: v for k, v in comparisons.items() if k != "errors"}, f, indent=2,
            default=float,
        )
    return paths


def run_closed_source_evaluation(
    questions: Sequence[str],
    output_dir: str,
    human_means: Optional[Dict[str, float]] = None,
    human_std: Optional[float] = None,
    cache_file: Optional[str] = None,
    confirm_fn: Optional[Callable[[str], bool]] = None,
    log: Callable[[str], None] = print,
    **eval_kwargs,
) -> Optional[pd.DataFrame]:
    """The reference main()'s orchestration shell (:1902-2110).

    Short-circuits to ``closed_source_evaluation_results.csv`` when a previous
    run finished; otherwise reports how many questions the cache already
    covers, and for the remainder estimates API-call count and wall time from
    the per-vendor sleeps and gates on ``confirm_fn`` (the reference's
    interactive "Proceed with evaluation? (yes/no)" prompt, :1938-1942; pass
    None to skip, e.g. under ``--yes``).  Returns the results DataFrame, or
    None when the user declines.
    """
    saved = os.path.join(output_dir, "closed_source_evaluation_results.csv")
    if os.path.exists(saved):
        log(f"Loading existing results from {saved}")
        df = pd.read_csv(saved)
    else:
        cache = ResponseCache(cache_file) if cache_file else ResponseCache()
        done = sum(1 for q in questions if cache.is_complete(q))
        fresh = len(questions) - done
        if done:
            log(f"Cache mode: ENABLED ({done}/{len(questions)} questions "
                f"complete in {cache_file})")
        vendors_configured = [v for v in ("gpt", "gemini", "claude")
                              if eval_kwargs.get(f"{v}_client") is not None]
        if fresh and vendors_configured:
            sleeps = eval_kwargs.get("sleeps") or DEFAULT_SLEEPS
            # 2 calls (binary + confidence) per CONFIGURED vendor per
            # question, one sleep after each call — mirrors the loop exactly
            calls = fresh * 2 * len(vendors_configured)
            minutes = fresh * 2 * sum(sleeps[v] for v in vendors_configured) / 60.0
            log(f"Estimated processing time: {minutes:.1f} minutes")
            log(f"Total API calls: {calls}")
            if confirm_fn is not None and not confirm_fn(
                "Proceed with evaluation? (yes/no): "
            ):
                log("Evaluation cancelled.")
                return None
        df = evaluate_all_models(questions, cache=cache, **eval_kwargs)
    correlations = calculate_correlations(df)
    comparisons = (
        compare_with_human_data(df, human_means, human_std)
        if human_means else {"mae": {}, "differences": {}, "errors": {}}
    )
    write_report(df, comparisons, correlations, output_dir)
    return df
