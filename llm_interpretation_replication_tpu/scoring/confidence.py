"""Verbalized-confidence parsing and logprob-weighted confidence.

Host-side behavioral replicas of the reference's confidence pipeline:

- ``extract_first_int`` — the ``re.search(r'\\b(\\d+)\\b')`` parse used on every
  confidence reply (perturb_prompts.py:443-448, perturb_prompts_claude.py:112-122).
- ``weighted_confidence_single_tokens`` — GPT-style: every numeric token in the
  top-logprobs of every generated position contributes value*prob
  (perturb_prompts.py:505-526, perturb_prompts_gpt.py:47-85).
- ``weighted_confidence_digits`` — Gemini-style multi-token reconstruction:
  combine 1-/2-/3-digit continuations ("1"+"0"+"0" → 100) while subtracting
  continuation mass from shorter readings
  (evaluate_closed_source_models.py:327-456, perturb_prompts_gemini.py:270-416).
- ``extract_final_number`` — thinking-model output parser: ***/### markers,
  last standalone-number line, last number, ≤3-digit concat fallback
  (evaluate_irrelevant_perturbations.py:190-265).
- ``top_candidates_from_scores`` — adapter turning our models' per-step score
  tensors into (token, logprob) candidate lists so local TPU models get the
  same weighted-confidence treatment the APIs get.
"""

from __future__ import annotations

import math
import re
from typing import List, Optional, Sequence, Tuple

Candidate = Tuple[str, float]  # (token text, logprob)


def extract_first_int(text: str) -> Optional[int]:
    if not text:
        return None
    m = re.search(r"\b(\d+)\b", text)
    if not m:
        return None
    try:
        return int(m.group(1))
    except ValueError:
        return None


def first_int_stable(text: str) -> bool:
    """Can :func:`extract_first_int` of ``text`` still change if more text
    is APPENDED?  False means yes (keep decoding), True means the parse is
    frozen: the first ``\\b``-delimited integer ends strictly before the
    end of the string, so the character after it is a non-word boundary —
    appended text can neither extend those digits nor introduce an
    earlier match.  A trailing integer ("...about 8") is NOT stable: the
    next token could extend it ("...about 85").  The pooled confidence
    decode's early-exit retirement rests on this predicate
    (runtime/engine._Phase2Pool._flush_confidence)."""
    if not text:
        return False
    m = re.search(r"\b(\d+)\b", text)
    return bool(m) and m.end() < len(text)


def weighted_confidence_single_tokens(
    positions: Sequence[Sequence[Candidate]],
) -> Optional[float]:
    """Every numeric token (0-100) across all positions' top-logprobs,
    probability-weighted.  Matches the OpenAI leg's batch extractor."""
    weighted = 0.0
    total = 0.0
    for cands in positions:
        for token, logprob in cands:
            m = re.search(r"\b(\d+)\b", token)
            if not m:
                continue
            value = int(m.group(1))
            if 0 <= value <= 100:
                p = math.exp(logprob)
                weighted += value * p
                total += p
    return weighted / total if total > 0 else None


def weighted_confidence_digits(
    positions: Sequence[Sequence[Candidate]],
    max_candidates: int = 19,
) -> Optional[float]:
    """Multi-token number reconstruction over the first three positions.

    Single-digit first tokens extend to 2-digit values via position 2 and to
    100 via position 3; the probability mass of continuations is subtracted
    from the shorter readings ("1"→"10"→"100" chain).  Complete number tokens
    ("42", "100") contribute directly.
    """
    if not positions:
        return None
    first = positions[0] if len(positions) > 0 else None
    second = positions[1] if len(positions) > 1 else None
    third = positions[2] if len(positions) > 2 else None
    if not first:
        return None

    one: dict = {}
    two: dict = {}
    three: dict = {}

    def digit_cands(pos):
        out = []
        for token, logprob in pos[:max_candidates]:
            t = token.strip()
            if t.isdigit() and len(t) == 1:
                out.append((int(t), math.exp(logprob)))
        return out

    second_digits = digit_cands(second) if second else []
    second_digit_mass = sum(p for _, p in second_digits)
    third_zero_prob = 0.0
    if third:
        for token, logprob in third[:max_candidates]:
            if token.strip() == "0":
                third_zero_prob = math.exp(logprob)
                break

    for token, logprob in first[:max_candidates]:
        t = token.strip()
        p1 = math.exp(logprob)
        if t.isdigit() and len(t) == 1:
            d1 = int(t)
            standalone = p1
            if second and 1 <= d1 <= 9:
                for d2, p2 in second_digits:
                    value = d1 * 10 + d2
                    if value == 10 and third:
                        # 1-0-0 chain → 100
                        three[100] = three.get(100, 0.0) + p1 * p2 * third_zero_prob
                    if 10 <= value <= 99:
                        combined = p1 * p2
                        if value == 10 and third:
                            combined *= 1 - third_zero_prob
                        two[value] = two.get(value, 0.0) + combined
                standalone *= 1 - second_digit_mass
            one[d1] = one.get(d1, 0.0) + standalone
        elif t.isdigit():
            value = int(t)
            if value == 100:
                three[100] = three.get(100, 0.0) + p1
            elif 10 <= value <= 99:
                two[value] = two.get(value, 0.0) + p1
            elif 0 <= value <= 9:
                one[value] = one.get(value, 0.0) + p1

    all_probs: dict = {}
    all_probs.update(one)
    all_probs.update(two)
    all_probs.update(three)
    total = sum(all_probs.values())
    if total <= 0 or not all_probs:
        return None
    return sum(v * p / total for v, p in all_probs.items())


def extract_final_number(response_text: str) -> Optional[float]:
    """Robust last-answer extraction for thinking-model outputs."""
    if not response_text:
        return None
    # number sandwiched between *** / ### markers
    m = re.search(
        r"(?:\*{3,}|#{3,})\s*(\d+(?:\.\d+)?)\s*(?:\*{3,}|#{3,})",
        response_text,
        re.MULTILINE | re.DOTALL,
    )
    if m:
        return float(m.group(1))
    lines = response_text.split("\n")
    # standalone number on a line above the last marker block
    after_marker = False
    for line in reversed(lines):
        line = line.strip()
        if "***" in line or "###" in line:
            after_marker = True
        elif after_marker and line:
            m = re.match(r"^(\d+(?:\.\d+)?)$", line)
            if m:
                return float(m.group(1))
    # last line that is exactly a number
    for line in reversed(lines):
        m = re.match(r"^(\d+(?:\.\d+)?)$", line.strip())
        if m:
            return float(m.group(1))
    # last number anywhere
    numbers = re.findall(r"\b(\d+(?:\.\d+)?)\b", response_text)
    if numbers:
        return float(numbers[-1])
    # digits-only concat, short numbers only
    digits = "".join(ch for ch in response_text if ch.isdigit())
    if digits and len(digits) <= 3:
        return float(digits)
    return None


def top_candidates_from_scores(
    scores,                     # np/jnp [P, V] fp32 per-position scores
    tokenizer,
    num_positions: int = 3,
    top_k: int = 19,
) -> List[List[Candidate]]:
    """Turn model score rows into API-style top-candidate lists so the digit
    reconstruction above applies to local TPU models."""
    import numpy as np

    scores = np.asarray(scores, dtype=np.float64)
    positions: List[List[Candidate]] = []
    for p in range(min(num_positions, scores.shape[0])):
        row = scores[p]
        logz = _logsumexp(row)
        idx = np.argpartition(-row, top_k)[:top_k]
        idx = idx[np.argsort(-row[idx])]
        cands = [(tokenizer.decode([int(i)]), float(row[i] - logz)) for i in idx]
        positions.append(cands)
    return positions


def _logsumexp(row):
    import numpy as np

    m = np.max(row)
    return m + math.log(np.sum(np.exp(row - m)))
