"""Packed multi-question batching (Auto-Demo batch prompting, arxiv
2410.01724): Q questions + their demonstrations in ONE sequence, scored at
per-question answer anchors in a single prefill.

The paper's studies score every question as an isolated prompt; the packed
formatter trades that isolation for throughput — one packed row amortizes
one prefill (and the shared scaffold tokens) across Q questions, and the
binary leg needs NO decode path at all: the engine gathers the logits at
each question's anchor offset (the last token of its prompt text) inside
the prefill program (models/decoder.forward_anchor_logits) and runs the
ordinary position-0 yes/no scan over the gathered rows.

Contract (PARITY.md "Packed batch prompting — measured drift"): packed
mode is a MEASURED-DRIFT workload, not a bit-parity one.  Question k >= 1
of a pack sees the earlier questions and their demonstration answers as
context, so its relative probability legitimately moves; the drift-parity
leg (:func:`drift_report`) quantifies exactly that movement — itself a
paper-relevant reliability measurement.  The FIRST question of each pack
carries no packed context (its token stream is byte-identical to the
isolated prompt), so its anchor logits are bit-identical to isolated
scoring — the anchor-position correctness pin in tests/test_packed.py.

Demonstrations follow Auto-Demo's self-generated convention when the
caller can supply them (the sweep's drift-parity leg scores the isolated
prompts first and feeds each question's OWN isolated answer back as its
demonstration); callers without a generated answer fall back to the
scenario's nominal yes target.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# the packed FORMATTING contract lives in scoring/prompts.py with every
# other prompt spelling; this module owns assembly + measurement
from .prompts import PACKED_SEPARATOR, format_packed_demo as format_demo

__all__ = ["PACKED_SEPARATOR", "format_demo", "build_packs",
           "encode_packs", "drift_report", "demos_from_relative_probs",
           "autoregressive_demos"]


def autoregressive_demos(engine, prompts: Sequence[str], packing: int,
                         max_demo_tokens: int = 8,
                         repack: Optional[bool] = None):
    """Auto-Demo's AUTOREGRESSIVE demonstrations (the PR-10 follow-up)
    via decode-then-repack (runtime/slots.py): question k's demo is the
    model's OWN greedy continuation decoded in the pack's packed context
    so far — not an answer imported from a separate isolated pass
    (:func:`demos_from_relative_probs`) — and each finished demo retires
    its decode slot, which immediately refills with whatever pack stage
    is ready.  Returns ``(packs, demos)`` with ``packs`` in
    :func:`build_packs` layout, ready for ``engine.score_packed``.

    Thin façade over
    :meth:`~..runtime.engine.ScoringEngine.packed_autoregressive_demos`
    so sweep code imports the packed toolbox from ONE module;
    ``repack=False`` runs the identical stages whole-flush (the parity
    comparator — demos are per-row pure, so both modes emit identical
    texts)."""
    return engine.packed_autoregressive_demos(
        prompts, packing, max_demo_tokens=max_demo_tokens, repack=repack)


def build_packs(prompts: Sequence, packing: int,
                demos: Optional[Sequence[str]] = None) -> List[List[Tuple]]:
    """Group ``prompts`` into packs of ``packing`` consecutive questions.

    Returns one pack per group: a list of ``(prompt, demo_continuation)``
    tuples where ``demo_continuation`` is the text appended AFTER the
    question's answer anchor (:func:`format_demo` of the question's own
    demonstration answer), and ``None`` for the last question of a pack —
    tokens after the final anchor are causally dead and only waste
    prefill FLOPs.  ``demos`` aligns with ``prompts`` (one demonstration
    answer per question); question order is preserved pack-major."""
    if packing < 1:
        raise ValueError(f"packing must be >= 1, got {packing}")
    packs: List[List[Tuple]] = []
    for start in range(0, len(prompts), packing):
        chunk = list(prompts[start:start + packing])
        pack = []
        for j, prompt in enumerate(chunk):
            demo = None
            if j + 1 < len(chunk):
                answer = demos[start + j] if demos is not None else "Yes"
                demo = format_demo(answer)
            pack.append((prompt, demo))
        packs.append(pack)
    return packs


def encode_packs(tokenizer, packs: Sequence[Sequence[Tuple]]
                 ) -> Tuple[List[List[int]], List[List[int]]]:
    """Tokenize packs into per-row id streams + per-question anchor offsets.

    The FIRST question's prompt tokenizes exactly like the isolated path
    (``batching.encode_prompts`` semantics), so its token stream — and
    therefore its anchor logits — are byte-identical to isolated scoring.
    Every later segment tokenizes with ``add_special_tokens=False`` (the
    fused-suffix convention, sweeps/perturbation.py): the packed stream
    is the concatenation spelling, self-consistent by construction —
    packed mode is measured-drift, not byte-parity, for questions > 0.

    ``anchors[i][k]`` is the index of question k's LAST prompt token in
    row i — the position whose next-token logits score its answer.
    Prompts/demos may be pre-tokenized id lists; strings tokenize once
    per call via one batched tokenizer invocation per role."""
    rows: List[List[int]] = []
    anchors: List[List[int]] = []
    # one batched tokenizer call per role (first prompts / continuation
    # prompts / demos) instead of one call per segment
    first_texts, later_texts, demo_texts = [], [], []
    for pack in packs:
        for k, (prompt, demo) in enumerate(pack):
            if isinstance(prompt, str):
                (first_texts if k == 0 else later_texts).append(prompt)
            if isinstance(demo, str):
                demo_texts.append(demo)
    first_ids = iter(tokenizer(first_texts)["input_ids"]
                     if first_texts else [])
    later_ids = iter(tokenizer(later_texts,
                               add_special_tokens=False)["input_ids"]
                     if later_texts else [])
    demo_ids = iter(tokenizer(demo_texts,
                              add_special_tokens=False)["input_ids"]
                    if demo_texts else [])
    for pack in packs:
        ids: List[int] = []
        offs: List[int] = []
        for k, (prompt, demo) in enumerate(pack):
            if isinstance(prompt, str):
                p_ids = next(first_ids) if k == 0 else next(later_ids)
            else:
                p_ids = prompt
            ids.extend(int(t) for t in p_ids)
            offs.append(len(ids) - 1)
            if demo is not None:
                d_ids = next(demo_ids) if isinstance(demo, str) else demo
                ids.extend(int(t) for t in d_ids)
        if not offs:
            raise ValueError("empty pack")
        rows.append(ids)
        anchors.append(offs)
    return rows, anchors


def drift_report(packed_rel: Sequence[float], isolated_rel: Sequence[float],
                 packing: int, flip_threshold: float = 0.5) -> Dict:
    """The drift-parity result block: per-question |Δ relative_prob|
    distribution + flip rate between packed and isolated scoring.

    A FIRST-CLASS measurement, not a guardrail (ISSUE 10): the judgment
    drift batch prompting introduces is itself a paper-relevant
    reliability number.  ``flip_rate`` counts questions whose binary
    verdict (relative_prob >= ``flip_threshold``) differs between the two
    modes; NaN rows (error rows in either leg) are excluded and counted
    in ``n_skipped``.  Deterministic: a pure function of the two arrays,
    so two runs over identical inputs emit identical blocks."""
    packed_rel = np.asarray(packed_rel, dtype=np.float64)
    isolated_rel = np.asarray(isolated_rel, dtype=np.float64)
    if packed_rel.shape != isolated_rel.shape:
        raise ValueError(
            f"packed/isolated length mismatch: {packed_rel.shape} vs "
            f"{isolated_rel.shape}")
    ok = np.isfinite(packed_rel) & np.isfinite(isolated_rel)
    delta = np.abs(packed_rel[ok] - isolated_rel[ok])
    flips = ((packed_rel[ok] >= flip_threshold)
             != (isolated_rel[ok] >= flip_threshold))
    n = int(ok.sum())
    report = {
        "packing": int(packing),
        "n_questions": n,
        "n_skipped": int(ok.size - n),
        "flip_rate": round(float(flips.mean()), 4) if n else None,
    }
    if n:
        report.update(
            mean_abs_delta=round(float(delta.mean()), 6),
            p50_abs_delta=round(float(np.percentile(delta, 50)), 6),
            p90_abs_delta=round(float(np.percentile(delta, 90)), 6),
            max_abs_delta=round(float(delta.max()), 6),
        )
    else:
        report.update(mean_abs_delta=None, p50_abs_delta=None,
                      p90_abs_delta=None, max_abs_delta=None)
    return report


def demos_from_relative_probs(rel: Sequence[float],
                              target_pairs: Sequence[Sequence[str]]
                              ) -> List[str]:
    """Auto-Demo's self-generated demonstrations from an isolated scoring
    pass: each question's demonstration answer is the target its OWN
    isolated relative probability favors (>= 0.5 → the yes target).  NaN
    rows (isolated error rows) fall back to the yes target."""
    out = []
    for r, pair in zip(rel, target_pairs):
        yes, no = pair[0], pair[1]
        out.append(no if (np.isfinite(r) and float(r) < 0.5) else yes)
    return out
