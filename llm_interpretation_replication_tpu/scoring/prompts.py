"""Prompt formatting contracts.

These strings are behavioral data, kept byte-identical to the reference so the
statistics reproduce (run_base_vs_instruct_100q.py:455-469,
compare_instruct_models.py:488-492, compare_base_vs_instruct.py:462-463).
"""

from __future__ import annotations

FEW_SHOT_PREFIX = (
    "Question: Is \"soup\" a \"beverage\"? Answer either 'Yes' or 'No', "
    "without any other text.\nAnswer: No.\n\n"
    "Question: Is a \"tweet\" a \"publication\"? Answer either 'Yes' or 'No', "
    "without any other text.\nAnswer: Yes.\n\n"
)

ANSWER_INSTRUCTION = "Answer either 'Yes' or 'No', without any other text."


def format_base_prompt(question: str) -> str:
    """Base checkpoints: 2-shot prefix + Question/Answer scaffold."""
    return f"{FEW_SHOT_PREFIX}Question: {question} {ANSWER_INSTRUCTION}\nAnswer:"


def format_instruct_prompt(question: str, model_name: str = "") -> str:
    """Instruction-tuned checkpoints: bare question + instruction; Baichuan
    gets its chat wrapping."""
    if "baichuan" in model_name.lower():
        return f"<human>: {question} {ANSWER_INSTRUCTION}\n<bot>:"
    return f"{question} {ANSWER_INSTRUCTION}"


def format_prompt(question: str, is_base_model: bool, model_name: str = "") -> str:
    if is_base_model:
        return format_base_prompt(question)
    return format_instruct_prompt(question, model_name)


def format_prompt_parts(question: str, is_base_model: bool,
                        model_name: str = "") -> tuple:
    """``(prefix, suffix)`` split of :func:`format_prompt` for the engine's
    prefix-reuse path (runtime/engine.score_prefixed): concatenating the
    parts reproduces the reference prompt byte-for-byte, and the split
    puts the SHARED text in the prefix — the 2-shot preamble for base
    checkpoints (identical across all 100 questions, so the host
    tokenizes it once per sweep via encode_prefix_pairs' memo), the bare
    question for instruct checkpoints."""
    if is_base_model:
        return (FEW_SHOT_PREFIX,
                f"Question: {question} {ANSWER_INSTRUCTION}\nAnswer:")
    if "baichuan" in model_name.lower():
        return (f"<human>: {question}", f" {ANSWER_INSTRUCTION}\n<bot>:")
    return (question, f" {ANSWER_INSTRUCTION}")


#: Separator between a packed question's demonstration answer and the next
#: question — two newlines, the reference few-shot scaffold's question
#: separator (FEW_SHOT_PREFIX above).  The packed batch-prompting machinery
#: (scoring/packed.py, Auto-Demo arxiv 2410.01724) builds rows from these
#: pieces; the formatting CONTRACT lives here with the other prompt
#: spellings.
PACKED_SEPARATOR = "\n\n"


def format_packed_demo(answer: str) -> str:
    """Packed batch prompting: the demonstration continuation appended
    after a question's answer anchor — ``" {answer}.\\n\\n"``, the
    reference few-shot scaffold's answer spelling (``Answer: No.\\n\\n``),
    minus the ``Answer:`` cue the packed question text already ends with.
    The anchor itself is the question prompt's last token; everything a
    packed row contains is therefore spelled by this module's formatters
    (scoring/packed.encode_packs assembles them)."""
    return f" {answer}.{PACKED_SEPARATOR}"


def format_binary_prompt(main_part: str, response_format: str) -> str:
    """Perturbation-sweep binary prompt: ``{rephrased_main} {response_format}``
    (perturb_prompts.py 'Full Rephrased Prompt' column)."""
    return f"{main_part} {response_format}"


def format_confidence_prompt(main_part: str, confidence_format: str) -> str:
    return f"{main_part} {confidence_format}"
