from .confidence import (
    extract_final_number,
    extract_first_int,
    top_candidates_from_scores,
    weighted_confidence_digits,
    weighted_confidence_single_tokens,
)
from .prompts import (
    ANSWER_INSTRUCTION,
    FEW_SHOT_PREFIX,
    format_base_prompt,
    format_binary_prompt,
    format_confidence_prompt,
    format_instruct_prompt,
    format_prompt,
)
from .yes_no import (
    YesNoResult,
    first_token_scan,
    relative_prob_first_token,
    steps_until_eos,
    target_token_ids,
    yes_no_from_reduced,
    yes_no_from_scores,
)

__all__ = [
    "extract_final_number",
    "extract_first_int",
    "top_candidates_from_scores",
    "weighted_confidence_digits",
    "weighted_confidence_single_tokens",
    "ANSWER_INSTRUCTION",
    "FEW_SHOT_PREFIX",
    "format_base_prompt",
    "format_binary_prompt",
    "format_confidence_prompt",
    "format_instruct_prompt",
    "format_prompt",
    "YesNoResult",
    "first_token_scan",
    "relative_prob_first_token",
    "steps_until_eos",
    "target_token_ids",
    "yes_no_from_reduced",
    "yes_no_from_scores",
]
