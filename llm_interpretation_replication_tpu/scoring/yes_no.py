"""Yes/No relative-probability extraction — the behavioral core.

Replaces the reference's ``get_yes_no_logprobs``
(run_base_vs_instruct_100q.py:279-392 and 3 near-identical copies): HF
``generate(max_new_tokens=50, output_scores=True)`` followed by a Python scan
of the first MAX_LOOK_AHEAD=10 positions for a step whose top-k (k=5, k=2 in
the older script) contains the Yes/No token, falling back to position 0.

Here the scan is a vectorized jit'd op over the per-step score tensor produced
by ``models.decoder.greedy_decode`` / ``models.t5.greedy_decode`` — one device
program for the whole batch instead of a per-prompt Python loop.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp


class YesNoResult(NamedTuple):
    yes_prob: jnp.ndarray       # [B]
    no_prob: jnp.ndarray        # [B]
    relative_prob: jnp.ndarray  # [B]  p_yes / (p_yes + p_no), 0.5 when both 0
    odds_ratio: jnp.ndarray     # [B]  p_yes / p_no, +inf when p_no == 0
    found: jnp.ndarray          # [B]  bool: scan hit within max_look_ahead
    position: jnp.ndarray       # [B]  int: position read (0 on fallback)


@functools.partial(jax.jit, static_argnames=("max_look_ahead", "top_k"))
def yes_no_from_scores(
    scores: jnp.ndarray,   # [B, P, V] fp32 per-step generation scores
    yes_id: jnp.ndarray,   # [] or [B] int token id ("Yes" with leading space)
    no_id: jnp.ndarray,
    max_look_ahead: int = 10,
    top_k: int = 5,
) -> YesNoResult:
    b, p, v = scores.shape
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    yes_id = jnp.broadcast_to(jnp.asarray(yes_id), (b,))
    no_id = jnp.broadcast_to(jnp.asarray(no_id), (b,))
    p_yes = jnp.take_along_axis(probs, yes_id[:, None, None], axis=-1)[..., 0]  # [B,P]
    p_no = jnp.take_along_axis(probs, no_id[:, None, None], axis=-1)[..., 0]
    # top-k membership == prob >= k-th largest prob (ties over-match, like the
    # reference's `token_id in topk(probs, k).indices` up to degenerate ties)
    kth = jax.lax.top_k(probs, top_k)[0][..., -1]                               # [B,P]
    look = min(max_look_ahead, p)
    hit = ((p_yes >= kth) | (p_no >= kth))[:, :look]
    found = jnp.any(hit, axis=1)
    first = jnp.argmax(hit, axis=1).astype(jnp.int32)
    sel = jnp.where(found, first, 0)
    yes = jnp.take_along_axis(p_yes, sel[:, None], axis=1)[:, 0]
    no = jnp.take_along_axis(p_no, sel[:, None], axis=1)[:, 0]
    total = yes + no
    relative = jnp.where(total > 0, yes / jnp.where(total > 0, total, 1.0), 0.5)
    odds = jnp.where(no > 0, yes / jnp.where(no > 0, no, 1.0), jnp.inf)
    return YesNoResult(yes, no, relative, odds, found, sel)


@functools.partial(jax.jit, static_argnames=("top_filter",))
def relative_prob_first_token(logits: jnp.ndarray, yes_id, no_id, top_filter: int = 0):
    """Fast path: single-forward scoring at the final prompt position (the
    pjit'd sweep's hot op — BASELINE.json north star).  logits: [B, V] fp32.

    ``top_filter`` > 0 zeroes probabilities outside the top-N, matching the
    API extractor that only sees top-20 logprobs (perturb_prompts.py:480-498).
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    b = logits.shape[0]
    yes_id = jnp.broadcast_to(jnp.asarray(yes_id), (b,))
    no_id = jnp.broadcast_to(jnp.asarray(no_id), (b,))
    yes = jnp.take_along_axis(probs, yes_id[:, None], axis=-1)[:, 0]
    no = jnp.take_along_axis(probs, no_id[:, None], axis=-1)[:, 0]
    if top_filter:
        kth = jax.lax.top_k(probs, top_filter)[0][:, -1]
        yes = jnp.where(yes >= kth, yes, 0.0)
        no = jnp.where(no >= kth, no, 0.0)
    total = yes + no
    relative = jnp.where(total > 0, yes / jnp.where(total > 0, total, 1.0), 0.5)
    return yes, no, relative


def target_token_ids(tokenizer, targets: Sequence[str], encoder_decoder: bool = False):
    """Token ids the scan looks for.

    Decoder-only models match the reference's leading-space convention
    (``tokenizer(" Yes", add_special_tokens=False).input_ids[0]`` with a
    no-space fallback — run_base_vs_instruct_100q.py:332-335); encoder-decoder
    models take the first id of the bare word (ibid.:306-307).
    """
    ids = []
    for t in targets:
        if encoder_decoder:
            ids.append(tokenizer(t).input_ids[0])
            continue
        with_space = tokenizer(" " + t, add_special_tokens=False).input_ids
        ids.append(with_space[0] if with_space else tokenizer.encode(t)[0])
    return ids
