"""Yes/No relative-probability extraction — the behavioral core.

Replaces the reference's ``get_yes_no_logprobs``
(run_base_vs_instruct_100q.py:279-392 and 3 near-identical copies): HF
``generate(max_new_tokens=50, output_scores=True)`` followed by a Python scan
of the first MAX_LOOK_AHEAD=10 positions for a step whose top-k (k=5, k=2 in
the older script) contains the Yes/No token, falling back to position 0.

Here the scan is a vectorized jit'd op over the per-step score tensor produced
by ``models.decoder.greedy_decode`` / ``models.t5.greedy_decode`` — one device
program for the whole batch instead of a per-prompt Python loop.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp


class YesNoResult(NamedTuple):
    yes_prob: jnp.ndarray       # [B]
    no_prob: jnp.ndarray        # [B]
    relative_prob: jnp.ndarray  # [B]  p_yes / (p_yes + p_no), 0.5 when both 0
    odds_ratio: jnp.ndarray     # [B]  p_yes / p_no, +inf when p_no == 0
    found: jnp.ndarray          # [B]  bool: scan hit within max_look_ahead
    position: jnp.ndarray       # [B]  int: position read (0 on fallback)


@functools.partial(jax.jit, static_argnames=("max_look_ahead", "top_k"))
def yes_no_from_scores(
    scores: jnp.ndarray,   # [B, P, V] fp32 per-step generation scores
    yes_id: jnp.ndarray,   # [] or [B] int token id ("Yes" with leading space)
    no_id: jnp.ndarray,
    max_look_ahead: int = 10,
    top_k: int = 5,
    valid_steps=None,      # [B] int: scan-visible positions per row — HF
                           # generate stops at EOS, so the reference's scores
                           # list ends at the eos-emitting position (incl.);
                           # later positions must not produce hits
) -> YesNoResult:
    b, p, v = scores.shape
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    yes_id = jnp.broadcast_to(jnp.asarray(yes_id), (b,))
    no_id = jnp.broadcast_to(jnp.asarray(no_id), (b,))
    p_yes = jnp.take_along_axis(probs, yes_id[:, None, None], axis=-1)[..., 0]  # [B,P]
    p_no = jnp.take_along_axis(probs, no_id[:, None, None], axis=-1)[..., 0]
    # top-k membership == prob >= k-th largest prob (ties over-match, like the
    # reference's `token_id in topk(probs, k).indices` up to degenerate ties)
    kth = jax.lax.top_k(probs, top_k)[0][..., -1]                               # [B,P]
    look = min(max_look_ahead, p)
    hit = ((p_yes >= kth) | (p_no >= kth))[:, :look]
    if valid_steps is not None:
        hit = hit & (jnp.arange(look)[None, :] < valid_steps[:, None])
    found = jnp.any(hit, axis=1)
    first = jnp.argmax(hit, axis=1).astype(jnp.int32)
    sel = jnp.where(found, first, 0)
    yes = jnp.take_along_axis(p_yes, sel[:, None], axis=1)[:, 0]
    no = jnp.take_along_axis(p_no, sel[:, None], axis=1)[:, 0]
    total = yes + no
    relative = jnp.where(total > 0, yes / jnp.where(total > 0, total, 1.0), 0.5)
    odds = jnp.where(no > 0, yes / jnp.where(no > 0, no, 1.0), jnp.inf)
    return YesNoResult(yes, no, relative, odds, found, sel)


@functools.partial(jax.jit, static_argnames=("max_look_ahead", "top_k"))
def yes_no_from_reduced(
    topk_vals: jnp.ndarray,      # [B, P, K] fp32 top-K logits, descending
    logz: jnp.ndarray,           # [B, P] fp32 logsumexp over the vocab
    target_logits: jnp.ndarray,  # [B, P, 2] fp32 logits at (yes_id, no_id)
    max_look_ahead: int = 10,
    top_k: int = 5,
    valid_steps=None,
) -> YesNoResult:
    """:func:`yes_no_from_scores` on ``models.decoder.ReducedScores``
    statistics instead of the full [B, P, V] score tensor.

    Same scan semantics: top-k membership compares raw logits against the
    k-th largest logit (softmax is strictly monotone per row, so the
    membership set is identical to the probability comparison), and the
    probabilities are ``exp(logit - logsumexp)`` — the same quantity
    ``softmax`` computes, differing only in float summation order.
    Requires ``top_k <= K``.

    Tie caveat: like :func:`yes_no_from_scores`, exact ties with the k-th
    candidate over-match (``>=``).  Additionally, DISTINCT logits whose
    fp32 softmax probabilities round to the same value — deep-tail targets
    where ``exp(logit - logz)`` underflows or collides at the 2^-24
    resolution — compare as a tie on the probability path but not on this
    raw-logit path, so the found bit can differ between the two
    implementations for such degenerate rows.  Both target probabilities
    are ~0 there, so the relative probability the sweep records is 0.5
    either way; only the ``scan_found`` flag is affected.
    """
    b, p, k = topk_vals.shape
    if top_k > k:
        raise ValueError(f"top_k={top_k} > {k} kept candidates")
    p_yes = jnp.exp(target_logits[..., 0] - logz)   # [B,P]
    p_no = jnp.exp(target_logits[..., 1] - logz)
    kth = topk_vals[..., top_k - 1]                 # [B,P]
    look = min(max_look_ahead, p)
    hit = ((target_logits[..., 0] >= kth) | (target_logits[..., 1] >= kth))[:, :look]
    if valid_steps is not None:
        hit = hit & (jnp.arange(look)[None, :] < valid_steps[:, None])
    found = jnp.any(hit, axis=1)
    first = jnp.argmax(hit, axis=1).astype(jnp.int32)
    sel = jnp.where(found, first, 0)
    yes = jnp.take_along_axis(p_yes, sel[:, None], axis=1)[:, 0]
    no = jnp.take_along_axis(p_no, sel[:, None], axis=1)[:, 0]
    total = yes + no
    relative = jnp.where(total > 0, yes / jnp.where(total > 0, total, 1.0), 0.5)
    odds = jnp.where(no > 0, yes / jnp.where(no > 0, no, 1.0), jnp.inf)
    return YesNoResult(yes, no, relative, odds, found, sel)


def steps_until_eos(tokens: jnp.ndarray, eos_id) -> jnp.ndarray:
    """[B, P] greedy tokens → [B] scan-visible position count.

    HF ``generate`` appends a score entry, then samples; emitting EOS stops
    the loop — so the reference's scores list runs up to AND INCLUDING the
    eos-emitting position (run_base_vs_instruct_100q.py:337-358).  Batched
    decode keeps generating forced EOS past that point; those positions do
    not exist for the reference and must be invisible to the scan."""
    b, p = tokens.shape
    if eos_id is None:
        return jnp.full((b,), p, jnp.int32)
    is_eos = tokens == eos_id
    first = jnp.argmax(is_eos, axis=1)
    return jnp.where(jnp.any(is_eos, axis=1), first + 1, p).astype(jnp.int32)


def first_token_scan(logits: jnp.ndarray, yes_id, no_id, top_k: int = 5):
    """Position-0 leg of the scan, on prefill logits alone: [B, V] fp32 →
    (yes, no, relative, odds, hit).  ``hit`` marks rows whose position-0
    top-k already contains a target — the reference's loop reads exactly
    these probabilities for such rows and never looks at positions 1..9
    (run_base_vs_instruct_100q.py:349-364), so the two-phase engine skips
    their decode entirely.

    One convention, one implementation: this IS :func:`yes_no_from_scores`
    on a single-position score tensor (``found`` ≡ position-0 top-k hit)."""
    res = yes_no_from_scores(
        logits[:, None, :], yes_id, no_id, max_look_ahead=1, top_k=top_k
    )
    return res.yes_prob, res.no_prob, res.relative_prob, res.odds_ratio, res.found


@functools.partial(jax.jit, static_argnames=("top_filter",))
def relative_prob_first_token(logits: jnp.ndarray, yes_id, no_id, top_filter: int = 0):
    """Fast path: single-forward scoring at the final prompt position (the
    pjit'd sweep's hot op — BASELINE.json north star).  logits: [B, V] fp32.

    ``top_filter`` > 0 zeroes probabilities outside the top-N, matching the
    API extractor that only sees top-20 logprobs (perturb_prompts.py:480-498).
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    b = logits.shape[0]
    yes_id = jnp.broadcast_to(jnp.asarray(yes_id), (b,))
    no_id = jnp.broadcast_to(jnp.asarray(no_id), (b,))
    yes = jnp.take_along_axis(probs, yes_id[:, None], axis=-1)[:, 0]
    no = jnp.take_along_axis(probs, no_id[:, None], axis=-1)[:, 0]
    if top_filter:
        kth = jax.lax.top_k(probs, top_filter)[0][:, -1]
        yes = jnp.where(yes >= kth, yes, 0.0)
        no = jnp.where(no >= kth, no, 0.0)
    total = yes + no
    relative = jnp.where(total > 0, yes / jnp.where(total > 0, total, 1.0), 0.5)
    return yes, no, relative


def target_token_ids(tokenizer, targets: Sequence[str], encoder_decoder: bool = False):
    """Token ids the scan looks for.

    Decoder-only models match the reference's leading-space convention
    (``tokenizer(" Yes", add_special_tokens=False).input_ids[0]`` with a
    no-space fallback — run_base_vs_instruct_100q.py:332-335); encoder-decoder
    models take the first id of the bare word (ibid.:306-307).
    """
    ids = []
    for t in targets:
        if encoder_decoder:
            ids.append(tokenizer(t).input_ids[0])
            continue
        with_space = tokenizer(" " + t, add_special_tokens=False).input_ids
        ids.append(with_space[0] if with_space else tokenizer.encode(t)[0])
    return ids
