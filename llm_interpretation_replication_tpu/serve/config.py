"""Scheduler admission/backpressure knobs.

Deliberately jax-free (importable by the CLI argument layer and tests
without touching the device runtime).  The defaults target the latency
knee the batch-prompting literature keeps rediscovering (PAPERS.md,
Auto-Demo Prompting; the TPU-vs-GPU serving comparison): coalesce as
wide as one engine batch, but never hold the head request more than a
few tens of milliseconds waiting for co-batchable traffic.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence


@dataclasses.dataclass
class SchedulerConfig:
    #: rows per micro-batch; 0 = the engine's ``EngineConfig.batch_size``
    #: (the shape the warm compiled programs already exist for).
    max_batch: int = 0
    #: how long the scheduler holds the HEAD request open for compatible
    #: co-batchable traffic before launching a partial micro-batch.
    max_wait_s: float = 0.02
    #: admission-queue bound; a submit past it raises the typed
    #: :class:`~.request.QueueFull` (backpressure, never silent deferral).
    queue_capacity: int = 2048
    #: default per-request deadline applied when a request carries none
    #: (None = requests without ``timeout_s`` never expire).
    default_timeout_s: Optional[float] = None
    #: OOM re-queue ladder for split micro-batches (the PR-1 machinery,
    #: runtime/faults.next_batch_down); () = halving.  The FLOOR is where
    #: the scheduler stops splitting and fails the requests instead.
    oom_ladder: Sequence[int] = ()
    oom_floor: int = 1
    #: transient-retry policy for scheduler-driven engine calls (None =
    #: runtime/faults.default_transient_policy); OOM is excluded — the
    #: split/re-queue path owns it.
    retry_policy: Optional[object] = None
    #: close(drain=True) gives in-flight + queued work this long to
    #: finish before leftover requests fail with SchedulerClosed.
    drain_timeout_s: float = 120.0
    #: /healthz degrades when the OLDEST queued request has waited this
    #: long (serve/cli._metrics_endpoint): queue depth alone reads a
    #: wedged coalescer with a short queue as healthy — the head
    #: request's age cannot lie.  0 disables the check.
    health_max_queue_age_s: float = 30.0
    #: Slot-level continuous batching (runtime/slots.py): eligible
    #: micro-batches (plain binary scored requests on an engine without
    #: completion decoding) launch through
    #: ``ScoringEngine.score_prompts_slotted``, and newly-queued
    #: COMPATIBLE requests are admitted into vacated decode slots
    #: MID-DECODE (the ring's starvation hook polls the queue between
    #: chunks) instead of waiting for the next coalescer boundary.
    #: Default ON since the replay harness pinned slotted-vs-offline
    #: BIT parity (tests/test_slots.py; PARITY.md "Decode-then-repack")
    #: — occupancy is free once parity holds, and the disaggregated
    #: fleet's decode replicas NEED near-full rings to earn their role.
    #: ``--no-slot-admission`` (bench/serve CLI) is the escape hatch
    #: back to coalescer-boundary launches for A/B comparison.
    slot_admission: bool = True
    #: Prometheus labels stamped onto this scheduler's ``serve_*``
    #: counters / sample rings / latency histograms IN ADDITION to the
    #: unlabeled family (which stays the fleet-wide aggregate) — the
    #: EnginePool sets ``{"replica": id, "model": name}`` per replica so
    #: one wedged replica is visible as ITS series, not a fleet average.
    #: The labeled spelling is the telemetry-name convention
    #: ``name|k=v,k2=v2`` (obs/metrics.split_labeled_name); None = no
    #: labeled series.
    metric_labels: Optional[Dict[str, str]] = None
