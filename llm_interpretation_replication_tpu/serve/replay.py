"""Replay: push an OFFLINE sweep workload through the scheduler and prove
row-level parity with the direct ``score_prompts`` path.

This is the serve subsystem's acceptance harness: the same prompts, same
targets, same engine — once through the offline entry point and once as
independent scheduler requests — must yield row-identical results (the
scheduler coalesces requests back onto the engine's own bucketed batch
shapes, and per-row scoring is independent of co-batched rows at a fixed
program shape).  The report also carries the throughput comparison the
coalescing win is measured by (``bench.py --serve-replay``).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional, Sequence

from ..utils.telemetry import (
    counters,
    counters_since,
    sample_percentiles,
    sample_ring_report,
    sample_total,
)
from .config import SchedulerConfig
from .request import ScoreRequest, ServeError
from .scheduler import Scheduler


def _values_equal(a, b) -> bool:
    if isinstance(a, float) and isinstance(b, float):
        return (a == b) or (math.isnan(a) and math.isnan(b))
    return a == b


def rows_equal(a: Dict, b: Dict) -> bool:
    """Row-level parity: same keys, same values (NaN == NaN so error rows
    compare equal to themselves).  ``trace_id`` is measurement-only
    decoration the scheduler attaches when span tracing is armed (obs/) —
    it is ignored so a traced serve run keeps the same parity contract
    as an untraced one."""
    a = {k: v for k, v in a.items() if k != "trace_id"}
    b = {k: v for k, v in b.items() if k != "trace_id"}
    return (set(a) == set(b)
            and all(_values_equal(a[k], b[k]) for k in a))


def _per_request_targets(targets, n: int):
    if targets and not isinstance(targets[0], str):
        if len(targets) != n:
            raise ValueError(
                f"per-prompt targets: got {len(targets)} pairs for "
                f"{n} prompts")
        return [tuple(t) for t in targets]
    return [tuple(targets)] * n


def replay(engine, prompts: Sequence, targets=("Yes", "No"),
           with_confidence: bool = False,
           max_new_tokens: Optional[int] = None,
           config: Optional[SchedulerConfig] = None,
           offline_rows: Optional[List[Dict]] = None,
           offline_s: Optional[float] = None,
           require_parity: bool = True,
           result_timeout_s: float = 1200.0) -> Dict:
    """Score ``prompts`` offline AND through the scheduler; return the
    parity + throughput report.

    ``offline_rows``/``offline_s`` reuse an already-measured offline pass
    (bench mode) instead of re-scoring.  ``require_parity=True`` raises
    :class:`ServeError` on any mismatched row — the replay contract is
    row-IDENTICAL results, with mismatches named, never a silent skew."""
    prompts = list(prompts)
    per_targets = _per_request_targets(targets, len(prompts))
    if offline_rows is None:
        t0 = time.perf_counter()
        offline_rows = engine.score_prompts(
            prompts, targets=targets, with_confidence=with_confidence,
            max_new_tokens=max_new_tokens)
        offline_s = time.perf_counter() - t0
    cfg = config or SchedulerConfig()
    if cfg.queue_capacity < len(prompts):
        cfg = dataclasses.replace(cfg, queue_capacity=len(prompts))
    snap = counters()
    wait_total0 = sample_total("serve_queue_wait_ms")
    lat_total0 = sample_total("serve_latency_ms")
    sched = Scheduler(engine, cfg)
    # the serve clock starts BEFORE submission: per-request host
    # tokenization happens inside submit(), and the offline side pays the
    # same tokenization inside its timed score_prompts call — excluding
    # it here would systematically overstate the serve throughput
    t0 = time.perf_counter()
    try:
        futures = [
            sched.submit(ScoreRequest(prompt=p, targets=pair,
                                      with_confidence=with_confidence,
                                      max_new_tokens=max_new_tokens))
            for p, pair in zip(prompts, per_targets)
        ]
        sched.start()
        serve_rows = [f.result(timeout=result_timeout_s) for f in futures]
        serve_s = time.perf_counter() - t0
    finally:
        # a failed future must not leak the loop thread (or skip the
        # engine-pool sweep) for the life of the process
        sched.close()
    delta = counters_since(snap)

    mismatched = [i for i, (a, b) in enumerate(zip(offline_rows, serve_rows))
                  if not rows_equal(a, b)]
    report = {
        "rows": len(prompts),
        "mismatched_rows": len(mismatched),
        "mismatched_indices": mismatched[:20],
        "offline_s": round(offline_s, 3) if offline_s is not None else None,
        "serve_s": round(serve_s, 3),
        "offline_rows_per_s": (round(len(prompts) / offline_s, 2)
                               if offline_s else None),
        "serve_rows_per_s": (round(len(prompts) / serve_s, 2)
                             if serve_s else None),
        "serve_batches": int(delta.get("serve_batches", 0)),
        "serve_batch_rows": int(delta.get("serve_batch_rows", 0)),
        "serve_oom_splits": int(delta.get("serve_oom_splits", 0)),
        "blocked_transfers": int(delta.get("blocked_transfers", 0)),
        # percentiles scoped to THIS replay's samples (the rings are
        # process-global; an earlier replay's latencies must not leak in)
        "queue_wait_ms": sample_percentiles(
            "serve_queue_wait_ms",
            last=sample_total("serve_queue_wait_ms") - wait_total0),
        "latency_ms": sample_percentiles(
            "serve_latency_ms",
            last=sample_total("serve_latency_ms") - lat_total0),
        # truncation visibility: when a ring's total exceeds retained,
        # the bounded ring dropped history and the percentiles above are
        # tail statistics (utils/telemetry sample-ring semantics)
        "samples": sample_ring_report(
            ["serve_queue_wait_ms", "serve_latency_ms",
             "serve_queue_depth"]),
    }
    if mismatched and require_parity:
        i = mismatched[0]
        raise ServeError(
            f"serve replay parity failed: {len(mismatched)} of "
            f"{len(prompts)} rows differ from the offline path (first at "
            f"row {i}: offline={offline_rows[i]!r} vs "
            f"serve={serve_rows[i]!r})")
    report["serve_rows"] = serve_rows
    return report
