"""serve/ — continuous-batching request scheduler over the scoring engine.

The serving front door the ROADMAP's "heavy traffic from millions of
users" north star needs: independent scoring requests share ONE resident
model by coalescing onto the engine's warm compiled shapes
(:mod:`.coalescer`), launching as micro-batches under a
max-wait/max-batch admission policy (:mod:`.scheduler`), and fanning
results back out per-request as futures (:mod:`.request`).  Replay
(:mod:`.replay`) proves row-level parity with the offline sweep path;
the stdlib JSONL driver (:mod:`.cli`) is the
``python -m llm_interpretation_replication_tpu serve`` subcommand.
:mod:`.pool` scales the front door to a FLEET: an :class:`EnginePool`
of N engine replicas (and ``api_backends/`` vendors as
:class:`RemoteBackend` replicas) behind one router with per-model
queues, hot load/unload over the engine's verified teardown, and
cost/latency-aware backend selection.  :mod:`.supervisor` makes the
fleet self-healing: per-replica watchdogs classify crash vs wedge,
quarantine-and-rebuild with backoff, fail requests over to siblings
at-most-once, and trip circuit breakers on flaky remote vendors.
"""

from .config import SchedulerConfig
from .pool import (
    EnginePool,
    LocalReplica,
    ParamShareGroup,
    PoolClient,
    PoolClosed,
    PoolConfig,
    RemoteBackend,
    RemoteReplica,
    UnknownModel,
)
from .queue import RequestQueue, Ticket
from .replay import replay, rows_equal
from .request import (
    DeadlineExceeded,
    PoisonousRequest,
    QueueFull,
    SchedulerClosed,
    ScoreFuture,
    ScoreRequest,
    ServeError,
)
from .scheduler import Scheduler, labeled_metric
from .supervisor import CircuitBreaker, ReplicaSupervisor, SupervisorConfig

__all__ = [
    "CircuitBreaker",
    "DeadlineExceeded",
    "EnginePool",
    "LocalReplica",
    "ParamShareGroup",
    "PoisonousRequest",
    "PoolClient",
    "PoolClosed",
    "PoolConfig",
    "QueueFull",
    "RemoteBackend",
    "RemoteReplica",
    "ReplicaSupervisor",
    "RequestQueue",
    "SchedulerClosed",
    "Scheduler",
    "SchedulerConfig",
    "ScoreFuture",
    "ScoreRequest",
    "ServeError",
    "SupervisorConfig",
    "Ticket",
    "UnknownModel",
    "labeled_metric",
    "replay",
    "rows_equal",
]
