"""Typed request/future surface of the continuous-batching scheduler.

A :class:`ScoreRequest` is one independent scoring question — a formatted
prompt (or a ``(prefix, suffix)`` pair that rides the engine's fused
prefix-reuse path), its yes/no target pair, the leg knobs the engine's
``GenerationPlan`` cache keys on (``with_confidence`` /
``max_new_tokens``), a priority, and an optional deadline.  ``submit``
returns a :class:`ScoreFuture` that resolves to the engine's ordinary
result-row dict (runtime/engine._result_row contract) or to one of the
TYPED errors below — a rejected request is always told WHY (deadline,
backpressure, shutdown), never silently dropped.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional, Tuple


class ServeError(RuntimeError):
    """Base of every scheduler-raised error."""


class DeadlineExceeded(ServeError):
    """The request's deadline passed before its micro-batch launched."""


class QueueFull(ServeError):
    """Backpressure: the admission queue is at capacity.  Raised at
    ``submit`` time so the caller can shed load or retry — admission is
    never silently deferred past the queue bound."""


class SchedulerClosed(ServeError):
    """The scheduler shut down before (or while) the request could run."""


class PoisonousRequest(ServeError):
    """The same request took down multiple replicas (the supervisor's
    poison-row ceiling, serve/supervisor.py): after ``poison_kill_limit``
    replica crashes attributable to one request, it is rejected with this
    typed error instead of being failed over to — and killing — a third
    replica.  The caller learns the request itself is the hazard."""


@dataclasses.dataclass
class ScoreRequest:
    """One scoring request.

    Exactly one of ``prompt`` (a formatted prompt string or a
    pre-tokenized id list) or ``prefix``+``suffix`` (the fused
    prefix-reuse spelling — requests sharing a prefix coalesce into one
    ``score_prefixed`` batch and ride one ``PrefixCachePool`` entry per
    micro-batch).  ``timeout_s`` is relative to submit time; the
    scheduler converts it to an absolute monotonic deadline.  Higher
    ``priority`` launches first; FIFO within a priority level."""

    prompt: Any = None
    prefix: Any = None
    suffix: Any = None
    targets: Tuple[str, str] = ("Yes", "No")
    with_confidence: bool = False
    max_new_tokens: Optional[int] = None
    priority: int = 0
    timeout_s: Optional[float] = None
    #: joint K-token decode block size for THIS request's launch (the
    #: engine override the scheduler applies — EngineConfig.decode_k);
    #: None inherits the engine's configured value.  Part of the
    #: coalescer compatibility key: mixed-K requests must never share an
    #: engine call (the K path's chunk consumption differs per K).
    decode_k: Optional[int] = None
    #: which model should answer — read by the EnginePool router
    #: (serve/pool.py) to pick a compatible replica; inert on a
    #: single-engine Scheduler (its one engine IS the model).  None on
    #: a single-model pool resolves to that model.
    model: Optional[str] = None

    def validate(self) -> None:
        has_prompt = self.prompt is not None
        has_pair = self.prefix is not None or self.suffix is not None
        if has_prompt == has_pair:
            raise ValueError(
                "ScoreRequest takes exactly one of prompt= or "
                "prefix=+suffix=")
        if has_pair and (self.prefix is None or self.suffix is None):
            raise ValueError("prefix and suffix must be given together")
        if len(self.targets) != 2:
            raise ValueError(f"targets must be a (yes, no) pair, got "
                             f"{self.targets!r}")
        if self.decode_k is not None and self.decode_k < 1:
            raise ValueError(f"decode_k must be >= 1, got {self.decode_k}")


class ScoreFuture:
    """Thread-safe one-shot result slot for a submitted request.

    ``timing`` is the request's latency anatomy — set by the scheduler
    just before the result lands, so it is readable whenever ``result()``
    has returned: ``{"e2e_ms", "queue_wait_ms", "coalesce_ms",
    "serve_engine_ms", "respond_ms"}`` (serve/load.py semantics; the
    four phases sum to e2e).  It rides the FUTURE, not the result row,
    so the replay bit-parity contract never sees it.

    Resolution is AT-MOST-ONCE: the first ``_set_result`` /
    ``_set_exception`` wins and every later attempt is a silent no-op.
    Under the pool's failover and hedging paths (serve/supervisor.py) two
    legs of the same request can race to answer — first-wins is what makes
    "requests re-route to a sibling" safe without a cancellation protocol
    for the loser."""

    __slots__ = ("_event", "_lock", "_row", "_err", "timing")

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._row: Optional[Dict] = None
        self._err: Optional[BaseException] = None
        self.timing: Optional[Dict] = None

    # -- scheduler side --------------------------------------------------

    def _set_result(self, row: Dict) -> bool:
        """First resolution wins; returns False when already resolved."""
        with self._lock:
            if self._event.is_set():
                return False
            self._row = row
            self._event.set()
            return True

    def _set_exception(self, err: BaseException) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._err = err
            self._event.set()
            return True

    # -- caller side -----------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict:
        """The result-row dict; raises the request's typed error (or the
        engine error that failed its micro-batch) instead of returning.
        ``TimeoutError`` when the result is not ready within ``timeout``."""
        if not self._event.wait(timeout):
            raise TimeoutError("score request still pending")
        if self._err is not None:
            raise self._err
        assert self._row is not None
        return self._row

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        # graftlint: disable=G11 the in-tree callers (pool reap / supervisor orphan sweep) enter with the router lock held but only ever on done() futures and with timeout=0 — the event wait returns without blocking
        if not self._event.wait(timeout):
            raise TimeoutError("score request still pending")
        return self._err
