"""Stdlib-only JSONL driver for the ``serve`` CLI subcommand.

One request per input line::

    {"prompt": "Is a tweet a publication? ...", "targets": ["Yes", "No"]}
    {"prefix": "Is soup a beverage?", "suffix": " Answer Yes or No.",
     "with_confidence": false, "max_new_tokens": 10,
     "priority": 5, "timeout_s": 30.0}

One result per output line, in INPUT order, each echoing the 0-based
input ``id``: the engine's ordinary result-row dict on success, or
``{"id": N, "error": "...", "error_type": "DeadlineExceeded"}`` on a
typed rejection — a request is always answered, never dropped.

The replay entry (``serve --replay perturbations.json``) rebuilds the
perturbation sweep's prompt workload exactly as the offline sweep shell
does and routes it through :func:`..serve.replay.replay`, asserting
row-level parity and reporting scheduler-vs-offline throughput.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, Optional

from .config import SchedulerConfig
from .replay import replay
from .request import ScoreRequest, ServeError
from .scheduler import Scheduler

#: request-line keys accepted by :func:`parse_request_line`
_REQUEST_KEYS = ("prompt", "prefix", "suffix", "targets",
                 "with_confidence", "max_new_tokens", "priority",
                 "timeout_s")


def parse_request_line(obj: Dict) -> ScoreRequest:
    unknown = set(obj) - set(_REQUEST_KEYS)
    if unknown:
        raise ValueError(f"unknown request field(s): {sorted(unknown)}")
    kw = {k: obj[k] for k in _REQUEST_KEYS if k in obj}
    if "targets" in kw:
        kw["targets"] = tuple(kw["targets"])
    req = ScoreRequest(**kw)
    req.validate()
    return req


def _metrics_endpoint(sched, port: int):
    """``/metrics`` + ``/healthz`` for a live scheduler (obs/metrics.py):
    the Prometheus exposition over the telemetry counters and serve
    sample rings, plus a periodic sampler feeding the registry's
    time-series.  Returns the started server (caller closes), or None
    when ``port`` is falsy."""
    if not port:
        return None
    from ..obs import metrics as obs_metrics

    registry = obs_metrics.get_registry()
    registry.start_sampler()

    def health():
        return {"scheduler": "closed" if sched._closed else "running",
                "queue_depth": len(sched.queue)}

    server = obs_metrics.MetricsServer(registry, port,
                                       healthz_fn=health).start()
    print(f"# serve: metrics on :{server.port}/metrics, health on "
          f"/healthz", file=sys.stderr)
    return server


def run_jsonl_driver(engine, in_stream, out_stream,
                     config: Optional[SchedulerConfig] = None,
                     metrics_port: int = 0) -> Dict:
    """Read JSONL requests, serve them, write JSONL results in input
    order.  Returns ``{"requests": N, "errors": M}``."""
    entries = []  # (id, future-or-None, error-or-None)
    metrics_server = None
    try:
        with Scheduler(engine, config) as sched:
            metrics_server = _metrics_endpoint(sched, metrics_port)
            for i, line in enumerate(in_stream):
                line = line.strip()
                if not line:
                    continue
                try:
                    future = sched.submit(
                        parse_request_line(json.loads(line)))
                    entries.append((i, future, None))
                except (ValueError, KeyError, TypeError, ServeError) as err:
                    # malformed line, OR a typed admission rejection
                    # (QueueFull backpressure / SchedulerClosed): this line
                    # gets its error answer and the driver keeps going —
                    # already-admitted requests must still be served
                    entries.append((i, None, err))
            results = []
            for i, future, parse_err in entries:
                if parse_err is not None:
                    results.append((i, None, parse_err))
                    continue
                try:
                    results.append((i, future.result(timeout=None), None))
                except Exception as err:  # graftlint: disable=G05 CLI result relay: every per-request failure (typed rejection or engine error) becomes that request's JSON error line; the driver must answer the remaining lines
                    results.append((i, None, err))
    finally:
        if metrics_server is not None:
            metrics_server.close()
            # the periodic sampler _metrics_endpoint started must die
            # with the endpoint, or it keeps accumulating series for a
            # scraper that no longer exists
            from ..obs import metrics as obs_metrics

            obs_metrics.get_registry().stop_sampler()
    errors = 0
    for i, row, err in results:
        if err is not None:
            errors += 1
            out_stream.write(json.dumps(
                {"id": i, "error": str(err),
                 "error_type": type(err).__name__}) + "\n")
        else:
            out_stream.write(json.dumps({"id": i, **row}) + "\n")
    return {"requests": len(results), "errors": errors}


def run_replay(engine, perturbations_path: str,
               max_rephrasings: Optional[int] = None,
               config: Optional[SchedulerConfig] = None,
               require_parity: bool = True) -> Dict:
    """Replay the perturbation sweep's binary-leg workload through the
    scheduler (the prompts the offline shell builds: ``{rephrasing}
    {response_format}`` with per-scenario target pairs) and return the
    parity + throughput report."""
    with open(perturbations_path, encoding="utf-8") as f:
        scenarios = json.load(f)
    prompts, targets = [], []
    for s in scenarios:
        rephrasings = s["rephrasings"]
        if max_rephrasings is not None:
            rephrasings = rephrasings[:max_rephrasings]
        for r in rephrasings:
            prompts.append(f"{r} {s['response_format']}")
            targets.append(tuple(s["target_tokens"][:2]))
    report = replay(engine, prompts, targets=targets, config=config,
                    require_parity=require_parity)
    report.pop("serve_rows", None)
    return report


def main(engine, args) -> int:
    """The ``serve`` subcommand body (argparse args from __main__)."""
    config = SchedulerConfig(
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        queue_capacity=args.queue_capacity,
        default_timeout_s=args.timeout_s,
    )
    if args.replay:
        # require_parity=False: the CLI's job on a skew is the full JSON
        # report plus exit 1 — raising would swallow the report the
        # operator needs to see WHICH rows diverged
        report = run_replay(engine, args.replay,
                            max_rephrasings=args.max_rephrasings,
                            config=config, require_parity=False)
        print(json.dumps(report, indent=2))
        return 0 if report["mismatched_rows"] == 0 else 1
    in_stream = sys.stdin if args.input == "-" else open(
        args.input, encoding="utf-8")
    out_stream = sys.stdout if args.output == "-" else open(
        args.output, "w", encoding="utf-8")
    try:
        summary = run_jsonl_driver(engine, in_stream, out_stream, config,
                                   metrics_port=getattr(
                                       args, "metrics_port", 0) or 0)
    finally:
        if in_stream is not sys.stdin:
            in_stream.close()
        if out_stream is not sys.stdout:
            out_stream.close()
    print(f"# serve: {summary['requests']} request(s), "
          f"{summary['errors']} error(s)", file=sys.stderr)
    return 0
