"""Stdlib-only JSONL driver for the ``serve`` CLI subcommand.

One request per input line::

    {"prompt": "Is a tweet a publication? ...", "targets": ["Yes", "No"]}
    {"prefix": "Is soup a beverage?", "suffix": " Answer Yes or No.",
     "with_confidence": false, "max_new_tokens": 10,
     "priority": 5, "timeout_s": 30.0}

One result per output line, in INPUT order, each echoing the 0-based
input ``id``: the engine's ordinary result-row dict on success, or
``{"id": N, "error": "...", "error_type": "DeadlineExceeded"}`` on a
typed rejection — a request is always answered, never dropped.

The replay entry (``serve --replay perturbations.json``) rebuilds the
perturbation sweep's prompt workload exactly as the offline sweep shell
does and routes it through :func:`..serve.replay.replay`, asserting
row-level parity and reporting scheduler-vs-offline throughput.
"""

from __future__ import annotations

import contextlib
import json
import sys
from typing import Dict, Optional

from . import load as load_mod
from .config import SchedulerConfig
from .replay import replay
from .request import ScoreRequest, ServeError
from .scheduler import Scheduler

#: request-line keys accepted by :func:`parse_request_line`
#: ("model" routes a line to an EnginePool replica under
#: --pool-replicas; inert on a single-engine scheduler)
_REQUEST_KEYS = ("prompt", "prefix", "suffix", "targets",
                 "with_confidence", "max_new_tokens", "priority",
                 "timeout_s", "model")


def parse_request_line(obj: Dict) -> ScoreRequest:
    unknown = set(obj) - set(_REQUEST_KEYS)
    if unknown:
        raise ValueError(f"unknown request field(s): {sorted(unknown)}")
    kw = {k: obj[k] for k in _REQUEST_KEYS if k in obj}
    if "targets" in kw:
        kw["targets"] = tuple(kw["targets"])
    req = ScoreRequest(**kw)
    req.validate()
    return req


def scheduler_health(sched) -> Dict:
    """The scheduler's /healthz contribution: liveness + queue depth +
    the OLDEST queued request's age.  Depth alone reads a wedged
    coalescer with a short queue as healthy; a head request older than
    ``SchedulerConfig.health_max_queue_age_s`` degrades the document
    (the endpoint reports degraded, never 500s — obs/metrics.py)."""
    doc = {"scheduler": "closed" if sched._closed else "running",
           "queue_depth": len(sched.queue)}
    age = sched.queue.oldest_wait_s()
    max_age = getattr(sched.config, "health_max_queue_age_s", 0)
    if age is not None:
        doc["oldest_queued_age_s"] = round(age, 3)
        if max_age and age > max_age:
            doc["status"] = "degraded"
            doc["degraded_reason"] = (
                f"oldest queued request has waited {age:.1f}s "
                f"(> {max_age:g}s threshold)")
    return doc


def _metrics_endpoint(sched, port: int, healthz_fn=None):
    """``/metrics`` + ``/healthz`` for a live scheduler (obs/metrics.py):
    the Prometheus exposition over the telemetry counters, serve sample
    rings, and latency-anatomy histograms, plus a periodic sampler
    feeding the registry's time-series.  ``healthz_fn`` overrides the
    health contributor (the EnginePool hands its per-replica document);
    default: :func:`scheduler_health` over ``sched``.  Returns the
    started server (caller closes), or None when ``port`` is falsy."""
    if not port:
        return None
    from ..obs import metrics as obs_metrics

    registry = obs_metrics.get_registry()
    registry.start_sampler()
    server = obs_metrics.MetricsServer(
        registry, port,
        healthz_fn=healthz_fn or (lambda: scheduler_health(sched))).start()
    print(f"# serve: metrics on :{server.port}/metrics, health on "
          f"/healthz", file=sys.stderr)
    return server


def shared_sibling_factory(engine):
    """A rebuild factory over ONE loaded snapshot: each call constructs
    a fresh :class:`~..runtime.engine.ScoringEngine` sibling around the
    same param buffers / tokenizer / mesh / operating point.  This is
    what the supervisor runs to resurrect a quarantined replica — the
    shared arrays are still alive (the dead sibling's share-group slot
    transfers to its successor), so a rebuild costs a scheduler + warm
    compiled-shape reuse, never a second weight load."""
    from ..runtime.engine import ScoringEngine

    def factory():
        sibling = ScoringEngine(
            engine.family, engine.cfg, engine.params, engine.tokenizer,
            mesh=engine.mesh, engine_config=engine.ecfg)
        sibling.plan_decision = getattr(engine, "plan_decision", None)
        return sibling

    return factory


def build_shared_pool(engine, model: str, replicas: int,
                      config: Optional[SchedulerConfig] = None,
                      supervise=None):
    """An :class:`~.pool.EnginePool` of ``replicas`` local replicas of
    ONE loaded snapshot: siblings share the param tree (no extra weight
    HBM on the same devices — the arrays are the same buffers), each
    behind its own scheduler with ``{replica, model}`` metric labels.
    Ownership of the shared buffers is REFCOUNTED
    (:class:`~.pool.ParamShareGroup`): only the last sibling to unload
    releases them, whatever order the operator hot-unloads in.  When the
    CLI's --plan-search factory chose the snapshot's operating point,
    every sibling inherits it through the primary's engine config.

    ``supervise`` arms fleet self-healing (serve/supervisor.py): pass
    ``True`` for the default :class:`~.supervisor.SupervisorConfig` or a
    config instance; the shared-snapshot sibling constructor doubles as
    the rebuild factory, so a crashed or wedged replica comes back
    without reloading weights."""
    from ..runtime.engine import ScoringEngine
    from .pool import EnginePool, ParamShareGroup, PoolConfig
    from .supervisor import SupervisorConfig

    n = max(1, replicas)
    group = ParamShareGroup(n)
    sup_cfg = None
    if supervise:
        sup_cfg = (supervise if isinstance(supervise, SupervisorConfig)
                   else SupervisorConfig())
    pool = EnginePool(PoolConfig(scheduler=config, supervision=sup_cfg))
    pool.load(model, engine, share_group=group,
              plan_note=getattr(engine, "plan_decision", None))
    for _ in range(1, n):
        sibling = ScoringEngine(
            engine.family, engine.cfg, engine.params, engine.tokenizer,
            mesh=engine.mesh, engine_config=engine.ecfg)
        sibling.plan_decision = engine.plan_decision
        pool.load(model, sibling, share_group=group,
                  plan_note=engine.plan_decision)
    if pool.supervisor is not None:
        pool.supervisor.register_rebuild(model, shared_sibling_factory(engine))
    return pool


def run_jsonl_driver(engine, in_stream, out_stream,
                     config: Optional[SchedulerConfig] = None,
                     metrics_port: int = 0, pool=None) -> Dict:
    """Read JSONL requests, serve them, write JSONL results in input
    order.  Returns ``{"requests": N, "errors": M}``.  With ``pool``
    the requests route through the EnginePool front door instead of a
    fresh single-engine scheduler (lines may carry ``"model"``), and
    /healthz serves the pool's per-replica document; the pool's
    lifetime belongs to the caller."""
    entries = []  # (id, future-or-None, error-or-None)
    metrics_server = None
    try:
        with (contextlib.nullcontext(pool) if pool is not None
              else Scheduler(engine, config)) as sched:
            metrics_server = _metrics_endpoint(
                sched, metrics_port,
                healthz_fn=pool.health if pool is not None else None)
            for i, line in enumerate(in_stream):
                line = line.strip()
                if not line:
                    continue
                try:
                    future = sched.submit(
                        parse_request_line(json.loads(line)))
                    entries.append((i, future, None))
                except (ValueError, KeyError, TypeError, ServeError) as err:
                    # malformed line, OR a typed admission rejection
                    # (QueueFull backpressure / SchedulerClosed): this line
                    # gets its error answer and the driver keeps going —
                    # already-admitted requests must still be served
                    entries.append((i, None, err))
            results = []
            for i, future, parse_err in entries:
                if parse_err is not None:
                    results.append((i, None, parse_err))
                    continue
                try:
                    results.append((i, future.result(timeout=None), None))
                except Exception as err:  # graftlint: disable=G05 CLI result relay: every per-request failure (typed rejection or engine error) becomes that request's JSON error line; the driver must answer the remaining lines
                    results.append((i, None, err))
    finally:
        if metrics_server is not None:
            metrics_server.close()
            # the periodic sampler _metrics_endpoint started must die
            # with the endpoint, or it keeps accumulating series for a
            # scraper that no longer exists
            from ..obs import metrics as obs_metrics

            obs_metrics.get_registry().stop_sampler()
    errors = 0
    for i, row, err in results:
        if err is not None:
            errors += 1
            out_stream.write(json.dumps(
                {"id": i, "error": str(err),
                 "error_type": type(err).__name__}) + "\n")
        else:
            out_stream.write(json.dumps({"id": i, **row}) + "\n")
    return {"requests": len(results), "errors": errors}


def run_replay(engine, perturbations_path: str,
               max_rephrasings: Optional[int] = None,
               config: Optional[SchedulerConfig] = None,
               require_parity: bool = True) -> Dict:
    """Replay the perturbation sweep's binary-leg workload through the
    scheduler (the prompts the offline shell builds: ``{rephrasing}
    {response_format}`` with per-scenario target pairs — ONE builder,
    shared with the load harness: :func:`..serve.load.corpus_workload`)
    and return the parity + throughput report."""
    prompts, targets = load_mod.corpus_workload(
        perturbations_path, max_rephrasings=max_rephrasings)
    report = replay(engine, prompts, targets=targets, config=config,
                    require_parity=require_parity)
    report.pop("serve_rows", None)
    return report


def run_load_cli(engine, args, config: SchedulerConfig, pool=None) -> int:
    """``serve --load-rate``: the open-loop load harness (serve/load.py)
    over the perturbation corpus (``--replay PATH`` supplies it) or the
    ``--input`` JSONL request lines as the prompt pool.  A single rate
    runs one operating point; a comma-separated list of >= 3 walks the
    rate sweep and reports the knee.  Exits 1 on a parity mismatch.
    With ``pool`` (``--pool-replicas``) the SAME harness drives the
    EnginePool front door via ``pool.client()``; ``engine`` stays the
    offline parity reference."""
    rates = [float(r) for r in str(args.load_rate).split(",") if r.strip()]
    if not rates:
        print("# serve load: --load-rate parsed to no rates; pass one "
              "rate or a comma list of >= 3", file=sys.stderr)
        return 2
    if 1 < len(rates) < 3:
        # never silently drop a requested rate: a sweep needs >= 3 points
        # to bracket a knee, one point runs alone — two is ambiguous
        print(f"# serve load: --load-rate with multiple rates needs >= 3 "
              f"to bracket a knee (got {len(rates)}); pass one rate or "
              f"add a third", file=sys.stderr)
        return 2
    if args.replay:
        prompts, targets = load_mod.corpus_workload(
            args.replay, max_rephrasings=args.max_rephrasings)
    else:
        prompts, targets = [], []
        stream = sys.stdin if args.input == "-" else open(
            args.input, encoding="utf-8")
        try:
            for line in stream:
                line = line.strip()
                if not line:
                    continue
                req = parse_request_line(json.loads(line))
                if req.prompt is None:
                    raise ValueError(
                        "load mode pools plain-prompt request lines; "
                        "prefix/suffix pairs are not poolable")
                prompts.append(req.prompt)
                targets.append(tuple(req.targets))
        finally:
            if stream is not sys.stdin:
                stream.close()
    if not prompts:
        print("# serve load: empty prompt pool (need --replay or "
              "--input lines)", file=sys.stderr)
        return 2
    # --metrics-port works in load mode too: the latency-anatomy
    # histogram families exported on /metrics exist exactly for a
    # scraper watching a load run.  The scheduler is created inside
    # run_load per rate point, so /healthz carries the generic liveness
    # document (no per-scheduler queue health here).
    server = None
    if getattr(args, "metrics_port", 0):
        from ..obs import metrics as obs_metrics

        registry = obs_metrics.get_registry()
        registry.start_sampler()
        server = obs_metrics.MetricsServer(
            registry, args.metrics_port).start()
        print(f"# serve load: metrics on :{server.port}/metrics",
              file=sys.stderr)
    try:
        kw = dict(duration_s=args.load_duration, seed=args.load_seed,
                  config=config, jsonl=getattr(args, "load_jsonl", None))
        if pool is not None:
            kw["scheduler_factory"] = lambda cfg: pool.client()
        if len(rates) >= 3:
            block = load_mod.rate_sweep(engine, prompts, targets=targets,
                                        rates=rates,
                                        closed_comparator=True, **kw)
            print(load_mod.format_rate_table(block), file=sys.stderr)
            print(json.dumps(block, indent=2))
            return 0 if block.get("parity_ok") in (True, None) else 1
        report = load_mod.run_load(engine, prompts, targets=targets,
                                   rate=rates[0], **kw)
        print(json.dumps(report, indent=2))
        parity = report.get("parity")
        return 0 if parity is None or parity["mismatched_rows"] == 0 else 1
    finally:
        if server is not None:
            server.close()
            from ..obs import metrics as obs_metrics

            obs_metrics.get_registry().stop_sampler()


def main(engine, args) -> int:
    """The ``serve`` subcommand body (argparse args from __main__)."""
    config = SchedulerConfig(
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        queue_capacity=args.queue_capacity,
        default_timeout_s=args.timeout_s,
        slot_admission=not getattr(args, "no_slot_admission", False),
    )
    replicas = getattr(args, "pool_replicas", 0) or 0
    pool = None
    # the bare --replay harness is single-engine parity by construction;
    # every other mode (JSONL driver, --load-rate — including load over
    # the --replay corpus) serves through the pool when asked
    if replicas > 1 and (getattr(args, "load_rate", None)
                         or not args.replay):
        supervise = bool(getattr(args, "supervise", False))
        pool = build_shared_pool(engine, getattr(args, "model", "model"),
                                 replicas, config, supervise=supervise)
        print(f"# serve: EnginePool with {replicas} replicas of "
              f"{getattr(args, 'model', 'model')} (shared snapshot"
              f"{', supervised' if supervise else ''})",
              file=sys.stderr)
    try:
        if getattr(args, "load_rate", None):
            return run_load_cli(engine, args, config, pool=pool)
        if args.replay:
            # require_parity=False: the CLI's job on a skew is the full
            # JSON report plus exit 1 — raising would swallow the report
            # the operator needs to see WHICH rows diverged.  (The replay
            # harness is single-engine by construction; --pool-replicas
            # is inert here.)
            report = run_replay(engine, args.replay,
                                max_rephrasings=args.max_rephrasings,
                                config=config, require_parity=False)
            print(json.dumps(report, indent=2))
            return 0 if report["mismatched_rows"] == 0 else 1
        in_stream = sys.stdin if args.input == "-" else open(
            args.input, encoding="utf-8")
        out_stream = sys.stdout if args.output == "-" else open(
            args.output, "w", encoding="utf-8")
        try:
            summary = run_jsonl_driver(engine, in_stream, out_stream,
                                       config,
                                       metrics_port=getattr(
                                           args, "metrics_port", 0) or 0,
                                       pool=pool)
        finally:
            if in_stream is not sys.stdin:
                in_stream.close()
            if out_stream is not sys.stdout:
                out_stream.close()
        print(f"# serve: {summary['requests']} request(s), "
              f"{summary['errors']} error(s)", file=sys.stderr)
        return 0
    finally:
        if pool is not None:
            pool.close()
