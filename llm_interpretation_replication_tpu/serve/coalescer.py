"""Compatibility keys: which requests may share one micro-batch.

Two requests coalesce only when the engine would run them through the
SAME warm compiled-program family — that is exactly the
``GenerationPlan`` cache key (runtime/plan.plan_cache_key: the per-call
``max_new_tokens`` cap and ``with_confidence`` change the generation
schedule, so mixing them would force one call's plan on the other's
rows), the same scoring path (plain vs fused prefix+suffix), and the
same length bucket (runtime/batching bucket menu — the shape the
bucketed prefill programs compile for).  Targets are NOT part of the key:
the engine broadcasts per-row (yes, no) token-id operands, so mixed
scenarios batch together (the PR-2 cross-scenario win).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ..runtime import batching
from ..runtime.plan import plan_cache_key
from .request import ScoreRequest

#: key kinds
PLAIN = "plain"
PREFIXED = "prefixed"


def encode_request(engine, req: ScoreRequest) -> Any:
    """Pre-tokenize on the SUBMIT thread (host work stays off the
    scheduler loop): a plain prompt becomes a token-id list; a
    ``(prefix, suffix)`` pair becomes ``(prefix_ids, suffix_ids)``
    (prefix with special tokens, suffix without — the fused-path
    contract).  Engines without a tokenizer (test fakes, remote shims)
    get ``None`` and receive the raw strings."""
    tok = getattr(engine, "tokenizer", None)
    if tok is None:
        return None
    if req.prefix is not None:
        pe, se = batching.encode_prefix_pairs(tok, [(req.prefix,
                                                     (req.suffix,))])
        return pe[0], se[0][0]
    return batching.encode_prompts(tok, [req.prompt])[0]


def _bucket_of(engine, length: Optional[int]) -> Any:
    if length is None:
        return None
    ecfg = getattr(engine, "ecfg", None)
    buckets = ecfg.buckets if ecfg is not None else batching.DEFAULT_BUCKETS
    try:
        return batching.bucket_for(length, buckets)
    except ValueError:
        return "overflow"  # longer than the largest bucket: own group


def compat_key(engine, req: ScoreRequest, encoded: Any) -> Tuple:
    """The micro-batch compatibility key for one request.

    ``decode_k`` is part of the key (ISSUE 13): the joint K-token decode
    consumes chunks in K-sized verification blocks, so two requests
    resolving to DIFFERENT K would force one request's block schedule on
    the other's rows — mixed-K requests must never share an engine call.
    A request's ``decode_k=None`` resolves to the engine's configured
    value, so plain traffic on a K-configured engine still coalesces."""
    ecfg = getattr(engine, "ecfg", None)
    if ecfg is not None:
        plan_part = plan_cache_key(
            ecfg.score_steps, ecfg.max_look_ahead, ecfg.max_new_tokens,
            ecfg.decode_completions, req.max_new_tokens)
    else:
        plan_part = (req.max_new_tokens,)
    engine_k = int(getattr(ecfg, "decode_k", 1) or 1) if ecfg is not None \
        else 1
    decode_k = int(req.decode_k) if req.decode_k is not None else engine_k
    if req.prefix is not None:
        length = len(encoded[0]) if encoded is not None else None
        kind = PREFIXED
    else:
        length = len(encoded) if encoded is not None else None
        kind = PLAIN
    return (kind, _bucket_of(engine, length), bool(req.with_confidence),
            req.max_new_tokens, decode_k, plan_part)
