"""EnginePool: a multi-replica serving fleet behind one front door.

Everything below serve/ so far assumes one process, one model, one
engine — the paper's own workloads do.  The ROADMAP north star ("heavy
traffic from millions of users") needs the other shape: N
:class:`~..runtime.engine.ScoringEngine` replicas — N copies of one
model across mesh slices, or N distinct models (the instruct-sweep
roster) — served through ONE front door, with hot load/unload and the
``api_backends/`` vendors riding the same router as local replicas.
This is the serving-economics territory of the Gemma TPU-serving
comparison (arxiv 2605.25645): the pool is measured through the SAME
``bench --serve-load`` harness as the single-engine scheduler, so
replica count becomes an axis of the latency-anatomy curve instead of a
deployment rumor.

Composition — the pool goes THROUGH the existing layers, never around
them:

- each LOCAL replica is an ordinary :class:`~.scheduler.Scheduler` over
  its own engine: coalescing, the OOM split/re-queue ladder, strict-mode
  transfer guards, and the latency-anatomy histograms all keep working
  per replica, and the pool stamps ``{replica, model}`` metric labels
  (:func:`~.scheduler.labeled_metric`) so the ``serve_*`` families
  export per-replica series next to the fleet aggregate;
- REMOTE replicas (:class:`RemoteBackend`) adapt the ``api_backends/``
  vendor clients to the same result-row contract and the same router,
  with per-request cost estimated from :mod:`..api_backends.cost`
  pricing and observed latency folded into the routing score —
  cost/latency-aware backend selection, not a separate code path;
- per-replica OPERATING POINTS come from the auto-parallel plan search
  (:func:`~..runtime.plan_search.replica_plan`): a replica's mesh slice
  prices its own batch/kv-dtype/chunk/pool-target instead of inheriting
  the single-engine flags;
- hot unload rides :meth:`~..runtime.engine.ScoringEngine.close`
  (verified device-buffer teardown): the drained replica's HBM returns
  to baseline, so loading a DIFFERENT model into the same process is an
  ordinary ``load()`` — the in-process capability the bench's
  full-study subprocess isolation stood in for.

Routing: ``submit`` lands the request on its model's FIFO queue; the
dispatcher moves it to the least-loaded compatible replica (smallest
predicted wait = observed-latency EWMA x (1 + outstanding), plus the
cost term for remote backends).  A replica mid-drain is never selected;
a request that a closing replica bounces (typed ``SchedulerClosed``)
re-enters its model queue and is re-dispatched — the pool's
always-answered contract: every admitted request resolves with a row or
a typed error, never silently dropped.

Disaggregation (ROADMAP item 1): replicas loaded with
``role="prefill"``/``role="decode"`` split the phases across the fleet
— prefill specialists run chunked prefill + the position-0 scan and
ship undecided rows' int8/bf16 KV slabs (:class:`~..runtime.slots.KVSlab`)
to decode specialists, whose slot rings import them mid-flight and stay
near-full.  The router's role affinity keeps fresh prompts off decode
chips unless nothing else is live, and every replica may own a REAL
mesh slice (``devices=`` from :func:`~..parallel.mesh.carve_slices`)
instead of time-slicing one default mesh.

Measurement-only routing (PARITY.md): the pool changes WHERE and WHEN a
row is computed, never WHAT — local replica rows are bit-identical to
the same engine's offline ``score_prompts`` (tests/test_pool.py pins
it).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.telemetry import record_counter
from .config import SchedulerConfig
from .request import (
    DeadlineExceeded,
    QueueFull,
    SchedulerClosed,
    ScoreFuture,
    ScoreRequest,
    ServeError,
)
from .scheduler import Scheduler
from .supervisor import ReplicaSupervisor, SupervisorConfig

#: router reap tick while work is IN FLIGHT: replica futures resolve on
#: replica threads that cannot signal the pool's condition, so completion
#: detection polls at this cadence (the per-hop latency floor it adds).
DISPATCH_TICK_S = 0.002

#: router tick while the pool is IDLE (nothing queued, nothing in
#: flight): submits/loads/unloads/close all signal the condition, so the
#: coarse tick only bounds how stale the deadline sweep of an orphaned
#: queue can get — a quiet serving process wakes ~4x/s, not ~500x.
IDLE_TICK_S = 0.25

#: observed-latency EWMA smoothing per replica (e2e seconds).
LATENCY_EWMA_ALPHA = 0.2

#: predicted-wait floor: before a replica has any observed latency its
#: EWMA is this, so the load term (1 + outstanding) still differentiates
#: two cold replicas instead of scoring both 0.
LATENCY_FLOOR_S = 1e-3


class PoolClosed(ServeError):
    """The pool shut down before (or while) the request could run."""


class UnknownModel(ServeError):
    """``submit`` named a model no replica serves (and none ever did)."""


@dataclasses.dataclass
class PoolConfig:
    """Router/backend-selection knobs.  ``scheduler`` is the TEMPLATE for
    every local replica's :class:`~.config.SchedulerConfig` — the pool
    copies it per replica and stamps the ``{replica, model}`` metric
    labels on each copy."""

    scheduler: Optional[SchedulerConfig] = None
    #: backend-selection weights: a replica's routing score is
    #: ``latency_weight * predicted_wait_s + cost_weight * cost_usd *
    #: cost_scale_s_per_usd``.  Local replicas cost $0, so with
    #: ``cost_weight`` dominant the router prefers local capacity and
    #: spills to vendors only when local queues grow; with
    #: ``latency_weight`` dominant it chases the fastest observed
    #: backend regardless of price.
    cost_weight: float = 0.5
    latency_weight: float = 0.5
    #: USD -> seconds exchange rate of the routing score (how many
    #: seconds of predicted wait one dollar of vendor spend is worth).
    cost_scale_s_per_usd: float = 1000.0
    #: close(drain=True) gives queued + in-flight work this long before
    #: leftovers fail with the typed :class:`PoolClosed`.
    drain_timeout_s: float = 120.0
    #: a replica whose oldest queued request has waited this long reads
    #: ``degraded`` in :meth:`EnginePool.health` (0 disables; falls back
    #: to the scheduler template's ``health_max_queue_age_s``).
    health_max_queue_age_s: float = 0.0
    #: fleet self-healing (serve/supervisor.py): None (default) keeps
    #: the pool report-only — replica failures propagate to callers
    #: exactly as before this layer existed.  A
    #: :class:`~.supervisor.SupervisorConfig` arms crash/wedge
    #: detection, quarantine + rebuild, in-flight failover, hedging,
    #: and vendor circuit breakers.
    supervision: Optional[SupervisorConfig] = None


@dataclasses.dataclass
class _PoolTicket:
    """One admitted request travelling through the pool router."""

    request: ScoreRequest
    future: ScoreFuture
    model: str
    enqueue_t: float
    seq: int = 0                    # admission order (FIFO tie-break)
    deadline: Optional[float] = None  # absolute monotonic, None = never
    replica_future: Optional[ScoreFuture] = None
    replica: Optional["_BaseReplica"] = None
    dispatch_t: Optional[float] = None
    #: supervision bookkeeping (serve/supervisor.py): failed-over hops,
    #: replicas this request's leg took down (the poison-row ceiling),
    #: and the optional tail-latency hedge leg.  Rides the TICKET, never
    #: the request or the row — replay bit-parity never sees it.
    failovers: int = 0
    kills: int = 0
    hedge_future: Optional[ScoreFuture] = None
    hedge_replica: Optional["_BaseReplica"] = None

    def sort_key(self):
        return (-self.request.priority, self.seq)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class ParamShareGroup:
    """Refcounted ownership of ONE param tree shared by sibling
    replicas (the bench/CLI fleet over a single snapshot): each
    sibling's teardown releases a reference, and only the LAST release
    reports that the shared buffers may be deleted — so hot-unloading
    the siblings in ANY order never deletes buffers a survivor still
    scores through."""

    def __init__(self, count: int):
        self._count = max(1, int(count))
        self._lock = threading.Lock()

    def acquire_one(self) -> None:
        """Add one reference (a rebuilt sibling joining the group after
        a quarantine, serve/supervisor.py)."""
        with self._lock:
            self._count += 1

    def release_one(self) -> bool:
        """True exactly once: on the release that drops the last ref."""
        with self._lock:
            self._count -= 1
            return self._count == 0


class _BaseReplica:
    """Shared replica surface: identity, lifecycle state, load/latency
    accounting the router scores on."""

    kind = "local"

    def __init__(self, rid: str, model: str,
                 role: Optional[str] = None):
        self.rid = rid
        self.model = model
        #: disaggregation role (ROADMAP item 1b): None = general (serves
        #: everything), "prefill" = runs prefill + position-0 scan and
        #: hands undecided KV slabs off, "decode" = imports slabs into
        #: its slot ring; fresh prompts route to it only when no
        #: prefill/general sibling is live (always-answered beats role
        #: purity).
        self.role = role
        self.state = "live"            # live | draining | closed
        self.outstanding = 0           # dispatched, not yet resolved
        self.completed = 0
        self.failed = 0
        self.latency_ewma_s = 0.0

    # -- router accounting ----------------------------------------------

    def note_latency(self, e2e_s: float) -> None:
        if self.latency_ewma_s <= 0.0:
            self.latency_ewma_s = e2e_s
        else:
            self.latency_ewma_s += LATENCY_EWMA_ALPHA * (
                e2e_s - self.latency_ewma_s)

    def predicted_wait_s(self) -> float:
        est = max(self.latency_ewma_s, LATENCY_FLOOR_S)
        return est * (1.0 + self.outstanding + self.queue_depth())

    def cost_estimate_usd(self, request: ScoreRequest) -> float:
        return 0.0

    def queue_depth(self) -> int:
        return 0

    def oldest_wait_s(self) -> Optional[float]:
        return None

    def health(self, max_age_s: float) -> Dict:
        doc = {
            "replica": self.rid,
            "model": self.model,
            "kind": self.kind,
            "state": self.state,
            "queue_depth": self.queue_depth(),
            "outstanding": self.outstanding,
            "completed": self.completed,
            "failed": self.failed,
            "latency_ewma_ms": round(self.latency_ewma_s * 1000.0, 3),
        }
        if self.role is not None:
            doc["role"] = self.role
        age = self.oldest_wait_s()
        if age is not None:
            doc["oldest_wait_s"] = round(age, 3)
            if max_age_s and age > max_age_s:
                doc["status"] = "degraded"
                doc["degraded_reason"] = (
                    f"oldest queued request has waited {age:.1f}s "
                    f"(> {max_age_s:g}s threshold)")
        return doc


class LocalReplica(_BaseReplica):
    """One resident :class:`ScoringEngine` behind its own
    :class:`Scheduler`.  ``owns_engine`` controls whether unload calls
    ``engine.close(release_params=True)``: replicas sharing one param
    tree (bench fleets over a single snapshot) release buffers only when
    the LAST sibling unloads.

    ``devices`` binds the replica's engine to a REAL mesh slice (a
    contiguous run from :func:`~..parallel.mesh.carve_slices`): the
    engine's params are ``device_put`` onto the slice before the
    scheduler starts, so the replica owns its chips instead of N
    replicas time-slicing one default mesh.  On the CPU harness the
    carver degenerates to shared placement (every slice = all devices)
    and the health doc says so."""

    def __init__(self, rid: str, model: str, engine,
                 config: SchedulerConfig, owns_engine: bool = True,
                 plan_note: Optional[str] = None,
                 share_group: Optional[ParamShareGroup] = None,
                 role: Optional[str] = None,
                 devices=None):
        super().__init__(rid, model, role=role)
        self.engine = engine
        self.owns_engine = owns_engine
        self.share_group = share_group
        self.plan_note = plan_note
        self.devices = None if devices is None else tuple(devices)
        if self.devices:
            from ..parallel import mesh as mesh_mod

            engine.bind_mesh(mesh_mod.make_mesh(
                data=len(self.devices), devices=list(self.devices)))
        cfg = dataclasses.replace(
            config, metric_labels={**(config.metric_labels or {}),
                                   "replica": rid, "model": model})
        self.scheduler = Scheduler(engine, cfg).start()

    def dispatch(self, ticket: _PoolTicket) -> ScoreFuture:
        return self.scheduler.submit(ticket.request)

    def queue_depth(self) -> int:
        return len(self.scheduler.queue)

    def oldest_wait_s(self) -> Optional[float]:
        return self.scheduler.queue.oldest_wait_s()

    def shutdown(self, drain: bool = True,
                 release_params: Optional[bool] = None) -> None:
        """Drain the scheduler, then tear the engine down
        (:meth:`ScoringEngine.close` — verified buffer release).  A
        replica in a :class:`ParamShareGroup` releases the shared tree
        only when it is the LAST sibling to shut down, whatever the
        unload order."""
        self.state = "closed"
        self.scheduler.close(drain=drain)
        close = getattr(self.engine, "close", None)
        if close is not None:
            if release_params is not None:
                release = release_params
            elif self.share_group is not None:
                release = self.share_group.release_one()
            else:
                release = self.owns_engine
            close(release_params=release)

    def health(self, max_age_s: float) -> Dict:
        doc = super().health(max_age_s)
        if self.plan_note:
            doc["plan"] = self.plan_note
        if self.devices is not None:
            doc["devices"] = len(self.devices)
            # the CPU-harness carver hands every slice the full device
            # list; flag it so a health reader never mistakes the
            # degenerate placement for a real slice
            import jax

            doc["placement"] = ("shared" if len(self.devices)
                                >= len(jax.devices()) else "sliced")
        return doc


class RemoteBackend:
    """An ``api_backends/`` vendor client as a pool replica's engine.

    ``evaluate(prompt, targets, with_confidence, max_new_tokens)``
    returns a vendor-shaped dict (the :mod:`..api_backends.evaluators`
    contract: ``yes_prob``/``no_prob``/``relative_prob``/``response``,
    optionally ``confidence``/``weighted_confidence``/``raw``); the
    backend normalizes it to the engine result-row schema so the pool's
    callers never see which backend answered.  Construction helpers
    (:meth:`openai`, :meth:`gemini`, :meth:`anthropic`) wrap the
    existing clients — tests drive them end to end with
    ``api_backends.transport.FakeTransport``.

    Cost: per-request USD estimated from :class:`CostTracker` pricing
    (chars/4 prompt-token heuristic; actual usage is recorded into the
    tracker when the vendor response carries a ``usage`` block), which
    the router's cost term reads BEFORE dispatch."""

    #: prompt-chars-per-token estimation heuristic for pre-dispatch cost.
    CHARS_PER_TOKEN = 4.0
    #: assumed completion tokens when the request caps nothing (the
    #: binary contract answers in a handful of tokens).
    DEFAULT_OUTPUT_TOKENS = 16

    def __init__(self, model: str, evaluate: Callable[..., Dict],
                 pricing: Optional[Dict] = None, cost_tracker=None):
        from ..api_backends.cost import CostTracker

        self.model = model
        self.evaluate = evaluate
        self.tracker = cost_tracker or CostTracker(pricing=pricing)
        if pricing is not None:
            self.tracker.pricing = dict(self.tracker.pricing or {})
            self.tracker.pricing.update(pricing)

    # -- vendor constructors --------------------------------------------

    @classmethod
    def openai(cls, client, model: str, **kw) -> "RemoteBackend":
        from ..api_backends import evaluators

        def evaluate(prompt, targets, with_confidence, max_new_tokens):
            if with_confidence:
                return evaluators.evaluate_gpt_confidence(
                    client, model, prompt)
            return evaluators.evaluate_gpt_binary(
                client, model, prompt, targets=tuple(targets))

        return cls(model, evaluate, **kw)

    @classmethod
    def gemini(cls, client, model: str, **kw) -> "RemoteBackend":
        from ..api_backends import evaluators

        def evaluate(prompt, targets, with_confidence, max_new_tokens):
            if with_confidence:
                return evaluators.evaluate_gemini_confidence(
                    client, model, prompt)
            return evaluators.evaluate_gemini_binary(
                client, model, prompt, targets=tuple(targets))

        return cls(model, evaluate, **kw)

    @classmethod
    def anthropic(cls, client, model: str, **kw) -> "RemoteBackend":
        from ..api_backends import evaluators

        def evaluate(prompt, targets, with_confidence, max_new_tokens):
            return evaluators.evaluate_claude(client, model, prompt)

        return cls(model, evaluate, **kw)

    # -- contract -------------------------------------------------------

    def cost_estimate_usd(self, request: ScoreRequest) -> float:
        p = self.tracker.pricing.get(self.model)
        if not p:
            return 0.0
        prompt = request.prompt if isinstance(request.prompt, str) else ""
        in_tok = len(prompt) / self.CHARS_PER_TOKEN
        out_tok = request.max_new_tokens or self.DEFAULT_OUTPUT_TOKENS
        return (in_tok / 1e6 * p.get("input", 0.0)
                + out_tok / 1e6 * p.get("output", 0.0))

    def score_one(self, request: ScoreRequest) -> Dict:
        if request.prompt is None:
            raise ValueError(
                "remote backends score plain prompts; the prefix/suffix "
                "fused spelling is a local-engine capability")
        vendor = self.evaluate(request.prompt, request.targets,
                               request.with_confidence,
                               request.max_new_tokens)
        raw = vendor.get("raw")
        if isinstance(raw, dict) and raw.get("usage"):
            self.tracker.record_response(self.model, raw)
        else:
            prompt = request.prompt if isinstance(request.prompt, str) else ""
            self.tracker.record(
                self.model, int(len(prompt) / self.CHARS_PER_TOKEN),
                self.DEFAULT_OUTPUT_TOKENS)
        return self._result_row(vendor)

    @staticmethod
    def _result_row(vendor: Dict) -> Dict:
        """Vendor dict -> the engine's result-row schema
        (runtime/engine._result_row contract).  Fields a vendor cannot
        provide (odds_ratio without both probs, scan_found) derive or
        default honestly rather than pretending."""
        yes = float(vendor.get("yes_prob", float("nan")))
        no = float(vendor.get("no_prob", float("nan")))
        rel = vendor.get("relative_prob")
        if rel is None and yes == yes and no == no and (yes + no) > 0:
            rel = yes / (yes + no)
        row = {
            "yes_prob": yes,
            "no_prob": no,
            "relative_prob": (float(rel) if rel is not None
                              else float("nan")),
            "odds_ratio": (yes / no if no and no == no and yes == yes
                           else float("nan")),
            "scan_found": bool(vendor.get("yes_prob") is not None
                               or vendor.get("no_prob") is not None),
            "completion": str(vendor.get("response", "")),
            "success": True,
        }
        for key in ("confidence", "weighted_confidence"):
            if key in vendor:
                row[key] = vendor[key]
        return row


class RemoteReplica(_BaseReplica):
    """A :class:`RemoteBackend` behind the same router as local
    replicas: one daemon worker drains this replica's FIFO (vendor
    clients are blocking HTTP), latency lands in the same EWMA the
    router scores, and spend accumulates in the backend's tracker."""

    kind = "remote"

    def __init__(self, rid: str, backend: RemoteBackend,
                 model: Optional[str] = None):
        super().__init__(rid, model or backend.model)
        self.backend = backend
        self._work: "queue_mod.SimpleQueue[Optional[_PoolTicket]]" = (
            queue_mod.SimpleQueue())
        self._thread = threading.Thread(
            target=self._worker, name=f"pool-remote-{rid}", daemon=True)
        self._thread.start()

    def cost_estimate_usd(self, request: ScoreRequest) -> float:
        return self.backend.cost_estimate_usd(request)

    def dispatch(self, ticket: _PoolTicket) -> ScoreFuture:
        future = ScoreFuture()
        ticket.replica_future = future
        self._work.put(ticket)
        return future

    def queue_depth(self) -> int:
        return self._work.qsize()

    def _worker(self) -> None:
        while True:
            ticket = self._work.get()
            if ticket is None:
                return
            t0 = time.monotonic()
            if ticket.expired(t0):
                # the deadline contract holds on the remote leg too: an
                # expired request must not spend real vendor dollars and
                # resolve late — it rejects typed, like the local
                # scheduler's queue sweep
                ticket.replica_future._set_exception(DeadlineExceeded(
                    f"deadline passed {t0 - ticket.deadline:.3f}s before "
                    f"the remote backend call"))
                continue
            try:
                row = self.backend.score_one(ticket.request)
            except Exception as err:  # graftlint: disable=G05 vendor relay: transport/HTTP errors become this request's typed failure on its future; the worker must keep draining the replica queue
                ticket.replica_future._set_exception(err)
                continue
            ticket.replica_future.timing = {
                "e2e_ms": (time.monotonic() - t0) * 1000.0}
            ticket.replica_future._set_result(row)

    def shutdown(self, drain: bool = True, **_kw) -> None:
        self.state = "closed"
        self._work.put(None)
        self._thread.join(timeout=5.0 if drain else 0.5)


class EnginePool:
    """Multi-replica serving front door (module docstring).

    Usage::

        pool = EnginePool(config=PoolConfig())
        pool.load("falcon-7b", engine_a)           # replica r0
        pool.load("falcon-7b", engine_b)           # replica r1 (same model)
        pool.load_remote(RemoteBackend.openai(client, "gpt-4o-mini"))
        fut = pool.submit(ScoreRequest(prompt=...), model="falcon-7b")
        row = fut.result(timeout=60)
        pool.unload("r0")                          # hot: r1 keeps serving
        pool.close()
    """

    def __init__(self, config: Optional[PoolConfig] = None):
        self.config = config or PoolConfig()
        self._sched_template = self.config.scheduler or SchedulerConfig()
        self._replicas: Dict[str, Any] = {}
        self._queues: Dict[str, collections.deque] = {}
        self._inflight: List[_PoolTicket] = []
        self._known_models: set = set()
        self._capacity = max(1, self._sched_template.queue_capacity)
        self._seq = 0
        self._rid_counter = itertools.count()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self.supervisor: Optional[ReplicaSupervisor] = None
        if self.config.supervision is not None:
            self.supervisor = ReplicaSupervisor(
                self, self.config.supervision)
        self._router = threading.Thread(
            target=self._route_loop, name="pool-router", daemon=True)
        self._router.start()

    def supervise(self, config: Optional[SupervisorConfig] = None
                  ) -> ReplicaSupervisor:
        """Arm fleet self-healing on a running pool (idempotent): every
        current and future replica gains crash/wedge supervision, and
        the returned :class:`ReplicaSupervisor` takes rebuild-factory
        registrations (:meth:`ReplicaSupervisor.register_rebuild`)."""
        with self._wake:
            if self.supervisor is None:
                self.supervisor = ReplicaSupervisor(
                    self, config or self.config.supervision)
                for replica in self._replicas.values():
                    self.supervisor.track(replica)
        return self.supervisor

    # -- replica lifecycle ----------------------------------------------

    def load(self, model: str, engine, replica_id: Optional[str] = None,
             owns_engine: bool = True,
             plan_note: Optional[str] = None,
             share_group: Optional[ParamShareGroup] = None,
             plan=None, role: Optional[str] = None,
             devices=None) -> LocalReplica:
        """Hot-add a local replica — traffic already queued for
        ``model`` starts draining onto it on the next router tick; no
        other replica pauses.  ``share_group`` refcounts a param tree
        shared with sibling replicas (the last sibling to unload
        releases the buffers, whatever the order).  ``plan`` (a
        :func:`~..runtime.plan_search.replica_plan` candidate) applies
        the searched operating point to THIS replica's engine config
        (:func:`replica_engine_config`) and doubles as its health-doc
        plan note.

        ``role`` splits the fleet into prefill/decode specialists
        (``None`` = general): a ``"prefill"`` replica's scheduler gets a
        handoff hook that ships finished int8/bf16 KV slabs to the
        least-loaded live ``"decode"`` sibling of the same model, whose
        slot ring imports them mid-flight; when no decode sibling is
        live the prefill replica decodes locally (always-answered beats
        role purity).  ``devices`` pins the replica to a mesh slice
        (:func:`~..parallel.mesh.carve_slices`) before its scheduler
        starts."""
        if role not in (None, "prefill", "decode"):
            raise ValueError(
                f"role must be None, 'prefill', or 'decode': {role!r}")
        if plan is not None:
            engine.ecfg = replica_engine_config(engine.ecfg, plan)
            plan_note = plan_note or plan.reason
        with self._wake:
            if self._closed:
                raise PoolClosed("pool is shut down")
            rid = replica_id or f"r{next(self._rid_counter)}"
            if rid in self._replicas:
                raise ValueError(f"replica id {rid!r} already loaded")
            replica = LocalReplica(rid, model, engine,
                                   self._sched_template,
                                   owns_engine=owns_engine,
                                   plan_note=plan_note,
                                   share_group=share_group,
                                   role=role, devices=devices)
            if role == "prefill":
                replica.scheduler.handoff = self._make_handoff(replica)
            self._replicas[rid] = replica
            self._known_models.add(model)
            self._queues.setdefault(model, collections.deque())
            if self.supervisor is not None:
                self.supervisor.track(replica)
            record_counter("pool_replicas_loaded")
            self._wake.notify_all()
        return replica

    def load_remote(self, backend: RemoteBackend,
                    model: Optional[str] = None,
                    replica_id: Optional[str] = None) -> RemoteReplica:
        """Hot-add an ``api_backends/`` vendor as a replica of ``model``
        (default: the backend's own model name) — it enters the same
        least-loaded/cost-aware selection as every local replica."""
        with self._wake:
            if self._closed:
                raise PoolClosed("pool is shut down")
            rid = replica_id or f"r{next(self._rid_counter)}"
            if rid in self._replicas:
                raise ValueError(f"replica id {rid!r} already loaded")
            replica = RemoteReplica(rid, backend, model=model)
            self._replicas[rid] = replica
            self._known_models.add(replica.model)
            self._queues.setdefault(replica.model, collections.deque())
            if self.supervisor is not None:
                self.supervisor.track(replica)
            record_counter("pool_replicas_loaded")
            self._wake.notify_all()
        return replica

    def unload(self, replica_id: str, drain: bool = True,
               release_params: Optional[bool] = None) -> None:
        """Hot-remove one replica WITHOUT draining the rest of the pool:
        the router stops selecting it immediately, its queued work
        finishes (``drain=True``), any request it bounces re-enters the
        model queue (always-answered), and the engine tears down through
        :meth:`ScoringEngine.close` — buffer census back to baseline, so
        a different model can load into the freed HBM in-process."""
        with self._wake:
            replica = self._replicas.get(replica_id)
            if replica is None:
                raise ValueError(f"unknown replica {replica_id!r}")
            if replica.state == "closed":
                return
            replica.state = "draining"
        # outside the lock: draining blocks on engine work, and the
        # router must keep serving the other replicas meanwhile
        replica.shutdown(drain=drain, release_params=release_params)
        with self._wake:
            self._replicas.pop(replica_id, None)
            if self.supervisor is not None:
                self.supervisor.untrack(replica_id)
            record_counter("pool_replicas_unloaded")
            self._wake.notify_all()

    def replicas(self, model: Optional[str] = None) -> List:
        with self._lock:
            return [r for r in self._replicas.values()
                    if model is None or r.model == model]

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._known_models)

    # -- submission ------------------------------------------------------

    def submit(self, request: ScoreRequest,
               model: Optional[str] = None) -> ScoreFuture:
        """Admit one request for ``model`` (optional when the request
        names one, or when the pool serves exactly one model).  Returns
        a future resolving to the replica's ordinary result row; typed
        errors follow the scheduler conventions: the per-model front
        queue is BOUNDED (the scheduler template's ``queue_capacity``
        — past it, typed :class:`QueueFull` backpressure), a deadline
        covers the POOL queue time too (expired tickets reject with
        :class:`DeadlineExceeded`, and the replica leg gets only the
        remaining budget), and higher ``priority`` dispatches first."""
        request.validate()
        model = model or getattr(request, "model", None)
        now = time.monotonic()
        with self._wake:
            if self._closed:
                record_counter("serve_rejected_closed")
                raise PoolClosed("pool is shut down")
            if model is None:
                if len(self._known_models) != 1:
                    raise ValueError(
                        f"pool serves {sorted(self._known_models)}; "
                        f"submit(model=...) must name one")
                model = next(iter(self._known_models))
            if model not in self._known_models:
                raise UnknownModel(
                    f"no replica serves {model!r} (loaded: "
                    f"{sorted(self._known_models)})")
            if len(self._queues[model]) >= self._capacity:
                record_counter("serve_rejected_full")
                raise QueueFull(
                    f"pool queue for {model!r} at capacity "
                    f"({self._capacity})")
            timeout_s = (request.timeout_s
                         if request.timeout_s is not None
                         else self._sched_template.default_timeout_s)
            self._seq += 1
            ticket = _PoolTicket(
                request=request, future=ScoreFuture(), model=model,
                enqueue_t=now, seq=self._seq,
                deadline=None if timeout_s is None else now + timeout_s)
            self._queues[model].append(ticket)
            record_counter("pool_enqueued")
            self._wake.notify_all()
        return ticket.future

    def submit_many(self, requests, model: Optional[str] = None
                    ) -> List[ScoreFuture]:
        return [self.submit(r, model=model) for r in requests]

    # -- router ----------------------------------------------------------

    def _select_replica(self, model: str, request: ScoreRequest):
        """Least-loaded compatible replica: smallest routing score =
        latency_weight x predicted wait (observed-latency EWMA x (1 +
        outstanding + queued)) + cost_weight x estimated USD x the
        configured exchange rate.  Local replicas cost $0, so the cost
        term is pure vendor-spill pressure.

        Role affinity rides on top: fresh prompts prefer prefill/general
        replicas — a ``"decode"`` specialist's chips are reserved for
        handed-off slabs and selected only when no other sibling is live
        (always-answered fallback, counted as ``pool_decode_fallback``)."""
        cfg = self.config
        best, best_score = None, None
        decode_best, decode_best_score = None, None
        for replica in self._replicas.values():
            if replica.model != model or replica.state != "live":
                continue
            if (self.supervisor is not None
                    and not self.supervisor.allows(replica)):
                continue        # vendor breaker open: shed to siblings
            score = (cfg.latency_weight * replica.predicted_wait_s()
                     + cfg.cost_weight * replica.cost_estimate_usd(request)
                     * cfg.cost_scale_s_per_usd)
            if getattr(replica, "role", None) == "decode":
                if decode_best_score is None or score < decode_best_score:
                    decode_best, decode_best_score = replica, score
                continue
            if best_score is None or score < best_score:
                best, best_score = replica, score
        if best is None and decode_best is not None:
            record_counter("pool_decode_fallback")
            return decode_best
        return best

    def _make_handoff(self, source: LocalReplica):
        """Build the prefill→decode slab-shipping hook installed on a
        ``"prefill"`` replica's scheduler (``scheduler.handoff``).

        Called on the PREFILL replica's scheduler loop thread with
        ``(slab, tickets, launch_t)``; picks the least-loaded live
        ``"decode"`` sibling of the same model under the pool lock, then
        submits OUTSIDE it (``submit_slab`` only touches the target's
        own locks, so no lock cycle with the router).  Returns False —
        prefill decodes locally — when no decode sibling accepts; a
        ``SchedulerClosed`` bounce tries the next candidate, mirroring
        the router's always-answered re-dispatch."""

        def handoff(slab, tickets, launch_t) -> bool:
            with self._lock:
                cands = sorted(
                    (r for r in self._replicas.values()
                     if r is not source and r.model == source.model
                     and r.state == "live"
                     and getattr(r, "role", None) == "decode"
                     and isinstance(r, LocalReplica)),
                    key=lambda r: r.predicted_wait_s())
            for target in cands:
                try:
                    target.scheduler.submit_slab(slab, tickets, launch_t)
                except SchedulerClosed:
                    continue
                record_counter("pool_slab_handoffs")
                return True
            return False

        return handoff

    def _route_loop(self) -> None:
        while True:
            with self._wake:
                if (self._closed and not self._inflight
                        and not any(self._queues.values())):
                    return
                dispatched = self._dispatch_ready()
                resolved = self._reap_inflight()
                if not dispatched and not resolved:
                    # replica futures resolve on replica threads that
                    # cannot signal this condition, so IN-FLIGHT work
                    # polls at the fine tick; an idle pool blocks at the
                    # coarse one (submit/load/unload/close all notify)
                    self._wake.wait(timeout=(
                        DISPATCH_TICK_S if self._inflight
                        else IDLE_TICK_S))

    def _expire_queued(self, q, now: float) -> None:
        """Deadline sweep of one model queue (lock held): the pool front
        queue honors request deadlines exactly like the scheduler's
        admission queue — expired tickets reject TYPED, and a queue
        orphaned by an unload cannot silently hold bounded-time
        requests forever."""
        expired = [t for t in q if t.expired(now)]
        for ticket in expired:
            q.remove(ticket)
            record_counter("serve_rejected_deadline")
            ticket.future._set_exception(DeadlineExceeded(
                f"deadline passed after "
                f"{now - ticket.enqueue_t:.3f}s in the pool queue"))

    def _dispatch_ready(self) -> int:
        """Move queued tickets onto replicas (callers hold the lock):
        highest priority first (FIFO within a level), each carrying only
        its REMAINING deadline budget into the replica leg."""
        n = 0
        now = time.monotonic()
        for model, q in self._queues.items():
            self._expire_queued(q, now)
            while q:
                ticket = min(q, key=_PoolTicket.sort_key)
                replica = self._select_replica(model, ticket.request)
                if replica is None:
                    break               # no live replica: wait (hot swap)
                if ticket.deadline is not None:
                    # the replica's scheduler re-anchors timeout_s at ITS
                    # submit time; hand it the remaining budget so the
                    # pool wait is not silently granted twice.  The
                    # ticket keeps the adjusted copy (recomputed from the
                    # absolute deadline on every re-dispatch).
                    ticket.request = dataclasses.replace(
                        ticket.request,
                        timeout_s=max(0.0,
                                      ticket.deadline - time.monotonic()))
                try:
                    rf = replica.dispatch(ticket)
                except ServeError:
                    # replica-level backpressure/shutdown race: back on
                    # the model queue, try again next tick (possibly on
                    # another replica) — never dropped
                    break
                q.remove(ticket)
                ticket.replica_future = rf
                ticket.replica = replica
                ticket.dispatch_t = time.monotonic()
                replica.outstanding += 1
                if self.supervisor is not None:
                    self.supervisor.on_dispatch(replica)
                self._inflight.append(ticket)
                n += 1
        return n

    def _reap_inflight(self) -> int:
        """Relay resolved replica futures onto pool futures (lock held).
        A ``SchedulerClosed`` bounce from a replica that shut down under
        the request re-queues the ticket — the unload path's
        always-answered guarantee.  Under supervision
        (serve/supervisor.py) this is also where failover happens:
        crashed legs re-queue, legs stranded on a torn-down quarantined
        replica are reclaimed, and hedge legs race first-wins."""
        n = 0
        still: List[_PoolTicket] = []
        for ticket in self._inflight:
            if self._reap_one(ticket):
                n += 1
            else:
                still.append(ticket)
        self._inflight = still
        return n

    def _reap_one(self, ticket: _PoolTicket) -> bool:
        """True when the ticket left the in-flight set (resolved,
        requeued, or typed-rejected); False = still waiting."""
        sup = self.supervisor
        # hedge leg first: a successful hedge answers the request
        # (first-wins on the pool future); a failed one drops silently —
        # the primary leg is still racing
        hf = ticket.hedge_future
        if hf is not None and hf.done():
            hedge_replica = ticket.hedge_replica
            hedge_replica.outstanding = max(
                0, hedge_replica.outstanding - 1)
            herr = hf.exception(timeout=0)
            ticket.hedge_future = None
            ticket.hedge_replica = None
            if herr is None:
                if sup is not None:
                    if ticket.replica is not None:
                        # the slow primary leg is orphaned: its replica's
                        # outstanding drops when it eventually resolves
                        sup.orphan_leg(ticket.replica,
                                       ticket.replica_future)
                        ticket.replica = None
                        ticket.replica_future = None
                    sup.note_hedge_won(ticket)
                self._resolve_success(ticket, hf, hedge_replica,
                                      hedged=True)
                return True
            if (sup is not None
                    and not isinstance(herr, SchedulerClosed)):
                sup.handle_hedge_failure(hedge_replica, herr)
        rf = ticket.replica_future
        replica = ticket.replica
        if rf is None or not rf.done():
            # supervised: a leg still unresolved AFTER a quarantined
            # replica's teardown completed (state reached "closed" and
            # the scheduler bounce already re-queued everything it
            # could) is the wedged batch itself — fail it over instead
            # of waiting on a corpse
            if (sup is not None and replica is not None
                    and getattr(replica, "quarantined", False)
                    and replica.state == "closed"):
                replica.outstanding = max(0, replica.outstanding - 1)
                if ticket.hedge_future is not None:
                    # promote the live hedge leg to primary
                    ticket.replica_future = ticket.hedge_future
                    ticket.replica = ticket.hedge_replica
                    ticket.hedge_future = None
                    ticket.hedge_replica = None
                    return False
                sup.reclaim_locked(ticket)
                return True
            return False
        replica.outstanding = max(0, replica.outstanding - 1)
        err = rf.exception(timeout=0)
        if isinstance(err, SchedulerClosed):
            record_counter("pool_redispatched")
            ticket.replica_future = None
            ticket.replica = None
            self._queues[ticket.model].appendleft(ticket)
            return True
        if err is not None:
            if sup is not None and sup.handle_failure(ticket, replica,
                                                      err):
                return True
            replica.failed += 1
            record_counter("pool_failed")
            ticket.future._set_exception(err)
            return True
        self._resolve_success(ticket, rf, replica, hedged=False)
        return True

    def _resolve_success(self, ticket: _PoolTicket, rf: ScoreFuture,
                         replica, hedged: bool) -> None:
        replica.completed += 1
        timing = rf.timing
        e2e_s = None
        if timing and "e2e_ms" in timing:
            e2e_s = timing["e2e_ms"] / 1000.0
        elif ticket.dispatch_t is not None:
            e2e_s = time.monotonic() - ticket.dispatch_t
        if e2e_s is not None:
            replica.note_latency(e2e_s)
        if self.supervisor is not None:
            self.supervisor.on_success(replica, e2e_s)
            if ticket.failovers or hedged:
                # failover/hedge provenance rides the TIMING (future-
                # side), never the row: replay bit-parity (PARITY.md)
                timing = dict(timing or {})
                timing["failovers"] = ticket.failovers
                if hedged:
                    timing["hedged"] = True
        ticket.future.timing = timing
        record_counter("pool_completed")
        ticket.future._set_result(rf.result(timeout=0))

    # -- lifecycle / health ---------------------------------------------

    def queue_depth(self) -> int:
        """Pool-level queued + every replica's local queue — the front
        door's total backlog (the load harness's depth trajectory)."""
        with self._lock:
            return (sum(len(q) for q in self._queues.values())
                    + sum(r.queue_depth() for r in self._replicas.values()))

    def health(self) -> Dict:
        """The /healthz contribution: per-replica health (id, model,
        queue depth, oldest-wait age) so ONE wedged replica reads
        degraded while the pool stays up; a model with queued traffic
        and no live replica degrades too (mid-swap visibility)."""
        max_age = (self.config.health_max_queue_age_s
                   or getattr(self._sched_template,
                              "health_max_queue_age_s", 0))
        with self._lock:
            replicas = [r.health(max_age) for r in self._replicas.values()]
            queued = {m: len(q) for m, q in self._queues.items() if q}
            orphaned = sorted(
                m for m, q in self._queues.items()
                if q and not any(r.model == m and r.state == "live"
                                 for r in self._replicas.values()))
        doc = {
            "pool": "closed" if self._closed else "running",
            "replicas": replicas,
            "queued_by_model": queued,
        }
        if self.supervisor is not None:
            breakers = self.supervisor.breaker_states()
            if breakers:
                doc["breakers"] = breakers
        degraded = [r["replica"] for r in replicas
                    if r.get("status") == "degraded"]
        if orphaned:
            doc["status"] = "degraded"
            doc["degraded_reason"] = (
                f"model(s) {orphaned} have queued traffic and no live "
                f"replica")
        elif degraded:
            doc["status"] = "degraded"
            doc["degraded_reason"] = (
                f"replica(s) {degraded} exceed the queue-age threshold")
        return doc

    def client(self, model: Optional[str] = None) -> "PoolClient":
        """A Scheduler-shaped facade over this pool (submit/queue/close
        with close a no-op) — what lets ``serve/load.py`` drive the pool
        through the SAME open-loop harness as a single engine."""
        return PoolClient(self, model=model)

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Shut the whole pool down: stop admitting, let the router
        drain queued + in-flight work (bounded by ``drain_timeout_s``),
        close every replica (verified engine teardown), and fail
        anything left with the typed :class:`PoolClosed`."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._wake.notify_all()
        deadline = time.monotonic() + (
            timeout if timeout is not None
            else (self.config.drain_timeout_s if drain else 0.5))
        while drain and time.monotonic() < deadline:
            with self._lock:
                idle = not self._inflight and not any(
                    self._queues.values())
            if idle:
                break
            time.sleep(DISPATCH_TICK_S)
        if self.supervisor is not None:
            self.supervisor.stop()
        for replica in list(self._replicas.values()):
            replica.shutdown(drain=drain)
        with self._wake:
            self._replicas.clear()
            leftovers = [t for q in self._queues.values() for t in q]
            leftovers += [t for t in self._inflight
                          if not t.future.done()]
            for q in self._queues.values():
                q.clear()
            self._inflight = []
            self._wake.notify_all()
        for ticket in leftovers:
            if not ticket.future.done():
                record_counter("serve_rejected_closed")
                ticket.future._set_exception(PoolClosed(
                    "pool shut down before the request completed"))
        self._router.join(timeout=2.0)

    def __enter__(self) -> "EnginePool":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)


class PoolClient:
    """Duck-typed :class:`Scheduler` facade for one model of a pool.

    ``serve/load.run_load`` drives whatever ``scheduler_factory`` hands
    it through submit/queue/close; this facade forwards submits to the
    pool (pinning ``model``), exposes the pool-wide backlog as
    ``len(client.queue)``, and makes ``close()`` a no-op — ONE pool
    serves every rate point of a sweep, its lifetime owned by the
    caller, not by one load run."""

    class _QueueView:
        def __init__(self, pool: EnginePool):
            self._pool = pool

        def __len__(self) -> int:
            return self._pool.queue_depth()

    def __init__(self, pool: EnginePool, model: Optional[str] = None):
        self.pool = pool
        self.model = model
        self.queue = self._QueueView(pool)

    def submit(self, request: ScoreRequest) -> ScoreFuture:
        return self.pool.submit(request, model=self.model)

    def close(self, drain: bool = True) -> None:
        pass  # the pool outlives one load run


def replica_engine_config(base, plan) -> Any:
    """Apply a plan-search-chosen operating point
    (:func:`~..runtime.plan_search.replica_plan`) to a replica's
    :class:`~..runtime.engine.EngineConfig`: batch / kv-dtype / chunk /
    pool-target come from the replica's OWN mesh slice instead of the
    fleet-wide flags."""
    if plan is None:
        return base
    return dataclasses.replace(
        base, batch_size=plan.batch, kv_dtype=plan.kv_dtype,
        prefill_chunk=plan.prefill_chunk,
        phase2_pool_target=plan.pool_target)
