"""Thread-safe admission queue with priority ordering and deadline sweep.

The queue holds :class:`Ticket`\\ s — a request plus its future, arrival
order, absolute deadline, pre-tokenized ids, and compatibility key — and
implements the max-wait/max-batch admission policy: ``pop_group`` blocks
for the highest-priority head ticket, then coalesces every compatible
ticket (same :mod:`.coalescer` key) up to ``max_batch``, launching early
only when the head has already waited ``max_wait_s``.  Deadline-expired
tickets are swept out and RETURNED to the caller (the scheduler rejects
them with the typed :class:`~.request.DeadlineExceeded`) — they are
never silently dropped inside the queue.

Capacity is a hard bound enforced at ``put`` (typed
:class:`~.request.QueueFull`); split micro-batches re-entering after an
OOM go through ``requeue`` which bypasses the bound — those rows were
already admitted once and dropping them on re-entry would lose work.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, List, Optional, Tuple

from .request import QueueFull, SchedulerClosed, ScoreFuture, ScoreRequest


@dataclasses.dataclass
class Ticket:
    """One admitted request travelling through the scheduler."""

    request: ScoreRequest
    future: ScoreFuture
    seq: int                        # admission order (FIFO tie-break)
    enqueue_t: float                # monotonic submit time
    deadline: Optional[float]       # absolute monotonic, None = never
    encoded: Any = None             # token ids (or (prefix_ids, suffix_ids))
    key: Any = None                 # coalescer compatibility key
    degraded: Optional[int] = None  # engine batch override after OOM splits
    trace_id: Optional[str] = None  # obs/ request-scoped span correlation
                                    # id (set at submit when tracing is on;
                                    # threads queue-wait/engine/respond
                                    # spans and the result row together)
    queue_wait_s: Optional[float] = None  # latency-anatomy stamps set at
    coalesce_s: Optional[float] = None    # launch (scheduler.HIST_PHASES)

    def sort_key(self) -> Tuple[int, int]:
        return (-self.request.priority, self.seq)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class RequestQueue:
    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._items: List[Ticket] = []
        self._cond = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)

    def put(self, ticket: Ticket) -> None:
        with self._cond:
            if self._closed:
                raise SchedulerClosed("scheduler is shut down")
            if len(self._items) >= self.capacity:
                raise QueueFull(
                    f"admission queue at capacity ({self.capacity})")
            self._items.append(ticket)
            self._cond.notify_all()

    def requeue(self, tickets: List[Ticket]) -> None:
        """Re-admit split micro-batch tickets (OOM re-entry): original
        ``seq`` values are preserved, so they sort ahead of traffic that
        arrived after them; the capacity bound does not apply."""
        with self._cond:
            self._items.extend(tickets)
            self._cond.notify_all()

    def close(self) -> None:
        """Stop admitting; ``pop_group`` keeps draining what is queued
        and returns ``None`` once empty."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def wake(self) -> None:
        """Nudge a blocked ``pop_group`` so it re-checks its caller's
        ``ready_fn`` — how the decode-role scheduler learns a KV slab
        landed while its loop sat in the empty-queue wait (slab work must
        run ON the loop thread; the engine is single-threaded)."""
        with self._cond:
            self._cond.notify_all()

    def drain(self) -> List[Ticket]:
        """Remove and return EVERY queued ticket without closing the
        queue.  The supervisor's failover path (serve/supervisor.py)
        reclaims a quarantined replica's queued-but-unlaunched tickets
        this way so they re-enter the pool's per-model queue instead of
        dying with the replica."""
        with self._cond:
            items, self._items = self._items, []
            return items

    def oldest_wait_s(self, now_fn=time.monotonic) -> Optional[float]:
        """Age of the OLDEST queued ticket in seconds (None when empty).
        The /healthz degraded condition reads this: queue depth alone
        cannot distinguish a short queue that is draining from a short
        queue behind a wedged coalescer — the head request's age can."""
        with self._cond:
            if not self._items:
                return None
            return now_fn() - min(t.enqueue_t for t in self._items)

    def pop_compatible(self, key, max_n: int,
                       now_fn=time.monotonic) -> List[Ticket]:
        """NON-BLOCKING pop of up to ``max_n`` live tickets whose
        coalescer key matches ``key`` — the slot-admission path
        (scheduler._launch's mid-decode refill hook): a vacated decode
        slot pulls freshly-queued compatible traffic without waiting for
        the coalescer boundary.  Deadline-expired tickets are left in
        place for ``pop_group``'s sweep (one rejection path, not two)."""
        with self._cond:
            now = now_fn()
            out: List[Ticket] = []
            for t in sorted(self._items, key=Ticket.sort_key):
                if len(out) >= max(1, max_n):
                    break
                if t.key == key and not t.expired(now):
                    out.append(t)
            for t in out:
                self._items.remove(t)
            return out

    def pop_group(self, max_batch: int, max_wait_s: float,
                  now_fn=time.monotonic, ready_fn=None
                  ) -> Tuple[Optional[List[Ticket]], List[Ticket]]:
        """``(group, expired)``: the next launchable micro-batch plus the
        tickets whose deadline passed while queued.  ``group`` is ``None``
        exactly when the queue is closed and drained.

        ``ready_fn`` is the out-of-band work probe (paired with
        :meth:`wake`): when it returns true the pop yields ``([],
        expired)`` immediately so the loop thread can service that work —
        an EMPTY group, distinct from the closed ``None`` — and the held
        head ticket keeps its enqueue-time-based max-wait accounting on
        the next call."""
        expired: List[Ticket] = []
        with self._cond:
            while True:
                now = now_fn()
                live: List[Ticket] = []
                for t in self._items:
                    (expired if t.expired(now) else live).append(t)
                self._items = live
                if ready_fn is not None and ready_fn():
                    return [], expired
                if not live:
                    if self._closed:
                        return None, expired
                    if expired:
                        # surface rejections promptly instead of holding
                        # them until the next arrival
                        return [], expired
                    self._cond.wait(timeout=0.05)
                    continue
                head = min(live, key=Ticket.sort_key)
                group = [t for t in sorted(live, key=Ticket.sort_key)
                         if t.key == head.key][: max(1, max_batch)]
                full = len(group) >= max(1, max_batch)
                waited = now - head.enqueue_t
                if full or self._closed or waited >= max_wait_s:
                    for t in group:
                        self._items.remove(t)
                    return group, expired
                if expired:
                    return [], expired
                self._cond.wait(timeout=max(0.001, max_wait_s - waited))
