"""Fleet self-healing for :class:`~.pool.EnginePool` (ISSUE 16).

The pool (PR 12) *reports* a sick replica — health() reads degraded —
but never heals it, and a replica failure strands its in-flight
requests, violating the serve layer's always-answered contract exactly
at the fleet scale the ROADMAP's disaggregated-serving north star
assumes.  PR 1's fault layer (:mod:`..runtime.faults`) classifies and
retries at the ENGINE level; this module lifts that discipline to the
FLEET level:

- **Failure detection & classification.**  Per-replica watchdog beats
  (reusing :class:`~..obs.flight.StallWatchdog`: beat on dispatch and
  on completion, checked only while the replica has work) distinguish
  a *crash* (an engine call raised a non-request error), a *wedge* (no
  forward progress past the wedge timeout while busy), and a
  *poison row* (the same request kills ``poison_kill_limit`` replicas
  → typed :class:`~.request.PoisonousRequest`, never a third kill).
- **Quarantine + rebuild.**  A failed replica leaves the router
  immediately (state ``quarantined`` — :meth:`EnginePool._select_replica`
  only picks ``live``), is torn down through the verified
  :meth:`~..runtime.engine.ScoringEngine.close` census, and is rebuilt
  from a registered per-model engine factory (the shared-snapshot
  sibling path makes rebuilds free of weight HBM) after a FULL-jitter
  exponential backoff (:func:`~..runtime.faults.fleet_backoff_delay`),
  with a ``max_rebuilds`` ceiling: a flapping replica is permanently
  quarantined instead of churning the pool forever.
- **In-flight failover.**  Requests stranded on a failed replica
  re-enter the per-model queue and re-route to a sibling.  At-most-once
  answer semantics ride the :class:`~.request.ScoreFuture` first-wins
  resolve guard; the failover count is stamped on the future's
  ``timing`` — never the result row, so replay bit-parity holds
  (PARITY.md: supervision changes WHERE/WHEN a row computes, never
  WHAT).  Opt-in tail-latency hedging launches a second leg on a
  sibling once a request has been in flight longer than ``hedge_k`` x
  the model's observed p99 — scoring requests are idempotent and
  deterministic, so the losing leg is simply dropped.
- **Vendor circuit breakers.**  Remote (:class:`~.pool.RemoteBackend`)
  replicas gain a closed/open/half-open :class:`CircuitBreaker` over
  the existing cost/latency router: a down vendor stops being selected
  (sheds to local replicas) instead of burning retry budget, and
  half-open probes re-admit it after the cooldown.

Locking: the supervisor's mutable state is guarded by the POOL's lock
— router hooks (`handle_failure`, `reclaim_locked`, `on_dispatch`,
`on_success`) run with it held, and the monitor thread / rebuild
workers acquire ``pool._wake`` before touching shared state.  The
:class:`CircuitBreaker` carries its own small lock so `allow()` is
safe from any thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs.flight import StallWatchdog
from ..runtime.faults import fleet_backoff_delay, fleet_backoff_policy
from ..utils.telemetry import record_counter, record_fault
from .request import PoisonousRequest, ScoreFuture, ServeError
from .scheduler import labeled_metric


def _labeled_counter(name: str, labels: Dict) -> None:
    """Base counter + its ``name|replica=…`` labeled twin (the
    scheduler's labeled-metric convention, so per-replica series export
    next to the fleet aggregate)."""
    record_counter(name)
    record_counter(name + labeled_metric("", labels))


@dataclasses.dataclass
class SupervisorConfig:
    """Self-healing knobs.  Defaults are conservative: wedge detection
    arms only when a wedge timeout is configured (here or via the
    pool's ``health_max_queue_age_s``), and hedging is opt-in."""

    #: a busy replica with no dispatch/completion beat for this long is
    #: wedged (0 falls back to the pool's ``health_max_queue_age_s``;
    #: both 0 disables wedge detection).
    wedge_timeout_s: float = 0.0
    #: the same request crashing/wedging this many replicas is poisoned:
    #: typed :class:`PoisonousRequest`, never another kill.
    poison_kill_limit: int = 2
    #: per-request ceiling on vendor-failure failovers (a persistently
    #: failing vendor row propagates its real error past this).
    max_failovers: int = 3
    #: rebuilds per replica lineage before permanent quarantine.
    max_rebuilds: int = 3
    #: full-jitter rebuild backoff window (runtime/faults.py
    #: fleet_backoff_policy — decorrelates N rebuilds/failovers that
    #: started their clocks at the same crash).
    rebuild_backoff_initial_s: float = 0.5
    rebuild_backoff_max_s: float = 30.0
    #: opt-in tail-latency hedging: a second leg launches on a sibling
    #: once a request has been in flight > hedge_k x observed p99 for
    #: its model (needs hedge_min_samples completions first).
    hedge: bool = False
    hedge_k: float = 3.0
    hedge_min_samples: int = 32
    #: vendor breaker: consecutive failures to open, cooldown before a
    #: half-open probe, probes that must succeed to re-close.
    breaker_failure_threshold: int = 5
    breaker_cooldown_s: float = 30.0
    breaker_halfopen_probes: int = 1
    #: monitor-thread tick (wedge checks, due rebuilds, hedge scans).
    poll_s: float = 0.05


class CircuitBreaker:
    """Closed/open/half-open breaker over one remote replica.

    closed --(threshold consecutive failures)--> open
    open --(cooldown elapsed)--> half-open (admits ``probes`` requests)
    half-open --(probe success x probes)--> closed
    half-open --(probe failure)--> open (cooldown restarts)

    State transitions record a ``breaker_state`` counter labeled with
    the replica and the NEW state; opening records a ``breaker_open``
    fault event (a flight-recorder trigger)."""

    def __init__(self, rid: str, model: str, threshold: int = 5,
                 cooldown_s: float = 30.0, probes: int = 1,
                 clock=time.monotonic):
        self.rid = rid
        self.model = model
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.probes = max(1, int(probes))
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self._failures = 0            # consecutive
        self._opened_t: Optional[float] = None
        self._probes_in_flight = 0
        self._probe_successes = 0

    def _transition(self, state: str) -> None:
        self.state = state
        _labeled_counter("breaker_state",
                         {"replica": self.rid, "state": state})

    def allow(self) -> bool:
        """May the router dispatch to this replica right now?"""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if (self._opened_t is not None
                        and self._clock() - self._opened_t
                        >= self.cooldown_s):
                    self._transition("half_open")
                    self._probes_in_flight = 1
                    self._probe_successes = 0
                    return True
                return False
            # half-open: bounded concurrent probes
            if self._probes_in_flight < self.probes:
                self._probes_in_flight += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self.state == "half_open":
                self._probes_in_flight = max(
                    0, self._probes_in_flight - 1)
                self._probe_successes += 1
                if self._probe_successes >= self.probes:
                    self._transition("closed")

    def record_failure(self) -> None:
        opened = False
        with self._lock:
            self._failures += 1
            if self.state == "half_open":
                self._probes_in_flight = max(
                    0, self._probes_in_flight - 1)
                self._opened_t = self._clock()
                self._transition("open")
                opened = True
            elif (self.state == "closed"
                    and self._failures >= self.threshold):
                self._opened_t = self._clock()
                self._transition("open")
                opened = True
            failures = self._failures
        if opened:
            record_fault("breaker_open", replica=self.rid,
                         model=self.model, failures=failures)


class ReplicaSupervisor:
    """The pool's self-healing brain (module docstring).  Built by
    :meth:`EnginePool.supervise`; hooks are called by the pool router
    with the pool lock held."""

    def __init__(self, pool, config: Optional[SupervisorConfig] = None,
                 clock=time.monotonic, sleep=time.sleep):
        self.pool = pool
        self.config = config or SupervisorConfig()
        self._clock = clock
        self._sleep = sleep
        # all guarded by pool._lock unless noted
        self._watchdogs: Dict[str, StallWatchdog] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._factories: Dict[str, Callable] = {}   # model -> engine fn
        self._lineage: Dict[str, int] = {}          # rid -> rebuilds so far
        self._latency: Dict[str, List[float]] = {}  # model -> recent e2e s
        self._orphans: List[tuple] = []             # (replica, future) legs
        self.incidents: List[Dict] = []
        self.crashes = 0
        self.wedges = 0
        self.restarts = 0
        self.permanent_quarantines = 0
        self.poison_rejects = 0
        self.failovers = 0
        self.hedges_launched = 0
        self.hedges_won = 0
        self._backoff_policy = fleet_backoff_policy(
            initial_delay_s=self.config.rebuild_backoff_initial_s,
            max_delay_s=self.config.rebuild_backoff_max_s,
            max_retries=max(1, self.config.max_rebuilds))
        self._workers: List[threading.Thread] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="pool-supervisor", daemon=True)
        self._thread.start()

    # -- registration (pool lock held) -----------------------------------

    def register_rebuild(self, model: str, factory: Callable) -> None:
        """``factory() -> engine`` rebuilds a quarantined replica of
        ``model`` (the shared-snapshot sibling constructor in
        serve/cli.build_shared_pool).  No factory = quarantine without
        rebuild."""
        with self.pool._wake:
            self._factories[model] = factory

    def track(self, replica) -> None:
        if replica.kind == "remote":
            self._breakers[replica.rid] = CircuitBreaker(
                replica.rid, replica.model,
                threshold=self.config.breaker_failure_threshold,
                cooldown_s=self.config.breaker_cooldown_s,
                probes=self.config.breaker_halfopen_probes,
                clock=self._clock)
            return
        wd = StallWatchdog(label=f"pool-replica-{replica.rid}", k=1.0,
                           min_beats=1,
                           floor_s=max(self._wedge_timeout_s(), 0.001),
                           clock=self._clock)
        wd.beat()   # arm: the first dispatch creates interval #1
        self._watchdogs[replica.rid] = wd

    def untrack(self, rid: str) -> None:
        self._watchdogs.pop(rid, None)
        self._breakers.pop(rid, None)

    def _wedge_timeout_s(self) -> float:
        if self.config.wedge_timeout_s:
            return self.config.wedge_timeout_s
        pool_cfg = self.pool.config
        return (pool_cfg.health_max_queue_age_s
                or getattr(self.pool._sched_template,
                           "health_max_queue_age_s", 0) or 0.0)

    # -- router hooks (pool lock held) -----------------------------------

    def allows(self, replica) -> bool:
        breaker = self._breakers.get(replica.rid)
        return breaker is None or breaker.allow()

    def on_dispatch(self, replica) -> None:
        wd = self._watchdogs.get(replica.rid)
        if wd is not None:
            wd.beat()

    def on_success(self, replica, e2e_s: Optional[float]) -> None:
        wd = self._watchdogs.get(replica.rid)
        if wd is not None:
            wd.beat()
        breaker = self._breakers.get(replica.rid)
        if breaker is not None:
            breaker.record_success()
        if e2e_s is not None:
            ring = self._latency.setdefault(replica.model, [])
            ring.append(e2e_s)
            if len(ring) > 512:
                del ring[: len(ring) - 512]

    def handle_failure(self, ticket, replica, err: BaseException) -> bool:
        """Classify a failed replica leg.  Returns True when the
        supervisor took ownership of the ticket (requeued for failover
        or typed-rejected); False = request-level error, the pool
        propagates it as before."""
        if isinstance(err, (ServeError, ValueError, TypeError)):
            return False        # this REQUEST's error, not the replica's
        if replica.kind == "remote":
            # vendor transport failure: breaker bookkeeping (opening
            # records the breaker_open fault), then failover if a
            # sibling can still answer
            breaker = self._breakers.get(replica.rid)
            if breaker is not None:
                breaker.record_failure()
            if (ticket.failovers >= self.config.max_failovers
                    or not self._has_sibling(replica)):
                return False    # real vendor error propagates typed
            self._failover_locked(ticket)
            return True
        # local crash: the engine call raised a non-request error
        self._quarantine_locked(replica, reason="crash", detection_ms=0.0,
                                error=str(err)[:160])
        ticket.kills += 1
        if ticket.kills >= self.config.poison_kill_limit:
            self._reject_poison_locked(ticket, err)
        else:
            self._failover_locked(ticket)
        return True

    def handle_hedge_failure(self, replica, err: BaseException) -> None:
        """A losing hedge leg failed: classify for the REPLICA only —
        the request is still racing on its primary leg, so nothing
        resolves here."""
        if isinstance(err, (ServeError, ValueError, TypeError)):
            return
        if replica.kind == "remote":
            breaker = self._breakers.get(replica.rid)
            if breaker is not None:
                breaker.record_failure()
            return
        self._quarantine_locked(replica, reason="crash", detection_ms=0.0,
                                error=str(err)[:160])

    def reclaim_locked(self, ticket) -> None:
        """Failover one leg stranded (unresolved) on a quarantined
        replica: the wedged batch and anything queued behind it."""
        ticket.kills += 1
        ticket.replica_future = None
        ticket.replica = None
        ticket.dispatch_t = None
        if ticket.kills >= self.config.poison_kill_limit:
            self._reject_poison_locked(ticket, None)
        else:
            self._failover_locked(ticket)

    def note_hedge_won(self, ticket) -> None:
        self.hedges_won += 1
        _labeled_counter("pool_hedges_won", {"model": ticket.model})

    def orphan_leg(self, replica, future: ScoreFuture) -> None:
        """Track a losing hedge/failover leg so its replica's
        ``outstanding`` drops when the leg eventually resolves."""
        self._orphans.append((replica, future))

    # -- failure plumbing (pool lock held) -------------------------------

    def _has_sibling(self, replica) -> bool:
        # any live sibling counts — a decode specialist CAN answer a
        # fresh prompt in a pinch (always-answered beats role purity);
        # _pick_sibling still prefers prefill/general capacity
        return any(r is not replica and r.model == replica.model
                   and r.state == "live"
                   for r in self.pool._replicas.values())

    def _failover_locked(self, ticket) -> None:
        ticket.failovers += 1
        self.failovers += 1
        ticket.replica_future = None
        ticket.replica = None
        ticket.dispatch_t = None
        _labeled_counter("pool_failovers", {"model": ticket.model})
        self.pool._queues[ticket.model].appendleft(ticket)

    def _reject_poison_locked(self, ticket, err) -> None:
        self.poison_rejects += 1
        record_fault("pool_poison_request", model=ticket.model,
                     kills=ticket.kills,
                     error=str(err)[:160] if err else None)
        ticket.future._set_exception(PoisonousRequest(
            f"request crashed/wedged {ticket.kills} replicas of "
            f"{ticket.model!r} (ceiling "
            f"{self.config.poison_kill_limit}); rejecting instead of "
            f"killing another"))

    def _quarantine_locked(self, replica, reason: str,
                           detection_ms: float,
                           error: Optional[str] = None) -> None:
        if replica.state != "live":
            return              # already quarantined/draining/closed
        # ONE incident per replica failure, however many stranded legs
        # observe it: the crash/wedge counters and their fault events
        # live here, behind the state check
        if reason == "crash":
            self.crashes += 1
            record_fault("pool_replica_crash", replica=replica.rid,
                         model=replica.model, error=error)
        else:
            self.wedges += 1
            record_fault("pool_replica_wedged", replica=replica.rid,
                         model=replica.model,
                         idle_ms=round(detection_ms, 1))
        replica.state = "quarantined"
        # sticky marker surviving shutdown()'s state="closed": the pool
        # reap distinguishes a quarantined corpse from a normal unload
        replica.quarantined = True
        incident = {"replica": replica.rid, "model": replica.model,
                    "reason": reason,
                    "detection_ms": round(detection_ms, 3),
                    "t_detect": self._clock()}
        self.incidents.append(incident)
        worker = threading.Thread(
            target=self._rebuild_worker, args=(replica, incident),
            name=f"pool-rebuild-{replica.rid}", daemon=True)
        self._workers.append(worker)
        worker.start()

    # -- quarantine / rebuild worker -------------------------------------

    def _teardown(self, replica, release_params) -> None:
        try:
            replica.shutdown(drain=False, release_params=release_params)
        except Exception:  # graftlint: disable=G05 quarantine teardown: a wedged engine may fail mid-close; the replica is being discarded either way and the rebuild must proceed
            pass

    def _rebuild_worker(self, replica, incident: Dict) -> None:
        t0 = self._clock()
        model = replica.model
        with self.pool._wake:
            births = self._lineage.pop(replica.rid, 0)
            factory = self._factories.get(model)
            self.untrack(replica.rid)
        if factory is None or births >= self.config.max_rebuilds:
            with self.pool._wake:
                # supervisor counters ride the POOL's lock (class
                # docstring): this worker thread races stats() readers
                # and sibling rebuild workers on the same field
                # (G09 serve/supervisor.py 'self.permanent_quarantines += 1')
                self.permanent_quarantines += 1
            record_fault(
                "pool_replica_quarantined", replica=replica.rid,
                model=model, rebuilds=births, permanent=True,
                reason=("no rebuild factory" if factory is None
                        else f"rebuild ceiling {self.config.max_rebuilds}"))
            # permanent: this lineage's shared-tree ref really releases
            self._teardown(replica, release_params=None)
            with self.pool._wake:
                self.pool._replicas.pop(replica.rid, None)
                self.pool._wake.notify_all()
            return
        # rebuild path: the dead sibling's share-group slot transfers to
        # its successor (release_params=False skips release_one), so the
        # shared param tree survives however the quarantines interleave
        self._teardown(
            replica,
            release_params=False if replica.share_group is not None
            else None)
        with self.pool._wake:
            self.pool._replicas.pop(replica.rid, None)
            self.pool._wake.notify_all()
        self._sleep(fleet_backoff_delay(births, self._backoff_policy))
        try:
            engine = factory()
            new = self.pool.load(model, engine,
                                 owns_engine=replica.owns_engine,
                                 plan_note=replica.plan_note,
                                 share_group=replica.share_group,
                                 role=getattr(replica, "role", None),
                                 devices=getattr(replica, "devices",
                                                 None))
        except Exception as err:  # graftlint: disable=G05 rebuild must never crash the supervisor: a failed factory (pool closed, OOM on reload) downgrades to permanent quarantine, recorded below
            if replica.share_group is not None:
                replica.share_group.release_one()
            with self.pool._wake:
                self.permanent_quarantines += 1
            record_fault("pool_replica_quarantined", replica=replica.rid,
                         model=model, rebuilds=births, permanent=True,
                         reason=f"rebuild failed: {str(err)[:120]}")
            return
        with self.pool._wake:
            self._lineage[new.rid] = births + 1
            # restarts and the incident row are pool-lock-guarded state
            # too: stats() snapshots both while this worker finishes
            self.restarts += 1
            incident["restart_ms"] = round(
                (self._clock() - t0) * 1000.0, 3)
        _labeled_counter("pool_replica_restarts",
                         {"replica": new.rid, "model": model})

    # -- monitor thread ---------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.config.poll_s):
            try:
                self._check_wedges()
                self._reap_orphans()
                if self.config.hedge:
                    self._scan_hedges()
            except Exception:  # graftlint: disable=G05 the monitor must survive any single check failing (a replica closing mid-scan): self-healing that dies on its first race heals nothing
                pass

    def _check_wedges(self) -> None:
        if self._wedge_timeout_s() <= 0:
            return
        with self.pool._wake:
            for replica in list(self.pool._replicas.values()):
                wd = self._watchdogs.get(replica.rid)
                if (wd is None or replica.state != "live"
                        or (replica.outstanding <= 0
                            and replica.queue_depth() <= 0)):
                    continue
                now = self._clock()
                if wd.check(now):
                    staleness_ms = ((now - wd._last_beat) * 1000.0
                                    if wd._last_beat else 0.0)
                    self._quarantine_locked(replica, reason="wedge",
                                            detection_ms=staleness_ms)
            self.pool._wake.notify_all()

    def _reap_orphans(self) -> None:
        with self.pool._wake:
            still = []
            for replica, future in self._orphans:
                if not future.done():
                    still.append((replica, future))
                    continue
                replica.outstanding = max(0, replica.outstanding - 1)
                breaker = self._breakers.get(replica.rid)
                if breaker is not None:
                    if future.exception(timeout=0) is None:
                        breaker.record_success()
                    else:
                        breaker.record_failure()
            self._orphans = still

    def _p99_s(self, model: str) -> Optional[float]:
        ring = self._latency.get(model)
        if not ring or len(ring) < self.config.hedge_min_samples:
            return None
        ordered = sorted(ring)
        return ordered[min(len(ordered) - 1,
                           int(0.99 * len(ordered)))]

    def _scan_hedges(self) -> None:
        with self.pool._wake:
            now = self._clock()
            for ticket in self.pool._inflight:
                if (ticket.hedge_future is not None
                        or ticket.dispatch_t is None
                        or ticket.replica is None):
                    continue
                p99 = self._p99_s(ticket.model)
                if p99 is None:
                    continue
                if now - ticket.dispatch_t <= self.config.hedge_k * p99:
                    continue
                sibling = self._pick_sibling(ticket)
                if sibling is None:
                    continue
                hedge_ticket = dataclasses.replace(
                    ticket, replica_future=None, hedge_future=None,
                    hedge_replica=None)
                if ticket.deadline is not None:
                    hedge_ticket.request = dataclasses.replace(
                        ticket.request,
                        timeout_s=max(0.0, ticket.deadline - now))
                try:
                    hf = sibling.dispatch(hedge_ticket)
                except ServeError:
                    continue    # sibling backpressure: try next tick
                ticket.hedge_future = hf
                ticket.hedge_replica = sibling
                sibling.outstanding += 1
                self.hedges_launched += 1

    def _pick_sibling(self, ticket):
        """Least-loaded live sibling for a hedge/failover leg, with the
        router's role affinity: a fresh-prompt leg lands on a decode
        specialist only when no prefill/general sibling is available."""
        cfg = self.pool.config
        best, best_score = None, None
        decode_best, decode_best_score = None, None
        for replica in self.pool._replicas.values():
            if (replica is ticket.replica
                    or replica.model != ticket.model
                    or replica.state != "live"
                    or not self.allows(replica)):
                continue
            score = (cfg.latency_weight * replica.predicted_wait_s()
                     + cfg.cost_weight
                     * replica.cost_estimate_usd(ticket.request)
                     * cfg.cost_scale_s_per_usd)
            if getattr(replica, "role", None) == "decode":
                if decode_best_score is None or score < decode_best_score:
                    decode_best, decode_best_score = replica, score
                continue
            if best_score is None or score < best_score:
                best, best_score = replica, score
        return best if best is not None else decode_best

    # -- reporting / lifecycle -------------------------------------------

    def breaker_states(self) -> Dict[str, str]:
        return {rid: b.state for rid, b in self._breakers.items()}

    def report(self) -> Dict:
        """The ``recovery`` block (bench --serve-load-replicas): every
        number a round-over-round diff needs to prove the fleet healed.
        ``requests_lost`` is filled by the harness (submitted minus
        answered-or-typed-rejected); the supervisor's own invariant is
        that it is structurally zero."""
        detection = [i["detection_ms"] for i in self.incidents
                     if "detection_ms" in i]
        restart = [i["restart_ms"] for i in self.incidents
                   if "restart_ms" in i]

        def stats(vals):
            if not vals:
                return None
            return {"mean": round(sum(vals) / len(vals), 3),
                    "max": round(max(vals), 3), "n": len(vals)}

        return {
            "incidents": len(self.incidents),
            "crashes": self.crashes,
            "wedges": self.wedges,
            "restarts": self.restarts,
            "permanent_quarantines": self.permanent_quarantines,
            "poison_rejects": self.poison_rejects,
            "requests_failed_over": self.failovers,
            "requests_lost": 0,
            "hedges_launched": self.hedges_launched,
            "hedges_won": self.hedges_won,
            "detection_ms": stats(detection),
            "restart_ms": stats(restart),
            "breaker_states": self.breaker_states(),
        }

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout=timeout)
        with self.pool._wake:
            # snapshot under the pool lock: _quarantine_locked appends
            # rebuild workers to this list from the router thread
            workers = list(self._workers)
        for worker in workers:
            worker.join(timeout=timeout)
