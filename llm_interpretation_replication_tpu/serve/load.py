"""Open-loop load harness + per-request latency anatomy for serve/.

Everything the repo measured before this module was CLOSED-loop: the
sweep shells and the replay harness submit work as fast as the engine
drains it, so "rows/s" is a throughput ceiling and the latency samples
only describe a system that is never waiting on traffic.  Serving
economics (ROADMAP item 1, the Gemma TPU-serving comparison's territory
— arxiv 2605.25645) need the other curve: hold the ARRIVAL rate fixed
regardless of completions (open loop — no coordinated omission), walk it
across a sweep of offered rates, and watch where tail latency leaves the
floor.  That knee, not the ceiling, is what a fleet is provisioned by.

Three cooperating pieces:

- :func:`poisson_schedule` — seeded exponential inter-arrivals at a
  configurable offered rate; same seed ⇒ bit-identical schedule, so a
  latency comparison across two builds replays the same traffic.
  Prompts are drawn (seeded) from the REAL perturbation corpus
  (:func:`corpus_workload`), so the prompt-length mix is the production
  heavy-tail one, not a synthetic constant.
- :func:`run_load` — drive the existing :class:`~.scheduler.Scheduler`
  in-process at one offered rate (or ``mode="closed"`` as the
  comparator), collect per-request end-to-end latency decomposed into
  the PR-6 span phases (queue_wait / coalesce / serve_engine / respond
  — :data:`~.scheduler.HIST_PHASES`, stamped by the scheduler onto each
  future), and report percentiles from the telemetry layer's
  log-bucketed streaming histograms — EXACT counts, no eviction: the
  bounded sample rings truncate to the newest 4096 values, which is
  precisely the history a p99.9 lives in.  The report carries the ring
  truncation block next to the histogram numbers so the two windows can
  never be confused.  A parity leg re-scores the served prompts offline
  and asserts bit-identical rows — load changes WHEN a row is computed,
  never WHAT.
- :func:`rate_sweep` — the knee finder: walk >= 3 offered rates,
  emit per-rate p50/p90/p99/p99.9 + per-phase medians +
  achieved-vs-offered + queue-depth trajectory, and estimate saturation
  throughput.  ``bench.py --serve-load`` attaches this block to the
  JSON record; ``obs bench-diff`` aligns it across records and ``obs
  report --serve-load`` renders the per-phase table.

Measurement-only: the harness submits ordinary :class:`ScoreRequest`\\ s
through the public scheduler surface; nothing here touches the scoring
path.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import telemetry
from .config import SchedulerConfig
from .replay import _per_request_targets, rows_equal
from .request import ScoreRequest, ServeError
from .scheduler import HIST_E2E, HIST_PHASES, Scheduler

#: report percentiles — p99.9 is the one the bounded rings cannot keep.
LOAD_PCTS = (50.0, 90.0, 99.0, 99.9)

#: queue-depth trajectory sampling interval / retained points.
DEPTH_SAMPLE_S = 0.05
DEPTH_TRAJECTORY_POINTS = 64

#: Knee criterion: a rate point "keeps up" when nothing was shed or
#: failed AND its post-arrival DRAIN (makespan minus the last scheduled
#: arrival — how long the queue took to clear once traffic stopped)
#: stayed within the sweep's sub-saturation floor (the smallest drain of
#: any swept rate: one in-flight latency) plus this slack.  Drain, not
#: achieved/offered: the makespan includes the final requests' natural
#: service latency, so an achieved-rate ratio misclassifies honest
#: sub-saturation points whenever per-request latency is non-trivial
#: relative to the arrival window; drain at sub-saturation is one
#: latency regardless of duration, while at saturation it grows with
#: the backlog.
KNEE_DRAIN_WINDOW_FRACTION = 0.15
KNEE_DRAIN_SLACK_S = 0.5

#: The drain floor is RELATIVE (the sweep's smallest drain), which
#: assumes at least one swept rate is below saturation (the auto
#: bracket's 0.5x anchor guarantees one).  When even the lowest rate
#: spent more than this fraction of the arrival window draining its
#: backlog, EVERY point was saturated, the relative floor is
#: meaningless, and the knee is reported as unknown (None +
#: ``knee_floor_saturated``) instead of confidently naming a saturated
#: operating point as "keeping up".
KNEE_FLOOR_MAX_DRAIN_FRACTION = 0.5


def poisson_schedule(rate: float, duration_s: float,
                     seed: int = 0) -> List[float]:
    """Seeded open-loop arrival offsets (seconds from t0) for a Poisson
    process at ``rate`` requests/s over ``duration_s``.  Deterministic:
    the same (rate, duration, seed) yields the identical schedule."""
    if rate <= 0:
        raise ValueError(f"offered rate must be > 0, got {rate}")
    rng = np.random.default_rng(seed)
    out: List[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / rate))
        if t >= duration_s:
            return out
        out.append(t)


def corpus_workload(path: str, max_rephrasings: Optional[int] = None
                    ) -> Tuple[List[str], List[Tuple[str, str]]]:
    """The perturbation corpus as a (prompts, per-prompt target pairs)
    pool — the same ``{rephrasing} {response_format}`` spelling the
    offline sweep shell and ``serve --replay`` build, so the load mix
    carries the production prompt-length histogram."""
    with open(path, encoding="utf-8") as f:
        scenarios = json.load(f)
    prompts, targets = [], []
    for s in scenarios:
        rephrasings = s["rephrasings"]
        if max_rephrasings is not None:
            rephrasings = rephrasings[:max_rephrasings]
        for r in rephrasings:
            prompts.append(f"{r} {s['response_format']}")
            targets.append(tuple(s["target_tokens"][:2]))
    return prompts, targets


def _phase_report(hist_delta: Dict[str, Dict]) -> Dict[str, Dict]:
    """Per-phase percentiles from a :func:`telemetry.hist_since` delta."""
    out = {}
    for phase, hist_name in HIST_PHASES.items():
        entry = hist_delta.get(hist_name)
        if entry:
            pct = telemetry.hist_percentiles_from(entry["counts"], LOAD_PCTS)
            pct["mean"] = round(entry["sum"] / entry["count"], 3)
            out[phase] = {k: round(v, 3) for k, v in pct.items()}
    return out


def _downsample(points: List, cap: int = DEPTH_TRAJECTORY_POINTS) -> List:
    if len(points) <= cap:
        return points
    step = len(points) / cap
    return [points[min(len(points) - 1, int(i * step))] for i in range(cap)]


class _DepthSampler:
    """Queue-depth trajectory: a daemon thread sampling ``len(queue)``
    every :data:`DEPTH_SAMPLE_S` for the duration of one load run."""

    def __init__(self, sched: Scheduler, t0: float):
        self.samples: List[Tuple[float, int]] = []
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(DEPTH_SAMPLE_S):
                self.samples.append(
                    (round(time.monotonic() - t0, 3), len(sched.queue)))

        self._thread = threading.Thread(target=loop, name="serve-load-depth",
                                        daemon=True)
        self._thread.start()

    def close(self) -> Dict:
        self._stop.set()
        self._thread.join(timeout=2.0)
        depths = [d for _, d in self.samples]
        if not depths:
            return {"max": 0, "mean": 0.0, "trajectory": []}
        return {
            "max": int(max(depths)),
            "mean": round(sum(depths) / len(depths), 2),
            "trajectory": _downsample(self.samples),
        }


def run_load(engine, prompts: Sequence, targets=("Yes", "No"),
             rate: float = 10.0, duration_s: float = 5.0, seed: int = 0,
             mode: str = "open", concurrency: int = 4,
             with_confidence: bool = False,
             max_new_tokens: Optional[int] = None,
             config: Optional[SchedulerConfig] = None,
             offline_rows: Optional[List[Dict]] = None,
             parity: bool = True,
             jsonl=None,
             result_timeout_s: float = 600.0,
             scheduler_factory=None) -> Dict:
    """Drive the scheduler at one operating point and report the latency
    anatomy.

    ``mode="open"``: submissions follow the seeded Poisson schedule
    regardless of completions — the generator never waits, so queueing
    delay is measured honestly (no coordinated omission).  A submit
    rejected by backpressure counts as ``shed``, not as latency.
    ``mode="closed"``: ``concurrency`` workers submit-wait-loop for
    ``duration_s`` — the throughput-ceiling comparator.

    ``offline_rows`` (aligned with ``prompts``) supplies the parity
    reference; without it and with ``parity=True`` the harness scores
    the prompt pool offline FIRST (which also warms the compiled
    shapes, so the load run measures steady-state serving).  ``jsonl``
    (path or open file) streams one per-request anatomy line.
    ``result_timeout_s`` is ONE shared budget for the whole
    result-collection phase — a wedged scheduler costs it once, never
    once per outstanding request.

    ``scheduler_factory(cfg)`` (optional) supplies the front door the
    harness drives INSTEAD of building ``Scheduler(engine, cfg)`` — the
    EnginePool measures its fleet through the SAME harness by handing
    ``pool.client(model)`` here (a Scheduler-shaped facade whose
    ``close()`` is a no-op: the pool outlives one load run, its
    lifetime owned by the caller).  ``engine`` is still the offline
    parity reference — for a pool of local replicas the served rows
    must be bit-identical to any single replica's ``score_prompts``."""
    prompts = list(prompts)
    per_targets = _per_request_targets(targets, len(prompts))
    if mode not in ("open", "closed"):
        raise ValueError(f"mode must be 'open' or 'closed', got {mode!r}")

    if parity and offline_rows is None:
        offline_rows = engine.score_prompts(
            prompts, targets=targets, with_confidence=with_confidence,
            max_new_tokens=max_new_tokens)

    cfg = config or SchedulerConfig()
    schedule = (poisson_schedule(rate, duration_s, seed)
                if mode == "open" else [])
    pick_rng = np.random.default_rng([seed, len(prompts)])

    close_jsonl = False
    if isinstance(jsonl, str):
        jsonl = open(jsonl, "w", encoding="utf-8")
        close_jsonl = True

    counters0 = telemetry.counters()
    hists0 = telemetry.hist_snapshot(
        [HIST_E2E] + list(HIST_PHASES.values()))
    records: List[Dict] = []   # {"i", "scheduled_s", "lag_ms",
    #                             "prompt_idx", "future"}
    shed = 0
    sched = (scheduler_factory(cfg) if scheduler_factory is not None
             else Scheduler(engine, cfg).start())
    t0 = time.monotonic()
    depth = _DepthSampler(sched, t0)
    try:
        if mode == "open":
            picks = pick_rng.integers(0, len(prompts), size=len(schedule))
            for i, offset in enumerate(schedule):
                delay = t0 + offset - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                idx = int(picks[i])
                # mode + offered rate ride every line: a rate_sweep
                # streams all its points (and the closed comparator)
                # into ONE jsonl, so each record must name its point
                rec = {"i": i, "mode": "open",
                       "offered_rate": round(rate, 3),
                       "scheduled_s": round(offset, 6),
                       "prompt_idx": idx, "future": None}
                rec["lag_ms"] = round(
                    (time.monotonic() - (t0 + offset)) * 1000.0, 3)
                try:
                    rec["future"] = sched.submit(ScoreRequest(
                        prompt=prompts[idx], targets=per_targets[idx],
                        with_confidence=with_confidence,
                        max_new_tokens=max_new_tokens))
                except ServeError as err:
                    # open loop: typed backpressure/shutdown sheds the
                    # arrival and the generator keeps its schedule —
                    # waiting here would silently turn the harness
                    # closed-loop
                    shed += 1
                    rec["error_type"] = type(err).__name__
                records.append(rec)
        else:
            lock = threading.Lock()
            state = {"n": 0}
            deadline = t0 + duration_s

            def worker():
                while time.monotonic() < deadline:
                    with lock:
                        i = state["n"]
                        state["n"] += 1
                    idx = i % len(prompts)   # deterministic round-robin
                    rec = {"i": i, "mode": "closed", "offered_rate": None,
                           "scheduled_s": None, "lag_ms": 0.0,
                           "prompt_idx": idx, "future": None}
                    try:
                        rec["future"] = sched.submit(ScoreRequest(
                            prompt=prompts[idx], targets=per_targets[idx],
                            with_confidence=with_confidence,
                            max_new_tokens=max_new_tokens))
                        rec["future"].result(timeout=result_timeout_s)
                    except Exception as err:  # graftlint: disable=G05 harness result relay: the scheduler already classified the error (OOM split/typed rejection) before it landed on the future; the worker records it as this request's data point and keeps offering load
                        rec["error_type"] = type(err).__name__
                    with lock:
                        records.append(rec)

            workers = [threading.Thread(target=worker, daemon=True,
                                        name=f"serve-load-closed-{k}")
                       for k in range(max(1, concurrency))]
            for w in workers:
                w.start()
            for w in workers:
                w.join(timeout=result_timeout_s + duration_s)
            records.sort(key=lambda r: r["i"])

        completed, errors = 0, 0
        errors_by_type: Dict[str, int] = {}
        mismatched: List[int] = []
        # ONE shared budget for the whole collection phase, not one per
        # future: a wedged scheduler must cost result_timeout_s once,
        # never N x result_timeout_s (resolved futures return instantly,
        # so a healthy run never notices the shared deadline)
        wait_deadline = time.monotonic() + result_timeout_s
        for rec in records:
            fut = rec.pop("future", None)
            if fut is None:
                if mode == "closed":   # open mode counted the shed at
                    shed += 1          # submit time
            else:
                try:
                    row = fut.result(timeout=max(
                        0.0, wait_deadline - time.monotonic()))
                except Exception as err:  # graftlint: disable=G05 harness result relay: the scheduler already classified the error (OOM split/typed rejection) before it landed on the future; the report counts it instead of sinking the other requests' anatomy
                    errors += 1
                    rec["error_type"] = type(err).__name__
                    errors_by_type[rec["error_type"]] = (
                        errors_by_type.get(rec["error_type"], 0) + 1)
                else:
                    completed += 1
                    rec["ok"] = True
                    if fut.timing is not None:
                        rec.update({k: round(v, 3)
                                    for k, v in fut.timing.items()})
                    if offline_rows is not None and not rows_equal(
                            row, offline_rows[rec["prompt_idx"]]):
                        mismatched.append(rec["i"])
            if jsonl is not None:
                jsonl.write(json.dumps(rec) + "\n")
        makespan_s = time.monotonic() - t0
    finally:
        depth_report = depth.close()
        sched.close()
        if close_jsonl:
            jsonl.close()
        elif jsonl is not None:
            jsonl.flush()

    delta = telemetry.counters_since(counters0)
    hist_delta = telemetry.hist_since(hists0)
    e2e = hist_delta.get(HIST_E2E)
    latency = (telemetry.hist_percentiles_from(e2e["counts"], LOAD_PCTS)
               if e2e else {})
    if e2e:
        latency["mean"] = e2e["sum"] / e2e["count"]
    lags = sorted(r.get("lag_ms", 0.0) for r in records)
    rings = telemetry.sample_ring_report(
        ["serve_queue_wait_ms", "serve_latency_ms", "serve_queue_depth"])
    report = {
        "mode": mode,
        "seed": seed,
        "offered_rate": round(rate, 3) if mode == "open" else None,
        "concurrency": concurrency if mode == "closed" else None,
        "duration_s": round(duration_s, 3),
        "makespan_s": round(makespan_s, 3),
        "requests": len(records),
        "completed": completed,
        "errors": errors,
        # typed-vs-lost split: a typed rejection (DeadlineExceeded,
        # PoisonousRequest, ...) is an ANSWERED request; a TimeoutError
        # here means the future never resolved inside the shared budget
        # — the "lost" signal the self-healing recovery block audits
        "errors_by_type": errors_by_type,
        "shed": shed,
        "achieved_rows_per_s": (round(completed / makespan_s, 2)
                                if makespan_s > 0 else None),
        # post-arrival drain: how long the queue took to clear after the
        # last scheduled arrival — ~one in-flight latency below
        # saturation, grows with the backlog above it (the knee signal)
        "drain_s": round(max(0.0, makespan_s - (schedule[-1] if schedule
                                                else duration_s)), 3),
        # exact-count log-bucketed histograms (telemetry.record_hist):
        # every request of this run is in the window — the p99.9 the
        # bounded rings would have evicted is the point of the exercise
        "latency_ms": {k: round(v, 3) for k, v in latency.items()},
        "phases_ms": _phase_report(hist_delta),
        "hist_requests": int(e2e["count"]) if e2e else 0,
        # open-loop honesty: how far the generator itself drifted off
        # the schedule (a lagging generator under-offers load)
        "gen_lag_ms_p99": (round(lags[max(0, math.ceil(
            0.99 * len(lags)) - 1)], 3) if lags else None),
        "queue_depth": depth_report,
        "blocked_transfers": int(delta.get("blocked_transfers", 0)),
        # ring-truncation visibility (satellite): the bounded sample
        # rings next door may have truncated (total > retained) — a
        # reader comparing ring percentiles to the histogram numbers
        # sees which window each describes
        "samples": rings,
        "rings_truncated": any(m["total"] > m["retained"]
                               for m in rings.values()),
    }
    if offline_rows is not None:
        report["parity"] = {
            "checked_rows": completed,
            "mismatched_rows": len(mismatched),
            "mismatched_indices": mismatched[:20],
        }
    return report


def rate_sweep(engine, prompts: Sequence, targets=("Yes", "No"),
               rates: Sequence[float] = (), duration_s: float = 5.0,
               seed: int = 0, config: Optional[SchedulerConfig] = None,
               offline_rows: Optional[List[Dict]] = None,
               parity: bool = True, jsonl=None,
               closed_comparator: bool = False,
               result_timeout_s: float = 600.0,
               scheduler_factory=None) -> Dict:
    """The ``serve_load`` block: walk >= 3 offered rates (ascending)
    through :func:`run_load`, estimate saturation throughput and the
    knee, and optionally append the closed-loop comparator point.
    ``scheduler_factory`` forwards to :func:`run_load` — the EnginePool
    rides the same sweep (one pool serves every rate point)."""
    rates = sorted(float(r) for r in rates)
    if len(rates) < 3:
        raise ValueError(f"rate_sweep needs >= 3 offered rates to "
                         f"bracket a knee, got {rates}")
    if parity and offline_rows is None:
        # ONE offline pass serves as parity reference for every rate
        # point (and warms the compiled shapes before the first run)
        offline_rows = engine.score_prompts(list(prompts), targets=targets)

    close_jsonl = False
    if isinstance(jsonl, str):
        jsonl = open(jsonl, "w", encoding="utf-8")
        close_jsonl = True
    try:
        points = [
            run_load(engine, prompts, targets=targets, rate=rate,
                     duration_s=duration_s, seed=seed, mode="open",
                     config=config, offline_rows=offline_rows,
                     parity=parity, jsonl=jsonl,
                     result_timeout_s=result_timeout_s,
                     scheduler_factory=scheduler_factory)
            for rate in rates
        ]
        closed = None
        if closed_comparator:
            closed = run_load(engine, prompts, targets=targets,
                              duration_s=duration_s, seed=seed,
                              mode="closed", config=config,
                              offline_rows=offline_rows, parity=parity,
                              jsonl=jsonl,
                              result_timeout_s=result_timeout_s,
                              scheduler_factory=scheduler_factory)
    finally:
        if close_jsonl:
            jsonl.close()

    achieved = [p["achieved_rows_per_s"] or 0.0 for p in points]
    base_drain = min(p["drain_s"] for p in points)
    floor_saturated = (base_drain
                       > KNEE_FLOOR_MAX_DRAIN_FRACTION * duration_s)
    drain_bound = base_drain + max(KNEE_DRAIN_SLACK_S,
                                   KNEE_DRAIN_WINDOW_FRACTION * duration_s)
    keeping_up = [] if floor_saturated else [
        p for p in points
        if not p["shed"] and not p["errors"]
        and p["drain_s"] <= drain_bound]
    knee = keeping_up[-1]["offered_rate"] if keeping_up else None
    block = {
        "mode": "open-loop poisson",
        "seed": seed,
        "duration_s": round(duration_s, 3),
        "rates": points,
        "saturation_rows_per_s": round(max(achieved), 2) if achieved else None,
        # the knee: the highest offered rate the scheduler still keeps
        # up with (nothing shed/failed, post-arrival drain within the
        # sub-saturation floor — KNEE_DRAIN_* above).  When even the
        # top swept rate keeps up, the knee is beyond the sweep —
        # reported honestly instead of pretending the last point is it
        "knee_offered_rate": knee,
        "knee_beyond_sweep": bool(keeping_up) and (
            keeping_up[-1] is points[-1]),
        # every swept rate saturated (relative drain floor unusable):
        # the knee is BELOW the sweep, not at its lowest point
        "knee_floor_saturated": floor_saturated,
        "parity_ok": all(
            (p.get("parity") or {}).get("mismatched_rows", 0) == 0
            for p in points) if parity else None,
    }
    if closed is not None:
        block["closed_loop"] = closed
    return block


def format_rate_table(block: Dict) -> str:
    """Human summary of a ``serve_load`` block (stderr / obs report)."""
    lines = [f"# serve load ({block.get('mode', '?')}, seed "
             f"{block.get('seed')}, {block.get('duration_s')}s/rate):"]
    header = (f"  {'offered':>8} {'achieved':>9} {'shed':>5} "
              + " ".join(f"{('p%g' % p):>9}" for p in LOAD_PCTS)
              + "   phase medians (ms)")
    lines.append(header)
    for p in block.get("rates", ()):
        lat = p.get("latency_ms", {})
        phases = p.get("phases_ms", {})
        med = ", ".join(
            f"{name} {phases[name]['p50']:g}"
            for name in ("queue_wait", "coalesce", "serve_engine",
                         "respond") if name in phases)
        lines.append(
            f"  {p.get('offered_rate') or 0:>8.2f} "
            f"{p.get('achieved_rows_per_s') or 0:>9.2f} "
            f"{p.get('shed', 0):>5d} "
            + " ".join(f"{lat.get('p%g' % q, float('nan')):>9.2f}"
                       for q in LOAD_PCTS)
            + f"   {med}")
    closed = block.get("closed_loop")
    if closed:
        lines.append(f"  closed-loop comparator: "
                     f"{closed.get('achieved_rows_per_s')} rows/s at "
                     f"concurrency {closed.get('concurrency')}")
    if block.get("knee_floor_saturated"):
        knee_txt = "unknown — every swept rate saturated (sweep lower)"
    elif block.get("knee_beyond_sweep"):
        knee_txt = f"beyond sweep (>= {block.get('knee_offered_rate')} offered)"
    else:
        knee_txt = f"at {block.get('knee_offered_rate')} offered"
    lines.append(
        f"  saturation {block.get('saturation_rows_per_s')} rows/s; "
        f"knee {knee_txt}")
    return "\n".join(lines)
