"""In-process continuous-batching scheduler over the scoring engine.

After PRs 1-3 the stack can only run OFFLINE sweeps: ``ScoringEngine``
consumes pre-materialized batch iterators, so a perturbation sweep, a
100q sweep, and ad-hoc judgment queries cannot share one resident model.
This scheduler closes that gap: independent :class:`~.request.ScoreRequest`\\ s
land on a thread-safe queue, coalesce into micro-batches of COMPATIBLE
requests (same :mod:`.coalescer` key — the same ``GenerationPlan`` cache
key and length bucket the engine's warm compiled shapes already exist
for; prefix-pair requests ride ``score_prefixed`` so a shared prefix
occupies one ``PrefixCachePool`` entry per batch), launch through the
existing engine entry points under a max-wait/max-batch admission
policy, and fan results back out per-request as futures.

Composition with the existing layers — the scheduler goes THROUGH them,
never around them:

- **OOM** — the engine's in-place re-bucket ladder is disarmed for
  scheduler-driven launches (``engine.config_overrides(oom_backoff=False)``);
  a device OOM instead splits the micro-batch down the SAME PR-1 ladder
  (:func:`~..runtime.faults.split_for_requeue`) and the chunks RE-ENTER
  THE QUEUE with a stepped-down engine batch override, so queued traffic
  interleaves with the retry instead of stalling behind an in-engine
  retry loop.  At the floor the requests fail with the original error.
- **Transients** — scheduler launches run under
  :func:`~..runtime.faults.retry_transient` (OOM excluded, as always).
- **Strict mode** — launches go through ``engine._run_pipelined``, so the
  transfer guard and recompile sentry stay armed; a clean serving run is
  provable as ``blocked_transfers == 0``.
- **Telemetry** — admission, rejection, batching factor, queue-depth and
  latency distributions land in the ``serve_*`` counters/samples
  (utils/telemetry.py).

Thread model: ``submit`` is safe from any thread (tokenization happens on
the submitting thread); ALL engine access is serialized on the single
scheduler loop thread, so the non-thread-safe engine needs no locking.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
from typing import List, Optional

from ..obs import tracer as obs
from ..runtime import faults
from ..runtime.engine import LegSpec
from ..utils.telemetry import (
    record_counter,
    record_fault,
    record_hist,
    record_sample,
)
from . import coalescer
from .config import SchedulerConfig
from .queue import RequestQueue, Ticket
from .request import (
    DeadlineExceeded,
    QueueFull,
    SchedulerClosed,
    ScoreFuture,
    ScoreRequest,
)


#: Streaming latency-anatomy histograms (telemetry.record_hist — exact
#: counts, log-bucketed, NO tail truncation, unlike the serve_* sample
#: rings): per-request end-to-end latency plus its DISJOINT phase
#: decomposition, stamped at result fan-out.  The four phases sum to the
#: e2e value: queue_wait (enqueue → the admission hold opened, i.e. time
#: spent behind other traffic), coalesce (inside the max-wait hold
#: window), serve_engine (micro-batch launch → engine return, shared by
#: the group), respond (engine return → this request's future resolved).
#: A request re-queued by an OOM split attributes everything before its
#: FINAL launch to queue_wait/coalesce — the anatomy decomposes the
#: launch that produced the result.  serve/load.py reads these.
HIST_E2E = "serve_req_e2e_ms"
HIST_PHASES = {
    "queue_wait": "serve_req_queue_wait_ms",
    "coalesce": "serve_req_coalesce_ms",
    "serve_engine": "serve_req_engine_ms",
    "respond": "serve_req_respond_ms",
}


def labeled_metric(name: str, labels) -> str:
    """The ``name|k=v,k2=v2`` labeled-telemetry spelling: the telemetry
    layer keys on plain strings, and :func:`..obs.metrics.prometheus_text`
    splits this convention back into one labeled series of the base
    Prometheus family — how the EnginePool's per-replica ``serve_*``
    counters and latency histograms export as ``{replica=...,model=...}``
    series instead of N separate metric families."""
    if not labels:
        return name
    return name + "|" + ",".join(
        f"{k}={v}" for k, v in sorted(labels.items()))


def _entry_k(entry):
    """decode_k class of one slab-intake entry ``(slab, tickets,
    launch_t)`` — slabs are single-K by construction (the prefill side's
    coalescer key includes decode_k), so the first ticket speaks for
    all."""
    tickets = entry[1]
    return (getattr(tickets[0].request, "decode_k", None)
            if tickets else None)


class Scheduler:
    """Continuous-batching front door for one resident :class:`ScoringEngine`.

    Usage::

        with Scheduler(engine) as sched:
            futures = [sched.submit(ScoreRequest(prompt=p)) for p in work]
            rows = [f.result(timeout=300) for f in futures]

    ``submit`` before ``start`` queues; ``close(drain=True)`` (the
    ``with`` exit) finishes queued work, then rejects anything left with
    the typed :class:`SchedulerClosed`."""

    def __init__(self, engine, config: Optional[SchedulerConfig] = None):
        self.engine = engine
        self.config = config or SchedulerConfig()
        self.queue = RequestQueue(self.config.queue_capacity)
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._labels = dict(self.config.metric_labels or {})
        # the label suffix is constant for this scheduler's lifetime:
        # build it once, not per event in the per-request hot path
        self._label_suffix = (
            labeled_metric("", self._labels) if self._labels else "")
        #: prefill→decode slab transfer hook, installed by the EnginePool
        #: on PREFILL-role replicas (serve/pool.py): called as
        #: ``handoff(slab, tickets, launch_t)`` and returns True when a
        #: decode-role sibling accepted the slab.  None (the default)
        #: keeps every launch fully local — single-engine schedulers and
        #: symmetric pools never take the handoff branch.
        self.handoff = None
        # decode-role intake: slabs handed off BY prefill siblings, each
        # entry ``(slab, tickets, launch_t)``.  Appended from the
        # prefill replica's loop thread, drained on THIS loop thread
        # (the engine's single-thread contract), with queue.wake()
        # nudging pop_group's ready_fn probe in between.
        self._slabs: List = []
        self._slab_lock = threading.Lock()

    # -- telemetry (labeled twin per metric when metric_labels is set) ---

    def _counter(self, name: str, value: float = 1) -> None:
        record_counter(name, value)
        if self._label_suffix:
            record_counter(name + self._label_suffix, value)

    def _sample(self, name: str, value: float) -> None:
        record_sample(name, value)
        if self._label_suffix:
            record_sample(name + self._label_suffix, value)

    def _hist(self, name: str, value: float) -> None:
        record_hist(name, value)
        if self._label_suffix:
            record_hist(name + self._label_suffix, value)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Scheduler":
        if self._closed:
            raise SchedulerClosed("scheduler is shut down")
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="serve-scheduler", daemon=True)
            self._thread.start()
        return self

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Idempotent shutdown: stop admitting, drain (or abandon) queued
        work, join the loop, and sweep the engine-side audit state.  Safe
        to call twice — the drain loop and ``__exit__`` both do."""
        if self._closed:
            return
        self._closed = True
        self.queue.close()
        if self._thread is not None:
            self._thread.join(
                timeout if timeout is not None
                else (self.config.drain_timeout_s if drain else 1.0))
        # anything still queued after the drain window gets a typed error
        while True:
            leftover, expired = self.queue.pop_group(max_batch=1 << 30,
                                                     max_wait_s=0)
            for t in expired:
                self._reject(t, DeadlineExceeded(
                    "deadline passed before the scheduler shut down"),
                    counter="serve_rejected_deadline")
            if not leftover:
                break
            for t in leftover:
                self._reject(t, SchedulerClosed(
                    "scheduler shut down before the request launched"),
                    counter="serve_rejected_closed")
        # slabs that landed after the loop exited get a typed rejection,
        # same contract as the queued leftovers above
        with self._slab_lock:
            leftovers, self._slabs = self._slabs, []
        for _slab, tickets, _t in leftovers:
            for t in tickets:
                self._reject(t, SchedulerClosed(
                    "decode replica shut down before its handed-off slab "
                    "decoded"), counter="serve_rejected_closed")
        # the prefix pool's close() is idempotent (safe double-close): the
        # engine already closed it per call; closing again here only sweeps
        # leak accounting from a launch that died mid-flight
        pool = getattr(self.engine, "last_prefix_pool", None)
        if pool is not None:
            pool.close()

    def __enter__(self) -> "Scheduler":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)

    # -- submission ------------------------------------------------------

    def submit(self, request: ScoreRequest) -> ScoreFuture:
        """Admit one request; returns its future.  Raises the typed
        :class:`QueueFull` on backpressure and :class:`SchedulerClosed`
        after shutdown.  An already-expired deadline resolves the future
        with :class:`DeadlineExceeded` (counted, never dropped)."""
        request.validate()
        if self._closed:
            # typed rejection, counted like its QueueFull/DeadlineExceeded
            # siblings so the serve_rejected_* split stays complete
            self._counter("serve_rejected_closed")
            raise SchedulerClosed("scheduler is shut down")
        now = time.monotonic()
        timeout_s = (request.timeout_s if request.timeout_s is not None
                     else self.config.default_timeout_s)
        future = ScoreFuture()
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        ticket = Ticket(
            request=request, future=future, seq=seq, enqueue_t=now,
            deadline=None if timeout_s is None else now + timeout_s,
            encoded=coalescer.encode_request(self.engine, request),
            # request-scoped span correlation: the same id tags this
            # request's queue-wait span, its micro-batch's engine span,
            # and (tracing only) a trace_id field on the result row, so
            # one JSONL answer line joins back to its spans
            trace_id=f"sv-{seq}" if obs.enabled() else None,
        )
        ticket.key = coalescer.compat_key(self.engine, request,
                                          ticket.encoded)
        try:
            self.queue.put(ticket)
        except QueueFull:
            self._counter("serve_rejected_full")
            raise
        self._counter("serve_enqueued")
        self._sample("serve_queue_depth", len(self.queue))
        return future

    def submit_many(self, requests) -> List[ScoreFuture]:
        return [self.submit(r) for r in requests]

    def submit_slab(self, slab, tickets, launch_t=None) -> None:
        """Accept a handed-off KV slab (decode-role side of the
        disaggregated fleet): a PREFILL sibling's scheduler calls this —
        via the pool's handoff closure — with the slab, the tickets whose
        rows it carries (slab-meta order), and the prefill launch start
        for latency attribution.  The slab decodes on THIS scheduler's
        loop thread (the engine's single-thread contract); this call just
        enqueues and wakes the loop.  Raises :class:`SchedulerClosed`
        after shutdown so the caller can pick another sibling or decode
        locally — never silently drops."""
        with self._slab_lock:
            if self._closed:
                raise SchedulerClosed("scheduler is shut down")
            self._slabs.append((slab, tickets, launch_t))
        self._counter("serve_slab_received")
        self.queue.wake()

    # -- scheduler loop --------------------------------------------------

    def _slabs_ready(self) -> bool:
        return bool(self._slabs)

    def _loop(self) -> None:
        while True:
            t_pop = time.monotonic()
            group, expired = self.queue.pop_group(
                self._max_batch(), self.config.max_wait_s,
                ready_fn=self._slabs_ready)
            hold_start = None
            if group:
                # the admission window: how long the loop held the head
                # request open for co-batchable traffic (max-wait
                # policy).  The hold starts when there was both a loop
                # waiting AND a request to hold — max(pop start, first
                # enqueue) — NOT at pop start, which on an idle server
                # would misattribute the whole idle wait as coalescing
                hold_start = max(t_pop, min(t.enqueue_t for t in group))
                if obs.enabled():
                    obs.add_span("coalesce", hold_start, time.monotonic(),
                                 phase="serve_coalesce", batch=len(group),
                                 trace_id=group[0].trace_id)
            for t in expired:
                self._reject(t, DeadlineExceeded(
                    f"deadline passed {time.monotonic() - t.deadline:.3f}s "
                    f"before the micro-batch launched"),
                    counter="serve_rejected_deadline")
            # handed-off slabs decode BEFORE the next launch: their rows
            # are mid-request (prefill already paid elsewhere), so they
            # are the closest-to-done work this thread holds
            if self._slabs_ready():
                self._drain_slabs()
            if group is None:
                return          # closed and drained
            if group:
                self._launch(group, hold_start)

    def _max_batch(self) -> int:
        if self.config.max_batch:
            return self.config.max_batch
        ecfg = getattr(self.engine, "ecfg", None)
        return ecfg.batch_size if ecfg is not None else 32

    def _reject(self, ticket: Ticket, err: Exception,
                counter: Optional[str] = None) -> None:
        """Resolve a ticket's future with a typed error, counting
        ``counter`` only when this resolution actually WON the future's
        first-wins guard — a future already answered elsewhere (the
        pool's failover/hedging orphan legs land here) must not inflate
        the serve_rejected_*/serve_failed split."""
        if ticket.future._set_exception(err) and counter:
            self._counter(counter)

    def _engine_overrides(self, group: List[Ticket]):
        """Per-launch EngineConfig overrides: the serve path owns OOM
        recovery (in-place ladder disarmed), split chunks carry the
        stepped-down batch size they re-entered the queue with, and a
        request-level ``decode_k`` overrides the engine's joint K-token
        decode block size for this launch (safe to read off the head
        request: the coalescer key includes the resolved decode_k, so a
        micro-batch can never mix K values)."""
        ov = {"oom_backoff": False}
        degraded = [t.degraded for t in group if t.degraded]
        if degraded:
            ov["batch_size"] = min(degraded)
        req_k = getattr(group[0].request, "decode_k", None)
        if req_k is not None:
            ov["decode_k"] = int(req_k)
        ctx = getattr(self.engine, "config_overrides", None)
        return ctx(**ov) if ctx is not None else contextlib.nullcontext()

    def _finish_ticket(self, t: Ticket, row, launch_t: float,
                       done: float) -> None:
        """Resolve one ticket with its row plus the four-phase timing
        anatomy (the handoff paths' twin of ``_launch``'s inline fan-out:
        ``serve_engine`` spans the prefill launch on the EXPORTING
        replica through decode completion here — the handoff transfer is
        engine time, not respond time)."""
        if t.trace_id is not None:
            row = dict(row)
            row["trace_id"] = t.trace_id
        t_set = time.monotonic()
        timing = {
            "e2e_ms": (t_set - t.enqueue_t) * 1000.0,
            "queue_wait_ms": (t.queue_wait_s or 0.0) * 1000.0,
            "coalesce_ms": (t.coalesce_s or 0.0) * 1000.0,
            "serve_engine_ms": (done - launch_t) * 1000.0,
            "respond_ms": (t_set - done) * 1000.0,
        }
        self._hist(HIST_E2E, timing["e2e_ms"])
        self._hist(HIST_PHASES["queue_wait"], timing["queue_wait_ms"])
        self._hist(HIST_PHASES["coalesce"], timing["coalesce_ms"])
        self._hist(HIST_PHASES["serve_engine"], timing["serve_engine_ms"])
        self._hist(HIST_PHASES["respond"], timing["respond_ms"])
        t.future.timing = timing
        t.future._set_result(row)

    def _launch_handoff(self, group: List[Ticket],
                        launch_t: float) -> None:
        """Prefill-role launch (disaggregated fleet): run prefill + the
        position-0 scan HERE, resolve the decided rows, and hand each
        undecided slab to a decode-role sibling via the pool-installed
        ``handoff`` closure.  A refused handoff (no decode sibling live,
        or it closed mid-transfer) decodes the slab locally — the pool's
        always-answered contract does not depend on roster composition.

        Load accounting caveat (documented, accepted): the pool
        attributes the full e2e to THIS replica's in-flight leg — the
        decode sibling's share shows up in its own ``serve_slab_*``
        counters, not in the router's EWMA."""
        pair_list = [tuple(t.request.targets) for t in group]
        prompts = [t.encoded if t.encoded is not None
                   else t.request.prompt for t in group]
        try:
            with self._engine_overrides(group):
                with obs.span("serve_engine", phase="serve_engine",
                              batch=len(group),
                              trace_id=group[0].trace_id):
                    rows0, slabs = faults.retry_transient(
                        lambda: self.engine.export_kv_slab(
                            prompts, targets=pair_list),
                        self.config.retry_policy, label="serve")()
        # graftlint: disable=G05 same serve fault boundary as _launch: OOM routes to the split/re-queue ladder, everything else lands typed on the futures
        except Exception as err:
            if faults.is_oom(err) and self._split_requeue(group, err):
                return
            self._counter("serve_failed", len(group))
            for t in group:
                self._reject(t, err)
            return
        done = time.monotonic()
        resolved = 0
        for t, row in zip(group, rows0):
            if row is None:
                continue        # rides out in a slab
            self._sample("serve_latency_ms",
                         (done - t.enqueue_t) * 1000.0)
            self._finish_ticket(t, row, launch_t, done)
            resolved += 1
        if resolved:
            self._counter("serve_completed", resolved)
        for slab in slabs:
            tickets = [group[m["orig"]] for m in slab.metas]
            if self.handoff(slab, tickets, launch_t):
                self._counter("serve_handoff_rows", len(tickets))
            else:
                self._counter("serve_handoff_local", len(tickets))
                self._decode_slabs([(slab, tickets, launch_t)])

    def _drain_slabs(self) -> None:
        """Decode every slab the intake holds, one launch per decode_k
        class (a micro-batch must never mix K values — the same rule the
        coalescer key enforces on the prefill side)."""
        while True:
            with self._slab_lock:
                batch, self._slabs = self._slabs, []
            if not batch:
                return
            by_k = {}
            for entry in batch:
                by_k.setdefault(_entry_k(entry), []).append(entry)
            for entries in by_k.values():
                self._decode_slabs(entries)

    def _decode_slabs(self, entries) -> None:
        """Decode handed-off slabs on the loop thread (decode-role side).
        The engine's ``admit_fn`` hook pulls same-K slabs that land
        MID-DECODE straight into vacated ring lanes, so a decode
        replica's lanes refill from the fleet's handoff stream without
        draining first."""
        now = time.monotonic()
        k_val = _entry_k(entries[0])
        flat: List = []

        def note(batch):
            out = []
            for slab, tickets, launch_t in batch:
                out.append(slab)
                lt = launch_t if launch_t is not None else now
                flat.extend((t, lt) for t in tickets)
            return out

        slabs = note(entries)
        base_n = len(flat)
        admitted_entries: List = []

        def admit():
            with self._slab_lock:
                more = [e for e in self._slabs if _entry_k(e) == k_val]
                for e in more:
                    self._slabs.remove(e)
            if not more:
                return None
            admitted_entries.extend(more)
            self._counter("serve_slab_admitted", len(more))
            return note(more)

        def call():
            if admitted_entries:
                # transient RETRY: the re-invoked decode feeds only the
                # original slabs, so a previous attempt's admissions go
                # back to the intake (same reasoning as the slotted
                # launch's requeue)
                with self._slab_lock:
                    self._slabs[:0] = admitted_entries
                admitted_entries.clear()
                del flat[base_n:]
            return self.engine.decode_kv_slabs(slabs, admit_fn=admit)

        try:
            with self._engine_overrides([t for t, _ in flat]):
                with obs.span("serve_engine", phase="serve_engine",
                              batch=len(flat),
                              trace_id=flat[0][0].trace_id):
                    rows = faults.retry_transient(
                        call, self.config.retry_policy, label="serve")()
        # graftlint: disable=G05 same serve fault boundary as _launch: the slab rows' errors land typed on each request's future, nothing re-raises above the loop thread
        except Exception as err:
            self._counter("serve_failed", len(flat))
            for t, _ in flat:
                self._reject(t, err)
            return
        done = time.monotonic()
        for (t, lt), row in zip(flat, rows):
            self._sample("serve_latency_ms",
                         (done - t.enqueue_t) * 1000.0)
            self._finish_ticket(t, row, lt, done)
        self._counter("serve_completed", len(flat))

    def _launch(self, group: List[Ticket],
                hold_start: Optional[float] = None) -> None:
        now = time.monotonic()
        self._counter("serve_batches")
        self._counter("serve_batch_rows", len(group))
        if hold_start is None:
            hold_start = now
        for t in group:
            self._sample("serve_queue_wait_ms",
                          (now - t.enqueue_t) * 1000.0)
            # latency-anatomy stamps (HIST_PHASES): the pre-launch wait
            # splits into DISJOINT queue_wait (behind other traffic,
            # before the admission hold opened) and coalesce (inside the
            # hold window) — the head request is all coalesce, a
            # late-arriving co-batched one all coalesce too, a request
            # that sat behind an earlier launch mostly queue_wait
            t.coalesce_s = max(0.0, now - max(hold_start, t.enqueue_t))
            t.queue_wait_s = max(0.0, (now - t.enqueue_t) - t.coalesce_s)
            if t.trace_id is not None and obs.enabled():
                # cross-thread span: enqueue happened on the submitting
                # thread, the pop on this loop thread — manually timed
                obs.add_span("queue_wait", t.enqueue_t, now,
                             phase="serve_queue_wait", trace_id=t.trace_id)
        first = group[0].request
        pair_list = [tuple(t.request.targets) for t in group]
        targets = (list(first.targets) if len(set(pair_list)) == 1
                   else pair_list)
        admitted: List[Ticket] = []

        if self.handoff is not None and self._slotted_eligible(first):
            # prefill-role replica of a disaggregated roster: the slotted
            # contract holding is exactly what makes the rows
            # slab-exportable (scored binary decode, no prefix pair, no
            # confidence leg)
            self._launch_handoff(group, now)
            return

        if self._slotted_eligible(first):
            # slot-level continuous batching (runtime/slots.py): the
            # micro-batch decodes through the slot ring, and the ring's
            # starvation hook pulls freshly-queued COMPATIBLE requests
            # into vacated slots MID-DECODE — admission stops being a
            # coalescer-boundary event.  Results come back in feed order
            # (group first, admitted appended).
            key = group[0].key
            prompts = [t.encoded if t.encoded is not None
                       else t.request.prompt for t in group]

            def admit():
                # bounded admission: at most one extra micro-batch worth
                # of rows joins a launch — an unbounded window under
                # sustained compatible load would keep this launch alive
                # forever, starving every OTHER key's traffic (and the
                # deadline sweep) behind the single loop thread
                budget = self._max_batch() - len(admitted)
                if budget <= 0:
                    return None
                extra = self.queue.pop_compatible(key, budget)
                if not extra:
                    return None
                t_adm = time.monotonic()
                for t in extra:
                    t.queue_wait_s = max(0.0, t_adm - t.enqueue_t)
                    t.coalesce_s = 0.0
                self._counter("serve_slot_admitted", len(extra))
                admitted.extend(extra)
                return ([t.encoded if t.encoded is not None
                         else t.request.prompt for t in extra],
                        [tuple(t.request.targets) for t in extra])

            def call():
                if admitted:
                    # transient RETRY: the re-invoked session feeds only
                    # the original prompts, so a previous attempt's
                    # admissions must re-enter the queue (original seq
                    # kept — they sort ahead of newer traffic) or their
                    # futures would be zipped against the wrong rows /
                    # never resolved
                    self.queue.requeue(list(admitted))
                    admitted.clear()
                return self.engine.score_prompts_slotted(
                    prompts, targets=pair_list, admit_fn=admit)
        elif first.prefix is not None:
            pairs = [
                (t.encoded[0], (t.encoded[1],)) if t.encoded is not None
                else (t.request.prefix, (t.request.suffix,))
                for t in group
            ]

            def call():
                return self.engine.score_prefixed(
                    pairs, targets=targets,
                    legs=[LegSpec("serve",
                                  with_confidence=first.with_confidence,
                                  max_new_tokens=first.max_new_tokens)])[0]
        else:
            prompts = [t.encoded if t.encoded is not None
                       else t.request.prompt for t in group]

            def call():
                return self.engine.score_prompts(
                    prompts, targets=targets,
                    with_confidence=first.with_confidence,
                    max_new_tokens=first.max_new_tokens)

        try:
            with self._engine_overrides(group):
                with obs.span("serve_engine", phase="serve_engine",
                              batch=len(group),
                              trace_id=group[0].trace_id):
                    rows = faults.retry_transient(
                        call, self.config.retry_policy, label="serve")()
        # graftlint: disable=G05 serve fault boundary: the error IS classified (faults.is_oom routes to the split/re-queue ladder) and everything else lands typed on each request's future — nothing above the scheduler thread could observe a re-raise
        except Exception as err:
            # slot-admitted tickets ride the SAME recovery as the group
            # they joined: an OOM re-queues everyone down the ladder,
            # anything else lands typed on every participating future
            group = group + admitted
            if faults.is_oom(err) and self._split_requeue(group, err):
                return
            self._counter("serve_failed", len(group))
            for t in group:
                self._reject(t, err)
            return
        done = time.monotonic()
        engine_s = done - now
        group = group + admitted        # slotted results ride feed order
        for t, row in zip(group, rows):
            self._sample("serve_latency_ms", (done - t.enqueue_t) * 1000.0)
            if t.trace_id is not None:
                # measurement-only: the trace id rides the answer row so
                # a JSONL output line joins back to its spans; replay
                # parity ignores the key (serve/replay.rows_equal)
                row = dict(row)
                row["trace_id"] = t.trace_id
            # per-request latency anatomy: four disjoint phases summing
            # to e2e, streamed into the exact-count histograms and
            # attached to the FUTURE (never the row — bit-parity)
            t_set = time.monotonic()
            respond_s = t_set - done
            timing = {
                "e2e_ms": (t_set - t.enqueue_t) * 1000.0,
                "queue_wait_ms": (t.queue_wait_s or 0.0) * 1000.0,
                "coalesce_ms": (t.coalesce_s or 0.0) * 1000.0,
                "serve_engine_ms": engine_s * 1000.0,
                "respond_ms": respond_s * 1000.0,
            }
            self._hist(HIST_E2E, timing["e2e_ms"])
            self._hist(HIST_PHASES["queue_wait"], timing["queue_wait_ms"])
            self._hist(HIST_PHASES["coalesce"], timing["coalesce_ms"])
            self._hist(HIST_PHASES["serve_engine"],
                        timing["serve_engine_ms"])
            self._hist(HIST_PHASES["respond"], timing["respond_ms"])
            t.future.timing = timing
            t.future._set_result(row)
        self._counter("serve_completed", len(group))
        if obs.enabled():
            obs.add_span("respond", done, time.monotonic(),
                         phase="serve_respond", batch=len(group),
                         trace_id=group[0].trace_id)

    def _slotted_eligible(self, first) -> bool:
        """Slot-level admission engages only where its contract holds:
        the pooled binary scored path (no prefix pair, no confidence
        leg, engine without completion decoding, decoder-only engine)
        and the knob on.  Everything else keeps the coalescer-boundary
        launch — including every configuration whose replay contract
        pins BIT parity with offline scoring."""
        if not self.config.slot_admission:
            return False
        if first.prefix is not None or first.with_confidence:
            return False
        ecfg = getattr(self.engine, "ecfg", None)
        if ecfg is None or ecfg.decode_completions:
            return False
        if getattr(self.engine, "is_encoder_decoder", False):
            return False
        return hasattr(self.engine, "score_prompts_slotted")

    def _split_requeue(self, group: List[Ticket], err) -> bool:
        """OOM recovery: split the micro-batch down the PR-1 ladder and
        push the chunks BACK INTO THE QUEUE (never an in-engine retry) at
        a stepped-down engine batch size.  False at the floor — the
        caller propagates ``err`` to the futures."""
        ecfg = getattr(self.engine, "ecfg", None)
        current = min(t.degraded for t in group if t.degraded) \
            if any(t.degraded for t in group) else (
                ecfg.batch_size if ecfg is not None else len(group))
        ladder = self.config.oom_ladder or (
            ecfg.oom_batch_ladder if ecfg is not None else ())
        split = faults.split_for_requeue(len(group), current,
                                         ladder=ladder,
                                         floor=self.config.oom_floor)
        if split is None:
            return False
        new_batch, sizes = split
        self._counter("serve_oom_splits")
        record_fault("serve_oom_split", rows=len(group), batch=current,
                     new_batch=new_batch, error=faults.oom_detail(err))
        print(f"# serve: device OOM at batch {current}; re-queueing "
              f"{len(group)} rows as {len(sizes)} micro-batch(es) at "
              f"batch {new_batch} [{faults.oom_detail(err)}]",
              file=sys.stderr)
        offset = 0
        for size in sizes:
            chunk = group[offset: offset + size]
            offset += size
            for t in chunk:
                t.degraded = new_batch
            self.queue.requeue(chunk)
        return True
