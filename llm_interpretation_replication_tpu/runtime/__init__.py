from .batching import Batch, batches_for_prompts, bucket_for, encode_prompts, rebatch
from .engine import EngineConfig, ScoringEngine
from .faults import (
    MEASURED_SWEEP_LADDER,
    Preempted,
    PreemptionGuard,
    TransientError,
    is_oom,
    is_transient,
    next_batch_down,
    oom_detail,
    retry_transient,
)
from .loader import CheckpointDir, load_hf_config, load_model, load_tokenizer
from .plan import ScoringPlan, resolve_scoring_plan
from .train import TrainState, causal_lm_loss, init_train_state, make_optimizer, make_train_step

__all__ = [
    "Batch",
    "batches_for_prompts",
    "bucket_for",
    "encode_prompts",
    "rebatch",
    "MEASURED_SWEEP_LADDER",
    "Preempted",
    "PreemptionGuard",
    "TransientError",
    "is_oom",
    "is_transient",
    "next_batch_down",
    "oom_detail",
    "retry_transient",
    "EngineConfig",
    "ScoringEngine",
    "CheckpointDir",
    "load_hf_config",
    "load_model",
    "load_tokenizer",
    "ScoringPlan",
    "resolve_scoring_plan",
    "TrainState",
    "causal_lm_loss",
    "init_train_state",
    "make_optimizer",
    "make_train_step",
]
