"""Runtime strict mode: the on-device complement of the graftlint rules.

The linter (:mod:`..lint`) catches host syncs and recompile bait
STATICALLY; this module catches what slips through at RUN time, and makes
both failure modes auditable numbers instead of perf mysteries:

- **Transfer guard** — :func:`scoring_guard` arms
  ``jax.transfer_guard_device_to_host("disallow")`` around the engine's
  scoring pipeline (runtime/engine._run_pipelined), so any implicit
  device→host sync in a launch path raises instead of silently
  serializing the async dispatch queue.  The pipeline's ``consume``
  callbacks — the sanctioned fetch points — run inside
  :func:`sanctioned_fetch`, which locally re-allows the fetch.  A blocked
  transfer increments the ``blocked_transfers`` telemetry counter before
  the error propagates, so a clean operating point is provable as
  ``blocked_transfers == 0``.
- **Recompile sentry** — :class:`RecompileSentry` turns on
  ``jax_log_compiles`` and attaches a logging handler to the ``jax``
  logger that counts every "Compiling <name> ..." record into the
  ``recompile_events`` telemetry counter.  A warm repeat of a sweep must
  hold this counter flat; growth means a shape/plan key leak (exactly
  what the PR-2 ``GenerationPlan`` cache keys and bucket warmup exist to
  prevent).

Enablement is env-gated — ``LLM_INTERP_STRICT=1`` (0/off/empty disables)
— or explicit via :func:`activate`; ``bench.py --strict`` and the CLI's
``--strict`` flag route here.  When inactive every context manager in
this module is a no-op, so the engine integration costs nothing in
ordinary runs.

Backend note: on the CPU test backend (``JAX_PLATFORMS=cpu``) jax treats
array→numpy conversion as zero-copy, so the device→host guard never
fires there — the tier-1 strict tests therefore exercise the counting
machinery through :func:`device_region` (which also guards host→device,
enforced on every backend) and prove the sweep contract as
``blocked_transfers == 0`` plus a flat warm-repeat ``recompile_events``.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Dict, Iterator, List, Optional

from ..utils.telemetry import (
    counter,
    record_counter,
    record_fault,
    sample_ring_report,
)

STRICT_ENV = "LLM_INTERP_STRICT"

#: telemetry counter names (documented in utils/telemetry.py)
RECOMPILE_COUNTER = "recompile_events"
BLOCKED_COUNTER = "blocked_transfers"

_ACTIVE = False
_SENTRY: Optional["RecompileSentry"] = None


def env_requests_strict() -> bool:
    val = os.environ.get(STRICT_ENV)
    if val is None:
        return False
    return val.strip().lower() not in ("", "0", "off", "false", "no")


def strict_enabled() -> bool:
    """Is strict mode currently armed (activate() or the env gate)?"""
    return _ACTIVE


class RecompileSentry(logging.Handler):
    """Counts XLA compilations via ``jax_log_compiles`` log records.

    jax emits one "Compiling <name> with global shapes and types ..."
    WARNING per XLA compile when ``jax_log_compiles`` is on
    (jax._src.interpreters.pxla); matching that prefix counts real
    compiles while ignoring the tracing/lowering chatter on the same
    logger.  Each hit feeds the ``recompile_events`` telemetry counter
    and keeps the program name (bounded ring) so a leaking plan key is
    attributable by name, not just by count."""

    MATCH = "Compiling "
    KEEP = 200

    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.programs: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        # graftlint: disable=G05 logging contract: a handler must never raise into the emitting code; a malformed record is not a device error
        except Exception:  # pragma: no cover - malformed record
            return
        if not msg.startswith(self.MATCH):
            return
        record_counter(RECOMPILE_COUNTER)
        name = msg[len(self.MATCH):].split(" ", 1)[0]
        self.programs.append(name)
        if len(self.programs) > self.KEEP:
            del self.programs[: len(self.programs) - self.KEEP]

    def install(self) -> None:
        import jax

        jax.config.update("jax_log_compiles", True)
        logging.getLogger("jax").addHandler(self)

    def uninstall(self) -> None:
        logging.getLogger("jax").removeHandler(self)
        try:
            import jax

            jax.config.update("jax_log_compiles", False)
        except (AttributeError, KeyError):  # pragma: no cover - old jax
            pass


def activate(sentry: bool = True) -> None:
    """Arm strict mode process-wide (idempotent).

    ``sentry=False`` arms only the transfer guards — for callers that
    cannot tolerate the log_compiles stderr chatter but still want
    blocked-transfer accounting."""
    global _ACTIVE, _SENTRY
    _ACTIVE = True
    # a later activate(sentry=True) upgrades an earlier guards-only
    # activation — idempotency must not freeze recompile_events at 0
    if sentry and _SENTRY is None:
        s = RecompileSentry()
        s.install()
        _SENTRY = s


def deactivate() -> None:
    global _ACTIVE, _SENTRY
    _ACTIVE = False
    if _SENTRY is not None:
        _SENTRY.uninstall()
        _SENTRY = None


def activate_from_env() -> bool:
    """Arm strict mode iff ``LLM_INTERP_STRICT`` requests it; returns the
    resulting state.  The CLI and bench call this once at startup."""
    if env_requests_strict():
        activate()
    return _ACTIVE


def sentry_programs() -> List[str]:
    """Names of the programs the sentry saw compile (newest last)."""
    return list(_SENTRY.programs) if _SENTRY is not None else []


def _is_transfer_guard_error(err: BaseException) -> bool:
    text = str(err)
    return "isallowed" in text and "transfer" in text


@contextlib.contextmanager
def _counting(label: str) -> Iterator[None]:
    """Count guard trips into ``blocked_transfers`` (+ a fault event for
    the audit trail) before propagating them."""
    try:
        yield
    except Exception as err:
        if _is_transfer_guard_error(err):
            record_counter(BLOCKED_COUNTER)
            record_fault("blocked_transfer", label=label,
                         error=" ".join(str(err).split())[:160])
        raise


@contextlib.contextmanager
def scoring_guard(label: str = "") -> Iterator[None]:
    """Disallow implicit device→host transfers for the duration — the
    engine wraps its scoring pipeline in this, so only code inside
    :func:`sanctioned_fetch` may materialize device values.  No-op unless
    strict mode is active."""
    if not _ACTIVE:
        yield
        return
    import jax

    with _counting(label or "scoring_guard"):
        with jax.transfer_guard_device_to_host("disallow"):
            yield


@contextlib.contextmanager
def sanctioned_fetch() -> Iterator[None]:
    """Re-allow device→host fetches inside a :func:`scoring_guard` — the
    pipeline's ``consume`` callbacks are THE sanctioned fetch points
    (mirrors graftlint G01's static contract).  No-op unless strict mode
    is active."""
    if not _ACTIVE:
        yield
        return
    import jax

    with jax.transfer_guard_device_to_host("allow"):
        yield


@contextlib.contextmanager
def device_region(label: str = "") -> Iterator[None]:
    """Strictest probe: disallow implicit transfers in BOTH directions.

    For code that must be transfer-free end to end (warmed inner loops,
    kernels, tests of the guard machinery itself).  Unlike
    :func:`scoring_guard` this also trips on host→device feeds, which the
    CPU backend enforces too — the tier-1 self-test drives the
    ``blocked_transfers`` counter through this."""
    if not _ACTIVE:
        yield
        return
    import jax

    with _counting(label or "device_region"):
        with jax.transfer_guard("disallow"):
            yield


def strict_report() -> Dict:
    """Snapshot for bench JSON / operator audit.

    ``samples`` carries the sample rings' truncation visibility
    (``{ring: {total, retained, cap}}`` — utils/telemetry
    .sample_ring_report): a ring whose ``total`` exceeds ``retained``
    was truncated, so any percentile computed from it is a tail
    statistic of the last ``retained`` samples, not a whole-run
    number."""
    report = {
        "enabled": _ACTIVE,
        RECOMPILE_COUNTER: int(counter(RECOMPILE_COUNTER)),
        BLOCKED_COUNTER: int(counter(BLOCKED_COUNTER)),
    }
    samples = sample_ring_report()
    if samples:
        report["samples"] = samples
    return report
