"""ScoringEngine: the TPU-native replacement for the reference's per-prompt
``model.generate`` loop.

Collapses HOT LOOP #1 (serial prompts) and #2 (per-token CUDA dispatch) of
run_base_vs_instruct_100q.py:464-472 into bucketed, data-parallel, jit'd
device programs: tokenize on host → length buckets → greedy decode with
per-step scores on the mesh → vectorized yes/no scan → host-side row dicts
whose keys match the reference CSV schemas (§2.8).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..models import decoder as dmod
from ..models import t5 as t5mod
from ..scoring import yes_no as yn
from ..scoring.confidence import top_candidates_from_scores, weighted_confidence_digits
from . import batching


@dataclasses.dataclass
class EngineConfig:
    batch_size: int = 32
    max_new_tokens: int = 50        # reference generate cap
    score_steps: int = 10           # MAX_LOOK_AHEAD — steps that need scores
    max_look_ahead: int = 10
    top_k: int = 5
    buckets: Sequence[int] = batching.DEFAULT_BUCKETS
    decode_completions: bool = True
    completion_chars: int = 100     # reference truncation (":379")
    pipeline_depth: int = 2         # in-flight device batches; host post-
                                    # processing of batch k overlaps device
                                    # compute of batch k+1 (JAX async dispatch)


class ScoringEngine:
    """Holds (family, model config, params, tokenizer, mesh) and runs batched
    scoring sweeps."""

    def __init__(self, family, cfg, params, tokenizer, mesh=None,
                 engine_config: Optional[EngineConfig] = None):
        self.family = family
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.mesh = mesh
        self.ecfg = engine_config or EngineConfig()

    # -- helpers ---------------------------------------------------------

    @property
    def is_encoder_decoder(self) -> bool:
        return self.family == "t5"

    def target_ids(self, targets: Sequence[str]) -> List[int]:
        return yn.target_token_ids(self.tokenizer, targets, self.is_encoder_decoder)

    def _put(self, arr):
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS

        return jax.device_put(
            jnp.asarray(arr), NamedSharding(self.mesh, P(DATA_AXIS, *([None] * (arr.ndim - 1))))
        )

    def _run_pipelined(self, batches: Iterable, launch: Callable, consume: Callable):
        """Launch device programs up to ``pipeline_depth`` ahead of host-side
        result consumption.

        JAX dispatch is asynchronous: ``launch`` returns device arrays
        immediately while the program runs, and only ``consume``'s host
        fetches (np.asarray) block.  Keeping a short queue of in-flight
        batches means the host's tokenizer-decode / row-building work for
        batch k runs while the chip computes batch k+1 — the double-buffered
        input feed of SURVEY.md §7 step 6, without threads."""
        depth = max(1, self.ecfg.pipeline_depth)
        pending: collections.deque = collections.deque()
        for batch in batches:
            pending.append((batch, launch(batch)))
            if len(pending) >= depth:
                done, out = pending.popleft()
                consume(done, out)
        while pending:
            done, out = pending.popleft()
            consume(done, out)

    # -- core ------------------------------------------------------------

    def score_prompts(
        self,
        prompts: Sequence[str],
        targets: Sequence[str] = ("Yes", "No"),
        with_confidence: bool = False,
    ) -> List[Dict]:
        """Yes/No-style scoring for a list of formatted prompts.

        Returns one dict per prompt: yes_prob, no_prob, relative_prob,
        odds_ratio, completion, success — the ``get_yes_no_logprobs``
        contract (run_base_vs_instruct_100q.py:376-382)."""
        ecfg = self.ecfg
        yes_id, no_id = self.target_ids(targets)[:2]
        encoded = batching.encode_prompts(self.tokenizer, prompts)
        results: List[Optional[Dict]] = [None] * len(prompts)
        steps = max(ecfg.score_steps, ecfg.max_look_ahead)

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            decode = t5mod.greedy_decode if self.is_encoder_decoder else dmod.greedy_decode
            tokens, scores = decode(self.params, self.cfg, ids, mask, num_steps=steps)
            res = yn.yes_no_from_scores(
                scores, yes_id, no_id,
                max_look_ahead=ecfg.max_look_ahead, top_k=ecfg.top_k,
            )
            # Only pin the [B, steps, V] scores buffer in the pending queue
            # when the confidence leg needs it — ~250 MB/batch at sweep sizes.
            return tokens, scores if with_confidence else None, res

        def consume(batch, out):
            tokens, scores, res = out
            tokens_np = np.asarray(tokens)
            scores_np = np.asarray(scores) if with_confidence else None
            yes_np = np.asarray(res.yes_prob)
            no_np = np.asarray(res.no_prob)
            rel_np = np.asarray(res.relative_prob)
            odds_np = np.asarray(res.odds_ratio)
            found_np = np.asarray(res.found)
            for r, orig in enumerate(batch.indices):
                if orig < 0:
                    continue
                completion = ""
                if ecfg.decode_completions:
                    completion = self.tokenizer.decode(
                        [int(t) for t in tokens_np[r]], skip_special_tokens=True
                    ).strip()[: ecfg.completion_chars]
                row = {
                    "yes_prob": float(yes_np[r]),
                    "no_prob": float(no_np[r]),
                    "relative_prob": float(rel_np[r]),
                    "odds_ratio": float(odds_np[r]),
                    "scan_found": bool(found_np[r]),
                    "completion": completion,
                    "success": True,
                }
                if with_confidence:
                    cands = top_candidates_from_scores(
                        scores_np[r], self.tokenizer, num_positions=3, top_k=19
                    )
                    row["weighted_confidence"] = weighted_confidence_digits(cands)
                results[int(orig)] = row

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, ecfg.batch_size, ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
            ),
            launch, consume,
        )
        return [r if r is not None else _error_row("missing") for r in results]

    def first_token_relative_prob(
        self, prompts: Sequence[str], targets: Sequence[str] = ("Yes", "No"),
        top_filter: int = 0,
    ) -> np.ndarray:
        """Fast path: one forward per bucket, no generation — the pjit'd
        perturbation-sweep hot op.  Returns [N, 3] (yes, no, relative)."""
        yes_id, no_id = self.target_ids(targets)[:2]
        encoded = batching.encode_prompts(self.tokenizer, prompts)
        out = np.zeros((len(prompts), 3), np.float64)

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            if self.is_encoder_decoder:
                dec = jnp.full((ids.shape[0], 1), self.cfg.decoder_start_token_id, jnp.int32)
                logits = t5mod.forward(self.params, self.cfg, ids, mask, dec)[:, 0, :]
            else:
                logits = dmod.forward_last_logits(self.params, self.cfg, ids, mask)
            return yn.relative_prob_first_token(logits, yes_id, no_id, top_filter)

        def consume(batch, res):
            yes, no, rel = (np.asarray(a) for a in res)
            for r, orig in enumerate(batch.indices):
                if orig >= 0:
                    out[int(orig)] = (float(yes[r]), float(no[r]), float(rel[r]))

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, self.ecfg.batch_size, self.ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
            ),
            launch, consume,
        )
        return out


def _error_row(msg: str) -> Dict:
    return {
        "yes_prob": float("nan"),
        "no_prob": float("nan"),
        "relative_prob": float("nan"),
        "odds_ratio": float("nan"),
        "scan_found": False,
        "completion": f"ERROR: {msg[:50]}",
        "success": False,
    }
