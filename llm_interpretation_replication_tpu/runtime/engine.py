"""ScoringEngine: the TPU-native replacement for the reference's per-prompt
``model.generate`` loop.

Collapses HOT LOOP #1 (serial prompts) and #2 (per-token CUDA dispatch) of
run_base_vs_instruct_100q.py:464-472 into bucketed, data-parallel, jit'd
device programs: tokenize on host → length buckets → greedy decode with
per-step scores on the mesh → vectorized yes/no scan → host-side row dicts
whose keys match the reference CSV schemas (§2.8).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import sys
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models import decoder as dmod
from ..models import t5 as t5mod
from ..obs import tracer as obs
from ..scoring import yes_no as yn
from ..scoring.confidence import weighted_confidence_digits
from ..utils.telemetry import record_counter, record_fault, record_hist
from . import batching, faults, strict
from . import plan as plan_mod
from . import slots as slots_mod


class EngineClosed(RuntimeError):
    """Scoring was attempted on a closed :class:`ScoringEngine`.

    The typed-lifecycle convention of serve/ (``SchedulerClosed``)
    extended to the engine itself: after :meth:`ScoringEngine.close`
    every scoring entry point raises this instead of dereferencing
    deleted device buffers — the caller is always told WHY, never handed
    an XLA use-after-free."""


def live_buffer_count() -> int:
    """Device-buffer census: live (not-yet-deleted) jax arrays in the
    process.  The teardown contract's yardstick — after
    :meth:`ScoringEngine.close` the count returns to its
    pre-construction baseline (tests/test_pool.py pins it), which is
    what makes unload-then-load-a-different-model possible in one
    process instead of the bench's old subprocess workaround."""
    return sum(1 for a in jax.live_arrays() if not a.is_deleted())


@functools.partial(jax.jit, static_argnames=("num_positions", "k"))
def _confidence_topk(scores, num_positions: int = 3, k: int = 19):
    """Device-side replacement for fetching the full [m, steps, V] score
    tensor just to read 3x19 candidates per row
    (scoring/confidence.top_candidates_from_scores): top-k + logsumexp run
    on device and the host fetches [m, P, k] logprobs + token ids — ~3000x
    less host traffic than the fp32 scores (a measured 200-330 MB per
    batch at sweep shapes over the tunneled chip)."""
    sub = scores[:, :num_positions, :].astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(sub, axis=-1)        # [m, P]
    vals, idx = jax.lax.top_k(sub, k)                       # [m, P, k]
    return vals - logz[..., None], idx


@dataclasses.dataclass
class EngineConfig:
    batch_size: int = 32
    max_new_tokens: int = 50        # reference generate cap — completion
                                    # chunks decode up to this many tokens
    score_steps: int = 10           # MAX_LOOK_AHEAD — steps that need scores
    max_look_ahead: int = 10
    scan_chunk: int = 5             # scored-decode chunk: the subset scan
                                    # stops early once every undecided row has
                                    # its answer (rows hit at positions 1-3 in
                                    # practice, so the 10-step tail is usually
                                    # never decoded — semantics unchanged, the
                                    # reference stops reading at the first hit)
    top_k: int = 5
    first_token_top_filter: int = 20
                                    # every scored row also carries
                                    # first_token_{yes,no,relative}_prob:
                                    # position-0 probabilities zeroed
                                    # outside the top-N, the API
                                    # extractor's top-20-logprobs view
                                    # (perturb_prompts.py:480-498) — free
                                    # at scoring time, and the perturbation
                                    # sweep's binary leg reads them instead
                                    # of paying a second full forward
    buckets: Sequence[int] = batching.DEFAULT_BUCKETS
    length_sorted_batches: bool = True
                                    # form batches from globally length-
                                    # sorted prompts so each batch pads to
                                    # ITS OWN longest prompt's bucket
                                    # (x1.13 padded tokens on the real
                                    # perturbation corpus vs x1.23 for
                                    # bucket-grouping) and only one partial
                                    # batch exists per sweep.  Output order
                                    # is unaffected (results key on prompt
                                    # indices).  Off = group by bucket in
                                    # input order (runtime/batching.py)
    decode_completions: bool = True
    completion_chars: int = 100     # reference truncation (":379")
    pipeline_depth: int = 2         # in-flight device batches; host post-
                                    # processing of batch k overlaps device
                                    # compute of batch k+1 (JAX async
                                    # dispatch).  Measured on the warm 10k
                                    # sweep (v5e): 1 = 67.6 p/s, 2 = 91.5,
                                    # 4 = 93.2.  Default stays 2 because the
                                    # completions path pins one FULL KV
                                    # cache per in-flight batch (~1.4 GB at
                                    # 192x432); the pooled+selected path
                                    # holds only small slices, so sweeps
                                    # without completions can raise it
    phase2_pool: bool = True        # pool undecided rows across prefill
                                    # batches and run ONE scored decode per
                                    # ~pool_target rows (decode is weight-
                                    # streaming-bound: a 10-step decode costs
                                    # nearly the same for 24 rows as for 192,
                                    # so amortizing it across batches removes
                                    # most of the two-phase overhead)
    phase2_pool_target: int = 0     # rows per pooled decode; 0 → batch_size
    phase2_select_slice: int = 0    # in-program phase-2 row selection: the
                                    # prefill outputs only this many cache
                                    # rows (undecided-first), so the full
                                    # cache never materializes (~106 ms/batch
                                    # at sweep shapes); 0 → batch_size // 4,
                                    # menu-padded.  Batches with more
                                    # undecided rows fall back to a full
                                    # prefill.
    phase2_pool_max_bytes: int = 512 << 20
                                    # HBM cap on gathered K/V held by the
                                    # pool ACROSS ALL buckets; a bucket
                                    # flushes early when the next add would
                                    # exceed it, so pooling can never push a
                                    # budget-fitting sweep into OOM (long
                                    # buckets hold ~3.5 MB/row at 7B)
    pooled_confidence: bool = True  # route the confidence leg's scored
                                    # decode through the leg-parameterized
                                    # cross-batch pool (_Phase2Pool with
                                    # leg="confidence"): rows gather out of
                                    # their prefill/extension caches, ONE
                                    # pooled digit decode runs per ~target
                                    # rows, and early-exit retirement stops
                                    # decoding (and frees each row's K/V
                                    # slice) as soon as positions 0-2 pin a
                                    # terminated digit answer — most rows
                                    # need ≪10 of the leg's 10 steps.
                                    # False = the r5 per-batch decode
                                    # (engages only when the leg's decode
                                    # cap fits inside the scored scan and
                                    # top_k <= ReducedScores' candidates)
    slot_repack: bool = True        # decode-then-repack (ROADMAP item 3,
                                    # runtime/slots.py): the cross-batch
                                    # pools decode through a fixed-capacity
                                    # slot ring where a retired row's lane
                                    # is immediately REFILLED from the
                                    # pending queue between chunks instead
                                    # of idling until the flush ends.
                                    # Row-level results are unchanged
                                    # (retirement is a pure per-row
                                    # function; scores stay in the
                                    # chunked-prefill fp32 class — PARITY
                                    # "Decode-then-repack").  False = the
                                    # legacy whole-flush schedule
                                    # (accumulate to target, decode, drain).
    kv_dtype: str = "bf16"          # decode-time KV cache storage dtype:
                                    # "bf16" keeps every bit-parity contract
                                    # (fused-vs-unfused, serve --replay);
                                    # "int8" quantizes on append (per-head
                                    # symmetric scales, ops/quant.quantize_kv)
                                    # — ~1.88x less cache HBM, the documented
                                    # sweep operating point (PARITY.md
                                    # tolerance).  Resolved into the decoder
                                    # config at engine construction; not a
                                    # config_overrides-able knob (compiled
                                    # program families key on it).
    decode_k: int = 1               # > 1: joint next-K-token decode with
                                    # verify-and-accept (K-Forcing, arxiv
                                    # 2606.10820): a K-head proposes up to
                                    # this many tokens per pass and ONE
                                    # joint verification program accepts
                                    # the block only when every proposal
                                    # matches the single-step argmax chain
                                    # — accepted blocks reproduce the
                                    # sequential decode exactly in tokens
                                    # and to fp32 reduction-order noise in
                                    # scores (PARITY.md "K-decode"),
                                    # rejections fall back bit-identically
                                    # to the unchanged step loop.  Engages on
                                    # both decode legs (the pooled
                                    # confidence scan and the completion
                                    # chunk loop) once a K-head is set
                                    # (ScoringEngine.distill_k_head_on);
                                    # 1 = the existing sequential path,
                                    # untouched.
    prefill_chunk: int = 0          # > 0: prompts whose bucket exceeds this
                                    # prefill in fixed-size chunks through
                                    # the suffix-extension path
                                    # (models/decoder.chunked_prefill),
                                    # bounding the [B, S, T] attention
                                    # transients of the long buckets.  0 =
                                    # monolithic prefill (default).  The
                                    # pooled phase-2 path keeps monolithic
                                    # prefills either way: its in-program
                                    # row selection (_prefill_select) is one
                                    # fused device program by design.
    # -- adaptive OOM back-off (runtime/faults.py) ----------------------
    # The chip is shared: a co-tenant allocation can RESOURCE_EXHAUST one
    # batch of a sweep that ran clean for hours.  With oom_backoff on, a
    # batch whose launch/fetch OOMs is re-bucketed at the next ladder size
    # down (halving when the ladder is empty, never below oom_batch_floor)
    # and retried IN PLACE — other batches keep the configured size, the
    # degraded batch is recorded in telemetry (fault_events) so operating
    # points stay auditable, and results are keyed by prompt index so no
    # row is lost or duplicated.  At the floor the OOM propagates.
    # Benchmarks that MEASURE an operating point should set
    # oom_backoff=False so degradation is never silent (bench.py does).
    oom_backoff: bool = dataclasses.field(
        default_factory=faults.default_engine_backoff)
    oom_batch_floor: int = dataclasses.field(
        default_factory=faults.default_engine_floor)
    oom_batch_ladder: Sequence[int] = dataclasses.field(
        default_factory=faults.default_engine_ladder)


@dataclasses.dataclass
class LegSpec:
    """One suffix leg of a fused prefix-reuse scoring call
    (:meth:`ScoringEngine.score_prefixed`).

    The perturbation sweep's full-study contract is two legs per row over
    the SAME rephrasing prefix: a binary leg (response format suffix,
    50-token completion) and a confidence leg (confidence format suffix,
    ``with_confidence`` + a 10-token cap).  ``max_new_tokens`` feeds the
    generation-plan cache key (runtime/plan.GenerationPlan), so the two
    legs keep separate plans/warm program families.
    ``decode_completions=None`` inherits the engine config."""

    name: str = ""
    with_confidence: bool = False
    max_new_tokens: Optional[int] = None
    decode_completions: Optional[bool] = None


class PrefixCachePool:
    """Lifetime accounting for the fused path's per-batch prefix KV caches.

    The engine prefills each batch's shared prefixes ONCE and every suffix
    leg extends that cache; the cache itself travels inside the pipeline's
    (batch, outputs) tuple, and this pool is the audit layer around it:
    bytes live per entry, acquire/release pairing (a release is mandatory
    exactly once — double frees raise, leaks are counted at close), and
    the prefix_hit / prefix_miss telemetry counters.  The OOM-re-bucket
    composition rule (PR-1 fault layer) is enforced here: a suffix batch
    that fails mid-leg must release its prefix entry before the re-bucket
    retries, so retried sub-batches acquire fresh entries and nothing is
    orphaned or freed twice."""

    class Entry:
        __slots__ = ("nbytes", "rows", "released")

        def __init__(self, nbytes: int, rows: int):
            self.nbytes = int(nbytes)
            self.rows = int(rows)
            self.released = False

    def __init__(self):
        self.live: List[PrefixCachePool.Entry] = []
        self.live_bytes = 0
        self.peak_bytes = 0
        self.acquired = 0
        self.released = 0
        self.hits = 0
        self.misses = 0
        self.leaked = 0
        self.closed = False

    def acquire(self, nbytes: int, rows: int) -> "PrefixCachePool.Entry":
        entry = self.Entry(nbytes, rows)
        self.live.append(entry)
        self.live_bytes += entry.nbytes
        self.peak_bytes = max(self.peak_bytes, self.live_bytes)
        self.acquired += 1
        self.misses += entry.rows
        record_counter("prefix_miss", entry.rows)
        return entry

    def hit(self, rows: int) -> None:
        """A suffix leg reused an already-prefilled prefix cache for
        ``rows`` real rows (every leg after the first rides free)."""
        self.hits += int(rows)
        record_counter("prefix_hit", int(rows))

    def release(self, entry: "PrefixCachePool.Entry") -> None:
        if entry.released:
            raise RuntimeError(
                "prefix cache entry released twice — the OOM re-bucket "
                "path must hand each retried sub-batch a FRESH entry")
        entry.released = True
        self.live.remove(entry)
        self.live_bytes -= entry.nbytes
        self.released += 1

    def close(self) -> None:
        """End-of-call sweep: any still-live entry is a leak (an error
        propagated past the pipeline) — force-release and count it so
        tests and telemetry can tell a clean run from an aborted one.

        IDEMPOTENT (safe double-close): the serve scheduler's shutdown
        path closes the engine's audit pool from both its drain loop and
        ``__exit__``, on top of the engine's own per-call close — a
        second close must neither re-count leaks into telemetry nor
        disturb the accounting."""
        if self.closed:
            return
        self.closed = True
        for entry in list(self.live):
            entry.released = True
            self.live.remove(entry)
            self.live_bytes -= entry.nbytes
            self.leaked += 1
        if self.leaked:
            record_counter("prefix_pool_leaked", self.leaked)

    @property
    def consistent(self) -> bool:
        """Every acquire was matched by exactly one release (leaks are
        force-released by close() but keep the pool inconsistent)."""
        return (not self.live and self.leaked == 0
                and self.acquired == self.released)


class ScoringEngine:
    """Holds (family, model config, params, tokenizer, mesh) and runs batched
    scoring sweeps."""

    def __init__(self, family, cfg, params, tokenizer, mesh=None,
                 engine_config: Optional[EngineConfig] = None):
        self.family = family
        ecfg = engine_config or EngineConfig()
        if ecfg.kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"unknown kv_dtype {ecfg.kv_dtype!r}")
        # the KV storage dtype is a property of the compiled decoder
        # programs, so it lives on the (static, hashable) decoder config:
        # resolve the engine knob into cfg ONCE, at construction.  T5 (and
        # test fakes without the field) have no decoder-side prompt cache
        # to quantize; the knob is a no-op there.
        if (ecfg.kv_dtype != "bf16" and dataclasses.is_dataclass(cfg)
                and hasattr(cfg, "kv_cache_dtype")
                and cfg.kv_cache_dtype != ecfg.kv_dtype):
            cfg = dataclasses.replace(cfg, kv_cache_dtype=ecfg.kv_dtype)
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.mesh = mesh
        self.ecfg = engine_config or EngineConfig()
        # per-engine mirror of the telemetry fault log: every OOM back-off
        # this engine performed (degraded batches are auditable per run)
        self.fault_events: List[Dict] = []
        # per-(cap, schedule-knobs) generation plans (runtime/plan.py) —
        # the binary and confidence legs' different max_new_tokens caps
        # key DIFFERENT plans, so neither evicts the other's
        self._plan_cache: Dict[Tuple, plan_mod.GenerationPlan] = {}
        # audit trail of the most recent score_prefixed call's prefix pool
        self.last_prefix_pool: Optional[PrefixCachePool] = None
        # the auto-parallel plan search's decision note when this engine's
        # operating point was chosen by search (runtime/plan_search.py via
        # the CLI engine factory); None = hand-configured.  Sweep shells
        # log it so every run names how its operating point was picked.
        self.plan_decision: Optional[str] = None
        # per-call slot-occupancy stats from the decode-then-repack rings
        # (runtime/slots.py) — bench drains them into the record's
        # ``occupancy`` block via occupancy_report()
        self._occupancy: List[slots_mod.OccupancyStats] = []
        # K-head params for the joint next-K-token decode (models/decoder.
        # k_propose); None with decode_k > 1 runs sequentially, noted once
        self.k_head = None
        self._k_head_missing_noted = False
        self._closed = False

    # -- lifecycle -------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def _check_open(self) -> None:
        if self._closed:
            raise EngineClosed(
                "ScoringEngine is closed — its device buffers are "
                "released; construct a new engine (or load a replica "
                "through serve.pool.EnginePool) before scoring")

    def close(self, release_params: bool = True) -> None:
        """Verified resource teardown: release every device buffer this
        engine pins so the HBM (and the allocator's arena state) return
        to the pre-construction baseline — the fix the bench's
        full-study subprocess isolation stood in for (VERDICT Missing
        #3), and the prerequisite for :class:`~..serve.pool.EnginePool`
        hot unload/load.

        - parameter buffers are deleted DETERMINISTICALLY
          (``jax.Array.delete`` per leaf) rather than waiting for GC —
          a 7B snapshot is ~7-13 GB of HBM whose release must not
          depend on reference-count timing; ``release_params=False``
          skips the deletes for engines sharing a param tree with a
          still-live sibling (bench replicas over one snapshot) and
          only drops this engine's references
        - the prefix-cache audit pool closes (idempotent — leak
          accounting swept exactly once)
        - the generation-plan and token-text caches clear

        Compiled executables stay in the process-wide jit caches: they
        close over SHAPES, not this engine's buffers, so an unload-then-
        load of the same geometry re-warms free while a different model
        compiles its own family.  Idempotent (double-close is a no-op);
        scoring after close raises the typed :class:`EngineClosed`.
        ``live_buffer_count()`` is the census tests verify around a
        construct → score → close cycle."""
        if self._closed:
            return
        self._closed = True
        if self.last_prefix_pool is not None:
            self.last_prefix_pool.close()
        if release_params and self.params is not None:
            for leaf in jax.tree_util.tree_leaves(self.params):
                delete = getattr(leaf, "delete", None)
                if delete is not None:
                    try:
                        delete()
                    except RuntimeError:
                        pass  # leaf shared with an already-closed sibling
        self.params = None
        self.k_head = None
        self._plan_cache.clear()
        self._tok_text_cache: Dict[int, str] = {}
        record_counter("engine_closed")

    def __enter__(self) -> "ScoringEngine":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- helpers ---------------------------------------------------------

    @property
    def is_encoder_decoder(self) -> bool:
        return self.family == "t5"

    @contextlib.contextmanager
    def config_overrides(self, **overrides):
        """Temporarily replace :class:`EngineConfig` fields for the
        duration — the serve scheduler's composition hook: a
        scheduler-driven launch disarms the engine's in-place OOM ladder
        (``oom_backoff=False`` — a split micro-batch re-enters the QUEUE,
        not the engine) and steps ``batch_size`` down for re-queued split
        chunks, while every other caller keeps the configured values.

        NOT safe against CONCURRENT engine calls (the scheduler
        serializes all engine access on its loop thread, which is also
        the engine's own thread-safety contract)."""
        prev = self.ecfg
        self.ecfg = dataclasses.replace(prev, **overrides)
        try:
            yield self.ecfg
        finally:
            self.ecfg = prev

    def target_ids(self, targets: Sequence[str]) -> List[int]:
        return yn.target_token_ids(self.tokenizer, targets, self.is_encoder_decoder)

    def _target_id_rows(self, prompts, targets) -> np.ndarray:
        """Normalize ``targets`` to a per-prompt [(yes_id, no_id)] array.

        ``targets`` is either one (yes, no) string pair applied to every
        prompt, or a sequence of per-prompt pairs (len == len(prompts)).
        Per-prompt pairs let ONE call score prompts from MIXED scenarios —
        every scoring op already broadcasts [B] token-id operands — so the
        sweep batches across scenarios instead of paying a partial tail
        batch per (scenario, bucket): at the real perturbation corpus that
        padding was ~40% of all prefill rows."""
        if targets and not isinstance(targets[0], str):
            if len(targets) != len(prompts):
                raise ValueError(
                    f"per-prompt targets: got {len(targets)} pairs for "
                    f"{len(prompts)} prompts")
            cache: Dict[tuple, tuple] = {}
            rows = np.empty((len(prompts), 2), np.int32)
            for i, pair in enumerate(targets):
                key = tuple(pair)
                if key not in cache:
                    cache[key] = tuple(self.target_ids(list(pair))[:2])
                rows[i] = cache[key]
            return rows
        yes_id, no_id = self.target_ids(list(targets))[:2]
        return np.tile(np.asarray([[yes_id, no_id]], np.int32),
                       (len(prompts), 1))

    @staticmethod
    def _batch_target_rows(ids_all: np.ndarray, batch) -> np.ndarray:
        """[B, 2] target ids for one batch; pad rows (index -1) duplicate
        row 0's content in the batcher, so they take row 0's ids too."""
        first = int(batch.indices[0])
        idx = np.where(batch.indices >= 0, batch.indices, first)
        return ids_all[idx]

    def _put(self, arr):
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS

        return jax.device_put(
            jnp.asarray(arr), NamedSharding(self.mesh, P(DATA_AXIS, *([None] * (arr.ndim - 1))))
        )

    def _put_replicated(self, arr):
        """Place an array replicated on this engine's mesh slice (plain
        ``jnp.asarray`` off-mesh) — the KV-slab import placement: slab
        rows arrive in whatever row count the exporter batched, which
        need not divide the slice's data axis, so batch-sharding is not
        an option and the cache rides replicated like the params."""
        if self.mesh is None:
            return jnp.asarray(arr)
        from ..parallel import mesh as mesh_mod

        return jax.device_put(jnp.asarray(arr), mesh_mod.replicated(self.mesh))

    def bind_mesh(self, mesh) -> "ScoringEngine":
        """Bind this engine to a device-mesh SLICE (the per-replica
        placement of the disaggregated fleet — serve/pool.py carves the
        pod via :func:`..parallel.mesh.carve_slices` and hands each
        replica its own mesh).  Re-places the param tree (and K-head, if
        loaded) replicated over the slice and clears the generation-plan
        cache so every later launch compiles against the new placement.

        Placement is a COPY when the slice differs from the params'
        current devices: a ``ParamShareGroup`` sibling bound to a
        different slice stops sharing HBM with its donor — that is the
        point (each replica owns its chips), but rosters that want
        zero-copy sharing must keep siblings on one slice.  Returns
        ``self`` so ``pool.load(model, engine.bind_mesh(m))`` reads
        naturally."""
        from ..parallel import mesh as mesh_mod

        self.mesh = mesh
        sharding = mesh_mod.replicated(mesh)
        self.params = jax.device_put(self.params, sharding)
        if getattr(self, "k_head", None) is not None:
            self.k_head = jax.device_put(self.k_head, sharding)
        self._plan_cache.clear()
        record_counter("replica_mesh_bound")
        return self

    def _run_pipelined(self, batches: Iterable, launch: Callable,
                       consume: Callable, rebatch: Optional[Callable] = None):
        """Launch device programs up to ``pipeline_depth`` ahead of host-side
        result consumption.

        JAX dispatch is asynchronous: ``launch`` returns device arrays
        immediately while the program runs, and only ``consume``'s host
        fetches (np.asarray) block.  Keeping a short queue of in-flight
        batches means the host's tokenizer-decode / row-building work for
        batch k runs while the chip computes batch k+1 — the double-buffered
        input feed of SURVEY.md §7 step 6, without threads.

        ``rebatch(batch, err)`` is the adaptive OOM back-off hook
        (:meth:`_oom_rebatch`): when a batch's launch or consume raises a
        device OOM, the hook returns replacement sub-batches (the same real
        rows re-bucketed at a stepped-down size) which are queued ahead of
        the remaining input; anything the hook cannot absorb it re-raises.
        Because async dispatch surfaces a failed program at the first host
        fetch of ITS outputs, the (batch, outputs) pairing below attributes
        the error to the right rows even mid-pipeline.  A consume that
        fails part-way re-scores its whole batch; results are keyed by
        prompt index, so the rewrite is idempotent.

        Under strict mode (runtime/strict.py, ``LLM_INTERP_STRICT=1``) the
        whole loop runs inside a device→host transfer guard and ONLY the
        ``consume`` callbacks — the sanctioned fetch points — may
        materialize device values: an implicit sync anywhere in a launch
        path raises (counted in the ``blocked_transfers`` telemetry
        counter) instead of silently draining the pipeline.  This is the
        runtime half of the graftlint G01 contract."""
        depth = max(1, self.ecfg.pipeline_depth)
        pending: collections.deque = collections.deque()
        retries: collections.deque = collections.deque()
        it = iter(batches)

        def handle(batch, err):
            if rebatch is None:
                raise err
            retries.extend(rebatch(batch, err))  # re-raises non-OOM/at-floor

        with strict.scoring_guard(type(self).__name__):
            while True:
                if retries:
                    batch = retries.popleft()
                else:
                    # batch formation (the bucketing generator's numpy
                    # work) is host prep the pipeline cannot overlap
                    with obs.span("next_batch", phase="host_prep"):
                        batch = next(it, None)
                if batch is not None:
                    try:
                        # dispatch only — JAX launches are async; the
                        # device time of in-flight work surfaces in the
                        # consume span's d2h_fetch below
                        with obs.span("launch", phase="dispatch",
                                      bucket=int(batch.bucket_len),
                                      batch=int(batch.token_ids.shape[0])):
                            out = launch(batch)
                            pending.append((batch, out))
                    # graftlint: disable=G05 pipeline handler: handle() re-raises via the _oom_rebatch faults classification
                    except Exception as err:
                        handle(batch, err)
                        continue
                elif not pending:
                    break
                if len(pending) >= depth or batch is None:
                    done, out = pending.popleft()
                    try:
                        with strict.sanctioned_fetch():
                            with obs.span("consume", phase="d2h_fetch",
                                          bucket=int(done.bucket_len)):
                                consume(done, out)
                    # graftlint: disable=G05 pipeline handler: handle() re-raises via the _oom_rebatch faults classification
                    except Exception as err:
                        handle(done, err)

    def _oom_rebatch(self, encoded) -> Optional[Callable]:
        """Per-call OOM back-off hook for :meth:`_run_pipelined`.

        Returns ``rebatch(batch, err)``: for a device OOM, step the failed
        batch's size down the configured ladder (halving between ladder
        points, never below ``oom_batch_floor`` — runtime/faults.py) and
        re-bucket its real rows via :func:`batching.rebatch`; the degraded
        batch is recorded in telemetry AND on ``self.fault_events`` so the
        run's true operating points stay auditable.  Non-OOM errors and
        OOMs at the floor re-raise.  None when back-off is disabled."""
        ecfg = self.ecfg
        if not ecfg.oom_backoff:
            return None

        def rebatch(batch, err):
            # _no_rebatch marks errors whose device program spans rows from
            # OTHER batches (the phase-2 pool): stepping THIS batch down
            # cannot shrink that program, and retrying would silently lose
            # the popped pool entries as "missing" rows — propagate to the
            # caller's repeat-level OOM policy instead.
            if getattr(err, "_no_rebatch", False) or not faults.is_oom(err):
                raise err
            size = int(batch.token_ids.shape[0])
            new_size = faults.next_batch_down(
                size, ladder=ecfg.oom_batch_ladder, floor=ecfg.oom_batch_floor)
            if new_size is None:
                raise err
            n_real = int((batch.indices >= 0).sum())
            event = record_fault(
                "engine_oom_backoff", batch=size, new_batch=new_size,
                bucket_len=int(batch.bucket_len), rows=n_real,
                error=faults.oom_detail(err))
            self.fault_events.append(event)
            print(f"# engine: device OOM at batch {size} "
                  f"(bucket {batch.bucket_len}); retrying {n_real} rows at "
                  f"batch {new_size} [{faults.oom_detail(err)}]",
                  file=sys.stderr)
            return batching.rebatch(
                batch, encoded, new_size, ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
                length_sorted=ecfg.length_sorted_batches)

        return rebatch

    def _prefill(self, ids, mask, cache_len: int):
        """One prompt forward -> (last_logits, KVCache): monolithic
        :func:`models.decoder.prefill`, or — when ``prefill_chunk`` is set
        and the bucket exceeds it — the chunked replay through the
        suffix-extension path (:func:`models.decoder.chunked_prefill`),
        bounding the long buckets' [B, S, T] attention transients.

        Telemetry: ``prefill_chunks`` counts extension programs launched
        (auditable proof the chunked path engaged) and, when the engine
        runs an int8 KV cache, ``kv_cache_bytes_saved`` accumulates the
        HBM the quantized cache does NOT pin vs its bf16 layout — both
        computed from static shapes, so no host sync happens inside the
        strict-mode transfer guard."""
        chunk = int(self.ecfg.prefill_chunk or 0)
        chunked = chunk > 0 and cache_len > chunk
        with obs.span("chunked_prefill" if chunked else "prefill",
                      phase="prefill", bucket=int(cache_len),
                      batch=int(ids.shape[0]),
                      kv_dtype=self.ecfg.kv_dtype) as sp:
            if chunked:
                last, cache, n_chunks = dmod.chunked_prefill(
                    self.params, self.cfg, ids, mask, chunk)
                record_counter("prefill_chunks", n_chunks)
            else:
                last, cache = dmod.prefill(self.params, self.cfg, ids, mask,
                                           cache_len=cache_len)
            if sp is not None:
                sp["_sync_obj"] = last  # device-time attribution (sync mode)
        if cache.k_scale is not None:
            bf16_bytes = 2 * int(cache.k.size + cache.v.size)
            record_counter("kv_cache_bytes_saved",
                           bf16_bytes - _cache_nbytes(cache))
        return last, cache

    # -- core ------------------------------------------------------------

    def score_prompts(
        self,
        prompts: Sequence[str],
        targets: Sequence[str] = ("Yes", "No"),
        with_confidence: bool = False,
        max_new_tokens: Optional[int] = None,
    ) -> List[Dict]:
        """Yes/No-style scoring for a list of formatted prompts.

        Returns one dict per prompt: yes_prob, no_prob, relative_prob,
        odds_ratio, completion, success — the ``get_yes_no_logprobs``
        contract (run_base_vs_instruct_100q.py:376-382).

        Decoder-only models run TWO-PHASE: one prompt forward (prefill)
        settles every row whose position-0 top-k already contains a target —
        the reference reads position 0 for those rows and never inspects
        positions 1..9 (run_base_vs_instruct_100q.py:349-364) — and only the
        undecided rows continue into the 10-step scored decode, reusing the
        prefill's KV cache.  When ``decode_completions`` is on, all rows also
        greedy-generate up to ``max_new_tokens=50`` score-free tokens in
        EOS-early-exit chunks so the ``completion`` column matches the
        reference's ``generate(max_new_tokens=50)`` text (ibid.:337-346,379).

        ``max_new_tokens`` overrides the engine config's generation cap for
        THIS call only (never below the scored-scan steps) — e.g. the
        perturbation sweep's confidence leg caps at the API legs' 10-token
        contract while the binary leg keeps the full 50.

        Prompts may be strings, pre-tokenized id sequences (lists of
        ints — how the host pipeline hands over work it encoded on a
        background thread), or ``(prefix, suffix)`` 2-tuples, which route
        through the fused prefix-reuse path (:meth:`score_prefixed` with
        one leg): the prefix prefills into a KV cache and the suffix runs
        as a short cache-extension prefill.
        """
        self._check_open()
        if prompts and _is_prefix_pair(prompts[0]):
            leg = LegSpec(with_confidence=with_confidence,
                          max_new_tokens=max_new_tokens)
            return self.score_prefixed(
                [(p[0], (p[1],)) for p in prompts], targets=targets,
                legs=[leg])[0]
        if self.is_encoder_decoder:
            return self._score_encdec(prompts, targets, with_confidence,
                                      max_new_tokens)
        return self._score_decoder(prompts, targets, with_confidence,
                                   max_new_tokens)

    def score_prefixed(
        self,
        pairs: Sequence,
        targets: Sequence[str] = ("Yes", "No"),
        legs: Optional[Sequence[LegSpec]] = None,
    ) -> List[List[Dict]]:
        """Fused multi-leg scoring over shared prefixes — the full-study
        row contract's hot path.

        ``pairs``: one ``(prefix, suffixes)`` tuple per row, where
        ``suffixes`` holds one format suffix per leg (strings tokenize
        once per distinct text, with no special tokens; pre-tokenized id
        lists pass through).  ``legs`` configures each leg (defaults to
        plain scoring); ``targets`` is one (yes, no) pair or per-row
        pairs, shared by every leg.

        Instead of tokenizing and prefilling ``{prefix} {suffix}`` once
        PER LEG (the unfused two-call contract — BENCH_r05's 31.64 rows/s
        full-study path), the engine prefills each row's prefix exactly
        once per batch into a bucketed KV cache and runs every leg as a
        short suffix-extension prefill against that cache
        (models/decoder.extend_prefill), cutting per-row prefill FLOPs
        nearly in half for the two-leg contract.  Rows/legs are
        numerically identical to unfused scoring over the same token
        streams (tests/test_prefix_reuse.py pins bit-equality on the CPU
        harness).

        Returns one result-row list per leg, each aligned with ``pairs``.
        Prefix cache lifetimes are audited on ``self.last_prefix_pool``
        (prefix_hit/prefix_miss telemetry; OOM re-buckets release their
        entry before retrying — the PR-1 composition rule)."""
        self._check_open()
        n_legs = len(legs) if legs is not None else (
            len(pairs[0][1]) if pairs else 1)
        legs = list(legs) if legs is not None else [
            LegSpec() for _ in range(n_legs)]
        if pairs and len(pairs[0][1]) != len(legs):
            raise ValueError(
                f"{len(legs)} legs configured but pairs carry "
                f"{len(pairs[0][1])} suffixes")
        if not pairs:
            return [[] for _ in legs]
        with obs.span("encode_prefix_pairs", phase="host_tokenize",
                      rows=len(pairs)):
            prefix_encoded, suffix_encoded = batching.encode_prefix_pairs(
                self.tokenizer, pairs)
        if self.is_encoder_decoder:
            # T5 has no decoder-side prompt cache to extend (the encoder
            # re-reads the full prompt every leg anyway): score each leg
            # over the same concatenated token streams — the
            # tokenize-once half of the contract still holds.
            return [
                self.score_prompts(
                    [list(p) + list(s) for p, s in
                     zip(prefix_encoded, suffix_encoded[li])],
                    targets=targets, with_confidence=leg.with_confidence,
                    max_new_tokens=leg.max_new_tokens)
                for li, leg in enumerate(legs)
            ]
        return self._score_decoder_prefixed(
            prefix_encoded, suffix_encoded, targets, legs)

    def _gen_plan(self, max_new_tokens: Optional[int] = None,
                  decode_completions: Optional[bool] = None
                  ) -> plan_mod.GenerationPlan:
        """Cached :class:`~.plan.GenerationPlan` for the current engine
        config; ``max_new_tokens`` is a per-call override of the config
        cap and is PART OF THE CACHE KEY — the perturbation sweep's binary
        (50-token) and confidence (10-token) legs resolve to distinct
        plans instead of overwriting one entry between chunks.  Unpacks
        like the legacy ``(steps, total)`` tuple."""
        ecfg = self.ecfg
        dc = ecfg.decode_completions if decode_completions is None \
            else decode_completions
        key = plan_mod.plan_cache_key(
            ecfg.score_steps, ecfg.max_look_ahead, ecfg.max_new_tokens,
            dc, max_new_tokens)
        plan = self._plan_cache.get(key)
        if plan is None:
            plan = self._plan_cache[key] = plan_mod.generation_plan(
                ecfg.score_steps, ecfg.max_look_ahead, ecfg.max_new_tokens,
                dc, max_new_tokens)
        return plan

    def _completion_text(self, row_tokens, eos_id) -> str:
        """Decode one row's generated tokens the way the reference records
        ``completion``: cut at the first EOS (HF generate stops there),
        skip specials, strip, truncate (run_base_vs_instruct_100q.py:366-379).
        """
        ids = []
        for t in row_tokens:
            t = int(t)
            if eos_id is not None and t == eos_id:
                break
            ids.append(t)
        return self.tokenizer.decode(ids, skip_special_tokens=True).strip()[
            : self.ecfg.completion_chars
        ]

    def _candidates_from_topk(self, lp_row, idx_row):
        """API-style (token text, logprob) candidate lists from one row's
        device top-k ([P, k] logprobs + token ids, _confidence_topk) — the
        inputs weighted_confidence_digits expects.  Token texts memoize in
        an id->text cache: a sweep re-decodes the same few thousand ids."""
        cache = getattr(self, "_tok_text_cache", None)
        if cache is None:
            cache = self._tok_text_cache = {}
        positions = []
        for p in range(lp_row.shape[0]):
            cands = []
            for lp, i in zip(lp_row[p], idx_row[p]):
                i = int(i)
                text = cache.get(i)
                if text is None:
                    text = cache[i] = self.tokenizer.decode([i])
                cands.append((text, float(lp)))
            positions.append(cands)
        return positions

    def _score_decoder(self, prompts, targets, with_confidence,
                   max_new_tokens=None) -> List[Dict]:
        ecfg = self.ecfg
        ids_all = self._target_id_rows(prompts, targets)   # [N, 2]
        eos_id = getattr(self.tokenizer, "eos_token_id", None)
        with obs.span("encode_prompts", phase="host_tokenize",
                      prompts=len(prompts)):
            encoded = batching.encode_prompts(self.tokenizer, prompts)
        results: List[Optional[Dict]] = [None] * len(prompts)
        steps, gen_total = self._gen_plan(max_new_tokens)

        if ecfg.phase2_pool and not with_confidence and not ecfg.decode_completions:
            return self._score_decoder_pooled(
                encoded, ids_all, results, eos_id, steps)
        if self._conf_pool_eligible(with_confidence, steps, gen_total):
            return self._score_decoder_conf_pooled(
                encoded, ids_all, results, eos_id, steps)

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            # cache_len == prompt length: generated K/V are concatenated as
            # per-chunk tails by decode_steps, so pre-padding slots for them
            # would only add permanently-invalid slots to every attention
            last, cache = self._prefill(ids, mask, batch.bucket_len)
            lengths = jnp.sum(mask, axis=-1)
            row_ids = self._batch_target_rows(ids_all, batch)
            scan0 = yn.first_token_scan(
                last, row_ids[:, 0], row_ids[:, 1], top_k=ecfg.top_k)
            first3 = yn.relative_prob_first_token(
                last, row_ids[:, 0], row_ids[:, 1],
                ecfg.first_token_top_filter)
            return last, cache, lengths, scan0, first3

        def consume(batch, out):
            self._consume_scored_batch(
                batch, out, ids_all, results, with_confidence, steps,
                gen_total, ecfg.decode_completions, eos_id)

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, ecfg.batch_size, ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
                length_sorted=ecfg.length_sorted_batches,
            ),
            launch, consume, rebatch=self._oom_rebatch(encoded),
        )
        return [r if r is not None else _error_row("missing") for r in results]

    def _consume_scored_batch(self, batch, out, ids_all, results,
                              with_confidence, steps, gen_total,
                              decode_completions, eos_id):
        """Consume one launched scored batch into ``results``: position-0
        scan rows, completion chunks, the scored look-ahead for undecided
        rows, and the confidence top-k — the per-batch half of
        ``score_prompts`` shared by the plain path (one prompt forward per
        batch) and every suffix leg of the fused prefix-reuse path
        (``out`` then comes from prefill+extend_prefill, and
        ``decode_completions``/``gen_total`` are the LEG's plan, not the
        engine default).  ``out`` is (last_logits, cache, lengths, scan0,
        first3).  Keyed by prompt index, so a re-consume after an OOM
        re-bucket is idempotent."""
        ecfg = self.ecfg
        last, cache, lengths, scan0, first3 = out
        yes0, no0, rel0, odds0, hit0 = (np.asarray(a) for a in scan0)
        first3 = tuple(np.asarray(a) for a in first3)
        row_ids = self._batch_target_rows(ids_all, batch)
        valid = batch.indices >= 0
        undecided = np.flatnonzero(~hit0 & valid)
        if with_confidence:
            undecided = np.flatnonzero(valid)  # every row needs scores
        need_scores = undecided.size > 0

        tokens_np = None      # [B, n_generated] when completions decoded
        conf_lp = conf_idx = None  # [B|m, P, 19] device top-k when
                                   # the confidence leg needs it
        res_np = None         # scan over positions 0..steps-1
        sub_pos = None        # batch row -> row in the subset arrays

        if decode_completions:
            # Completion chunks: every row generates (the reference's
            # generate does, regardless of where the scan hit); the first
            # chunk doubles as the scored look-ahead when any row needs it.
            #
            # REDUCED scores: the scored chunk stacks per-step
            # ReducedScores statistics (top-19 + logsumexp + target
            # logits) instead of [B, steps, V] fp32 logits — everything
            # the yes/no scan and the confidence leg read, ~1600x
            # smaller.  The fp32 buffer (~580 MB at full-study sweep
            # shapes) was what HBM-capped the sweep's batch at 224
            # (runtime/plan.resolve_full_sweep_plan).  Falls back to
            # full scores only for top_k beyond the kept candidates.
            #
            # COMPILE FAN-OUT (deliberate): each chunk concatenates its
            # tail into the cache, so successive chunks see cache lengths
            # T, T+10, T+20, ... and compile ~gen_total/steps (≈5)
            # executables per length bucket, amortized by XLA's
            # persistent compilation cache.  The alternative — pre-pad
            # the cache once to T+max_new_tokens and write tails in with
            # dynamic-update-slice for a single shared executable — is
            # exactly the scatter-updated-cache design the profiler
            # killed in round 3: the DUS made XLA pick a T-minor cache
            # layout whose full-cache relayout loop cost 150-310 ms per
            # batch (models/decoder.KVCache docstring).  Five cheap
            # compiles beat a relayout per batch.
            reduced = ecfg.top_k <= dmod.REDUCED_TOPK
            use_k = self._k_active()
            prev_h = None  # K-path frontier hidden (proposal input)
            prev, done, offset = last, None, 0
            chunk_toks, scores_dev = [], None
            lag_flag = None  # all-done flag of the PREVIOUS chunk
            with obs.span("completion_decode", phase="decode",
                          gen_total=int(gen_total),
                          bucket=int(batch.bucket_len)) as dsp:
                while offset < gen_total:
                    n = min(steps, gen_total - offset)
                    ws = offset == 0 and need_scores
                    if use_k:
                        # joint K-token verify-and-accept over THIS chunk
                        # (fold boundaries unchanged — same positions,
                        # same programs' partition on reject): accepted
                        # chunks collapse to 1-2 verification passes
                        toks, sc, cache, prev, done, prev_h, _acc = \
                            self._k_decode_chunk(
                                cache, prev, lengths, np.int32(offset), n,
                                eos_id, done,
                                ("reduced" if reduced else True)
                                if ws else False,
                                jnp.asarray(row_ids) if ws and reduced
                                else None,
                                prev_h, valid, "completion")
                    else:
                        toks, sc, cache, prev, done = dmod.decode_steps(
                            self.params, self.cfg, cache, prev, lengths,
                            np.int32(offset), n, eos_id, done,
                            with_scores=("reduced" if reduced else True) if ws else False,
                            target_ids=jnp.asarray(row_ids) if ws and reduced else None,
                        )
                    if ws:
                        scores_dev = sc
                    chunk_toks.append(toks)
                    offset += n
                    if use_k and eos_id is not None and offset < gen_total:
                        # the K path already synced this chunk's accept
                        # data, so the EOS stop is EXACT (no lag chunk):
                        # remaining chunks count into decode_steps_saved
                        # below exactly like the sequential early stop
                        if bool(np.asarray(done).all()):
                            break
                        continue
                    if eos_id is not None and offset < gen_total:
                        # EOS early exit with a ONE-CHUNK LAG: reading chunk
                        # k's `done` flag synchronously would leave the device
                        # idle for a host round-trip before chunk k+1 could
                        # dispatch.  Instead the flag is reduced on device,
                        # its host copy starts immediately, and the LOOP EXIT
                        # decision for chunk k+2 reads chunk k's flag — by
                        # then chunk k+1 is already queued, so the device
                        # pipeline never drains.  Cost: at most one surplus
                        # chunk whose tokens are EOS-frozen (done rows emit
                        # eos_id, _completion_text cuts at the first EOS), so
                        # semantics are unchanged.
                        if lag_flag is not None and bool(np.asarray(lag_flag)):
                            break  # every row emitted EOS — generate stops
                        lag_flag = done.all()
                        try:
                            lag_flag.copy_to_host_async()
                        except AttributeError:
                            pass  # non-jax array backends: plain fetch later
                if dsp is not None:
                    dsp["_sync_obj"] = chunk_toks[-1]
            if eos_id is not None and offset < gen_total:
                # EOS early stop actually saved decode work: the remaining
                # chunks were never launched because every row had emitted
                # EOS.  Static shapes only (no host sync inside the strict
                # guard) — the ISSUE-10 measured number that was always 0
                # under the no-EOS synthetic weights.
                record_counter("decode_steps_saved",
                               (gen_total - offset) * int(valid.sum()))
            tokens_np = np.concatenate(
                [np.asarray(t) for t in chunk_toks], axis=1
            )
            if need_scores:
                sc_steps = (
                    dmod.ReducedScores(*(f[:, :steps] for f in scores_dev))
                    if reduced else scores_dev[:, :steps])
                res = self._scan_results(
                    sc_steps, row_ids[:, 0], row_ids[:, 1],
                    chunk_toks[0][:, :steps], eos_id)
                res_np = {k: np.asarray(v) for k, v in res._asdict().items()}
                if with_confidence:
                    conf_lp, conf_idx = self._conf_topk_np(scores_dev)
        elif need_scores:
            # No completions wanted: scored decode only, and only for the
            # undecided rows — gathered out of the prefill cache so the
            # prompt forward never re-runs.  The gathered rows normally
            # accumulate in the cross-batch pool (one decode per
            # ~pool_target rows); when most of the batch is undecided the
            # gather-copy is pointless and the batch decodes in place,
            # and the confidence leg (which needs per-row score buffers
            # at emission time) always decodes immediately.
            m = _pad_slice(undecided.size, hit0.shape[0])
            if m == hit0.shape[0]:
                sub_cache, last_s, len_s = cache, last, lengths
                real, sub_pos, ids_sub = valid, None, row_ids
            else:
                idx = np.zeros((m,), np.int32)
                idx[: undecided.size] = undecided
                sub_cache, last_s, len_s = _gather_rows(
                    cache, last, lengths, jnp.asarray(idx)
                )
                sub_pos = {int(r): j for j, r in enumerate(undecided)}
                real = np.zeros((m,), bool)
                real[: undecided.size] = True
                ids_sub = row_ids[idx]
            sc, toks_s = self._scan_decode_chunked(
                sub_cache, last_s, len_s, steps, eos_id,
                ids_sub[:, 0], ids_sub[:, 1],
                min_steps=3 if with_confidence else 0,
                real_mask=real,
            )
            res = self._scan_results(sc, ids_sub[:, 0], ids_sub[:, 1],
                                     toks_s, eos_id)
            res_np = {k: np.asarray(v) for k, v in res._asdict().items()}
            if with_confidence:
                conf_lp, conf_idx = self._conf_topk_np(sc)

        for r, orig in enumerate(batch.indices):
            if orig < 0:
                continue
            if hit0[r] and not with_confidence:
                vals = (yes0[r], no0[r], rel0[r], odds0[r], True)
            else:
                j = r if sub_pos is None else sub_pos.get(r)
                vals = (
                    res_np["yes_prob"][j], res_np["no_prob"][j],
                    res_np["relative_prob"][j], res_np["odds_ratio"][j],
                    res_np["found"][j],
                )
            completion = ""
            if decode_completions:
                completion = self._completion_text(tokens_np[r], eos_id)
            row = _attach_first_token(_result_row(*vals, completion),
                                      first3, r)
            if with_confidence:
                k = r if sub_pos is None else sub_pos[r]
                cands = self._candidates_from_topk(conf_lp[k], conf_idx[k])
                row["weighted_confidence"] = weighted_confidence_digits(cands)
            results[int(orig)] = row

    def _score_decoder_prefixed(self, prefix_encoded, suffix_encoded,
                                targets, legs) -> List[List[Dict]]:
        """Decoder-only fused path: batches form over PREFIX token lengths
        (the ordinary length-sorted bucketing); per batch, one prefix
        prefill + one suffix-extension prefill per leg, then each leg
        consumes through the shared scored-batch consumer with its own
        generation plan.  The prefix cache travels inside the pipeline's
        in-flight tuple and its lifetime is audited by
        :class:`PrefixCachePool`."""
        ecfg = self.ecfg
        n = len(prefix_encoded)
        ids_all = self._target_id_rows(prefix_encoded, targets)
        eos_id = getattr(self.tokenizer, "eos_token_id", None)
        results: List[List[Optional[Dict]]] = [[None] * n for _ in legs]
        decode_flags = [
            ecfg.decode_completions if leg.decode_completions is None
            else leg.decode_completions for leg in legs]
        # each leg's plan resolves with the LEG's completion flag, not the
        # engine default — a leg overriding decode_completions=True on an
        # engine configured False must still budget its full decode length
        plans = [self._gen_plan(leg.max_new_tokens, decode_flags[li])
                 for li, leg in enumerate(legs)]
        pad_id = self.tokenizer.pad_token_id or 0
        pool = PrefixCachePool()
        self.last_prefix_pool = pool
        # leg-parameterized cross-batch pools: each eligible confidence
        # leg's scored digit decode moves out of the per-batch consume and
        # into ONE pooled decode per ~target rows (early-exit retirement,
        # per-chunk cache streaming — _Phase2Pool._flush_confidence)
        conf_pools = {
            li: self._make_conf_pool(
                plans[li].scan_steps, eos_id, results[li],
                leg_name=leg.name or "confidence",
                completions=decode_flags[li])
            for li, leg in enumerate(legs)
            if self._conf_pool_eligible(
                leg.with_confidence, plans[li].scan_steps,
                plans[li].total_new_tokens)
        }

        def _suffix_batch(batch, li):
            """[B, suffix_bucket] ids+mask for one leg, aligned with the
            batch's rows; pad rows (index -1) duplicate row 0's suffix,
            mirroring batching._emit_batch's prefix padding."""
            rows = [suffix_encoded[li][int(orig)] if orig >= 0 else None
                    for orig in batch.indices]
            first = next(r for r in rows if r is not None)
            rows = [r if r is not None else first for r in rows]
            sb = batching.suffix_bucket_for(max(len(r) for r in rows))
            ids = np.full((len(rows), sb), pad_id, np.int32)
            mask = np.zeros((len(rows), sb), np.int32)
            for r, src in enumerate(rows):
                ids[r, : len(src)] = src
                mask[r, : len(src)] = 1
            return ids, mask

        def launch(batch):
            entry = None
            try:
                ids = self._put(batch.token_ids)
                mask = self._put(batch.attention_mask)
                last_p, pcache = self._prefill(ids, mask, batch.bucket_len)
                plen = jnp.sum(mask, axis=-1)
                n_real = int((batch.indices >= 0).sum())
                entry = pool.acquire(_cache_nbytes(pcache), n_real)
                row_ids = self._batch_target_rows(ids_all, batch)
                leg_outs = []
                for li in range(len(legs)):
                    with obs.span("extend_prefill", phase="extend_prefill",
                                  leg=legs[li].name or f"leg{li}",
                                  bucket=int(batch.bucket_len)) as sp:
                        sids, smask = _suffix_batch(batch, li)
                        last, cache, lengths = dmod.extend_prefill(
                            self.params, self.cfg, pcache, self._put(sids),
                            self._put(smask), plen)
                        scan0 = yn.first_token_scan(
                            last, row_ids[:, 0], row_ids[:, 1],
                            top_k=ecfg.top_k)
                        first3 = yn.relative_prob_first_token(
                            last, row_ids[:, 0], row_ids[:, 1],
                            ecfg.first_token_top_filter)
                        if sp is not None:
                            sp["_sync_obj"] = last
                    leg_outs.append((last, cache, lengths, scan0, first3))
                    if li:  # every leg past the first rides the warm cache
                        pool.hit(n_real)
                return entry, leg_outs
            except Exception:
                # an OOM here re-buckets THIS batch (runtime/faults.py);
                # the retried sub-batches acquire fresh entries, so the
                # failed attempt's entry must die now — never orphaned,
                # never double-freed
                if entry is not None:
                    pool.release(entry)
                raise

        def consume(batch, out):
            entry, leg_outs = out
            try:
                for li in range(len(legs)):
                    # one d2h_fetch span per LEG so the phases block
                    # separates where the binary vs confidence fetch
                    # time goes; nested decode spans inherit the leg
                    with obs.span("consume_leg", phase="d2h_fetch",
                                  leg=legs[li].name or f"leg{li}",
                                  bucket=int(batch.bucket_len)):
                        if li in conf_pools:
                            self._pool_confidence_batch(
                                conf_pools[li], batch, leg_outs[li],
                                ids_all)
                        else:
                            self._consume_scored_batch(
                                batch, leg_outs[li], ids_all, results[li],
                                legs[li].with_confidence,
                                plans[li].scan_steps,
                                plans[li].total_new_tokens,
                                decode_flags[li], eos_id)
            finally:
                # release exactly once whether the legs consumed clean or
                # an OOM sends the batch back through the re-bucket ladder
                pool.release(entry)

        try:
            self._run_pipelined(
                batching.batches_for_prompts(
                    prefix_encoded, ecfg.batch_size, ecfg.buckets,
                    pad_id=pad_id,
                    length_sorted=ecfg.length_sorted_batches,
                ),
                launch, consume, rebatch=self._oom_rebatch(prefix_encoded),
            )
        finally:
            pool.close()
        for cpool in conf_pools.values():
            cpool.flush_all()
        return [
            [r if r is not None else _error_row("missing") for r in rows]
            for rows in results
        ]

    def warmup(self, prompt_lengths: Optional[Sequence[int]] = None,
               legs: Optional[Sequence[LegSpec]] = None,
               suffix_length=0,
               targets: Sequence[str] = ("Yes", "No"),
               compile_hit_secs: float = 5.0) -> List[Dict]:
        """Explicit bucket-warmup pass: score one synthetic full batch per
        occupied length bucket so every device program the sweep will need
        (prefill, suffix extends, decode chunks, scans) compiles — or
        deserializes from the persistent compilation cache
        (runtime/loader.enable_compile_cache) — BEFORE the timed/real rows
        arrive.  Repeat-0 and preemption-resume runs then start hot
        (BENCH_r05 measured ~150 s of repeat-0 compilation).

        ``prompt_lengths``: representative prompt (or prefix) token
        lengths; each distinct bucket warms once (default: the smallest
        configured bucket).  With ``suffix_length`` truthy the fused
        prefix-reuse programs warm instead, one suffix leg per entry of
        ``legs`` (default: one plain leg); pass a PER-LEG sequence when
        the legs' format suffixes land in different SUFFIX_BUCKETS (an
        int warms only one suffix shape — a leg bucketing smaller would
        still compile inside the timed run).  Each leg's
        ``max_new_tokens`` keys its own generation plan, so warming
        binary + confidence legs registers BOTH plans
        (runtime/plan.GenerationPlan.cache_key).

        Returns one report dict per bucket ({bucket, seconds, cache_hit});
        a bucket whose wall time beat ``compile_hit_secs`` is counted a
        ``compile_cache_hit`` (deserialization takes seconds; sweep-shape
        compiles take minutes on the remote-compile chip), else a
        ``compile_cache_miss``.  The heuristic is for telemetry trend
        lines, not billing: a tiny model compiling fast on CPU also
        counts as a hit."""
        self._check_open()
        ecfg = self.ecfg
        if prompt_lengths:
            buckets = sorted({batching.bucket_for(int(l), ecfg.buckets)
                              for l in prompt_lengths})
        else:
            buckets = [ecfg.buckets[0]]
        legs = list(legs) if legs else [LegSpec()]
        if isinstance(suffix_length, (int, np.integer)):
            suffix_lens = [int(suffix_length)] * len(legs)
        else:
            suffix_lens = [int(s) for s in suffix_length]
            if len(suffix_lens) != len(legs):
                raise ValueError(
                    f"{len(suffix_lens)} suffix lengths for "
                    f"{len(legs)} legs")
        # any real in-vocab token works; scoring output is discarded
        tid = int(self.tokenizer.pad_token_id or 0)
        report = []
        for bucket in buckets:
            prompt = [tid] * int(bucket)
            t0 = time.perf_counter()
            if any(suffix_lens):
                pairs = [(prompt, tuple([tid] * max(1, sl)
                                        for sl in suffix_lens))
                         ] * ecfg.batch_size
                self.score_prefixed(pairs, targets=targets, legs=legs)
            else:
                for leg in legs:
                    self.score_prompts(
                        [prompt] * ecfg.batch_size, targets=targets,
                        with_confidence=leg.with_confidence,
                        max_new_tokens=leg.max_new_tokens)
            dt = time.perf_counter() - t0
            hit = dt < compile_hit_secs
            record_counter("compile_cache_hit" if hit
                           else "compile_cache_miss")
            report.append({"bucket": int(bucket), "seconds": dt,
                           "cache_hit": hit})
        return report

    def _score_decoder_pooled(self, encoded, ids_all, results, eos_id,
                              steps) -> List[Dict]:
        """Two-phase path with the cross-batch pool AND in-program phase-2
        row selection: the prefill program outputs only a
        ``phase2_select_slice``-row cache slice (undecided rows first), a
        ~4x smaller output than the full cache — an HBM win (two pipelined
        batches stay in flight), not a throughput win (the layer scan still
        stacks the full K/V internally; see _prefill_select).  Batches
        where more rows are undecided than the slice holds fall back to a
        full prefill + in-place decode (they were going to decode
        near-full-lane anyway)."""
        ecfg = self.ecfg
        pool = _Phase2Pool(
            self, steps, eos_id,
            target=ecfg.phase2_pool_target or ecfg.batch_size,
            results=results, max_bytes=ecfg.phase2_pool_max_bytes,
        )
        select_m = _pad_slice(
            ecfg.phase2_select_slice or max(8, ecfg.batch_size // 4),
            ecfg.batch_size)

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            row_ids = self._batch_target_rows(ids_all, batch)
            return _prefill_select(
                self.params, self.cfg, ids, mask,
                jnp.asarray(batch.indices >= 0),
                row_ids[:, 0], row_ids[:, 1],
                cache_len=batch.bucket_len, slice_m=select_m,
                top_k=ecfg.top_k, top_filter=ecfg.first_token_top_filter,
                out_len=_pool_len(batch.bucket_len),
            )

        def consume(batch, out):
            scan0, first3, sel, sub_cache, last_s, len_s = out
            yes0, no0, rel0, odds0, hit0 = (np.asarray(a) for a in scan0)
            first3 = tuple(np.asarray(a) for a in first3)
            row_ids = self._batch_target_rows(ids_all, batch)
            valid = batch.indices >= 0
            undecided = np.flatnonzero(~hit0 & valid)
            count = undecided.size
            # the slice actually produced: select_m normally, but an OOM-
            # rebatched sub-batch smaller than select_m yields its own size
            slice_rows = int(sel.shape[0])  # static shape: no device fetch
            if count > slice_rows:
                # Overflow fallback: re-run the prompt forward with the full
                # cache and decode in place.
                ids = self._put(batch.token_ids)
                mask = self._put(batch.attention_mask)
                last_f, cache = self._prefill(ids, mask, batch.bucket_len)
                sc, toks_s = self._scan_decode_chunked(
                    cache, last_f, jnp.sum(mask, axis=-1), steps, eos_id,
                    row_ids[:, 0], row_ids[:, 1], real_mask=valid,
                )
                res = self._scan_results(sc, row_ids[:, 0], row_ids[:, 1],
                                         toks_s, eos_id)
                res_np = {k: np.asarray(v) for k, v in res._asdict().items()}
                for r, orig in enumerate(batch.indices):
                    if orig < 0:
                        continue
                    if hit0[r]:
                        vals = (yes0[r], no0[r], rel0[r], odds0[r], True)
                    else:
                        vals = (res_np["yes_prob"][r], res_np["no_prob"][r],
                                res_np["relative_prob"][r],
                                res_np["odds_ratio"][r], res_np["found"][r])
                    results[int(orig)] = _attach_first_token(
                        _result_row(*vals, ""), first3, r)
                return
            if count:
                # slice rows 0..count-1 ARE the undecided rows (the sort key
                # is False for exactly those rows), though their order
                # within the slice is the sort's business — every per-row
                # association below therefore goes through sel, never
                # through the ascending `undecided` list.  Shrink to the
                # tight menu size before pooling so held bytes stay
                # proportional to real rows.
                sel_np = np.asarray(sel)
                m = _pad_slice(count, slice_rows)
                if m < slice_rows:
                    idx = np.zeros((m,), np.int32)
                    idx[:count] = np.arange(count)
                    sub_cache, last_s, len_s = _gather_rows(
                        sub_cache, last_s, len_s, jnp.asarray(idx))
                    mapped = sel_np[idx]
                else:
                    mapped = sel_np[:slice_rows]
                try:
                    pool.add(_pool_len(batch.bucket_len), sub_cache, last_s,
                             len_s, count,
                             batch.indices[mapped[:count]], row_ids[mapped],
                             first3=np.stack([a[mapped] for a in first3],
                                             axis=1))
                except Exception as err:
                    # a pooled decode holds rows popped from MANY earlier
                    # batches; if it OOMs, re-bucketing the batch that
                    # happened to trigger the flush cannot help and the
                    # popped rows would silently become "missing" error
                    # rows after the retry — bypass the per-batch rebatch
                    err._no_rebatch = True
                    raise
            for r, orig in enumerate(batch.indices):
                if orig >= 0 and hit0[r]:
                    results[int(orig)] = _attach_first_token(_result_row(
                        yes0[r], no0[r], rel0[r], odds0[r], True, ""),
                        first3, r)

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, ecfg.batch_size, ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
                length_sorted=ecfg.length_sorted_batches,
            ),
            launch, consume, rebatch=self._oom_rebatch(encoded),
        )
        pool.flush_all()
        return [r if r is not None else _error_row("missing") for r in results]

    def _conf_pool_eligible(self, with_confidence, steps, gen_total) -> bool:
        """The confidence leg routes through the leg-parameterized
        cross-batch pool when (a) pooling is on for the leg, (b) the leg's
        completion cap fits inside the scored scan — the 10-token
        confidence contract: the scored decode's greedy tokens ARE the
        completion, so one pooled decode serves scores and text — and
        (c) the scan top-k reads from ReducedScores' kept candidates (the
        pooled decode stacks reduced statistics only).  Anything else
        keeps the r5 per-batch decode."""
        ecfg = self.ecfg
        return (with_confidence and ecfg.phase2_pool
                and ecfg.pooled_confidence and gen_total <= steps
                and ecfg.top_k <= dmod.REDUCED_TOPK)

    def _make_conf_pool(self, steps, eos_id, results, leg_name="confidence",
                        completions=None):
        ecfg = self.ecfg
        return _Phase2Pool(
            self, steps, eos_id,
            target=ecfg.phase2_pool_target or ecfg.batch_size,
            results=results, max_bytes=ecfg.phase2_pool_max_bytes,
            leg=leg_name, confidence=True,
            completions=(ecfg.decode_completions if completions is None
                         else completions),
        )

    @staticmethod
    def _pool_add_batch(pool, plen, sub_cache, last_s, len_s, count,
                        orig_idx, row_ids, first3_cols, sel):
        """Queue one batch's confidence rows (mapped through ``sel``, the
        slice-row -> batch-row index) on ``pool``, marking any failure
        ``_no_rebatch``: a pooled decode holds rows popped from MANY
        earlier batches, so the per-batch OOM re-bucket cannot shrink it
        and retrying would silently lose the popped rows (see
        _score_decoder_pooled's consume)."""
        try:
            pool.add(plen, sub_cache, last_s, len_s, count,
                     orig_idx[sel[:count]], row_ids[sel],
                     first3=np.stack([a[sel] for a in first3_cols], axis=1))
        except Exception as err:
            err._no_rebatch = True
            raise

    def _score_decoder_conf_pooled(self, encoded, ids_all, results, eos_id,
                                   steps) -> List[Dict]:
        """Confidence-leg scoring through the cross-batch pool: every
        valid row needs the scored digit decode, so the prefill program
        selects ALL rows (``_prefill_select`` with ``select_all`` — the
        same in-program slice machinery, minus the undecided filter, so
        the full cache still never materializes as a program output at
        more than the menu-padded slice) and each batch's rows accumulate
        in a ``leg="confidence"`` pool; ONE pooled digit decode runs per
        ``target`` rows with early-exit row retirement
        (:meth:`_Phase2Pool._flush_confidence`)."""
        ecfg = self.ecfg
        pool = self._make_conf_pool(steps, eos_id, results)

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            row_ids = self._batch_target_rows(ids_all, batch)
            return _prefill_select(
                self.params, self.cfg, ids, mask,
                jnp.asarray(batch.indices >= 0),
                row_ids[:, 0], row_ids[:, 1],
                cache_len=batch.bucket_len,
                slice_m=int(batch.token_ids.shape[0]),
                top_k=ecfg.top_k, top_filter=ecfg.first_token_top_filter,
                out_len=_conf_pool_len(batch.bucket_len), select_all=True,
            )

        def consume(batch, out):
            scan0, first3, sel, sub_cache, last_s, len_s = out
            first3 = tuple(np.asarray(a) for a in first3)
            row_ids = self._batch_target_rows(ids_all, batch)
            count = int((batch.indices >= 0).sum())
            if not count:
                return
            sel_np = np.asarray(sel)
            # valid rows sort first under select_all (decided := padding);
            # shrink partial batches to the tight menu size before pooling
            m = _pad_slice(count, int(sel_np.shape[0]))
            if m < sel_np.shape[0]:
                idx = np.zeros((m,), np.int32)
                idx[:count] = np.arange(count)
                sub_cache, last_s, len_s = _gather_rows(
                    sub_cache, last_s, len_s, jnp.asarray(idx))
                mapped = sel_np[idx]
            else:
                mapped = sel_np
            self._pool_add_batch(
                pool, _conf_pool_len(batch.bucket_len), sub_cache, last_s,
                len_s, count, batch.indices, row_ids, first3, mapped)

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, ecfg.batch_size, ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
                length_sorted=ecfg.length_sorted_batches,
            ),
            launch, consume, rebatch=self._oom_rebatch(encoded),
        )
        pool.flush_all()
        return [r if r is not None else _error_row("missing") for r in results]

    def _pool_confidence_batch(self, pool, batch, out, ids_all):
        """Fused-path confidence leg -> pool: gather the batch's valid
        rows out of the suffix-extended cache, pad the slot axis to the
        pool's quantized cache length, and queue them — the per-batch
        decode the r5 consume ran here moves into the pooled flush."""
        last, cache, lengths, scan0, first3 = out
        first3 = tuple(np.asarray(a) for a in first3)
        row_ids = self._batch_target_rows(ids_all, batch)
        valid = batch.indices >= 0
        count = int(valid.sum())
        if not count:
            return
        m = _pad_slice(count, int(last.shape[0]))
        idx = np.zeros((m,), np.int32)
        idx[: count] = np.flatnonzero(valid)
        sub_cache, last_s, len_s = _gather_rows(
            cache, last, lengths, jnp.asarray(idx))
        cache_len = int(sub_cache.k.shape[2])
        plen = _conf_pool_len(cache_len)
        if plen > cache_len:
            sub_cache = _pad_cache_slots(sub_cache, plen)
        self._pool_add_batch(pool, plen, sub_cache, last_s, len_s, count,
                             batch.indices, row_ids, first3, idx)

    def _scan_results(self, sc, yes_ids, no_ids, toks, eos_id):
        """Yes/no scan over a chunked decode's scores — full [m, P, V]
        tensor or ReducedScores, whichever the decode produced."""
        vsteps = yn.steps_until_eos(toks, eos_id)
        if isinstance(sc, dmod.ReducedScores):
            return yn.yes_no_from_reduced(
                sc.topk_vals, sc.logz, sc.target_logits,
                max_look_ahead=self.ecfg.max_look_ahead,
                top_k=self.ecfg.top_k, valid_steps=vsteps)
        return yn.yes_no_from_scores(
            sc, yes_ids, no_ids, max_look_ahead=self.ecfg.max_look_ahead,
            top_k=self.ecfg.top_k, valid_steps=vsteps)

    def _conf_topk_np(self, sc):
        """[m, 3, 19] (logprobs, ids) for the confidence leg, as numpy."""
        if isinstance(sc, dmod.ReducedScores):
            return (np.asarray(sc.topk_vals[:, :3] - sc.logz[:, :3, None]),
                    np.asarray(sc.topk_ids[:, :3]))
        return tuple(np.asarray(a) for a in _confidence_topk(sc))

    def _scan_decode_chunked(self, sub_cache, last_s, len_s, steps, eos_id,
                             yes_id, no_id, min_steps: int = 0,
                             real_mask: Optional[np.ndarray] = None):
        """Scored look-ahead decode in ``scan_chunk``-step chunks with early
        exit: once every row has either a top-k hit or an EOS-terminated
        score list, later positions can never be read by the reference's scan
        (it stops at the first hit, run_base_vs_instruct_100q.py:349-358), so
        decoding them is pure waste.  In real sweeps undecided rows usually
        hit at positions 1-3, so the 10-step tail is rarely decoded.

        ``real_mask`` ([m] bool): rows outside the mask are padding
        (duplicates of other rows, or blank pool filler) and must not hold
        the exit open.  Returns (scores, tokens [m, P]) with P <= steps;
        ``scores`` is ReducedScores (the default — the [m, P, V] fp32
        tensor never materializes) or the full tensor when ``top_k``
        exceeds the kept candidates."""
        ecfg = self.ecfg
        chunk = max(1, ecfg.scan_chunk)
        reduced = ecfg.top_k <= dmod.REDUCED_TOPK
        target_ids = None
        if reduced:
            m = int(last_s.shape[0])
            target_ids = jnp.stack(
                [jnp.broadcast_to(jnp.asarray(yes_id), (m,)),
                 jnp.broadcast_to(jnp.asarray(no_id), (m,))], axis=1
            ).astype(jnp.int32)

        def cat(parts):
            return _cat_scores(parts, reduced)

        with obs.span("scan_decode", phase="decode", steps=int(steps),
                      rows=int(last_s.shape[0])):
            return self._scan_decode_loop(
                sub_cache, last_s, len_s, steps, eos_id, min_steps,
                real_mask, chunk, reduced, target_ids, cat, yes_id, no_id)

    def _scan_decode_loop(self, sub_cache, last_s, len_s, steps, eos_id,
                          min_steps, real_mask, chunk, reduced, target_ids,
                          cat, yes_id, no_id):
        """Body of :meth:`_scan_decode_chunked` (split so the decode span
        wraps the whole chunked loop without re-indenting it)."""
        ecfg = self.ecfg
        sc_parts, tok_parts = [], []
        cur_cache, prev, done = sub_cache, last_s, None
        offset = 0
        while offset < steps:
            n = min(chunk, steps - offset)
            toks_c, sc_c, cur_cache, prev, done = dmod.decode_steps(
                self.params, self.cfg, cur_cache, prev, len_s,
                np.int32(offset), n, eos_id, done,
                with_scores="reduced" if reduced else True,
                target_ids=target_ids,
            )
            sc_parts.append(sc_c)
            tok_parts.append(toks_c)
            offset += n
            if offset >= steps:
                break
            toks_sofar = jnp.concatenate(tok_parts, axis=1)
            vsteps = yn.steps_until_eos(toks_sofar, eos_id)
            if reduced:
                sofar = cat(sc_parts)
                part = yn.yes_no_from_reduced(
                    sofar.topk_vals, sofar.logz, sofar.target_logits,
                    max_look_ahead=offset, top_k=ecfg.top_k,
                    valid_steps=vsteps,
                )
            else:
                part = yn.yes_no_from_scores(
                    jnp.concatenate(sc_parts, axis=1), yes_id, no_id,
                    max_look_ahead=offset, top_k=ecfg.top_k,
                    valid_steps=vsteps,
                )
            # resolved = scan hit so far, or EOS actually emitted (the `done`
            # mask from decode_steps) — no later position can change the row
            resolved = np.asarray(part.found) | np.asarray(done)
            if real_mask is not None:
                resolved = resolved[real_mask]
            if offset >= min_steps and bool(resolved.all()):
                break
        return cat(sc_parts), jnp.concatenate(tok_parts, axis=1)

    # -- joint next-K-token decode (verify-and-accept) --------------------

    def _k_enabled(self) -> bool:
        """decode_k asks for the K path (decoder-only; T5 re-reads its
        prompt per step — there is no frontier cache to verify against)."""
        return int(self.ecfg.decode_k) > 1 and not self.is_encoder_decoder

    def _k_active(self) -> bool:
        """The K path engages: ``decode_k > 1`` AND a K-head is resident.
        A missing head is noted once (counter + stderr) and the decode
        legs run the unchanged sequential loop — never an error."""
        if not self._k_enabled():
            return False
        if self.k_head is None:
            if not self._k_head_missing_noted:
                self._k_head_missing_noted = True
                record_counter("k_decode_head_missing")
                print(f"# engine: decode_k={self.ecfg.decode_k} configured "
                      f"but no K-head is set (distill_k_head_on); decode "
                      f"legs run sequentially", file=sys.stderr)
            return False
        return True

    def distill_k_head_on(self, prompts, max_rows: int = 32,
                          gen_steps: Optional[int] = None):
        """Distill this engine's K-head on sample prompts (greedy
        self-distillation — models/decoder.distill_k_head): the head
        learns the model's OWN continuations, which is exactly the
        distribution the decode legs replay.  Callers re-distill after
        swapping ``engine.params`` (bench calibration, the EOS-typical
        bracket) — proposals from a stale head still verify safely, they
        just reject.  No-op (returns None) when ``decode_k <= 1``."""
        self._check_open()
        if not self._k_enabled():
            return None
        with obs.span("distill_k_head", phase="host_prep",
                      rows=min(len(prompts), max_rows)):
            encoded = batching.encode_prompts(self.tokenizer,
                                              list(prompts)[:max_rows])
            pad_id = self.tokenizer.pad_token_id or 0
            width = max(len(e) for e in encoded)
            ids = np.full((len(encoded), width), pad_id, np.int32)
            mask = np.zeros((len(encoded), width), np.int32)
            for r, e in enumerate(encoded):
                ids[r, : len(e)] = e
                mask[r, : len(e)] = 1
            self.k_head = dmod.distill_k_head(
                self.params, self.cfg, ids, mask,
                k=int(self.ecfg.decode_k),
                eos_token_id=getattr(self.tokenizer, "eos_token_id", None),
                gen_steps=gen_steps)
        record_counter("k_head_distilled")
        return self.k_head

    def _k_propose(self, hidden, prev_logits, kb, done, eos_id):
        """Proposal source for one verification pass — a method (not a
        direct ``dmod.k_propose`` call) so tests can inject oracle or
        adversarial proposals; the verify pass re-derives the true chain
        either way, so a bad injection costs a rejection, never a wrong
        row.  ``hidden=None`` (no frontier hidden yet — the chunk's
        bootstrap block) proposes only the free, exact argmax."""
        if hidden is None or kb <= 1:
            props = jnp.argmax(prev_logits, axis=-1).astype(jnp.int32)[:, None]
            if eos_id is not None and done is not None:
                props = jnp.where(done[:, None], eos_id, props)
            return props
        return dmod.k_propose(self.k_head, hidden, prev_logits, kb, done,
                              eos_id)

    def _k_decode_chunk(self, cache, prev, lens, offset, n, eos_id, done,
                        with_scores, target_ids, prev_h, real_mask, leg):
        """One reference chunk — one ``decode_steps`` call's worth of
        positions — through the K-token verify-and-accept path.

        The chunk's ``n``-slot tail buffer is shared by every proposal
        block and folds into the cache ONLY at chunk end, so fold
        boundaries (and the int8 quantization points) match the
        sequential path's exactly — the partition-sensitivity the
        two-block softmax has at 1 ulp makes this the load-bearing
        parity rule: fold-point drift would compound chunk over chunk,
        while the remaining multi-query reduction-order noise stays
        bounded at the last ulp (PARITY.md "K-decode").  Per block:
        propose up to
        ``decode_k`` tokens (``_k_propose``; the chunk's first block
        bootstraps at size 1 when no frontier hidden exists yet), run
        ONE joint ``k_verify_block`` pass, and accept iff every REAL row
        (``real_mask``; gather padding and pool blanks are per-row inert
        and must not veto) matched the whole block.  Any rejection
        discards the pass and re-runs the WHOLE chunk through the
        unchanged ``dmod.decode_steps`` — so every emitted bit, on
        either path, is the sequential path's.

        Telemetry (SPECULATIVE passes only — kb=1 bootstrap/remainder
        blocks propose the free exact argmax and can never reject, so
        they are excluded or they would dilute the very numbers the
        accept-prior recalibration reads): ``k_blocks_proposed``/
        ``k_blocks_rejected`` (reject rate), the ``accepted_k``
        histogram (batch-min accepted length per pass), and
        ``k_steps_saved`` (+ a ``|leg=`` labeled twin) — sequential
        steps the K path covered beyond one program per block, recorded
        only when the WHOLE chunk completed on the K path (a late
        reject erases earlier blocks' savings).  Host reads here are
        fine under strict mode: both
        decode legs run inside the pipeline's sanctioned consume fetch
        (or after it, in ``flush_all``).

        Returns ``(toks, scores, cache, prev_logits, done, prev_hidden,
        accepted)`` — the ``decode_steps`` contract plus the frontier
        hidden for the next chunk's proposals (None after a fallback)."""
        ecfg = self.ecfg
        b = int(prev.shape[0])
        n_real = int(real_mask.sum()) if real_mask is not None else b
        quantized = cache.k_scale is not None
        cdt = (self.params["embed"]["tokens"].dtype if quantized
               else cache.k.dtype)
        tail_shape = (self.cfg.num_layers, b, n, self.cfg.num_kv_heads,
                      self.cfg.head_dim)
        tail_k = jnp.zeros(tail_shape, cdt)
        tail_v = jnp.zeros(tail_shape, cdt)
        cache0, prev0, done0 = cache, prev, done
        kmax = max(1, min(int(ecfg.decode_k),
                          1 + dmod.k_head_num_heads(self.k_head)))
        toks_parts, sc_parts = [], []
        j, cur_done, hid = 0, done, prev_h
        saved_steps = 0   # recorded only if the WHOLE chunk stays on the
        #                   K path — a later block's reject re-runs the
        #                   chunk sequentially and erases every earlier
        #                   block's saving, so per-block recording would
        #                   report savings on runs that did MORE work
        out = None
        while j < n:
            kb = 1 if hid is None else max(1, min(kmax, n - j))
            props = self._k_propose(hid, prev, kb, cur_done, eos_id)
            out = dmod.k_verify_block(
                self.params, self.cfg, cache, tail_k, tail_v, prev, lens,
                offset, jnp.int32(j), props, eos_id, cur_done, target_ids,
                with_scores=with_scores, fold=(j + kb >= n))
            a_len = np.asarray(out.a_len)
            acc = np.asarray(out.accepted)
            if real_mask is not None and n_real:
                a_min = int(a_len[real_mask].min())
                ok = bool(acc[real_mask].all())
            else:
                a_min = int(a_len.min()) if n_real else kb
                ok = bool(acc.all()) if n_real else True
            if kb > 1:
                # telemetry counts SPECULATIVE passes only: a kb=1 pass
                # (chunk bootstrap, kmax-remainder tail) proposes the
                # free exact argmax and can never reject, so counting it
                # would dilute k_reject_rate and drag accepted_k_mean
                # toward 1 — the two numbers the accept-prior
                # recalibration reads from the first driver record
                record_counter("k_blocks_proposed")
                record_hist("accepted_k", a_min)
            if not ok:
                # verify-and-accept REJECT: the pass's outputs are
                # discarded wholesale and the chunk re-runs through the
                # unchanged sequential loop from the chunk-entry state —
                # the fallback leg of the parity contract
                record_counter("k_blocks_rejected")
                toks, sc, cache, prev, cur_done = dmod.decode_steps(
                    self.params, self.cfg, cache0, prev0, lens, offset, n,
                    eos_id, done0, with_scores=with_scores,
                    target_ids=target_ids)
                return toks, sc, cache, prev, cur_done, None, False
            saved_steps += (kb - 1) * n_real
            toks_parts.append(out.tokens)
            if out.scores is not None:
                sc_parts.append(out.scores)
            prev, cur_done, hid = out.last_logits, out.done, out.last_hidden
            tail_k, tail_v = out.tail_k, out.tail_v
            j += kb
        if saved_steps:
            record_counter("k_steps_saved", saved_steps)
            record_counter(f"k_steps_saved|leg={leg}", saved_steps)
        cache = out.cache                 # folded by the chunk's last block
        toks = (toks_parts[0] if len(toks_parts) == 1
                else jnp.concatenate(toks_parts, axis=1))
        if not sc_parts:
            sc = None
        elif len(sc_parts) == 1:
            sc = sc_parts[0]
        else:
            sc = _cat_scores(sc_parts, with_scores == "reduced")
        return toks, sc, cache, prev, cur_done, hid, True

    def _score_encdec(self, prompts, targets, with_confidence,
                  max_new_tokens=None) -> List[Dict]:
        """T5 path: one scanned decode per batch (the decoder re-runs its
        short prefix each step — models/t5.py greedy_decode), generating
        ``max_new_tokens`` when completions are recorded and scanning only
        the first MAX_LOOK_AHEAD positions, like the reference's
        encoder-decoder branch (run_base_vs_instruct_100q.py:291-326)."""
        ecfg = self.ecfg
        ids_all = self._target_id_rows(prompts, targets)
        eos_id = getattr(self.tokenizer, "eos_token_id", None)
        with obs.span("encode_prompts", phase="host_tokenize",
                      prompts=len(prompts)):
            encoded = batching.encode_prompts(self.tokenizer, prompts)
        results: List[Optional[Dict]] = [None] * len(prompts)
        steps, gen_total = self._gen_plan(max_new_tokens)

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            tokens, scores = t5mod.greedy_decode(
                self.params, self.cfg, ids, mask, num_steps=gen_total,
                eos_token_id=eos_id, score_steps=steps,
            )
            row_ids = self._batch_target_rows(ids_all, batch)
            res = yn.yes_no_from_scores(
                scores, row_ids[:, 0], row_ids[:, 1],
                max_look_ahead=ecfg.max_look_ahead, top_k=ecfg.top_k,
                valid_steps=yn.steps_until_eos(tokens[:, :steps], eos_id),
            )
            first3 = yn.relative_prob_first_token(
                scores[:, 0, :], row_ids[:, 0], row_ids[:, 1],
                ecfg.first_token_top_filter)
            # The confidence leg needs only 3x19 candidates per row: reduce
            # on device (_confidence_topk) instead of pinning + fetching the
            # [B, steps, V] scores buffer (~250 MB/batch at sweep sizes).
            conf = _confidence_topk(scores) if with_confidence else None
            return tokens, conf, res, first3

        def consume(batch, out):
            tokens, conf, res, first3 = out
            first3 = tuple(np.asarray(a) for a in first3)
            tokens_np = np.asarray(tokens)
            if with_confidence:
                conf_lp, conf_idx = (np.asarray(a) for a in conf)
            yes_np = np.asarray(res.yes_prob)
            no_np = np.asarray(res.no_prob)
            rel_np = np.asarray(res.relative_prob)
            odds_np = np.asarray(res.odds_ratio)
            found_np = np.asarray(res.found)
            for r, orig in enumerate(batch.indices):
                if orig < 0:
                    continue
                completion = ""
                if ecfg.decode_completions:
                    completion = self._completion_text(tokens_np[r], eos_id)
                row = _attach_first_token(
                    _result_row(yes_np[r], no_np[r], rel_np[r],
                                odds_np[r], found_np[r], completion),
                    first3, r)
                if with_confidence:
                    cands = self._candidates_from_topk(conf_lp[r], conf_idx[r])
                    row["weighted_confidence"] = weighted_confidence_digits(cands)
                results[int(orig)] = row

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, ecfg.batch_size, ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
                length_sorted=ecfg.length_sorted_batches,
            ),
            launch, consume, rebatch=self._oom_rebatch(encoded),
        )
        return [r if r is not None else _error_row("missing") for r in results]

    def score_packed(
        self,
        packs: Sequence,
        targets: Sequence,
        top_filter: Optional[int] = None,
    ) -> List[Dict]:
        """Packed multi-question scoring (scoring/packed.py — Auto-Demo
        batch prompting, arxiv 2410.01724): each pack is a list of
        ``(prompt, demo_continuation)`` segments that concatenate into ONE
        row; the row prefills once and the yes/no relative probability of
        every question reads from the logits gathered at its answer anchor
        (the last token of its prompt segment) inside the prefill program
        (models/decoder.forward_anchor_logits) — no decode path at all.

        ``targets``: one (yes, no) pair, or one pair PER QUESTION in
        pack-major order.  Returns one result row per question (pack-major)
        with the ``get_yes_no_logprobs`` fields; ``completion`` is always
        empty (nothing decodes), ``scan_found`` is the anchor's top-k
        membership, and the ``first_token_*`` fields carry the
        ``top_filter``-filtered view (default: the engine's API top-20
        contract) — the fields the drift-parity leg compares against
        isolated scoring.  Packed mode is MEASURED-DRIFT (PARITY.md):
        question 0 of each pack is bit-identical to isolated scoring,
        later questions legitimately move with their packed context."""
        from ..scoring import packed as packed_mod

        self._check_open()
        if self.is_encoder_decoder:
            raise ValueError(
                "packed anchor scoring is decoder-only (T5 re-reads the "
                "full prompt per decoder step; there is no single prefill "
                "to gather anchors from)")
        ecfg = self.ecfg
        with obs.span("encode_packed", phase="host_tokenize",
                      rows=len(packs)):
            encoded, anchors = packed_mod.encode_packs(self.tokenizer, packs)
        n_questions = sum(len(a) for a in anchors)
        ids_all = self._target_id_rows(list(range(n_questions)), targets)
        kmax = max(len(a) for a in anchors)
        # [N, kmax] anchor offsets + per-slot flat question index; padded
        # slots duplicate anchor 0 (inert — consume skips them) so the
        # device gather stays rectangular
        anchor_arr = np.zeros((len(packs), kmax), np.int32)
        qindex = np.zeros((len(packs), kmax), np.int64)
        qvalid = np.zeros((len(packs), kmax), bool)
        qi = 0
        for i, offs in enumerate(anchors):
            for k, off in enumerate(offs):
                anchor_arr[i, k] = off
                qindex[i, k] = qi
                qvalid[i, k] = True
                qi += 1
            anchor_arr[i, len(offs):] = offs[0]
            qindex[i, len(offs):] = qindex[i, 0]
        results: List[Optional[Dict]] = [None] * n_questions
        tf = ecfg.first_token_top_filter if top_filter is None else top_filter

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            first = int(batch.indices[0])
            idx = np.where(batch.indices >= 0, batch.indices, first)
            banchors = anchor_arr[idx]                         # [B, kmax]
            tids = ids_all[qindex[idx]]                        # [B, kmax, 2]
            yes_f = jnp.asarray(tids[..., 0].reshape(-1))
            no_f = jnp.asarray(tids[..., 1].reshape(-1))
            with obs.span("packed_prefill", phase="prefill",
                          bucket=int(batch.bucket_len),
                          batch=int(batch.token_ids.shape[0]),
                          questions=int(kmax)) as sp:
                logits = dmod.forward_anchor_logits(
                    self.params, self.cfg, ids, mask, jnp.asarray(banchors))
                flat = logits.reshape((-1, logits.shape[-1]))  # [B*K, V]
                scan0 = yn.first_token_scan(flat, yes_f, no_f,
                                            top_k=ecfg.top_k)
                first3 = yn.relative_prob_first_token(flat, yes_f, no_f, tf)
                if sp is not None:
                    sp["_sync_obj"] = first3[2]
            return scan0, first3

        def consume(batch, out):
            scan0, first3 = out
            yes0, no0, rel0, odds0, hit0 = (np.asarray(a) for a in scan0)
            first3 = tuple(np.asarray(a) for a in first3)
            for r, orig in enumerate(batch.indices):
                if orig < 0:
                    continue
                for k in range(kmax):
                    if not qvalid[int(orig), k]:
                        continue
                    f = r * kmax + k
                    results[int(qindex[int(orig), k])] = _attach_first_token(
                        _result_row(yes0[f], no0[f], rel0[f], odds0[f],
                                    bool(hit0[f]), ""),
                        first3, f)

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, ecfg.batch_size, ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
                length_sorted=ecfg.length_sorted_batches,
            ),
            launch, consume, rebatch=self._oom_rebatch(encoded),
        )
        record_counter("packed_rows", len(packs))
        record_counter("packed_questions", n_questions)
        return [r if r is not None else _error_row("missing")
                for r in results]

    # -- decode-then-repack consumers (runtime/slots.py) ------------------

    def record_occupancy(self, stats) -> None:
        """Collect one ring's :class:`~.slots.OccupancyStats` (pools and
        slotted sessions call this as they finish)."""
        if stats is not None and (stats.capacity_steps or stats.rows):
            self._occupancy.append(stats)

    def occupancy_report(self, clear: bool = True):
        """Merged slot-occupancy block for everything scored since the
        last drain (None when no ring ran) — the bench record's
        ``occupancy`` block."""
        merged = slots_mod.merge_occupancy(self._occupancy)
        if clear:
            self._occupancy = []
        return slots_mod.occupancy_block(merged)

    def score_prompts_slotted(
        self,
        prompts: Sequence,
        targets: Sequence = ("Yes", "No"),
        admit_fn: Optional[Callable] = None,
    ) -> List[Dict]:
        """Binary scored decoding through the slot allocator with
        MID-DECODE admission — the serve scheduler's slot-level
        continuous-batching entry (ROADMAP item 3's serve consumer).

        Prompts prefill in ordinary batches; rows whose position-0 scan
        already hit resolve immediately, undecided rows feed the slot
        ring.  Between decode chunks the ring's starvation hook calls
        ``admit_fn()``, which may return ``(prompts, target_pairs)`` of
        NEWLY-ARRIVED work — those rows prefill and drop into vacated
        slots while earlier rows keep decoding, instead of waiting for
        the next coalescer boundary.  Results return in feed order
        (initial prompts first, admitted rows appended).

        The scored contract matches ``score_prompts`` with
        ``decode_completions=False`` / ``with_confidence=False`` (the
        pooled binary path): tokens/verdicts identical, probability
        fields within the chunked-prefill fp32 class vs the whole-flush
        schedule (PARITY.md "Decode-then-repack")."""
        self._check_open()
        if self.is_encoder_decoder:
            raise ValueError("slotted scoring is decoder-only (T5 has no "
                             "decoder-side prompt cache to refill)")
        ecfg = self.ecfg
        eos_id = getattr(self.tokenizer, "eos_token_id", None)
        steps, _ = self._gen_plan(None, False)
        results: List[Optional[Dict]] = []

        def emit(rows):
            self._emit_scored_slot_rows(rows, steps, eos_id, results)

        ring = slots_mod.SlotRing(
            self, steps=steps, eos_id=eos_id,
            capacity=ecfg.phase2_pool_target or ecfg.batch_size,
            leg="binary", workload="serve",
            retire=_binary_retire, emit=emit,
            batch_review=self._binary_batch_review(steps, eos_id),
            pad_slice=lambda n: _pad_slice(n, max(n, 1)),
        )

        def feed(batch_prompts, batch_targets):
            base = len(results)
            results.extend([None] * len(batch_prompts))
            ids_all = self._target_id_rows(batch_prompts, batch_targets)
            with obs.span("encode_prompts", phase="host_tokenize",
                          prompts=len(batch_prompts)):
                encoded = batching.encode_prompts(self.tokenizer,
                                                  batch_prompts)
            for batch in batching.batches_for_prompts(
                    encoded, ecfg.batch_size, ecfg.buckets,
                    pad_id=self.tokenizer.pad_token_id or 0,
                    length_sorted=ecfg.length_sorted_batches):
                out = _prefill_select(
                    self.params, self.cfg, self._put(batch.token_ids),
                    self._put(batch.attention_mask),
                    jnp.asarray(batch.indices >= 0),
                    self._batch_target_rows(ids_all, batch)[:, 0],
                    self._batch_target_rows(ids_all, batch)[:, 1],
                    cache_len=batch.bucket_len,
                    slice_m=int(batch.token_ids.shape[0]),
                    top_k=ecfg.top_k,
                    top_filter=ecfg.first_token_top_filter,
                    out_len=_pool_len(batch.bucket_len),
                )
                scan0, first3, sel, sub_cache, last_s, len_s = out
                yes0, no0, rel0, odds0, hit0 = (np.asarray(a)
                                                for a in scan0)
                first3 = tuple(np.asarray(a) for a in first3)
                row_ids = self._batch_target_rows(ids_all, batch)
                valid = batch.indices >= 0
                undecided = np.flatnonzero(~hit0 & valid)
                sel_np = np.asarray(sel)
                for r, orig in enumerate(batch.indices):
                    if orig >= 0 and hit0[r]:
                        results[base + int(orig)] = _attach_first_token(
                            _result_row(yes0[r], no0[r], rel0[r],
                                        odds0[r], True, ""), first3, r)
                if undecided.size:
                    count = undecided.size
                    idx = jnp.asarray(np.arange(count, dtype=np.int32))
                    sub, last_u, len_u = slots_mod._gather_ring_rows(
                        sub_cache, idx), last_s[idx], len_s[idx]
                    mapped = sel_np[:count]
                    metas = [
                        {"orig": base + int(batch.indices[m]),
                         "first3": np.asarray([first3[0][m], first3[1][m],
                                               first3[2][m]])}
                        for m in mapped]
                    ring.feed(sub, last_u, len_u, row_ids[mapped], metas)

        def refill_hook(n_free):
            # NOTE: admit_fn owns the admission BOUND (the scheduler caps
            # at one extra micro-batch per launch) — a hook that never
            # returns empty would keep this session alive indefinitely
            if admit_fn is None:
                return False
            more = admit_fn()
            if not more:
                return False
            more_prompts, more_targets = more
            if not more_prompts:
                return False
            feed(more_prompts, more_targets)
            slots_mod.slot_counter("slot_admitted", len(more_prompts),
                                   "binary", "serve")
            return True

        ring.refill_hook = refill_hook
        with strict.scoring_guard(type(self).__name__):
            with strict.sanctioned_fetch():
                feed(list(prompts), targets)
                ring.drain()
                # one more admission window after the drain so work that
                # arrived during the last chunk is not orphaned
                while admit_fn is not None and refill_hook(0):
                    ring.drain()
        self.record_occupancy(ring.stats)
        return [r if r is not None else _error_row("missing")
                for r in results]

    def _binary_batch_review(self, steps, eos_id):
        """Vectorized found-scan hook for binary slot rows: one yes/no
        reduction per chunk over the live rows' accumulated statistics —
        the per-row ``retire`` then just reads the cached verdict."""
        ecfg = self.ecfg

        def review(rows):
            vals = np.stack([r.vals for r in rows])
            logz = np.stack([r.logz for r in rows])
            tgt = np.stack([r.tgt for r in rows])
            toks = np.stack([r.toks for r in rows])
            vsteps = np.asarray([r.decoded for r in rows], np.int32)
            if eos_id is not None:
                for i, r in enumerate(rows):
                    hits = np.flatnonzero(toks[i, : r.decoded] == eos_id)
                    if hits.size:
                        vsteps[i] = min(vsteps[i], int(hits[0]) + 1)
            res = yn.yes_no_from_reduced(
                jnp.asarray(vals), jnp.asarray(logz), jnp.asarray(tgt),
                max_look_ahead=ecfg.max_look_ahead, top_k=ecfg.top_k,
                valid_steps=jnp.asarray(vsteps))
            found = np.asarray(res.found)
            for i, r in enumerate(rows):
                done = (eos_id is not None
                        and bool((toks[i, : r.decoded] == eos_id).any()))
                r.meta["resolved"] = bool(found[i]) or done

        return review

    def _emit_scored_slot_rows(self, rows, steps, eos_id, results):
        """Finish binary slot rows: one batched yes/no scan over their
        decoded statistics (valid steps cut at EOS), then the ordinary
        result-row assembly keyed by the meta's original index."""
        ecfg = self.ecfg
        vals = np.stack([r.vals for r in rows])
        logz = np.stack([r.logz for r in rows])
        tgt = np.stack([r.tgt for r in rows])
        vsteps = np.asarray([max(1, r.decoded) for r in rows], np.int32)
        if eos_id is not None:
            for i, r in enumerate(rows):
                hits = np.flatnonzero(r.toks[: r.decoded] == eos_id)
                if hits.size:
                    vsteps[i] = min(vsteps[i], int(hits[0]) + 1)
        res = yn.yes_no_from_reduced(
            jnp.asarray(vals), jnp.asarray(logz), jnp.asarray(tgt),
            max_look_ahead=ecfg.max_look_ahead, top_k=ecfg.top_k,
            valid_steps=jnp.asarray(vsteps))
        res_np = {k: np.asarray(v) for k, v in res._asdict().items()}
        for i, r in enumerate(rows):
            f3 = r.meta["first3"]
            row = _attach_first_token(
                _result_row(res_np["yes_prob"][i], res_np["no_prob"][i],
                            res_np["relative_prob"][i],
                            res_np["odds_ratio"][i],
                            res_np["found"][i], ""),
                (f3[0:1], f3[1:2], f3[2:3]), 0)
            results[int(r.meta["orig"])] = row

    def export_kv_slab(
        self,
        prompts: Sequence,
        targets: Sequence = ("Yes", "No"),
    ):
        """Prefill-specialist half of the cross-replica KV handoff
        (ROADMAP item 1b).  Runs the same prefill + position-0 scan as
        :meth:`score_prompts_slotted`, but instead of decoding the
        undecided rows HERE, it gathers them and materializes one host
        :class:`~.slots.KVSlab` per prefill batch for a decode-specialist
        replica to import (:meth:`decode_kv_slabs`).

        Returns ``(rows, slabs)``: ``rows`` is per-prompt results with
        the position-0-decided rows already resolved and ``None`` at
        every index that shipped out in a slab; each slab's metas carry
        ``{"orig": prompt index, "first3": ...}`` so the caller can map
        decode-side rows back.  The union of resolved rows and slab-
        decoded rows is bit-identical to a single-replica
        ``score_prompts_slotted`` call over the same prompts (PARITY.md
        "Cross-replica KV handoff") — same prefill program, same
        position-0 resolution, and the slab round-trip moves bytes, not
        values."""
        self._check_open()
        if self.is_encoder_decoder:
            raise ValueError("KV slab export is decoder-only (T5 has no "
                             "decoder-side prompt cache to hand off)")
        ecfg = self.ecfg
        results: List[Optional[Dict]] = [None] * len(prompts)
        slabs: List[slots_mod.KVSlab] = []
        ids_all = self._target_id_rows(prompts, targets)
        with obs.span("encode_prompts", phase="host_tokenize",
                      prompts=len(prompts)):
            encoded = batching.encode_prompts(self.tokenizer, list(prompts))
        with strict.scoring_guard(type(self).__name__):
            with strict.sanctioned_fetch():
                for batch in batching.batches_for_prompts(
                        encoded, ecfg.batch_size, ecfg.buckets,
                        pad_id=self.tokenizer.pad_token_id or 0,
                        length_sorted=ecfg.length_sorted_batches):
                    out = _prefill_select(
                        self.params, self.cfg, self._put(batch.token_ids),
                        self._put(batch.attention_mask),
                        jnp.asarray(batch.indices >= 0),
                        self._batch_target_rows(ids_all, batch)[:, 0],
                        self._batch_target_rows(ids_all, batch)[:, 1],
                        cache_len=batch.bucket_len,
                        slice_m=int(batch.token_ids.shape[0]),
                        top_k=ecfg.top_k,
                        top_filter=ecfg.first_token_top_filter,
                        out_len=_pool_len(batch.bucket_len),
                    )
                    scan0, first3, sel, sub_cache, last_s, len_s = out
                    yes0, no0, rel0, odds0, hit0 = (np.asarray(a)
                                                    for a in scan0)
                    first3 = tuple(np.asarray(a) for a in first3)
                    row_ids = self._batch_target_rows(ids_all, batch)
                    valid = batch.indices >= 0
                    undecided = np.flatnonzero(~hit0 & valid)
                    sel_np = np.asarray(sel)
                    for r, orig in enumerate(batch.indices):
                        if orig >= 0 and hit0[r]:
                            results[int(orig)] = _attach_first_token(
                                _result_row(yes0[r], no0[r], rel0[r],
                                            odds0[r], True, ""), first3, r)
                    if undecided.size:
                        count = undecided.size
                        idx = jnp.asarray(np.arange(count, dtype=np.int32))
                        sub = slots_mod._gather_ring_rows(sub_cache, idx)
                        mapped = sel_np[:count]
                        metas = [
                            {"orig": int(batch.indices[m]),
                             "first3": np.asarray([first3[0][m],
                                                   first3[1][m],
                                                   first3[2][m]])}
                            for m in mapped]
                        slabs.append(slots_mod.slab_from_device(
                            sub, last_s[idx], len_s[idx],
                            row_ids[mapped], metas))
        if slabs:
            slots_mod.slot_counter(
                "slot_slab_export_rows", sum(s.rows() for s in slabs),
                "binary", "serve")
            record_counter("slab_export_bytes",
                           sum(s.nbytes() for s in slabs))
        return results, slabs

    def decode_kv_slabs(
        self,
        slabs: Sequence,
        admit_fn: Optional[Callable] = None,
    ) -> List[Dict]:
        """Decode-specialist half of the cross-replica KV handoff: import
        host :class:`~.slots.KVSlab`\\ s straight into a slot ring's
        pending queue and run the scored decode to retirement — no
        prompt text, no prefill, just near-full decode lanes (ROADMAP
        item 1b's occupancy goal).

        Returns one result row per slab row in FLAT FEED ORDER (slabs in
        the given order, rows in each slab's meta order) — the caller
        maps back to its requests via the slab metas' ``orig`` indices.
        ``admit_fn()`` may return MORE slabs between decode chunks (the
        mid-decode admission hook, same shape as
        :meth:`score_prompts_slotted`'s), so a decode replica's lanes
        refill from the fleet's handoff queue without draining first.
        Rows are bit-identical to the exporting replica decoding its own
        cache (PARITY.md "Cross-replica KV handoff")."""
        self._check_open()
        if self.is_encoder_decoder:
            raise ValueError("KV slab decode is decoder-only")
        ecfg = self.ecfg
        eos_id = getattr(self.tokenizer, "eos_token_id", None)
        steps, _ = self._gen_plan(None, False)
        results: List[Optional[Dict]] = []

        def emit(rows):
            self._emit_scored_slot_rows(rows, steps, eos_id, results)

        ring = slots_mod.SlotRing(
            self, steps=steps, eos_id=eos_id,
            capacity=ecfg.phase2_pool_target or ecfg.batch_size,
            leg="binary", workload="serve",
            retire=_binary_retire, emit=emit,
            batch_review=self._binary_batch_review(steps, eos_id),
            pad_slice=lambda n: _pad_slice(n, max(n, 1)),
        )

        def feed_slab(slab):
            base = len(results)
            results.extend([None] * slab.rows())
            cache, last, lens, row_ids, metas = slots_mod.slab_to_device(
                slab, self._put_replicated)
            # re-key to LOCAL result indices; the exporter's orig stays
            # on the slab for the caller's request mapping
            local = [{"orig": base + i, "first3": m["first3"]}
                     for i, m in enumerate(metas)]
            ring.feed(cache, last, lens, row_ids, local)
            slots_mod.slot_counter("slot_slab_import_rows", slab.rows(),
                                   "binary", "serve")

        def refill_hook(n_free):
            if admit_fn is None:
                return False
            more = admit_fn()
            if not more:
                return False
            for slab in more:
                feed_slab(slab)
            return True

        ring.refill_hook = refill_hook
        with strict.scoring_guard(type(self).__name__):
            with strict.sanctioned_fetch():
                for slab in slabs:
                    feed_slab(slab)
                ring.drain()
                # post-drain admission window, same contract as the
                # slotted path: slabs that arrived during the last chunk
                # are not orphaned
                while admit_fn is not None and refill_hook(0):
                    ring.drain()
        self.record_occupancy(ring.stats)
        return [r if r is not None else _error_row("missing")
                for r in results]

    def packed_autoregressive_demos(
        self,
        prompts: Sequence[str],
        packing: int,
        max_demo_tokens: int = 8,
        repack: Optional[bool] = None,
        extend_stages: bool = True,
    ):
        """Auto-Demo's AUTOREGRESSIVE demonstrations (the PR-10 follow-up)
        through decode-then-repack: each pack builds stage by stage —
        question k's demonstration is the model's OWN greedy continuation
        decoded in the pack's packed context so far, then the grown pack
        (prompt + demo + next question) re-enters the pending queue.  A
        slot retires the moment its question's demo finishes (EOS or the
        token budget) and is refilled by whatever pack stage is ready —
        packs at different stages share the ring, which is the occupancy
        win over decoding each stage as its own static batch.

        Returns ``(packs, demos)``: ``packs`` in
        :func:`~..scoring.packed.build_packs` layout (ready for
        ``score_packed``; the last question of each pack stays
        demo-free), ``demos`` the raw per-question continuation texts
        (pack-major; None for each pack's last question).

        ``repack=False`` runs the same stages whole-flush (slots only
        fill when the ring is empty) — the legacy comparator the parity
        suite pins; demos are per-row pure either way, so the two modes
        emit identical texts.

        ``extend_stages`` (default ON — the PR-10/14 follow-up): a grown
        pack EXTENDS its previous stage's pristine prefill cache by just
        the (formatted demo + next question) suffix via
        :func:`models.decoder.extend_prefill`, instead of re-prefilling
        the whole grown pack — stage k's prefill cost drops from
        O(pack-so-far) to O(suffix).  The ring's decoded-token K/V is
        NOT reusable (the grown pack appends the re-tokenized FORMATTED
        demo, different tokens than the raw decode), so each stage pins
        its prefill-only cache until its demo emits — the HBM-for-FLOPs
        trade this flag names.  ``extend_stages=False`` is the legacy
        re-prefill comparator; both spellings compute the same positions
        over the same real tokens, so packs and demos are pinned
        identical across them."""
        from ..scoring import packed as packed_mod

        self._check_open()
        if self.is_encoder_decoder:
            raise ValueError("packed demo decode is decoder-only")
        if packing < 1:
            raise ValueError(f"packing must be >= 1, got {packing}")
        ecfg = self.ecfg
        use_repack = ecfg.slot_repack if repack is None else bool(repack)
        eos_id = getattr(self.tokenizer, "eos_token_id", None)
        groups = [list(prompts[i: i + packing])
                  for i in range(0, len(prompts), packing)]
        with obs.span("encode_packed_demos", phase="host_tokenize",
                      rows=len(prompts)):
            first_ids = batching.encode_prompts(
                self.tokenizer, [g[0] for g in groups])
            later: Dict[int, List[int]] = {}
            texts, keys = [], []
            for gi, g in enumerate(groups):
                for qi in range(1, len(g)):
                    keys.append((gi, qi))
                    texts.append(g[qi])
            if texts:
                enc = self.tokenizer(texts,
                                     add_special_tokens=False)["input_ids"]
                later = {k: [int(t) for t in e]
                         for k, e in zip(keys, enc)}
        demos: List[List[Optional[str]]] = [
            [None] * len(g) for g in groups]
        use_extend = bool(extend_stages)
        # stage items: (pack_idx, question_idx, ids_so_far, src, suffix) —
        # question_idx is the question whose demo the slot decodes next;
        # src is None (fresh full prefill of ids_so_far) or the previous
        # stage's pristine (cache, row, prefix_len), in which case suffix
        # is the token-id tail (formatted demo + next question) to extend
        # that cache with
        stage_ready: List = [
            (gi, 0, [int(t) for t in first_ids[gi]], None, None)
            for gi, g in enumerate(groups) if len(g) > 1]
        steps = max(1, int(max_demo_tokens))

        def retire(row):
            if eos_id is not None and \
                    (row.toks[: row.decoded] == eos_id).any():
                return int(np.flatnonzero(
                    row.toks[: row.decoded] == eos_id)[0]) + 1
            return row.decoded if row.decoded >= steps else -1

        def emit(rows):
            for r in rows:
                gi, qi = r.meta["pack"], r.meta["question"]
                text = self._completion_text(
                    r.toks[: r.retire_step], eos_id)
                demos[gi][qi] = text
                # the grown pack carries the FORMATTED demo (the same
                # spelling encode_packs tokenizes), so the autoregressive
                # context matches the pack score_packed will prefill
                demo_ids = [int(t) for t in (self.tokenizer(
                    packed_mod.format_demo(text),
                    add_special_tokens=False)["input_ids"]
                    if text else [])]
                grown = r.meta["ids"] + demo_ids
                if qi + 1 < len(groups[gi]) - 1:
                    # the NEXT question needs a demo too: re-enter pending
                    suffix = demo_ids + list(later[(gi, qi + 1)])
                    src = r.meta.get("src") if use_extend else None
                    if src is None or not suffix:
                        src = suffix = None
                    stage_ready.append(
                        (gi, qi + 1, grown + list(later[(gi, qi + 1)]),
                         src, suffix))

        ring = slots_mod.SlotRing(
            self, steps=steps, eos_id=eos_id,
            capacity=ecfg.phase2_pool_target or ecfg.batch_size,
            leg="packed", workload="packed",
            retire=retire, emit=emit, refill=use_repack,
            with_scores=False,
            pad_slice=lambda n: _pad_slice(n, max(n, 1)),
        )

        def feed_extended(chunk):
            """Extend each item's pristine stage cache by its suffix
            (formatted demo + next question) via
            :func:`models.decoder.extend_prefill` and feed the ring —
            the extend-stages half: stage k's prefill touches only the
            suffix tokens, the pack-so-far rides the retained cache."""
            # gather pristine rows source-cache by source-cache (items in
            # one chunk may descend from different stage batches), then
            # pad to a common slot width and concatenate in gather order
            by_src: Dict[int, List[int]] = {}
            caches: Dict[int, object] = {}
            for n, (_, _, _, src, _) in enumerate(chunk):
                caches[id(src[0])] = src[0]
                by_src.setdefault(id(src[0]), []).append(n)
            parts, order = [], []
            width = 0
            for key, members in by_src.items():
                idx = jnp.asarray(np.asarray(
                    [chunk[n][3][1] for n in members], np.int32))
                part = slots_mod._gather_ring_rows(caches[key], idx)
                width = max(width, int(part.k.shape[2]))
                parts.append(part)
                order.extend(members)
            parts = [p if int(p.k.shape[2]) == width
                     else _pad_cache_slots(p, width) for p in parts]
            cache = slots_mod._concat_caches(parts)
            # suffix block right-padded to a multiple of 8 so stage
            # shapes bucket coarsely — every new (T, S) pair is one
            # extend_prefill compile
            s_pad = max(8, -(-max(len(chunk[n][4]) for n in order) // 8) * 8)
            suf = np.zeros((len(order), s_pad), np.int32)
            mask = np.zeros((len(order), s_pad), np.int32)
            prefix_lens = np.asarray(
                [chunk[n][3][2] for n in order], np.int32)
            for row, n in enumerate(order):
                sfx = chunk[n][4]
                suf[row, : len(sfx)] = sfx
                mask[row, : len(sfx)] = 1
            with obs.span("extend_prefill", phase="extend_prefill",
                          batch=len(order), bucket=int(s_pad)):
                last, ext, total = dmod.extend_prefill(
                    self.params, self.cfg, cache,
                    self._put_replicated(suf), self._put_replicated(mask),
                    jnp.asarray(prefix_lens))
            plen = _pool_len(int(ext.k.shape[2]))
            if plen > int(ext.k.shape[2]):
                ext = _pad_cache_slots(ext, plen)
            metas = []
            for row, n in enumerate(order):
                gi, qi, ids, _, sfx = chunk[n]
                metas.append(
                    {"pack": gi, "question": qi, "ids": ids,
                     "src": (ext, row, int(prefix_lens[row]) + len(sfx))})
            ring.feed(ext, last, total,
                      np.zeros((len(order), 2), np.int32), metas)
            slots_mod.slot_counter("slot_stage_extends", len(order),
                                   "packed", "packed")

        def prefill_stage():
            """Prefill every ready stage item and feed the ring (the
            decode-then-REPACK half: a grown pack's prefill lands its
            cache row into whatever lane is free).  Fresh items (stage
            0, or extend_stages off) batch through the full prefill;
            extension items ride :func:`feed_extended`."""
            if not stage_ready:
                return False
            items, stage_ready[:] = list(stage_ready), []
            fresh = [it for it in items if it[3] is None]
            extends = [it for it in items if it[3] is not None]
            pad_id = self.tokenizer.pad_token_id or 0
            for batch in (batching.batches_for_prompts(
                    [ids for _, _, ids, _, _ in fresh], ecfg.batch_size,
                    ecfg.buckets, pad_id=pad_id,
                    length_sorted=ecfg.length_sorted_batches)
                    if fresh else ()):
                last, cache = self._prefill(
                    self._put(batch.token_ids),
                    self._put(batch.attention_mask), batch.bucket_len)
                lengths = jnp.sum(
                    self._put(batch.attention_mask), axis=-1)
                valid = batch.indices >= 0
                count = int(valid.sum())
                idx = jnp.asarray(
                    np.flatnonzero(valid).astype(np.int32))
                sub, last_u, len_u = _gather_rows(cache, last, lengths,
                                                  idx)
                plen = _pool_len(int(sub.k.shape[2]))
                if plen > int(sub.k.shape[2]):
                    sub = _pad_cache_slots(sub, plen)
                metas = []
                for j, m in enumerate(np.flatnonzero(valid)):
                    gi, qi, ids, _, _ = fresh[int(batch.indices[m])]
                    meta = {"pack": gi, "question": qi, "ids": ids}
                    if use_extend:
                        meta["src"] = (
                            sub, j, int(batch.attention_mask[m].sum()))
                    metas.append(meta)
                ring.feed(sub, last_u, len_u,
                          np.zeros((count, 2), np.int32), metas)
            step = max(1, int(ecfg.batch_size))
            for at in range(0, len(extends), step):
                feed_extended(extends[at: at + step])
            return True

        # starvation hook: a freed lane pulls the next READY pack stage
        # in mid-decode (prefill + feed), instead of waiting for the ring
        # to drain — the decode-then-repack loop proper
        ring.refill_hook = lambda n_free: prefill_stage()
        with strict.scoring_guard(type(self).__name__):
            with strict.sanctioned_fetch():
                while prefill_stage() or ring.live_rows():
                    ring.drain()
        self.record_occupancy(ring.stats)
        packs = []
        for gi, g in enumerate(groups):
            pack = []
            for qi, prompt in enumerate(g):
                demo = None
                if qi + 1 < len(g) and demos[gi][qi]:
                    demo = packed_mod.format_demo(demos[gi][qi])
                pack.append((prompt, demo))
            packs.append(pack)
        flat_demos = [d for g in demos for d in g]
        return packs, flat_demos

    def first_token_relative_prob(
        self, prompts: Sequence[str], targets: Sequence[str] = ("Yes", "No"),
        top_filter: int = 0,
    ) -> np.ndarray:
        """Fast path: one forward per bucket, no generation — the pjit'd
        perturbation-sweep hot op.  Returns [N, 3] (yes, no, relative).
        ``targets`` may be per-prompt pairs (see ``_target_id_rows``)."""
        self._check_open()
        ids_all = self._target_id_rows(prompts, targets)
        with obs.span("encode_prompts", phase="host_tokenize",
                      prompts=len(prompts)):
            encoded = batching.encode_prompts(self.tokenizer, prompts)
        out = np.zeros((len(prompts), 3), np.float64)

        def launch(batch):
            ids = self._put(batch.token_ids)
            mask = self._put(batch.attention_mask)
            if self.is_encoder_decoder:
                dec = jnp.full((ids.shape[0], 1), self.cfg.decoder_start_token_id, jnp.int32)
                logits = t5mod.forward(self.params, self.cfg, ids, mask, dec)[:, 0, :]
            else:
                logits = dmod.forward_last_logits(self.params, self.cfg, ids, mask)
            row_ids = self._batch_target_rows(ids_all, batch)
            return yn.relative_prob_first_token(
                logits, row_ids[:, 0], row_ids[:, 1], top_filter)

        def consume(batch, res):
            yes, no, rel = (np.asarray(a) for a in res)
            for r, orig in enumerate(batch.indices):
                if orig >= 0:
                    out[int(orig)] = (float(yes[r]), float(no[r]), float(rel[r]))

        self._run_pipelined(
            batching.batches_for_prompts(
                encoded, self.ecfg.batch_size, self.ecfg.buckets,
                pad_id=self.tokenizer.pad_token_id or 0,
                length_sorted=self.ecfg.length_sorted_batches,
            ),
            launch, consume, rebatch=self._oom_rebatch(encoded),
        )
        return out


def _binary_retire(row) -> int:
    """Slot-ring retirement for binary scored rows: a row leaves its lane
    as soon as its yes/no scan is RESOLVED (top-k hit or EOS — no later
    position can change the row, the same early-exit rule
    ``_scan_decode_loop`` applies batch-wide), computed once per chunk by
    the vectorized ``_binary_batch_review`` hook."""
    return row.decoded if row.meta.get("resolved") else -1


def _is_prefix_pair(prompt) -> bool:
    """A ``(prefix, suffix)`` 2-TUPLE routes score_prompts through the
    fused path; pre-tokenized prompts are LISTS/arrays of ints, so the
    two spellings never collide."""
    return (isinstance(prompt, tuple) and len(prompt) == 2
            and not isinstance(prompt[0], (int, np.integer)))


def _cat_scores(parts, reduced: bool):
    """Concatenate per-chunk/per-block score pieces along the step axis —
    ONE spelling of the ReducedScores stitching rule, shared by the
    sequential scan loop (``_scan_decode_loop``) and the K-decode chunk
    driver (``_k_decode_chunk``) so a field/axis change can never make
    the two paths' scores silently diverge."""
    if not reduced:
        return jnp.concatenate(parts, axis=1)
    return dmod.ReducedScores(*(
        jnp.concatenate([getattr(p, f) for p in parts], axis=1)
        for f in dmod.ReducedScores._fields))


def _cache_nbytes(cache) -> int:
    """Device bytes of one KVCache's K/V blocks (the prefix-pool unit) —
    including the per-head fp32 scales of an int8-quantized cache."""
    n = int(cache.k.size + cache.v.size) * cache.k.dtype.itemsize
    if cache.k_scale is not None:
        n += 4 * int(cache.k_scale.size + cache.v_scale.size)
    return n


#: Fixed menu of phase-2 decode slice sizes.  Finer than powers of two
#: (each pow2 entry gets a 1.5x midpoint) so the padded slice wastes at most
#: ~33% lanes instead of ~50% — at the sweep's own operating point (batch 192,
#: ~90% rows decided at position 0 → 19 undecided) the pow2 menu decoded 32
#: rows with 13 of them padding; the 24-row entry decodes 5 padding rows.
#: Each entry costs at most one compile per length bucket, amortized by XLA's
#: persistent compilation cache.
_SLICE_MENU = (8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512)


def _pad_slice(n: int, cap: int) -> int:
    """Smallest menu size >= n, capped at the batch size."""
    for m in _SLICE_MENU:
        if m >= n:
            return min(m, cap)
    return cap


#: Quantized cache lengths for the phase-2 pools: every pooled slice is
#: padded (inert invalid slots) up to the menu entry covering its cache
#: length, so slices from DIFFERENT length buckets pool and decode
#: together.  Without this the pool fragments per bucket — the step-16
#: length-sorted menu touches ~9 buckets on the real perturbation corpus,
#: each holding a sub-target remnant that flushes padded at end of sweep —
#: and every bucket costs its own family of decode compiles.  Attention
#: over the extra invalid slots is negligible: the pooled decode is
#: weight-streaming-bound (~8.5 ms/step at 7B int8 for ANY slice under a
#: few hundred rows).  The menus live in runtime/plan.py so the budget
#: model prices the same quantized shapes the engine pools: the binary
#: pool keeps the coarse r4 menu (finer entries would fragment its
#: flushes for no HBM win — it holds ~10% of rows), the confidence pool
#: uses the finer CONF menu (it holds EVERY row; dead slots cost real
#: HBM).
_pool_len = plan_mod.pool_len_for
_conf_pool_len = plan_mod.conf_pool_len_for


class _Phase2Pool:
    """Leg-parameterized cross-batch pool of scored-decode rows.

    The scored look-ahead decode is weight-streaming-bound: every step
    streams the full weight set from HBM regardless of how few rows decode,
    so a 10-step decode costs nearly the same for 24 rows as for 192.
    Running it once per prefill batch therefore pays the full ~100-300 ms
    decode cost for a handful of rows, every batch.  Instead, each batch's
    rows are gathered out of its prefill cache (a few MB per row) and
    accumulate here, keyed by quantized cache length; ONE pooled decode
    runs per ``target`` accumulated rows (and at end of sweep), amortizing
    the per-step weight streaming across batches.  Semantics are unchanged
    — the same rows decode the same tokens from the same caches, just
    grouped into fewer device programs.

    Two legs share the machinery (``leg``/``confidence``):

    - **binary** (default): the undecided slice of each batch pools; the
      flush is ONE async full-``steps`` decode whose [m]-sized outputs
      resolve later in :meth:`drain` (the launch loop keeps feeding the
      device).
    - **confidence** (``confidence=True``): EVERY row pools (each needs
      the digit decode); the flush decodes in chunks with per-row
      EARLY-EXIT RETIREMENT — a row retires at the first step where its
      completion's first-integer parse can no longer change
      (:func:`..scoring.confidence.first_int_stable`, never before the 3
      positions ``weighted_confidence_digits`` reads), retired rows'
      cache slices compact away per chunk (``completion_cache_bytes_freed``),
      and the whole flush stops once every real row has retired
      (``conf_steps_saved``).  Retirement is a pure function of the row's
      own greedy tokens, so pooled rows are bit-reproducible across batch
      shapes and pool compositions (serve replay parity holds).
    """

    def __init__(self, engine, steps, eos_id, target, results,
                 max_bytes: int = 512 << 20, leg: str = "binary",
                 confidence: bool = False, completions: bool = False,
                 repack: Optional[bool] = None):
        self.engine = engine
        self.steps = steps
        self.eos_id = eos_id
        self.target = max(1, int(target))
        self.max_bytes = max(1, int(max_bytes))
        self.results = results
        self.leg = leg
        self.confidence = bool(confidence)
        self.completions = bool(completions)
        # decode-then-repack (runtime/slots.py): rows stream through a
        # fixed-capacity slot ring — retired lanes refill from the queue
        # mid-decode — instead of accumulating to a whole flush.  The
        # engine config is the default; False keeps the legacy schedule.
        self.repack = (bool(engine.ecfg.slot_repack) if repack is None
                       else bool(repack))
        self._rings: Dict[int, slots_mod.SlotRing] = {}
        self.entries: Dict[int, List] = {}
        self.counts: Dict[int, int] = {}
        self.bytes: Dict[int, int] = {}
        self.deferred: List = []   # [(layout, fields, first3, parcels)] —
                                   # dispatched flushes awaiting host fetch;
                                   # parcels = mutable [bytes, probe] pairs:
                                   # the K/V bytes the flush pins in HBM
                                   # until its queued decode EXECUTES,
                                   # counted against max_bytes and zeroed
                                   # PER OUTPUT as each probe reports ready
                                   # (not whole-flush — see _inflight_bytes)

    @staticmethod
    def _entry_bytes(cache) -> int:
        return _cache_nbytes(cache)

    def add(self, pool_len, sub_cache, last_s, len_s, n_real, orig_idx,
            row_ids, first3):
        """Queue one batch's gathered undecided slice (rows past ``n_real``
        are gather padding).  ``pool_len`` is the slice's QUANTIZED cache
        length (_pool_len of its bucket — slices from different buckets
        arrive pre-padded by _prefill_select and pool together under one
        key).  ``orig_idx``: original prompt index per real row;
        ``row_ids``: [m, 2] per-row (yes, no) target ids — rows from
        DIFFERENT scenarios pool together.  Flushes when the key reaches
        ``target`` rows or the pool's TOTAL held K/V would exceed
        ``max_bytes`` (the largest key flushes first, freeing the most per
        row); an add that would push the key past _SLICE_MENU's largest
        entry flushes FIRST, so a padded flush total never exceeds the menu
        and never compiles a bespoke decode shape (user-set targets above
        ~450 used to)."""
        if self.repack:
            self._ring_add(pool_len, sub_cache, last_s, len_s, n_real,
                           orig_idx, row_ids, first3)
            return
        nb = self._entry_bytes(sub_cache)
        # Evict from the POOL (largest key first, as before — flushing moves
        # its bytes to the dispatched set, so this loop terminates)...
        while self.entries and sum(self.bytes.values()) + nb > self.max_bytes:
            self.flush(max(self.bytes, key=self.bytes.get))
        # ...and only when flush caches still QUEUED behind prefills (not
        # yet executed — _inflight_bytes reaps finished ones first) push the
        # TOTAL past the cap, block until the queue has consumed them — the
        # one place the async pool trades throughput back for the HBM bound.
        if self.deferred and (self._inflight_bytes()
                              + sum(self.bytes.values()) + nb > self.max_bytes):
            self.drain()
        rows = int(last_s.shape[0])
        if self.counts.get(pool_len, 0) and (
                self.counts[pool_len] + rows > _SLICE_MENU[-1]):
            self.flush(pool_len)
        self.entries.setdefault(pool_len, []).append(
            (sub_cache, last_s, len_s, int(n_real), np.asarray(orig_idx),
             np.asarray(row_ids, np.int32), np.asarray(first3))
        )
        self.counts[pool_len] = self.counts.get(pool_len, 0) + rows
        self.bytes[pool_len] = self.bytes.get(pool_len, 0) + nb
        if self.counts[pool_len] >= self.target:
            self.flush(pool_len)

    def flush_all(self):
        if self.repack:
            for ring in self._rings.values():
                with obs.span("pool_flush", phase="pooled_decode",
                              leg=self.leg, rows=ring.stats.rows,
                              repack=True):
                    ring.drain()
                self.engine.record_occupancy(ring.stats)
            self._rings = {}
            return
        for bucket_len in list(self.entries):
            self.flush(bucket_len)
        self.drain()

    # -- decode-then-repack (runtime/slots.py) ---------------------------

    def _ring_add(self, pool_len, sub_cache, last_s, len_s, n_real,
                  orig_idx, row_ids, first3):
        """Feed one batch's real rows into the slot ring for this
        quantized cache length, then crank: the ring spins up once a
        full capacity of pending rows exists (the flush-at-target
        cadence) and from then on refills retired lanes from the queue
        between chunks instead of draining whole flushes."""
        if not n_real:
            return
        ring = self._rings.get(pool_len)
        if ring is None:
            ring = self._rings[pool_len] = self._make_ring()
        orig_idx = np.asarray(orig_idx)
        row_ids = np.asarray(row_ids, np.int32)
        first3 = np.asarray(first3)
        idx = jnp.asarray(np.arange(int(n_real), dtype=np.int32))
        sub, last_u, len_u = _gather_rows(sub_cache, last_s, len_s, idx)
        metas = [{"orig": int(orig_idx[j]), "first3": first3[j]}
                 for j in range(int(n_real))]
        if self.confidence:
            record_counter("pooled_conf_rows", int(n_real))
        ring.feed(sub, last_u, len_u, row_ids[: int(n_real)], metas)
        with obs.span("pool_flush", phase="pooled_decode", leg=self.leg,
                      rows=int(n_real), repack=True):
            ring.pump(drain=False)

    def _make_ring(self) -> slots_mod.SlotRing:
        min_conf = min(3, self.steps) if self.confidence else 1
        return slots_mod.SlotRing(
            self.engine, steps=self.steps, eos_id=self.eos_id,
            capacity=self.target, leg=self.leg, workload="engine",
            retire=(self._conf_ring_retire if self.confidence
                    else _binary_retire),
            emit=(self._conf_ring_emit if self.confidence
                  else self._binary_ring_emit),
            batch_review=(None if self.confidence
                          else self.engine._binary_batch_review(
                              self.steps, self.eos_id)),
            min_check=min_conf,
            pad_slice=lambda n: _pad_slice(n, max(n, 1)),
        )

    def _conf_ring_retire(self, row) -> int:
        """r* for one ring row — the SAME per-row predicate the legacy
        flush scans (:meth:`_conf_retired_at`, monkeypatch point of the
        retirement tests), checked incrementally over the new window."""
        min_conf = min(3, self.steps)
        start = max(int(row.checked), min_conf - 1) + 1
        for k in range(start, row.decoded + 1):
            if self._conf_retired_at(row.toks, k):
                return k
        return -1

    def _binary_ring_emit(self, rows):
        self.engine._emit_scored_slot_rows(rows, self.steps, self.eos_id,
                                           self.results)

    def _conf_ring_emit(self, rows):
        """Finish retired confidence rows (batched): identical emitted
        fields to the legacy flush tail — weighted confidence from
        positions 0..2, yes/no scan over positions < min(r*, EOS),
        completion cut at r* — just grouped by retirement instead of by
        flush."""
        engine = self.engine
        ecfg = engine.ecfg
        steps = self.steps
        min_conf = min(3, steps)
        record_counter("pooled_conf_retired_rows",
                       sum(1 for r in rows if r.natural))
        saved = sum(steps - r.decoded for r in rows)
        if saved > 0:
            record_counter("conf_steps_saved", saved)
        vals = np.stack([r.vals for r in rows])
        idsk = np.stack([r.ids_k for r in rows])
        logz = np.stack([r.logz for r in rows])
        tgt = np.stack([r.tgt for r in rows])
        r_star = np.asarray([max(1, r.retire_step) for r in rows],
                            np.int32)
        vs = r_star.copy()
        if self.eos_id is not None:
            for i, r in enumerate(rows):
                hits = np.flatnonzero(r.toks[: r_star[i]] == self.eos_id)
                if hits.size:
                    vs[i] = min(int(vs[i]), int(hits[0]) + 1)
        res = yn.yes_no_from_reduced(
            jnp.asarray(vals), jnp.asarray(logz), jnp.asarray(tgt),
            max_look_ahead=ecfg.max_look_ahead, top_k=ecfg.top_k,
            valid_steps=jnp.asarray(vs))
        res_np = {k: np.asarray(v) for k, v in res._asdict().items()}
        conf_lp = vals[:, :min_conf] - logz[:, :min_conf, None]
        conf_idx = idsk[:, :min_conf]
        for i, r in enumerate(rows):
            completion = ""
            if self.completions:
                completion = engine._completion_text(
                    r.toks[: r_star[i]], self.eos_id)
            f3 = np.asarray(r.meta["first3"], np.float64)
            out = _attach_first_token(
                _result_row(res_np["yes_prob"][i], res_np["no_prob"][i],
                            res_np["relative_prob"][i],
                            res_np["odds_ratio"][i],
                            res_np["found"][i], completion),
                (f3[0:1], f3[1:2], f3[2:3]), 0)
            cands = engine._candidates_from_topk(conf_lp[i], conf_idx[i])
            out["weighted_confidence"] = weighted_confidence_digits(cands)
            self.results[int(r.meta["orig"])] = out

    def _blank_entry(self, template, rows: int):
        """Numerically-inert filler rows that pad a pooled decode up to a
        menu size: one valid zero-K cache slot per row (so the attention
        softmax never reduces over an empty set) and zero logits."""
        cache_t, last_t, len_t = template
        L, _, T, G, D = cache_t.k.shape
        kv = jnp.zeros((L, rows, T, G, D), cache_t.k.dtype)
        valid = jnp.zeros((rows, T), bool).at[:, 0].set(True)
        # unit scales keep a quantized blank inert: zero codes decode to
        # exact zeros, matching the bf16 blank's zero-K slots
        scale = (jnp.ones((L, rows, T, G), jnp.float32)
                 if cache_t.k_scale is not None else None)
        cache = dmod.KVCache(
            k=kv, v=kv,
            positions=jnp.zeros((rows, T), cache_t.positions.dtype),
            valid=valid, length=cache_t.length,
            k_scale=scale, v_scale=scale,
        )
        last = jnp.zeros((rows, last_t.shape[1]), last_t.dtype)
        lens = jnp.ones((rows,), len_t.dtype)
        return (cache, last, lens, 0, np.empty((0,), np.int64),
                np.zeros((rows, 2), np.int32), np.full((rows, 3), np.nan))

    def flush(self, bucket_len):
        entries = self.entries.pop(bucket_len, [])
        self.counts.pop(bucket_len, None)
        self.bytes.pop(bucket_len, None)
        if not entries:
            return
        total = sum(e[1].shape[0] for e in entries)
        m = _pad_slice(total, total if total > _SLICE_MENU[-1] else _SLICE_MENU[-1])
        if m > total:
            entries.append(self._blank_entry(entries[0][:3], m - total))
        if len(entries) == 1:
            cache, last, lens = entries[0][:3]
        else:
            first = entries[0][0]
            cache = dmod.KVCache(
                k=jnp.concatenate([e[0].k for e in entries], axis=1),
                v=jnp.concatenate([e[0].v for e in entries], axis=1),
                positions=jnp.concatenate([e[0].positions for e in entries], axis=0),
                valid=jnp.concatenate([e[0].valid for e in entries], axis=0),
                length=first.length,
                k_scale=(jnp.concatenate([e[0].k_scale for e in entries],
                                         axis=1)
                         if first.k_scale is not None else None),
                v_scale=(jnp.concatenate([e[0].v_scale for e in entries],
                                         axis=1)
                         if first.v_scale is not None else None),
            )
            last = jnp.concatenate([e[1] for e in entries], axis=0)
            lens = jnp.concatenate([e[2] for e in entries], axis=0)
        ids = np.concatenate([e[5] for e in entries], axis=0)   # [m, 2]
        first3 = np.concatenate([e[6] for e in entries], axis=0)  # [m, 3]
        if self.confidence:
            layout = [(int(e[1].shape[0]), e[3], e[4]) for e in entries]
            self._flush_confidence(bucket_len, layout, total, cache, last,
                                   lens, ids, first3)
            return
        ecfg = self.engine.ecfg
        # ASYNC flush: dispatch the full scored decode and the on-device
        # yes/no reduction, then return — only the small [m] result arrays
        # are fetched, later, in drain().  The r4 flush ran the CHUNKED
        # early-exit decode here, whose mid-decode host reads blocked
        # consume() until the device drained every in-flight prefill ahead
        # of the decode — a measured 19.5 s of the 93 s warm 10k repeat
        # (cProfile, r5) — and then restarted the pipeline empty.  Decoding
        # all ``steps`` positions costs ~100 ms more device time per flush
        # (weight-streaming-bound) but never reads the early-exit flag, so
        # the launch loop keeps feeding the device.  The decode stacks
        # ReducedScores statistics in-scan (top-19 + logsumexp + target
        # logits) — the [m, steps, V] fp32 tensor this path used to
        # materialize between the decode and the reduction (~1.3 GB at the
        # 512-row menu cap) is what OOM'd sweep batches 320/384 in r4;
        # only [m]-sized outputs wait in the deferred list.
        # ReducedScores (default): the decode stacks per-step top-19 +
        # logsumexp + target-logit statistics instead of the [m, steps, V]
        # fp32 tensor (~1.3 GB at the 512-row menu cap) that used to live
        # between the decode and the reduction programs.
        reduced = ecfg.top_k <= dmod.REDUCED_TOPK
        with obs.span("pool_flush", phase="pooled_decode", leg=self.leg,
                      rows=int(total), padded=int(m),
                      bucket=int(bucket_len)) as sp:
            toks, sc, _, _, _ = dmod.decode_steps(
                self.engine.params, self.engine.cfg, cache, last, lens,
                np.int32(0), self.steps, self.eos_id, None,
                with_scores="reduced" if reduced else True,
                target_ids=jnp.asarray(ids) if reduced else None,
            )
            res = self.engine._scan_results(sc, ids[:, 0], ids[:, 1], toks,
                                            self.eos_id)
            if sp is not None:
                sp["_sync_obj"] = toks
        fields = res._asdict()
        for v in fields.values():
            try:
                v.copy_to_host_async()
            except AttributeError:
                pass
        # keep only the row layout — NOT the entries themselves, whose
        # device cache slices would otherwise stay pinned until drain().
        # Until the queued decode executes, BOTH the source slices (held by
        # the pending concatenate) and the concatenated copy (held by the
        # decode) are resident, so the pinned accounting is 2x the slices.
        # The pinned bytes split into one parcel PER OUTPUT so
        # _inflight_bytes can decrement incrementally as individual
        # outputs report ready, instead of reaping whole flushes only.
        layout = [(int(e[1].shape[0]), e[3], e[4]) for e in entries]
        fb = 2 * sum(self._entry_bytes(e[0]) for e in entries)
        vals = list(fields.values())
        share, rem = divmod(fb, len(vals))
        parcels = [[share + (rem if i == 0 else 0), v]
                   for i, v in enumerate(vals)]
        self.deferred.append((layout, fields, first3, parcels))

    def _conf_retired_at(self, toks_row, k: int) -> bool:
        """Is a confidence row's result frozen after its first ``k``
        greedy tokens?  True when (a) EOS already landed in the window
        (the completion is cut there — nothing later exists), (b) the
        decoded text's first-integer parse is terminated
        (scoring.confidence.first_int_stable: appended text can neither
        extend the digits nor introduce an earlier match), or (c) the
        stripped text already fills the completion_chars truncation.

        A window whose decode ends in U+FFFD NEVER retires: the
        replacement char marks a byte sequence the window cut mid-token —
        the next token can complete it into a real character, changing
        both the text tail and, crucially, the word-boundary structure
        (U+FFFD is a non-word char, so '8\\ufffd' reads as a terminated
        integer while the completed '8µ' would not be).  Waiting one more
        window keeps the parity contract exact; interior U+FFFDs are
        genuine invalid bytes and stay put."""
        from ..scoring import confidence as conf_mod

        window = toks_row[:k]
        if self.eos_id is not None and bool((window == self.eos_id).any()):
            return True
        text = self.engine.tokenizer.decode(
            [int(t) for t in window], skip_special_tokens=True)
        if text.endswith("�"):
            return False
        if len(text.strip()) >= self.engine.ecfg.completion_chars:
            return True
        return conf_mod.first_int_stable(text)

    def _flush_confidence(self, bucket_len, layout, total, cache, last,
                          lens, ids, first3):
        """One pooled confidence decode with early-exit row retirement
        and per-chunk completion-cache streaming.

        The decode runs in chunks (3 positions first — the minimum
        ``weighted_confidence_digits`` reads — then ``scan_chunk``-sized).
        After each chunk the greedy tokens come back to host and every
        still-live row's retirement step resolves: ``r*`` = the smallest
        k >= 3 whose k-token completion prefix is frozen
        (:meth:`_conf_retired_at`) — a pure function of the row's own
        tokens, NEVER of pool composition or chunk schedule, so a row's
        emitted fields are bit-reproducible across batch shapes (the
        serve-replay contract).  Retired rows' K/V slices are compacted
        away (menu-padded gather) before the next chunk — the HBM the
        per-batch path pinned to step 10 frees the moment each row
        retires (``completion_cache_bytes_freed``) — and the flush stops
        once every real row has retired (``conf_steps_saved``).

        Emitted fields vs the full 10-step per-batch decode: the
        weighted confidence (positions 0-2) and the completion's
        first-integer parse are IDENTICAL by construction; the completion
        text is the r*-token prefix of the full decode's text; the yes/no
        scan reads positions < r* (a hit past a row's retirement falls
        back to position 0 — the PARITY.md pooled-confidence contract)."""
        engine = self.engine
        ecfg = engine.ecfg
        steps = self.steps
        K = dmod.REDUCED_TOPK
        m = sum(r for r, _, _ in layout)
        min_conf = min(3, steps)
        record_counter("pooled_conf_rows", sum(n for _, n, _ in layout))

        real = np.zeros((m,), bool)
        row = 0
        for rows, n_real, _orig in layout:
            real[row: row + n_real] = True
            row += rows
        toks_np = np.zeros((m, steps), np.int32)
        vals_np = np.zeros((m, steps, K), np.float32)
        idsk_np = np.zeros((m, steps, K), np.int32)
        logz_np = np.zeros((m, steps), np.float32)
        tgt_np = np.zeros((m, steps, 2), np.float32)
        retire_step = np.full((m,), -1, np.int32)
        checked_upto = np.full((m,), min_conf - 1, np.int32)
        decoded_upto = np.zeros((m,), np.int32)

        cache_map = np.arange(m)          # cache row -> flush-layout row
        cache_real = real.copy()          # cache row holds a live real row
        cur_cache, prev, cur_lens, done = cache, last, lens, None
        cur_ids = jnp.asarray(ids)
        use_k = engine._k_active()
        prev_h = None                     # K-path frontier hidden
        retired_log = []
        offset = 0
        with obs.span("pool_flush", phase="pooled_decode", leg=self.leg,
                      rows=int(total), padded=int(m),
                      bucket=int(bucket_len)) as sp:
            while offset < steps:
                n = min_conf if offset == 0 else min(
                    max(1, ecfg.scan_chunk), steps - offset)
                if use_k:
                    # K-block confidence scan (verify-and-accept): the
                    # chunk schedule — and so the retirement points the
                    # first_int_stable parse reads — is unchanged; only
                    # the launches per chunk collapse.  Blank filler
                    # rows (cache_real False) never veto acceptance.
                    toks_c, sc_c, cur_cache, prev, done, prev_h, _acc = \
                        engine._k_decode_chunk(
                            cur_cache, prev, cur_lens, np.int32(offset),
                            n, self.eos_id, done, "reduced", cur_ids,
                            prev_h, cache_real, "confidence")
                else:
                    toks_c, sc_c, cur_cache, prev, done = dmod.decode_steps(
                        engine.params, engine.cfg, cur_cache, prev, cur_lens,
                        np.int32(offset), n, self.eos_id, done,
                        with_scores="reduced", target_ids=cur_ids,
                    )
                for a in (toks_c,) + tuple(sc_c):
                    try:
                        a.copy_to_host_async()
                    except AttributeError:
                        pass
                live = np.flatnonzero(cache_real)
                lr = cache_map[live]
                toks_np[lr, offset:offset + n] = np.asarray(toks_c)[live]
                vals_np[lr, offset:offset + n] = \
                    np.asarray(sc_c.topk_vals)[live]
                idsk_np[lr, offset:offset + n] = \
                    np.asarray(sc_c.topk_ids)[live]
                logz_np[lr, offset:offset + n] = np.asarray(sc_c.logz)[live]
                tgt_np[lr, offset:offset + n] = \
                    np.asarray(sc_c.target_logits)[live]
                decoded_upto[lr] = offset + n
                offset += n
                if offset >= steps:
                    break
                # retirement: r* resolves from each row's own tokens only
                newly = 0
                for r in lr:
                    if retire_step[r] >= 0:
                        continue
                    for k in range(int(checked_upto[r]) + 1, offset + 1):
                        if self._conf_retired_at(toks_np[r], k):
                            retire_step[r] = k
                            newly += 1
                            break
                    checked_upto[r] = offset
                retired_log.append([int(offset), int(newly)])
                alive = [int(c) for c in live if retire_step[cache_map[c]] < 0]
                if not alive:
                    break
                m2 = _pad_slice(len(alive), int(cache_map.shape[0]))
                if m2 < cache_map.shape[0]:
                    # stream the retired rows' K/V back to the allocator:
                    # gather the live rows into a menu-padded slice and
                    # drop the wider cache — the next chunk decodes only
                    # what still needs decoding
                    idx = np.zeros((m2,), np.int32)
                    idx[: len(alive)] = alive
                    freed = _cache_nbytes(cur_cache)
                    idx_dev = jnp.asarray(idx)
                    cur_cache, prev, cur_lens = _gather_rows(
                        cur_cache, prev, cur_lens, idx_dev)
                    done = done[idx_dev]
                    cur_ids = cur_ids[idx_dev]
                    if prev_h is not None:  # K-path frontier rides along
                        prev_h = prev_h[idx_dev]
                    freed -= _cache_nbytes(cur_cache)
                    record_counter("completion_cache_bytes_freed", freed)
                    cache_map = cache_map[idx]
                    cache_real = np.zeros((m2,), bool)
                    cache_real[: len(alive)] = True
            if sp is not None:
                sp["args"]["retired_per_step"] = retired_log
        saved = int(np.sum(steps - decoded_upto[real]))
        if saved:
            record_counter("conf_steps_saved", saved)
        record_counter("pooled_conf_retired_rows",
                       int((retire_step[real] >= 0).sum()))

        # r* per row: the retirement step, or everything decoded; the scan
        # sees positions < min(r*, EOS) — the same yes_no_from_reduced the
        # per-batch path runs, on bit-identical per-position statistics
        r_star = np.where(retire_step >= 0, retire_step, decoded_upto)
        r_star = np.maximum(r_star, 1)
        vs = r_star.copy()
        if self.eos_id is not None:
            for g in np.flatnonzero(real):
                w = toks_np[g, : r_star[g]]
                hits = np.flatnonzero(w == self.eos_id)
                if hits.size:
                    vs[g] = min(int(vs[g]), int(hits[0]) + 1)
        res = yn.yes_no_from_reduced(
            jnp.asarray(vals_np), jnp.asarray(logz_np), jnp.asarray(tgt_np),
            max_look_ahead=ecfg.max_look_ahead, top_k=ecfg.top_k,
            valid_steps=jnp.asarray(vs),
        )
        res_np = {k: np.asarray(v) for k, v in res._asdict().items()}
        conf_lp = vals_np[:, :min_conf] - logz_np[:, :min_conf, None]
        conf_idx = idsk_np[:, :min_conf]

        row = 0
        for rows, n_real, orig in layout:
            for j in range(n_real):
                g = row + j
                completion = ""
                if self.completions:
                    # a retired row's window never ends mid-character
                    # (_conf_retired_at refuses U+FFFD tails), so the
                    # stored text is a true prefix of the full-decode
                    # completion as-is
                    completion = engine._completion_text(
                        toks_np[g, : r_star[g]], self.eos_id)
                out = _attach_first_token(
                    _result_row(
                        res_np["yes_prob"][g], res_np["no_prob"][g],
                        res_np["relative_prob"][g], res_np["odds_ratio"][g],
                        res_np["found"][g], completion,
                    ), (first3[:, 0], first3[:, 1], first3[:, 2]), g)
                cands = engine._candidates_from_topk(conf_lp[g], conf_idx[g])
                out["weighted_confidence"] = weighted_confidence_digits(cands)
                self.results[int(orig[j])] = out
            row += rows

    def _inflight_bytes(self) -> int:
        """K/V bytes pinned by dispatched-but-unexecuted flush decodes.

        Each deferred flush's pinned bytes are split into per-output
        parcels; a parcel whose probe reports ready stops counting
        (checked NON-blockingly via jax.Array.is_ready, keeping the
        common case async; only genuinely queued flushes force the drain
        above).  Today's binary flush dispatches ONE reduction, so its
        parcels usually resolve together — the per-output granularity is
        the accounting CONTRACT (a flush built from several programs, or
        a backend that materializes outputs independently, decrements
        incrementally instead of all-or-nothing), not a claim about the
        current program count.  Confidence flushes resolve synchronously
        inside :meth:`_flush_confidence` and never reach this list —
        their retired rows relieve pool pressure immediately via the
        per-chunk compaction there."""
        total = 0
        for _layout, _fields, _first3, parcels in self.deferred:
            for p in parcels:
                if p[0] and getattr(p[1], "is_ready", lambda: True)():
                    p[0] = 0
                total += p[0]
        return total

    def drain(self):
        """Resolve every dispatched flush into result rows (host fetches)."""
        for layout, fields, first3, _parcels in self.deferred:
            with obs.span("pool_drain", phase="d2h_fetch", leg=self.leg,
                          flushes=len(self.deferred)):
                res_np = {k: np.asarray(v) for k, v in fields.items()}
            row = 0
            for rows, n_real, orig in layout:
                for j in range(n_real):
                    g = row + j
                    self.results[int(orig[j])] = _attach_first_token(
                        _result_row(
                            res_np["yes_prob"][g], res_np["no_prob"][g],
                            res_np["relative_prob"][g],
                            res_np["odds_ratio"][g],
                            res_np["found"][g], "",
                        ), (first3[:, 0], first3[:, 1], first3[:, 2]), g)
                row += rows
        self.deferred = []


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "cache_len", "slice_m", "top_k", "top_filter",
                     "out_len", "select_all"))
def _prefill_select(params, cfg, ids, mask, valid_rows, yes_ids, no_ids,
                    cache_len: int, slice_m: int, top_k: int,
                    top_filter: int = 20, out_len: int = 0,
                    select_all: bool = False):
    """Prefill + position-0 scan + IN-PROGRAM phase-2 row selection.

    Selecting the undecided rows INSIDE the program — undecided-first
    stable sort of the scan's hit mask — outputs a ``slice_m``-row cache
    slice instead of the full batch.  Measured effect (v5e, 2026-07): the
    THROUGHPUT cost of producing a cache is unchanged (36.9 p/s either
    way at the 430-token point; the cost is the layer scan's internal
    ys-stacking of K/V, ~106 ms/batch, which the gather still reads —
    prefill 37.32 p/s vs 38.11 pure forward), but the program OUTPUT
    shrinks ~4x (e.g. 1.36 GB -> 340 MB at 192x432), freeing the HBM that
    two in-flight pipelined batches would otherwise pin and enabling
    larger sweep batches.  ``valid_rows`` masks batch padding rows
    (treated as decided, sorted last).

    Returns (scan0, first3 [top-filtered position-0 (yes, no, relative)],
    sel [slice_m] original batch row per slice row, sub_cache, last_sel,
    len_sel).  Callers must fall back to :func:`models.decoder.prefill`
    when more than ``slice_m`` rows are undecided.

    ``select_all`` (the pooled-confidence leg): EVERY valid row needs the
    scored digit decode, so the undecided filter drops out — the sort key
    is just batch-padding-last and the slice (``slice_m`` = the batch
    size) carries all valid rows, still menu-padded to ``out_len`` so
    cross-bucket pooling holds."""
    last, cache = dmod.prefill(params, cfg, ids, mask, cache_len=cache_len)
    lengths = jnp.sum(mask, axis=-1)
    scan0 = yn.first_token_scan(last, yes_ids, no_ids, top_k=top_k)
    decided = (~valid_rows) if select_all else (scan0[4] | ~valid_rows)
    sel = jnp.argsort(decided, stable=True)[:slice_m]   # undecided first
    sub = dmod.cache_kv_map(
        cache, lambda a: a[:, sel],
        positions=cache.positions[sel], valid=cache.valid[sel],
    )
    if out_len and out_len > cache_len:
        # Pad the slice to the pool's quantized cache length (_POOL_LEN_MENU)
        # INSIDE the prefill program — invalid zero slots the attention bias
        # masks out — so cross-bucket pooling costs zero extra programs.
        # (Zero int8 codes decode to zero under any scale, so the padded
        # slots stay inert in the quantized layout too.)
        pad_t = out_len - cache_len

        def pad_slots(a):  # k/v are [L, m, T, G, D]; scales [L, m, T, G]
            widths = ((0, 0), (0, 0), (0, pad_t)) + ((0, 0),) * (a.ndim - 3)
            return jnp.pad(a, widths)

        sub = dmod.cache_kv_map(
            sub, pad_slots,
            positions=jnp.pad(sub.positions, ((0, 0), (0, pad_t))),
            valid=jnp.pad(sub.valid, ((0, 0), (0, pad_t))),
        )
    first3 = yn.relative_prob_first_token(last, yes_ids, no_ids, top_filter)
    # Deliberately NOT returning the full-batch `last`/`lengths`: the
    # pooled consumer never reads them, and at batch 256 the [B, V] logits
    # alone would pin ~66 MB of dead output per in-flight pipelined batch.
    return scan0, first3, sel, sub, last[sel], lengths[sel]


@jax.jit
def _gather_rows(cache, last, lengths, idx):
    """Gather the phase-2 subset's rows out of the prefill outputs: cache
    k/v (and their int8 per-head scales, when present) are [L, B, T, ...]
    (batch axis 1); everything else batch-leading."""
    sub = dmod.cache_kv_map(
        cache, lambda a: a[:, idx],
        positions=cache.positions[idx], valid=cache.valid[idx],
    )
    return sub, last[idx], lengths[idx]


#: Pad a cache's slot axis to ``out_len`` with inert invalid slots — the
#: host-dispatched twin of _prefill_select's in-program padding, for
#: caches that already exist (the fused confidence leg's suffix-extended
#: cache): zero K/V the attention bias masks out (zero int8 codes decode
#: to zero under any scale), ``valid=False``, position 0.  ONE definition
#: (runtime/slots.py owns it — the ring's newcomer-into-vacated-lane pad
#: is the same rule) so the inert-slot convention can never fork.
_pad_cache_slots = slots_mod._pad_cache_to


def _attach_first_token(row: Dict, first3, i: int) -> Dict:
    """Attach the top-filtered position-0 probabilities (the API
    extractor's top-20-logprobs view, perturb_prompts.py:480-498) that
    every scoring pass computes for free from its prefill logits —
    ``first3`` is a (yes, no, relative) triple of [B] arrays."""
    row["first_token_yes_prob"] = float(first3[0][i])
    row["first_token_no_prob"] = float(first3[1][i])
    row["first_token_relative_prob"] = float(first3[2][i])
    return row


def _result_row(yes, no, rel, odds, found, completion: str) -> Dict:
    """One prompt's result dict — the ``get_yes_no_logprobs`` contract
    (run_base_vs_instruct_100q.py:376-382)."""
    return {
        "yes_prob": float(yes),
        "no_prob": float(no),
        "relative_prob": float(rel),
        "odds_ratio": float(odds),
        "scan_found": bool(found),
        "completion": completion,
        "success": True,
    }


def _error_row(msg: str) -> Dict:
    return {
        "yes_prob": float("nan"),
        "no_prob": float("nan"),
        "relative_prob": float("nan"),
        "odds_ratio": float("nan"),
        "scan_found": False,
        "completion": f"ERROR: {msg[:50]}",
        "success": False,
    }
