"""Sharded training step (fine-tuning capability + multi-chip dry-run target).

The reference only does inference, but instruction-tuning is the phenomenon it
studies; this module adds the capability TPU-first: causal-LM cross-entropy
with optax, params TP-sharded over ``model``, batch over ``data``, activations
optionally sequence-sharded, gradients reduced by XLA's GSPMD partitioner
(psum over ``data`` emitted automatically from the sharding annotations).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from ..models import decoder as dmod
from ..parallel.mesh import DATA_AXIS, SEQ_AXIS
from ..parallel.sharding import param_specs


class TrainState(NamedTuple):
    params: dict
    opt_state: object
    step: jnp.ndarray


def make_optimizer(learning_rate: float = 1e-5, weight_decay: float = 0.01,
                   warmup_steps: int = 100, total_steps: int = 10_000):
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, weight_decay=weight_decay),
    )


def init_train_state(params, optimizer) -> TrainState:
    return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


def causal_lm_loss(params, cfg, token_ids, attention_mask, mesh=None):
    """Next-token cross entropy over real (non-pad) positions, fp32."""
    logits = dmod.forward(params, cfg, token_ids, attention_mask)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        logits = jax.lax.with_sharding_constraint(
            logits, NamedSharding(mesh, P(DATA_AXIS, None, None))
        )
    targets = token_ids[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    weights = (attention_mask[:, 1:] * attention_mask[:, :-1]).astype(jnp.float32)
    return jnp.sum(nll * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def make_train_step(cfg, optimizer, mesh=None, donate: bool = True):
    """Returns a jit'd ``(state, token_ids, attention_mask) -> (state, loss)``.

    With a mesh, input/param shardings are declared so GSPMD partitions the
    whole step (forward, backward, optimizer update) with ICI collectives.
    """

    def step(state: TrainState, token_ids, attention_mask):
        loss, grads = jax.value_and_grad(causal_lm_loss)(
            state.params, cfg, token_ids, attention_mask, mesh
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    donate_argnums = (0,) if donate else ()
    if mesh is None:
        return jax.jit(step, donate_argnums=donate_argnums)

    from jax.sharding import NamedSharding, PartitionSpec as P

    def shard(spec):
        return NamedSharding(mesh, spec)

    def state_shardings(params):
        pspecs = jax.tree.map(lambda s: shard(s), param_specs(params))
        return TrainState(
            params=pspecs,
            # optax state mirrors the param tree for moments; replicate scalars
            opt_state=None,
            step=shard(P()),
        )

    data_sh = shard(P(DATA_AXIS, None))
    return jax.jit(step, donate_argnums=donate_argnums,
                   in_shardings=(None, data_sh, data_sh))
