"""Length-bucketed batching for the jit'd sweep.

Ragged prompt lengths (few-shot prefix ≈150 tokens + question — SURVEY.md §7
hard parts) would either recompile per shape or waste FLOPs on one global pad
length.  Buckets quantize pad lengths to a small fixed set so XLA compiles
once per (bucket_len, batch_size) and stays on cached executables; batches are
padded up to a full batch so every program has a static shape.

The bucket set is deliberately fine-grained (step 16) around the sweep's
dominant prompt shape (few-shot prefix + question ≈ 430 tokens): padding to
432 instead of 512 measures 13% faster on a v5e chip (38.2 vs 34.0
prompts/sec at batch 192; the coarser 448 bucket measured 37.7).  Each extra
bucket costs one compile, amortized by XLA's persistent compilation cache.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..config import DEFAULT_BUCKETS  # single source of truth (stdlib-only module)
from ..obs import tracer as obs
from ..utils.telemetry import record_counter


@dataclasses.dataclass
class Batch:
    token_ids: np.ndarray       # [B, S] int32, right-padded
    attention_mask: np.ndarray  # [B, S] int32
    indices: np.ndarray         # [B] original prompt index, -1 for pad rows
    bucket_len: int


def bucket_for(length: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(f"prompt length {length} exceeds the largest bucket {buckets[-1]}")


def _emit_batch(chunk, batch_size: int, bucket_len: int, pad_id: int) -> Batch:
    token_ids = np.full((batch_size, bucket_len), pad_id, np.int32)
    mask = np.zeros((batch_size, bucket_len), np.int32)
    indices = np.full((batch_size,), -1, np.int64)
    for r, (idx, ids) in enumerate(chunk):
        token_ids[r, : len(ids)] = ids
        mask[r, : len(ids)] = 1
        indices[r] = idx
    # fill pad rows with the first row so the model sees valid tokens
    for r in range(len(chunk), batch_size):
        token_ids[r] = token_ids[0]
        mask[r] = mask[0]
    return Batch(token_ids, mask, indices, bucket_len)


def batches_for_prompts(
    encoded: Sequence[Sequence[int]],
    batch_size: int,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    pad_id: int = 0,
    keep_order_within_bucket: bool = True,
    min_bucket_rows: Optional[int] = None,
    length_sorted: bool = False,
) -> Iterator[Batch]:
    """Emit fixed-shape padded batches for a ragged prompt list.

    Short final batches are padded with duplicate rows (index -1) so the
    compiled program shape never varies with sweep size.

    Two batch-formation strategies:

    ``length_sorted=True`` (the engine default): ALL prompts sort by token
    length and consecutive runs of ``batch_size`` form each batch, padded to
    the bucket of the batch's own longest prompt.  Each prompt then pays
    only the quantization gap to the next menu entry above its batch's max
    — on the real 10k-perturbation corpus (60-203 tokens) this pads x1.13
    vs x1.23 for bucket-grouping with the same menu — and exactly ONE
    partial batch exists per sweep instead of one per occupied bucket.
    Results are keyed by ``indices`` so emission order never affects
    callers' output order.

    ``length_sorted=False``: prompts group by their own bucket and batches
    form within each bucket (preserving input order unless
    ``keep_order_within_bucket=False``).  Buckets holding fewer than
    ``min_bucket_rows`` prompts (default batch_size // 8) merge UPWARD into
    the next occupied larger bucket: a handful of stray lengths is never
    worth a fresh XLA compile (~1.5-4 min per program on a remote-compile
    chip) when padding them into the neighboring shape costs microseconds.
    The largest occupied bucket never merges (there is nowhere to go).
    """
    if length_sorted:
        order = sorted(enumerate(encoded), key=lambda it: len(it[1]))
        for start in range(0, len(order), batch_size):
            chunk = [(idx, list(ids)) for idx, ids in order[start : start + batch_size]]
            bucket_len = bucket_for(len(chunk[-1][1]), buckets)
            yield _emit_batch(chunk, batch_size, bucket_len, pad_id)
        return
    if min_bucket_rows is None:
        min_bucket_rows = max(1, batch_size // 8)
    by_bucket: dict = {}
    for idx, ids in enumerate(encoded):
        b = bucket_for(len(ids), buckets)
        by_bucket.setdefault(b, []).append((idx, list(ids)))
    occupied = sorted(by_bucket)
    for i, b in enumerate(occupied[:-1]):
        if len(by_bucket[b]) < min_bucket_rows:
            by_bucket[occupied[i + 1]] = (
                by_bucket.pop(b) + by_bucket[occupied[i + 1]]
            )
    for bucket_len in sorted(by_bucket):
        items = by_bucket[bucket_len]
        if not keep_order_within_bucket:
            items.sort(key=lambda it: len(it[1]))
        for start in range(0, len(items), batch_size):
            yield _emit_batch(items[start : start + batch_size], batch_size,
                              bucket_len, pad_id)


def rebatch(
    batch: Batch,
    encoded: Sequence[Sequence[int]],
    batch_size: int,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    pad_id: int = 0,
    length_sorted: bool = True,
) -> List[Batch]:
    """Re-bucket one emitted batch's REAL rows at a smaller batch size.

    The engine's OOM back-off path (runtime/faults.py): when a batch's
    device program RESOURCE_EXHAUSTs, its real rows (``indices >= 0``) are
    re-encoded from the original ``encoded`` prompt list and re-emitted as
    fixed-shape batches of ``batch_size`` rows through the ordinary
    :func:`batches_for_prompts` machinery — same buckets, same padding
    discipline — with ``indices`` remapped to the ORIGINAL prompt indices,
    so consumers key results exactly as before and no row is lost or
    duplicated by the retry."""
    rows = batch.indices[batch.indices >= 0]
    sub_encoded = [encoded[int(i)] for i in rows]
    out = []
    for sb in batches_for_prompts(sub_encoded, batch_size, buckets,
                                  pad_id=pad_id, length_sorted=length_sorted):
        sb.indices = np.where(sb.indices >= 0,
                              rows[np.clip(sb.indices, 0, None)], -1)
        out.append(sb)
    return out


def encode_prompts(tokenizer, prompts: Sequence, add_special_tokens: bool = True) -> List[List[int]]:
    """Tokenize a prompt list; entries that are already token-id sequences
    (anything non-str) pass through unchanged.  Pre-tokenized prompts are
    how the host pipeline hands the engine work it encoded on a background
    thread, and how the fused-vs-unfused equivalence tests feed both paths
    the SAME token stream."""
    out: List[Optional[List[int]]] = [None] * len(prompts)
    str_idx = [i for i, p in enumerate(prompts) if isinstance(p, str)]
    if str_idx:
        enc = tokenizer([prompts[i] for i in str_idx],
                        add_special_tokens=add_special_tokens)["input_ids"]
        for i, ids in zip(str_idx, enc):
            out[i] = list(ids)
    for i, p in enumerate(prompts):
        if out[i] is None:
            out[i] = [int(t) for t in p]
    return out


#: Pad-length menu for the fused path's SUFFIX blocks (the per-leg format
#: strings appended to a shared prefix — runtime/engine.score_prefixed).
#: Real response/confidence formats are 8-25 tokens, so the menu is fine
#: at the bottom; anything longer rounds up to a multiple of 64 instead of
#: raising (a long suffix costs padding, never a crash).
SUFFIX_BUCKETS = (8, 16, 24, 32, 48, 64)


def suffix_bucket_for(length: int,
                      buckets: Sequence[int] = SUFFIX_BUCKETS) -> int:
    for b in buckets:
        if length <= b:
            return b
    return -(-length // 64) * 64


def encode_prefix_pairs(
    tokenizer, pairs: Sequence,
) -> Tuple[List[List[int]], List[List[List[int]]]]:
    """Tokenize ``(prefix, suffixes)`` pairs ONCE each for the fused
    prefix-reuse path: prefixes encode with special tokens (they open the
    prompt), suffixes without (they continue it), and both memoize on text
    so a format string shared by 2000 rows — or a few-shot preamble shared
    by 100 questions — tokenizes exactly once per call.  Entries that are
    already token-id sequences pass through.

    Returns ``(prefix_encoded[N], suffix_encoded[n_legs][N])``.
    """
    n_legs = len(pairs[0][1]) if pairs else 0
    memo: dict = {}

    def enc(text, special: bool) -> List[int]:
        if not isinstance(text, str):
            return [int(t) for t in text]
        key = (special, text)
        ids = memo.get(key)
        if ids is None:
            ids = memo[key] = list(tokenizer(
                [text], add_special_tokens=special)["input_ids"][0])
        return list(ids)

    prefix_encoded = []
    suffix_encoded: List[List[List[int]]] = [[] for _ in range(n_legs)]
    for prefix, suffixes in pairs:
        if len(suffixes) != n_legs:
            raise ValueError(
                f"every pair must carry {n_legs} suffixes; got "
                f"{len(suffixes)}")
        prefix_encoded.append(enc(prefix, True))
        for li, suffix in enumerate(suffixes):
            suffix_encoded[li].append(enc(suffix, False))
    return prefix_encoded, suffix_encoded


class HostPrefetcher:
    """Double-buffered host pipeline: compute ``fn(item)`` for work item
    N+1 on a background thread while the caller consumes item N.

    The sweep shells' per-chunk host work (tokenizing ~2000 rephrasings,
    building suffix id lists) is pure CPU and used to run serially between
    engine calls — dead time the device spent idle.  Iterating a
    ``HostPrefetcher(chunks, tokenize_chunk)`` yields ``fn(chunk)`` results
    in order while the NEXT chunk tokenizes concurrently with device
    execution of the current one, closing most of the e2e-vs-steady-state
    host gap (BENCH_r05: 120 e2e vs 128 steady prompts/s).

    Telemetry: the wall time the consumer spends BLOCKED waiting for the
    worker (host work the overlap failed to hide) accumulates in the
    ``host_overlap_idle_ms`` counter, and ``host_overlap_chunks`` counts
    items served — a sweep whose idle stays near zero is fully overlapped.

    Worker exceptions re-raise in the consumer at the failed item's
    position.  ``close()`` (or dropping the iterator mid-way) stops the
    worker — IDEMPOTENTLY: the serve scheduler's shutdown path calls it
    from both the drain loop and ``__exit__``, and the iterator's own
    ``finally`` may already have run, so a second (or third) close is a
    no-op that never double-joins or raises.  The thread is a daemon
    either way, so an abandoned prefetcher can never hang interpreter
    exit."""

    _DONE = object()

    def __init__(self, items: Iterable, fn: Callable, depth: int = 1):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._work, args=(list(items), fn), daemon=True)
        self._thread.start()

    def _work(self, items, fn):
        try:
            for item in items:
                if self._stop.is_set():
                    return
                # tokenize/encode work on the background thread: tagged
                # host_tokenize so the phases block shows how much host
                # prep ran OVERLAPPED with device time (coverage over
                # wall-clock can legitimately exceed 1.0 because of it)
                with obs.span("prefetch", phase="host_tokenize",
                              background=True):
                    result = fn(item)
                self._put((None, result))
        # graftlint: disable=G05 producer-thread relay: the error is stored and re-raised at the consumer's get (classification still sees it there)
        except BaseException as err:
            self._put((err, None))
            return
        self._put((None, self._DONE))

    def _put(self, payload):
        while not self._stop.is_set():
            try:
                self._q.put(payload, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        try:
            while True:
                t0 = time.perf_counter()
                err, result = self._q.get()
                record_counter("host_overlap_idle_ms",
                               (time.perf_counter() - t0) * 1000.0)
                if err is not None:
                    raise err
                if result is self._DONE:
                    return
                record_counter("host_overlap_chunks")
                yield result
        finally:
            # exhaustion, consumer break, or consumer exception all stop
            # the worker — without this an abandoned iterator leaves the
            # thread tokenizing the rest of the corpus and then polling
            # its full queue forever
            self.close()

    @property
    def closed(self) -> bool:
        return self._stop.is_set()

    def close(self):
        """Stop the worker (idempotent; see class docstring).  The first
        close signals the stop event and briefly joins the worker so its
        queue slots free deterministically; later closes return
        immediately."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=1.0)
