"""Length-bucketed batching for the jit'd sweep.

Ragged prompt lengths (few-shot prefix ≈150 tokens + question — SURVEY.md §7
hard parts) would either recompile per shape or waste FLOPs on one global pad
length.  Buckets quantize pad lengths to a small fixed set so XLA compiles
once per (bucket_len, batch_size) and stays on cached executables; batches are
padded up to a full batch so every program has a static shape.

The bucket set is deliberately fine-grained (step 16) around the sweep's
dominant prompt shape (few-shot prefix + question ≈ 430 tokens): padding to
432 instead of 512 measures 13% faster on a v5e chip (38.2 vs 34.0
prompts/sec at batch 192; the coarser 448 bucket measured 37.7).  Each extra
bucket costs one compile, amortized by XLA's persistent compilation cache.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..config import DEFAULT_BUCKETS  # single source of truth (stdlib-only module)


@dataclasses.dataclass
class Batch:
    token_ids: np.ndarray       # [B, S] int32, right-padded
    attention_mask: np.ndarray  # [B, S] int32
    indices: np.ndarray         # [B] original prompt index, -1 for pad rows
    bucket_len: int


def bucket_for(length: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(f"prompt length {length} exceeds the largest bucket {buckets[-1]}")


def _emit_batch(chunk, batch_size: int, bucket_len: int, pad_id: int) -> Batch:
    token_ids = np.full((batch_size, bucket_len), pad_id, np.int32)
    mask = np.zeros((batch_size, bucket_len), np.int32)
    indices = np.full((batch_size,), -1, np.int64)
    for r, (idx, ids) in enumerate(chunk):
        token_ids[r, : len(ids)] = ids
        mask[r, : len(ids)] = 1
        indices[r] = idx
    # fill pad rows with the first row so the model sees valid tokens
    for r in range(len(chunk), batch_size):
        token_ids[r] = token_ids[0]
        mask[r] = mask[0]
    return Batch(token_ids, mask, indices, bucket_len)


def batches_for_prompts(
    encoded: Sequence[Sequence[int]],
    batch_size: int,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    pad_id: int = 0,
    keep_order_within_bucket: bool = True,
    min_bucket_rows: Optional[int] = None,
    length_sorted: bool = False,
) -> Iterator[Batch]:
    """Emit fixed-shape padded batches for a ragged prompt list.

    Short final batches are padded with duplicate rows (index -1) so the
    compiled program shape never varies with sweep size.

    Two batch-formation strategies:

    ``length_sorted=True`` (the engine default): ALL prompts sort by token
    length and consecutive runs of ``batch_size`` form each batch, padded to
    the bucket of the batch's own longest prompt.  Each prompt then pays
    only the quantization gap to the next menu entry above its batch's max
    — on the real 10k-perturbation corpus (60-203 tokens) this pads x1.13
    vs x1.23 for bucket-grouping with the same menu — and exactly ONE
    partial batch exists per sweep instead of one per occupied bucket.
    Results are keyed by ``indices`` so emission order never affects
    callers' output order.

    ``length_sorted=False``: prompts group by their own bucket and batches
    form within each bucket (preserving input order unless
    ``keep_order_within_bucket=False``).  Buckets holding fewer than
    ``min_bucket_rows`` prompts (default batch_size // 8) merge UPWARD into
    the next occupied larger bucket: a handful of stray lengths is never
    worth a fresh XLA compile (~1.5-4 min per program on a remote-compile
    chip) when padding them into the neighboring shape costs microseconds.
    The largest occupied bucket never merges (there is nowhere to go).
    """
    if length_sorted:
        order = sorted(enumerate(encoded), key=lambda it: len(it[1]))
        for start in range(0, len(order), batch_size):
            chunk = [(idx, list(ids)) for idx, ids in order[start : start + batch_size]]
            bucket_len = bucket_for(len(chunk[-1][1]), buckets)
            yield _emit_batch(chunk, batch_size, bucket_len, pad_id)
        return
    if min_bucket_rows is None:
        min_bucket_rows = max(1, batch_size // 8)
    by_bucket: dict = {}
    for idx, ids in enumerate(encoded):
        b = bucket_for(len(ids), buckets)
        by_bucket.setdefault(b, []).append((idx, list(ids)))
    occupied = sorted(by_bucket)
    for i, b in enumerate(occupied[:-1]):
        if len(by_bucket[b]) < min_bucket_rows:
            by_bucket[occupied[i + 1]] = (
                by_bucket.pop(b) + by_bucket[occupied[i + 1]]
            )
    for bucket_len in sorted(by_bucket):
        items = by_bucket[bucket_len]
        if not keep_order_within_bucket:
            items.sort(key=lambda it: len(it[1]))
        for start in range(0, len(items), batch_size):
            yield _emit_batch(items[start : start + batch_size], batch_size,
                              bucket_len, pad_id)


def rebatch(
    batch: Batch,
    encoded: Sequence[Sequence[int]],
    batch_size: int,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    pad_id: int = 0,
    length_sorted: bool = True,
) -> List[Batch]:
    """Re-bucket one emitted batch's REAL rows at a smaller batch size.

    The engine's OOM back-off path (runtime/faults.py): when a batch's
    device program RESOURCE_EXHAUSTs, its real rows (``indices >= 0``) are
    re-encoded from the original ``encoded`` prompt list and re-emitted as
    fixed-shape batches of ``batch_size`` rows through the ordinary
    :func:`batches_for_prompts` machinery — same buckets, same padding
    discipline — with ``indices`` remapped to the ORIGINAL prompt indices,
    so consumers key results exactly as before and no row is lost or
    duplicated by the retry."""
    rows = batch.indices[batch.indices >= 0]
    sub_encoded = [encoded[int(i)] for i in rows]
    out = []
    for sb in batches_for_prompts(sub_encoded, batch_size, buckets,
                                  pad_id=pad_id, length_sorted=length_sorted):
        sb.indices = np.where(sb.indices >= 0,
                              rows[np.clip(sb.indices, 0, None)], -1)
        out.append(sb)
    return out


def encode_prompts(tokenizer, prompts: Sequence[str], add_special_tokens: bool = True) -> List[List[int]]:
    out = tokenizer(list(prompts), add_special_tokens=add_special_tokens)["input_ids"]
    return [list(ids) for ids in out]
