"""Shared fault-tolerance layer for the runtime and the sweep shells.

The north-star workloads (study-3 local inference and the 10k-perturbation
sweep) run for hours on shared/preemptible TPU slices, where co-tenant
RESOURCE_EXHAUSTED and SIGTERM preemption are routine operating conditions,
not exceptional ones — the TPUv4 pjit-training literature treats both as
normal for long-running pod jobs (PAPERS.md, "Scalable Training of Language
Models using JAX pjit and TPUv4").  This module centralizes the policies the
r5 bench proved out in its private copy (`bench.py` "Shared-chip OOM
resilience") so the engine and every sweep shell share one implementation:

- :func:`is_oom` / :func:`oom_detail` — normalized device-OOM detection
  across the spellings the stack produces, plus a truncated diagnostic
  string so a misclassified RESOURCE_EXHAUSTED (RPC/quota vs HBM) leaves a
  trail in stderr/telemetry.
- :func:`next_batch_down` + :data:`MEASURED_SWEEP_LADDER` — the measured
  batch back-off ladder (384/352 → 320 → 256 at the sweep's ~107-token
  operating point), falling back to halving between ladder points.  The
  engine's per-batch retry and the bench's per-repeat step-down both walk
  this.
- :func:`sweep_oom_action` — the bench's skip-or-step-down policy for a
  mid-repeat OOM (kept best-of when an earlier repeat succeeded; one batch
  step-down and retry otherwise).
- :func:`is_transient` / :func:`retry_transient` — the RetryPolicy-based
  transient-retry path shared with :mod:`..utils.retry`: wraps an engine
  call so RPC hiccups and connection resets retry with backoff while real
  errors (shape bugs, OOM — which has its own path) propagate immediately.
- :class:`PreemptionGuard` — SIGTERM/SIGINT handler that flushes registered
  checkpoint state (side-log rows, CheckpointFile/ProcessedSet saves)
  before exiting, so a preempted 10k sweep resumes losing at most the
  in-flight chunk.

Deliberately jax-free: importable by `bench.py`, the sweep shells, and
tests without touching the device runtime.

Env knobs (documented in README.md "Fault tolerance"):

- ``LLM_INTERP_OOM_BACKOFF=0``   disable the engine's per-batch OOM retry
- ``LLM_INTERP_OOM_FLOOR=N``     smallest batch the engine steps down to
- ``LLM_INTERP_OOM_LADDER=a,b``  explicit engine back-off ladder
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import Callable, Optional, Sequence, Tuple

from ..utils.retry import RetryPolicy, retry_with_exponential_backoff
from ..utils.telemetry import record_fault

__all__ = [
    "MEASURED_SWEEP_LADDER",
    "Preempted",
    "PreemptionGuard",
    "TransientError",
    "fleet_backoff_delay",
    "fleet_backoff_policy",
    "is_oom",
    "is_transient",
    "next_batch_down",
    "oom_detail",
    "retry_transient",
    "split_for_requeue",
    "sweep_oom_action",
]


# ---------------------------------------------------------------------------
# OOM classification
# ---------------------------------------------------------------------------

def is_oom(err: BaseException) -> bool:
    """Device out-of-memory, across the spellings the stack produces:
    'RESOURCE_EXHAUSTED' (status code), 'ResourceExhausted' (class name),
    'Resource exhausted: Out of memory' (absl status text)."""
    s = str(err).lower().replace("_", "").replace(" ", "")
    return "resourceexhausted" in s


def oom_detail(err: BaseException, limit: int = 160) -> str:
    """One-line truncated error text for OOM skip/retry messages.

    RESOURCE_EXHAUSTED is not always HBM: the tunneled runtime can surface
    RPC/quota exhaustion under the same status code.  Including the raw
    (truncated) text in every skip/retry message leaves a diagnostic trail
    when a misclassification silently changes the recorded operating
    point."""
    text = " ".join(str(err).split())
    return text[:limit] + ("..." if len(text) > limit else "")


# ---------------------------------------------------------------------------
# Batch back-off ladder
# ---------------------------------------------------------------------------

#: Measured e2e-sweep operating points at the real corpus' ~107-token shape
#: (v5e, 2026-07): 320 runs 120.5-120.9 p/s warm, 256 runs 111.8-112.1;
#: 384 and 352 OOM.  A sweep batch that OOMs therefore steps 384/352 → 320
#: → 256 — each landing on a fully-measured point — instead of jumping flat
#: to 256 and skipping the better 320 point.
MEASURED_SWEEP_LADDER: Tuple[int, ...] = (320, 256)


def next_batch_down(batch: int, ladder: Sequence[int] = (),
                    floor: int = 1) -> Optional[int]:
    """Next smaller batch size on the back-off ladder, or None at the floor.

    Walks ``ladder`` (descending measured operating points) first: the
    largest entry strictly below ``batch``.  Below the ladder (or with no
    ladder) the batch halves.  Never returns a value below ``floor``;
    returns None when ``batch`` is already at/below the floor, signalling
    the caller to re-raise.  ``floor`` clamps to 1: a zero floor (e.g.
    ``LLM_INTERP_OOM_FLOOR=0`` meaning "no floor") must step to batch 1,
    never to an unlaunchable batch 0."""
    floor = max(1, int(floor))
    if batch <= floor:
        return None
    for step in sorted(ladder, reverse=True):
        if step < batch:
            return max(floor, int(step))
    return max(floor, batch // 2)


def split_for_requeue(rows: int, current: int, ladder: Sequence[int] = (),
                      floor: int = 1
                      ) -> Optional[Tuple[int, Tuple[int, ...]]]:
    """Serve-path OOM composition rule: ``(new_batch, chunk_sizes)`` for a
    micro-batch that must re-enter the scheduler QUEUE (never the engine's
    in-place retry — the scheduler owns serve-path recovery so queued
    traffic keeps flowing between retries).

    ``current`` is the engine batch size the failed launch ran at;
    ``new_batch`` is the next ladder step down (:func:`next_batch_down` —
    the PR-1 machinery) and ``chunk_sizes`` partitions the micro-batch's
    ``rows`` real rows into re-queue chunks of at most ``new_batch`` rows
    each, so every re-entered chunk fits one stepped-down device batch.
    ``None`` at the floor: the caller fails the requests with the original
    error instead of splitting forever."""
    new_batch = next_batch_down(current, ladder=ladder, floor=floor)
    if new_batch is None:
        return None
    sizes = [new_batch] * (rows // new_batch)
    if rows % new_batch:
        sizes.append(rows % new_batch)
    return new_batch, tuple(sizes)


def _env_flag(name: str, default: bool) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() not in ("0", "false", "no", "off", "")


def _env_int(name: str, default: int) -> int:
    val = os.environ.get(name)
    try:
        return int(val) if val else default
    except ValueError:
        return default


def _env_ladder(name: str, default: Tuple[int, ...]) -> Tuple[int, ...]:
    val = os.environ.get(name)
    if not val:
        return default
    try:
        return tuple(int(p) for p in val.replace(";", ",").split(",") if p.strip())
    except ValueError:
        return default


def default_engine_backoff() -> bool:
    return _env_flag("LLM_INTERP_OOM_BACKOFF", True)


def default_engine_floor() -> int:
    return _env_int("LLM_INTERP_OOM_FLOOR", 8)


def default_engine_ladder() -> Tuple[int, ...]:
    return _env_ladder("LLM_INTERP_OOM_LADDER", ())


# ---------------------------------------------------------------------------
# Bench / repeat-level OOM policy (moved from bench.py's private copy)
# ---------------------------------------------------------------------------

def sweep_oom_action(err, batch: int, rep, had_success, floor,
                     fallback: Callable[[int], int], label: str
                     ) -> Tuple[str, Optional[int]]:
    """Shared skip-or-step-down policy for a mid-repeat device OOM.

    The sweep operating points sit near the HBM edge and the chip is
    SHARED: a co-tenant's allocation can RESOURCE_EXHAUST a repeat that
    ran clean three times (observed 2026-07: repeat 0 at 110 s, repeat 1
    ResourceExhausted).  The driver records the bench's single JSON line
    every round, so a flaky OOM must never sink the whole record.

    Pure policy over ``batch``, the repeat's current batch size: returns
    ``("skip", None)`` (an earlier repeat succeeded: keep best-of) or
    ``("retry", new_batch)`` (no success yet: step down via ``fallback``
    — the caller applies ``new_batch`` to its own config); re-raises for
    non-OOM errors or when already at ``floor``.  Every path prints the
    truncated error text so misclassified RESOURCE_EXHAUSTED (RPC/quota
    vs HBM) is auditable, and records a telemetry fault event."""
    if not is_oom(err):
        raise err
    detail = oom_detail(err)
    if had_success:
        print(f"# {label} repeat {rep}: device OOM (shared chip); "
              f"keeping earlier repeat(s) [{detail}]", file=sys.stderr)
        record_fault("sweep_oom_skip", label=label, repeat=rep, error=detail)
        return "skip", None
    if batch > floor:
        new_batch = max(floor, fallback(batch))
        print(f"# {label} repeat {rep}: device OOM at batch "
              f"{batch}; falling back to {new_batch} [{detail}]",
              file=sys.stderr)
        record_fault("sweep_oom_backoff", label=label, repeat=rep,
                     batch=batch, new_batch=new_batch, error=detail)
        return "retry", new_batch
    raise err


# ---------------------------------------------------------------------------
# Transient-error retry (shared with utils/retry.py)
# ---------------------------------------------------------------------------

class TransientError(RuntimeError):
    """Marker for injected/known-transient failures (utils/testing.py)."""


#: Exception classes retried as transient.  OOM is deliberately excluded —
#: it has its own back-off path (the batch ladder); retrying an OOM at the
#: same shape only reproduces it.
TRANSIENT_ERROR_TYPES: Tuple[type, ...] = (
    TransientError, ConnectionError, TimeoutError, BrokenPipeError,
)

#: Substrings marking a transient failure when the class is generic (the
#: tunneled runtime wraps RPC errors in RuntimeError).
_TRANSIENT_MARKERS = ("unavailable", "deadline exceeded", "connection reset",
                      "transient", "temporarily")


def is_transient(err: BaseException) -> bool:
    """Worth retrying in place: RPC hiccups, resets, injected transients —
    never OOM (which steps the batch down instead) and never ordinary
    programming errors."""
    if is_oom(err):
        return False
    if isinstance(err, TRANSIENT_ERROR_TYPES):
        return True
    text = str(err).lower()
    return any(m in text for m in _TRANSIENT_MARKERS)


def default_transient_policy() -> RetryPolicy:
    """Local-engine transient policy: 3 quick retries (the reference's 60 s
    API ladder is for rate limits; a local RPC hiccup clears in seconds)."""
    return RetryPolicy(max_retries=3, initial_delay=2.0, max_delay=30.0,
                       retry_predicate=is_transient)


def fleet_backoff_policy(initial_delay_s: float = 1.0,
                         max_delay_s: float = 60.0,
                         max_retries: int = 5) -> RetryPolicy:
    """Fleet-event backoff: FULL jitter (delay uniform in [0, clamped
    base]) instead of the multiplicative [0.8, 1.2] band.  When a replica
    dies, every failing-over request and every rebuild attempt starts its
    clock at the same instant — multiplicative jitter keeps them within
    +-20% of lockstep and the whole herd lands on the rebuilt replica at
    once.  Full jitter spreads them across the entire window (the AWS
    exponential-backoff result the serving literature leans on)."""
    return RetryPolicy(max_retries=max_retries,
                       initial_delay=initial_delay_s,
                       max_delay=max_delay_s, full_jitter=True)


def fleet_backoff_delay(attempt: int,
                        policy: Optional[RetryPolicy] = None) -> float:
    """The full-jittered delay before rebuild/failover ``attempt``
    (0-based) under ``policy`` (default :func:`fleet_backoff_policy`).
    A function, not an inlined formula, so the supervisor and any future
    fleet actor share ONE jitter discipline."""
    return (policy or fleet_backoff_policy()).delay_for_attempt(attempt)


def retry_transient(fn: Callable, policy: Optional[RetryPolicy] = None,
                    label: str = "") -> Callable:
    """Wrap ``fn`` so transient errors retry per ``policy`` (default
    :func:`default_transient_policy`), recording a telemetry fault event
    per retried error.  Non-transient errors propagate immediately.

    A transient error that STILL propagates means the retry budget is
    exhausted — the run is about to lose work — so that case records a
    distinct ``transient_exhausted`` fault event (an obs/ flight-recorder
    trigger) before re-raising."""
    import dataclasses as dc

    policy = policy or default_transient_policy()
    inner = policy.retry_predicate or is_transient
    name = label or getattr(fn, "__name__", "")

    def recording_predicate(err: BaseException) -> bool:
        if not inner(err):
            return False
        record_fault("transient_retry", label=name, error=oom_detail(err))
        return True

    policy = dc.replace(policy, retry_predicate=recording_predicate)
    retrying = retry_with_exponential_backoff(policy)(fn)

    def run(*args, **kwargs):
        try:
            return retrying(*args, **kwargs)
        except Exception as err:
            if inner(err):
                record_fault("transient_exhausted", label=name,
                             retries=policy.max_retries,
                             error=oom_detail(err))
            raise

    return run


# ---------------------------------------------------------------------------
# Preemption guard
# ---------------------------------------------------------------------------

class Preempted(SystemExit):
    """Raised (from the signal handler) after checkpoint state is flushed.

    Subclasses SystemExit so an unguarded production run exits with the
    conventional 128+signum code, while tests catch it explicitly."""

    def __init__(self, signum: int):
        super().__init__(128 + int(signum))
        self.signum = int(signum)


class PreemptionGuard:
    """Flush checkpoint state on SIGTERM/SIGINT, then exit.

    Shared/preemptible slices deliver SIGTERM with a short grace window; a
    sweep that dies mid-chunk without flushing loses every pending side-log
    row since the last ``checkpoint_every`` threshold.  Installed around a
    sweep's chunk loop::

        with PreemptionGuard(flush, label="perturbation"):
            for chunk in chunks: ...

    On SIGTERM/SIGINT each registered flush callback runs once (exceptions
    in one flush never block the next), a telemetry fault event records the
    preemption, and :class:`Preempted` (SystemExit) / KeyboardInterrupt is
    raised in the main thread — so the sweep resumes losing at most the
    in-flight chunk.  Handlers are restored on exit; nesting composes (the
    inner guard defers to the previously-installed handler's flushes by
    restoring them).  Outside the main thread signal handlers cannot be
    installed; the guard then degrades to a no-op rather than failing the
    sweep."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, *flushes: Callable[[], None], label: str = "",
                 signals: Optional[Sequence[int]] = None):
        self.flushes = list(flushes)
        self.label = label
        self.signals = tuple(signals) if signals is not None else self.SIGNALS
        self.triggered: Optional[int] = None
        self.active = False
        self._previous = {}

    def add_flush(self, fn: Callable[[], None]) -> None:
        self.flushes.append(fn)

    def _handler(self, signum, frame):
        self.triggered = signum
        self.flush(reason=f"signal {signum}")
        record_fault("preempted", label=self.label, signum=int(signum))
        if signum == signal.SIGINT:
            raise KeyboardInterrupt
        raise Preempted(signum)

    def flush(self, reason: str = "") -> None:
        """Run every registered flush once, guarding each: a failing flush
        (e.g. a full disk) must not block the remaining checkpoint state
        from landing inside the grace window."""
        for fn in self.flushes:
            try:
                fn()
            # graftlint: disable=G05 preemption grace window: a failing flush (full disk) must not block the remaining checkpoint state from landing
            except Exception as err:  # pragma: no cover - best-effort path
                print(f"# preemption flush failed ({reason}): {err}",
                      file=sys.stderr)

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self  # signals only deliverable to the main thread
        try:
            for sig in self.signals:
                self._previous[sig] = signal.signal(sig, self._handler)
            self.active = True
        except (ValueError, OSError):  # non-main thread / exotic platform
            for sig, prev in self._previous.items():
                signal.signal(sig, prev)
            self._previous.clear()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._previous.clear()
        self.active = False
