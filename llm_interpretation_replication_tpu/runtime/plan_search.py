"""Auto-parallel plan search: pick the operating point from the model.

Every operating point in BENCH_r01-r05 and MULTICHIP_r01-r05 was
hand-chosen, even though the repo already owns the pieces a search needs:
dp/tp/pp mesh axes (parallel/), a calibrated kv-dtype- and pool-aware HBM
budget model (runtime/plan.py), and measured rows/s anchors in the bench
records.  Following AMP (arxiv 2210.07297) and the pjit/TPUv4 scaling
playbook (arxiv 2204.06514), this module enumerates the candidate space —

    mesh shapes over the device count (parallel/mesh.enumerate_mesh_shapes)
    x batch (sublane-aligned step-32 ladder)
    x kv_dtype {bf16, int8}
    x prefill_chunk {0, 64, 128, 256}
    x pooled-confidence pool target

— rejects candidates that violate the per-device HBM budget (the SAME
``need()`` terms resolve_full_sweep_plan sums, via
plan.full_study_need_terms, each divided across the mesh axis that shards
it), and ranks survivors by a predicted-rows/s cost model calibrated
against the measured anchor points.  The chosen plan plus a ranked
runner-up table with per-candidate fit/reject reasons goes into the bench
JSON record (auditable, in the style of the PR-5 fit-decision string);
the PR-1 OOM back-off ladder stays armed as the safety net when the
prediction misses on hardware.

The search is ADVISORY: it picks shapes and batch sizes, never touches
scoring numerics (PARITY.md "Plan search").

Cost model
----------
``rate(B) = CEIL * sat(B_dev)`` with ``sat(b) = b / (b + HALF)`` — a
saturating per-device rate in binary-leg rows/s.  The two coefficients are
solved from the measured BENCH_r05 pair (120.15 p/s at batch 320, 112.0 at
256, same code); the full-study work factor from the measured 31.64 rows/s
at batch 224 against the same curve.  Mesh axes apply as a data-parallel
multiplier (each device runs ``B/dp`` rows), a tensor-parallel collective
penalty per extra tp degree (the pjit playbook's ICI overhead regime), and
small measured-magnitude penalties for int8-KV dequant and chunked
prefill.  Every coefficient is a literal pinned in
tests/test_plan_search.py so the estimator cannot silently drift — the
PR-5 anchor discipline.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from . import plan as plan_mod
from .plan import (
    HBM_BYTES_V5E,
    RESERVE_BYTES,
    THRASH_HEADROOM_BYTES,
    budget_audit,
    budget_reject,
    full_study_need_terms,
    weight_bytes,
)

# ---------------------------------------------------------------------------
# Calibrated cost-model coefficients (anchor-pinned in tests)
# ---------------------------------------------------------------------------

#: Saturating per-device binary-leg rate: rows/s ceiling and the per-device
#: batch at half ceiling.  Solved from the measured BENCH_r05 pair —
#: 120.15 p/s at batch 320 and 112.0 at batch 256 on identical code:
#: 169.5 * 320/(320+131.4) = 120.2, * 256/(256+131.4) = 112.0.
ROWS_CEILING = 169.5  # anchor: BENCH_r05
BATCH_HALF_SAT = 131.4  # anchor: BENCH_r05
#: Binary-leg equivalents per full-study row, solved against the same
#: curve from the measured 31.64 rows/s at batch 224:
#: 169.5 * 224/(224+131.4) / 3.38 = 31.6.  (ROADMAP's ~3.8 figure divides
#: the BATCH-320 binary rate by the batch-224 full rate and so mixes two
#: batch efficiencies; the work factor here is batch-controlled.)
FULL_STUDY_WORK = 3.38  # anchor: BENCH_r05
#: Collective overhead per extra tensor-parallel degree (all-reduce per
#: projection riding ICI — the arxiv 2204.06514 overhead regime; the
#: MULTICHIP legs are parity runs on virtual CPU devices, so this is a
#: playbook prior, not a measured v5e number: revisit at the first real
#: multi-chip bench).
TP_COMM_PENALTY = 0.07  # prior: pjit-playbook guess, no multi-chip bench yet
#: int8 KV dequant-at-the-readers cost (PARITY.md: the quantize/dequant
#: epilogues are VPU work overlapping the weight streams; small).
INT8_KV_PENALTY = 0.02  # prior: PARITY.md overlap argument, unmeasured
#: Chunked-prefill replay overhead PER EXTRA CHUNK (PR-5: chunked prefill
#: re-enters the suffix-extension program once per chunk beyond the
#: first; near-noise at chunk 128 / the 256-token bucket, i.e. one
#: replay).  Scaling by replay count — not a flat nonzero-chunk tax —
#: keeps chunk 64 (3 replays at seq 256) from tying chunk 128 (1 replay)
#: and winning on an arbitrary tie-break.
CHUNK_PENALTY = 0.01  # prior: replay-count model, unmeasured
#: Parameter count of the falcon-7b bench geometry the coefficients were
#: calibrated on; other geometries scale the rate by params ratio (per-row
#: FLOPs are ~proportional to parameter count in this regime).
CALIBRATION_PARAMS = 6_921_420_800  # anchor: BENCH_r05

# -- joint next-K-token decode (ISSUE 13 — models/decoder.k_verify_block) ---
#: Per-position proposal-accept prior for the K-head on this system's
#: decode legs.  A PRIOR, not a measurement: both legs are short, highly
#: predictable continuations (digit positions with an early-settling
#: first-int parse; EOS-terminated completions), the K-Forcing regime
#: (arxiv 2606.10820).  Recalibrate from the first driver bench record's
#: ``k_decode.accepted_k_hist`` (the block exists for exactly this).
K_ACCEPT_PRIOR = 0.9  # prior: K-Forcing regime, await accepted_k_hist
#: Fraction of the full-study per-row work spent in the two decode legs —
#: what K-decode can touch (Amdahl).  Derived from the phases-block
#: shape of the r05-era decomposition (decode launches dominate per-row
#: time after the prefill-side wins); a prior until a K>1 bench record
#: exists, like the accept prior above.
K_DECODE_SHARE = 0.55  # prior: r05 phases-block shape, await K>1 record
#: decode_k values the full-study search enumerates (1 = the sequential
#: baseline row in the runner-up table).
DEFAULT_DECODE_KS = (1, 2, 4, 8)


def k_decode_speedup(decode_k: int, accept: float = K_ACCEPT_PRIOR) -> float:
    """Expected decode-leg speedup of verify-and-accept at block size K.

    Per proposed block: position 0 is the free exact argmax, positions
    1..K-1 each hold with probability ``accept``, and acceptance is
    all-or-nothing per block (the engine's parity rule —
    runtime/engine._k_decode_chunk): with probability ``accept^(K-1)``
    the block costs ~1 weight stream for K tokens, otherwise the pass is
    wasted and the block's positions re-run sequentially (1 + K
    streams).  Speedup = K / expected streams — non-monotone in K, which
    is the whole point of pricing the axis instead of hardcoding a
    block size.

    The closed form is exact when the block IS the chunk (n == K) and
    OPTIMISTIC for multi-block chunks: the engine's fallback is
    chunk-granular (a late block's reject re-runs the whole n-position
    chunk, wasting earlier accepted blocks' passes too).  That optimism
    is part of why both coefficients are PRIORS — the first driver
    record's measured ``k_decode`` block (accepted-K histogram + reject
    rate) is the recalibration input that replaces them."""
    k = int(decode_k)
    if k <= 1:
        return 1.0
    p_blk = accept ** (k - 1)
    return k / (p_blk + (1.0 - p_blk) * (1.0 + k))

# -- disaggregated prefill/decode roles (ISSUE 20 — serve/pool.py) ----------
#: Fraction of a symmetric binary-scoring row's wall time spent in
#: prefill + the position-0 scan (the remainder is the pooled phase-2
#: decode leg a ``role="prefill"`` replica never runs).  Shaped from the
#: r05 phases-block decomposition — the monolithic prefill launch
#: dominates per-row time once the pool amortizes decode — and a PRIOR
#: until a roles bench record (``serve_load_pool`` with a
#: ``prefill:N,decode:M`` roster) measures the split directly.
PREFILL_PHASE_SHARE = 0.72  # prior: r05 phases-block shape, await roles record
#: Slot-ring residency gain a ``role="decode"`` specialist sees from
#: imported KV slabs: its ring refills from the cross-replica handoff
#: queue instead of stalling on its own prefill, so pool-target
#: candidates run nearer capacity (the occupancy block's mean-occupancy
#: tail is the recalibration input).
DECODE_REFILL_GAIN = 1.08  # prior: occupancy-block tail model, await roles record


def role_rate_factor(role: Optional[str], *, prefill_chunk: int = 0,
                     seq: int = 256, pool_target: int = 0,
                     decode_k: int = 1) -> float:
    """Multiplier taking a SYMMETRIC binary-workload rate estimate to a
    role-specialist estimate (serve/pool.py disaggregation).

    ``"prefill"``: the replica runs only the prefill share of each row,
    so per-chip row throughput rises by ~1/PREFILL_PHASE_SHARE — but
    chunk replays now charge against the prefill-only row instead of
    being diluted by decode time, so chunked candidates separate harder
    than under symmetric pricing (the ISSUE's "prefill replicas weight
    chunked-prefill terms").  ``"decode"``: only the decode share, with
    the slot-refill residency gain on pooled candidates and the full
    (un-Amdahled) K-decode speedup — a specialist's whole row IS the
    decode leg.  ``None`` returns 1.0."""
    if role is None:
        return 1.0
    if role == "prefill":
        replays = 0
        if prefill_chunk and prefill_chunk < seq:
            replays = -(-seq // prefill_chunk) - 1
        # un-apply the symmetric chunk discount, then charge the replay
        # cost absolutely against the prefill-only row
        sym = max(0.05, 1.0 - CHUNK_PENALTY * replays)
        return 1.0 / (sym * (PREFILL_PHASE_SHARE
                             + CHUNK_PENALTY * replays))
    if role == "decode":
        factor = 1.0 / (1.0 - PREFILL_PHASE_SHARE)
        if pool_target:
            factor *= DECODE_REFILL_GAIN
        if decode_k > 1:
            factor *= k_decode_speedup(decode_k)
        return factor
    raise ValueError(
        f"role must be None, 'prefill', or 'decode': {role!r}")


# -- packed batch prompting (ISSUE 10 — scoring/packed.py) ------------------
#: Mean question tokens of the real perturbation corpus (the bench's own
#: stderr line: "token lengths mean 104" on the 10k rephrasings at the
#: sweep tokenizer; the sweep secondary measures its steady state at the
#: same 104-token point).
PACKED_QUESTION_TOKENS = 104.0  # prior: corpus tokenizer mean, no packed record
#: Per-ROW shared scaffold tokens an isolated prompt pays once (the format
#: suffix — the " Answer only 'Yes' or 'No'." texts tokenize to ~16 via
#: the sweep tokenizer); a packed row pays it once per Q questions.
PACKED_SHARED_TOKENS = 16.0  # prior: suffix tokenization count, no packed record
#: Demonstration-continuation tokens per packed question (scoring/packed.
#: format_demo: " {answer}.\n\n" plus the answer token — ~12 through the
#: sweep tokenizer) — the overhead packing pays that isolated rows don't.
PACKED_DEMO_TOKENS = 12.0  # prior: format_demo tokenization, no packed record
#: Throughput the packed path recovers by having NO decode path at all:
#: the r01-r04 steady-state anchors put the single forward at 38.15 p/s
#: against the two-phase parity mode's 36.9 — the pooled phase-2 decode
#: overhead packed rows never pay.  38.15 / 36.9 = 1.034.
PACKED_NO_DECODE_GAIN = 1.034  # anchor: BENCH_r01
#: Packing factors the search enumerates (1 shows the demo-overhead
#: tradeoff in the runner-up table; the attention transient's quadratic
#: growth in the packed row length prices out large Q on its own).
DEFAULT_PACKINGS = (1, 2, 4, 8)
#: Per-device transient slack for the packed sweep beyond plan.py's
#: reserve: the anchor-gather epilogue and host staging of the [B, K]
#: result arrays — no pool, no completion caches, so a quarter GiB
#: covers it (no measured OOM boundary exists yet for this workload;
#: recalibrate from the first real packed bench the way
#: BINARY_SWEEP_HEADROOM_BYTES was).
PACKED_SWEEP_HEADROOM_BYTES = 1 << 28  # prior: no measured packed OOM boundary


def packed_seq_tokens(packing: int,
                      question_tokens: float = PACKED_QUESTION_TOKENS,
                      shared_tokens: float = PACKED_SHARED_TOKENS,
                      demo_tokens: float = PACKED_DEMO_TOKENS) -> int:
    """Expected packed-row token length at one packing factor: the shared
    scaffold once per row plus Q (question + demonstration) segments."""
    return int(round(shared_tokens
                     + packing * (question_tokens + demo_tokens)))


#: Extra per-device headroom for the BINARY sweep beyond plan.py's reserve:
#: the pooled phase-2 path holds the menu-capped cross-batch pool
#: (EngineConfig.phase2_pool_max_bytes, 512 MiB) plus depth-4 in-flight
#: logits, and the measured r5 boundary — batch 320 runs 120.5-120.9 p/s
#: warm while 352/384 ResourceExhaust at fragmentation level — sits well
#: inside the naive weights+scores+activations sum.  1.75 GiB is
#: calibrated so the model reproduces that exact boundary (fits 320,
#: rejects 352); anchor-pinned in tests like every other coefficient.
BINARY_SWEEP_HEADROOM_BYTES = 7 << 28  # anchor: BENCH_r05

# ---------------------------------------------------------------------------
# Candidate space defaults
# ---------------------------------------------------------------------------

DEFAULT_BATCH_LADDER = tuple(range(32, 513, 32))
DEFAULT_KV_DTYPES = ("bf16", "int8")
DEFAULT_PREFILL_CHUNKS = (0, 64, 128, 256)
#: Pool targets for the pooled-confidence decode: 0 = the engine default
#: (pool at batch size); the nonzero entries are the r7 menu sizes the
#: confidence pool quantizes well onto (plan.CONF_POOL_LEN_MENU).
DEFAULT_POOL_TARGETS = (0, 192, 320)

#: The hand-picked dp x tp scoring mesh of MULTICHIP_r05 — the operating
#: point the dryrun leg must reproduce or beat.
HAND_PICKED_MULTICHIP = {"data": 4, "pipe": 1, "model": 2}


@dataclasses.dataclass(frozen=True)
class PlanCandidate:
    """One point of the search space with its budget verdict and rank."""

    data: int
    pipe: int
    model: int
    batch: int
    kv_dtype: str
    prefill_chunk: int
    pool_target: int            # 0 = pool at batch size (engine default)
    fits: bool
    reason: str                 # fit/reject audit (plan.budget_audit spelling)
    need_bytes: int             # per-device live set (0 when pre-budget reject)
    predicted_rows_per_s: float  # 0.0 when rejected
    packing: int = 1            # questions per packed row (1 = isolated;
                                # > 1 only on the "packed" workload)
    decode_k: int = 1           # joint K-token decode block size (1 = the
                                # sequential path; > 1 only on the "full"
                                # workload — the legs K-decode touches)

    @property
    def mesh_shape(self) -> Dict[str, int]:
        return {"data": self.data, "pipe": self.pipe, "model": self.model}

    def as_record(self) -> Dict:
        """JSON-able row for the bench record's runner-up table."""
        return {
            "mesh": self.mesh_shape,
            "batch": self.batch,
            "kv_dtype": self.kv_dtype,
            "prefill_chunk": self.prefill_chunk,
            "pool_target": self.pool_target,
            "packing": self.packing,
            "decode_k": self.decode_k,
            "fits": self.fits,
            "predicted_rows_per_s": round(self.predicted_rows_per_s, 2),
            "need_gib": round(self.need_bytes / 2**30, 2),
            "reason": self.reason,
        }


def predicted_rows_per_s(cfg, data: int, model: int, batch: int,
                         kv_dtype: str = "bf16", prefill_chunk: int = 0,
                         workload: str = "full", seq: int = 256,
                         packing: int = 1, decode_k: int = 1) -> float:
    """Calibrated throughput estimate for one candidate (module docstring).

    ``workload``: "binary" (the yes/no scoring sweep, prompts/s), "full"
    (the two-leg full-study row contract, rows/s), or "packed" (anchor-
    gathered batch prompting, questions/s — ``batch`` then counts PACKED
    ROWS and ``packing`` questions ride each row).  ``seq`` sizes the
    chunked-prefill replay count (extra chunks beyond the first each cost
    CHUNK_PENALTY).

    The packed estimate reuses the binary saturating curve at the
    QUESTION batch (Q questions per row saturate the device like Q rows
    — prefill FLOPs are token-proportional), scaled by (a) the
    no-decode gain (PACKED_NO_DECODE_GAIN: anchor gather replaces the
    whole phase-2 decode) and (b) the per-question token ratio — an
    isolated question pays the shared scaffold every row, a packed one
    amortizes it across Q but pays its demonstration continuation:
    ``(SHARED + QUESTION) / (SHARED/Q + QUESTION + DEMO)``."""
    per_dev_batch = batch / data
    if workload == "packed":
        per_dev_batch *= max(1, packing)
    sat = per_dev_batch / (per_dev_batch + BATCH_HALF_SAT)
    scale = CALIBRATION_PARAMS / max(1, plan_mod.param_count(cfg))
    rate = ROWS_CEILING * scale * sat * data
    rate /= 1.0 + TP_COMM_PENALTY * (model - 1)
    if kv_dtype == "int8":
        rate *= 1.0 - INT8_KV_PENALTY
    if prefill_chunk and prefill_chunk < seq:
        replays = -(-seq // prefill_chunk) - 1
        rate *= 1.0 - CHUNK_PENALTY * replays
    if workload == "full":
        rate /= FULL_STUDY_WORK
        if decode_k > 1:
            # Amdahl over the decode share: only the two decode legs
            # (K_DECODE_SHARE of full-study work) see the K multiplier,
            # priced by the accepted-K prior (k_decode_speedup)
            rate /= (1.0 - K_DECODE_SHARE
                     + K_DECODE_SHARE / k_decode_speedup(decode_k))
    elif workload == "packed":
        q = max(1, packing)
        iso_tokens = PACKED_SHARED_TOKENS + PACKED_QUESTION_TOKENS
        per_q_tokens = (PACKED_SHARED_TOKENS / q + PACKED_QUESTION_TOKENS
                        + PACKED_DEMO_TOKENS)
        rate *= PACKED_NO_DECODE_GAIN * iso_tokens / per_q_tokens
    return rate


def sharded_need_bytes(terms: Dict[str, int], cfg, data: int, model: int,
                       pipe: int) -> int:
    """Per-device live set: each plan.py term divided across the mesh axis
    that shards it.  Weights shard over tp (column/row-parallel
    projections) and pp (layer stages); batch-leading transients shard
    over dp; KV-cache terms additionally shard over tp only when the kv
    heads divide (falcon's MQA single kv head is replicated per tp shard,
    so its caches do NOT shrink with tp — the search must know that or it
    will predict fits tp cannot deliver)."""
    head_div = model if cfg.num_heads % model == 0 else 1
    kv_heads = cfg.num_kv_heads or cfg.num_heads
    kv_div = data * (model if kv_heads % model == 0 else 1)
    return (terms["weights"] // (model * pipe)
            + terms["attn"] // (data * head_div)
            + terms["act"] // data
            + terms.get("completions", 0) // kv_div
            + terms.get("conf_pool", 0) // kv_div
            # the K-head is a second lm_head: vocab-sharded over tp and
            # staged over pp exactly like the weights term
            + terms.get("k_head", 0) // (model * pipe))


def binary_need_terms(cfg, weight_b: int, batch: int, seq: int,
                      pipeline_depth: int = 4,
                      attention_impl: str = "xla") -> Dict[str, int]:
    """Per-term live set of the BINARY pooled-phase-2 sweep: weights, the
    monolithic-prefill score tensor (or the flash kernel's fp32 output
    workspace), activations, and the path's extras — the menu-capped
    cross-batch pool (EngineConfig.phase2_pool_max_bytes) plus the
    in-flight fp32 [B, V] logits at the sweep's pipeline depth.  Keys
    mirror :func:`plan.full_study_need_terms` so
    :func:`sharded_need_bytes` prices both workloads."""
    attn = (plan_mod.flash_workspace_bytes(cfg, batch, seq)
            if attention_impl == "flash"
            else plan_mod.dense_attention_bytes(cfg, batch, seq))
    return {
        "weights": weight_b,
        "attn": attn,
        "act": plan_mod.activation_bytes(cfg, batch, seq),
        # batch-leading extras ride the "completions" key (same dp/tp
        # sharding rule: logits shard over dp; the pool holds gathered KV)
        "completions": (512 << 20) + pipeline_depth * batch
        * cfg.vocab_size * 4,
    }


def search_plans(cfg, quant: str, n_devices: int, seq: int = 256,
                 workload: str = "full",
                 batches: Sequence[int] = DEFAULT_BATCH_LADDER,
                 kv_dtypes: Sequence[str] = DEFAULT_KV_DTYPES,
                 prefill_chunks: Sequence[int] = DEFAULT_PREFILL_CHUNKS,
                 pool_targets: Optional[Sequence[int]] = None,
                 gen_tokens: int = 50, score_steps: int = 10,
                 pipeline_depth: int = 2,
                 hbm_bytes: int = HBM_BYTES_V5E,
                 max_pipe: int = 2,
                 max_model: Optional[int] = None,
                 attention_impl: str = "xla",
                 packings: Sequence[int] = DEFAULT_PACKINGS,
                 decode_ks: Sequence[int] = DEFAULT_DECODE_KS,
                 slot_repack: bool = False) -> List[PlanCandidate]:
    """Enumerate, budget-filter, and rank the candidate space.

    Returns every candidate, ranked: fitting plans first by predicted
    rows/s (ties break toward the simpler config — lower tp, pp, pool
    target, packing), then rejected plans grouped by reason.
    ``ranked[0]`` is the chosen plan when any candidate fits.

    ``slot_repack=True`` prices each full-study candidate's confidence
    pool with the REFILL model (plan.slot_refill_pool_bytes — ring
    residency is capacity-shaped, retired lanes drop at repack) instead
    of the all-or-nothing flush accumulation; the default keeps every
    anchor pin byte-identical, and bench passes the engine's actual
    ``--slot-repack`` setting so searched plans price what will run.

    ``workload="packed"`` (ISSUE 10) adds the PACKING axis and drops the
    axes the anchor-gather path has no use for (no decode → no kv dtype,
    no pool; monolithic prefill → no chunk): candidates are (mesh, packed
    ROW batch, Q) points budgeted at the packed row length
    (plan.packed_need_terms — dense attention is quadratic in it, which
    is what prices out large Q) and ranked in predicted questions/s."""
    if workload not in ("full", "binary", "packed"):
        raise ValueError(f"unknown workload {workload!r}")
    from ..parallel.mesh import enumerate_mesh_shapes

    if pool_targets is None:
        pool_targets = DEFAULT_POOL_TARGETS if workload == "full" else (0,)
    if workload in ("binary", "packed"):
        # the pooled binary path has no confidence pool and keeps
        # monolithic prefill by design (_prefill_select is one fused
        # program), so its chunk axis collapses to {0}; and its need
        # terms are not kv-dtype-aware (binary_need_terms prices the
        # pool with the flat 512 MiB cap), so enumerating int8 would
        # only produce dominated duplicates that can never win the 2%
        # dequant penalty back — the kv axis collapses to bf16 until the
        # binary pool term is kv-priced.  The packed path has no decode
        # AT ALL (anchor gather inside the prefill program), so the same
        # collapses apply there a fortiori.
        pool_targets = (0,)
        kv_dtypes = ("bf16",)
    packings = tuple(packings) if workload == "packed" else (1,)
    # the K axis prices the two decode legs — only the full-study
    # workload runs them (the binary pooled flush is the async no-read
    # decode, the packed path has no decode at all)
    decode_ks = tuple(decode_ks) if workload == "full" else (1,)
    wb = weight_bytes(cfg, quant)
    budget = hbm_bytes - RESERVE_BYTES - {
        "full": THRASH_HEADROOM_BYTES,
        "binary": BINARY_SWEEP_HEADROOM_BYTES,
        "packed": PACKED_SWEEP_HEADROOM_BYTES,
    }[workload]
    candidates: List[PlanCandidate] = []

    def add(dp, pp, tp, b, kv, chunk, pool, fits, reason, need=0, pred=0.0,
            packing=1, decode_k=1):
        candidates.append(PlanCandidate(dp, pp, tp, b, kv, chunk, pool,
                                        fits, reason, need, pred, packing,
                                        decode_k))

    for dp, pp, tp in enumerate_mesh_shapes(n_devices, max_model=max_model,
                                            max_pipe=max_pipe):
        if pp > 1:
            # parallel/pipeline.py is a train-path capability; the scoring
            # engine has no pipelined forward, so pp candidates are priced
            # out with an explicit reason instead of silently skipped
            add(dp, pp, tp, batches[0], kv_dtypes[0], 0, 0, False,
                "pipe axis unsupported for scoring workloads "
                "(parallel/pipeline.py is train-only)")
            continue
        if cfg.num_heads % tp:
            add(dp, pp, tp, batches[0], kv_dtypes[0], 0, 0, False,
                f"num_heads {cfg.num_heads} not divisible by model axis "
                f"{tp} (padded head shards waste MXU tiles)")
            continue
        for b in batches:
            if b % (8 * dp):
                add(dp, pp, tp, b, kv_dtypes[0], 0, 0, False,
                    f"per-device batch {b}/{dp} not sublane-aligned "
                    f"(multiple of 8)")
                continue
            for kv in kv_dtypes:
                # a chunk covering the whole bucket IS monolithic prefill
                # (zero replays, identical bound): enumerate only chunks
                # that actually chunk, or duplicates pad the runner-up
                # table with no-op rows
                for chunk in ([c for c in prefill_chunks if c < seq]
                              if workload == "full" else (0,)):
                    for pool in pool_targets:
                        for packing, dk in [
                                (p, k) for p in packings
                                for k in decode_ks]:
                            if workload == "full":
                                terms = full_study_need_terms(
                                    cfg, wb, attention_impl, b, seq,
                                    gen_tokens, score_steps, pipeline_depth,
                                    reduced_scores=True, kv_dtype=kv,
                                    prefill_chunk=chunk,
                                    pooled_confidence=True,
                                    pool_target=pool or None,
                                    decode_k=dk,
                                    slot_repack=slot_repack)
                            elif workload == "packed":
                                terms = plan_mod.packed_need_terms(
                                    cfg, wb, attention_impl, b,
                                    packed_seq_tokens(packing), packing,
                                    pipeline_depth)
                            else:
                                terms = binary_need_terms(
                                    cfg, wb, b, seq, pipeline_depth,
                                    attention_impl)
                            need = sharded_need_bytes(terms, cfg, dp, tp,
                                                      pp)
                            if need > budget:
                                add(dp, pp, tp, b, kv, chunk, pool, False,
                                    f"over budget: "
                                    f"{budget_reject(need, budget)} "
                                    f"per device",
                                    need, packing=packing, decode_k=dk)
                                continue
                            pred = predicted_rows_per_s(
                                cfg, dp, tp, b, kv, chunk, workload, seq,
                                packing=packing, decode_k=dk)
                            add(dp, pp, tp, b, kv, chunk, pool, True,
                                f"fits: {budget_audit(need, budget)} per "
                                f"device at dp{dp}" +
                                (f"xtp{tp}" if tp > 1 else "") +
                                (f" (Q={packing} packed)"
                                 if workload == "packed" else "") +
                                (f" (K={dk} joint decode)"
                                 if dk > 1 else ""),
                                need, pred, packing=packing, decode_k=dk)
    candidates.sort(key=lambda c: (
        not c.fits, -c.predicted_rows_per_s, c.model, c.pipe,
        c.pool_target, c.kv_dtype != "bf16", c.prefill_chunk, c.packing,
        c.decode_k, -c.batch, c.reason))
    return candidates


def chosen_plan(ranked: Sequence[PlanCandidate]) -> Optional[PlanCandidate]:
    """The winning candidate, or None when nothing fits."""
    return ranked[0] if ranked and ranked[0].fits else None


def replica_plan(cfg, quant: str, n_devices: int, workload: str = "binary",
                 seq: int = 256, attention_impl: str = "xla",
                 role: Optional[str] = None,
                 **kw) -> Optional[PlanCandidate]:
    """Per-REPLICA operating point for the EnginePool (serve/pool.py):
    search this replica's own mesh slice (``n_devices`` = the devices
    the slice holds, not the fleet total) and return the chosen
    candidate — batch / kv-dtype / prefill-chunk / pool-target priced
    for the slice instead of inherited from fleet-wide flags.  None
    when nothing fits the slice's budget (the caller keeps its
    hand-configured EngineConfig and says so).

    ``role`` re-ranks the fitting candidates by the role-specialist
    rate (:func:`role_rate_factor`): a ``"prefill"`` replica's plan
    weights chunked-prefill terms harder, a ``"decode"`` replica's
    weights slot-refill and K-decode terms — the returned candidate
    carries the adjusted prediction and a ``[role=...]`` reason tag so
    the health doc's plan note says what was priced."""
    ranked = search_plans(cfg, quant, n_devices, seq=seq,
                          workload=workload,
                          attention_impl=attention_impl, **kw)
    if role is None:
        return chosen_plan(ranked)
    fit = [c for c in ranked if c.fits]
    if not fit:
        return None

    def adjusted(c: PlanCandidate) -> float:
        return c.predicted_rows_per_s * role_rate_factor(
            role, prefill_chunk=c.prefill_chunk, seq=seq,
            pool_target=c.pool_target, decode_k=c.decode_k)

    fit.sort(key=lambda c: (
        -adjusted(c), c.model, c.pipe, c.pool_target,
        c.kv_dtype != "bf16", c.prefill_chunk, c.packing, c.decode_k,
        -c.batch, c.reason))
    best = fit[0]
    return dataclasses.replace(
        best, predicted_rows_per_s=adjusted(best),
        reason=f"{best.reason} [role={role} "
               f"x{role_rate_factor(role, prefill_chunk=best.prefill_chunk, seq=seq, pool_target=best.pool_target, decode_k=best.decode_k):.2f}]")


def plan_search_record(ranked: Sequence[PlanCandidate], top: int = 8,
                       rejects: int = 4) -> Dict:
    """The bench JSON record's ``plan_search`` block: the chosen plan, the
    ranked runner-up table, a sample of rejections with reasons, and the
    candidate-space census — nothing silently truncated without a count."""
    fit = [c for c in ranked if c.fits]
    rej = [c for c in ranked if not c.fits]
    return {
        "chosen": fit[0].as_record() if fit else None,
        "runners_up": [c.as_record() for c in fit[1:1 + top]],
        "rejected_sample": [c.as_record() for c in rej[:rejects]],
        "n_candidates": len(ranked),
        "n_fit": len(fit),
        "n_rejected": len(rej),
    }


def format_candidate_table(ranked: Sequence[PlanCandidate], top: int = 8,
                           title: str = "plan search") -> str:
    """stderr table of the chosen plan + runner-ups (one line per
    candidate, reason included — the human-readable twin of
    :func:`plan_search_record`)."""
    fit = [c for c in ranked if c.fits]
    rej = len(ranked) - len(fit)
    lines = [f"# {title}: {len(ranked)} candidates, {len(fit)} fit, "
             f"{rej} rejected"]
    for rank, c in enumerate(fit[:1 + top]):
        tag = "chosen " if rank == 0 else f"rank {rank + 1:2d}"
        lines.append(
            f"#   {tag}: mesh dp{c.data}xpp{c.pipe}xtp{c.model} "
            f"batch {c.batch} kv {c.kv_dtype} chunk {c.prefill_chunk} "
            f"pool {c.pool_target or 'batch'}"
            + (f" packing {c.packing}" if c.packing > 1 else "")
            + (f" decode-k {c.decode_k}" if c.decode_k > 1 else "")
            + f" -> {c.predicted_rows_per_s:.1f} rows/s ({c.reason})")
    if not fit:
        lines.append("#   NO candidate fits the budget; first reject: "
                     + (ranked[0].reason if ranked else "(empty space)"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Dryrun leg: the virtual 8-device mesh vs the hand-picked MULTICHIP points
# ---------------------------------------------------------------------------

def _flagship_small_config():
    """The compile-check Falcon-architecture geometry the multichip dryrun
    scores (__graft_entry__._flagship_config(small=True)) — the shared
    spelling in models/config.py."""
    from ..models.config import FLAGSHIP_SMALL_GEOMETRY, DecoderConfig

    return DecoderConfig(**FLAGSHIP_SMALL_GEOMETRY)


def _ensure_virtual_devices(n_devices: int, platform: str = "cpu") -> None:
    """Pin the CPU platform and force >= n virtual devices BEFORE any JAX
    backend initializes (the __graft_entry__ dryrun discipline); if a
    backend is already up (pytest), just require enough devices."""
    import os
    import re

    try:
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except Exception:  # graftlint: disable=G05 private API moved; keep assert
        initialized = False
    import jax

    if initialized:
        if len(jax.devices()) < n_devices:
            raise RuntimeError(
                f"plan search dryrun needs {n_devices} devices; backends "
                f"already initialized with {len(jax.devices())} — run in a "
                f"fresh process")
        return
    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if match is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(match.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            match.group(0),
            f"--xla_force_host_platform_device_count={n_devices}")
    jax.config.update("jax_platforms", platform)


def run_dryrun(n_devices: int = 8, exec_leg: bool = True,
               out=None) -> Dict:
    """The acceptance leg: search the virtual n-device mesh and show the
    chosen plan reproduces or beats every hand-picked dp x tp operating
    point from MULTICHIP_r05, then (``exec_leg``) build the chosen mesh
    and run a tiny sharded scoring parity check so the plan is proven
    constructible AND runnable, not just priced."""
    out = out or sys.stderr
    hand_n = (HAND_PICKED_MULTICHIP["data"] * HAND_PICKED_MULTICHIP["pipe"]
              * HAND_PICKED_MULTICHIP["model"])
    if n_devices != hand_n:
        raise ValueError(
            f"the dryrun compares against the hand-picked MULTICHIP_r05 "
            f"mesh {HAND_PICKED_MULTICHIP}, which factorizes exactly "
            f"{hand_n} devices — got n_devices={n_devices}")
    _ensure_virtual_devices(n_devices)
    cfg = _flagship_small_config()
    ranked = search_plans(cfg, "int8", n_devices, seq=96, workload="binary",
                          batches=tuple(range(32, 513, 32)))
    best = chosen_plan(ranked)
    assert best is not None, "dryrun: no candidate fits the tiny geometry"
    hand = [c for c in ranked
            if c.fits and c.mesh_shape == HAND_PICKED_MULTICHIP
            and c.batch == best.batch]
    hand_best = hand[0] if hand else None
    assert hand_best is not None, (
        f"hand-picked mesh {HAND_PICKED_MULTICHIP} missing from the "
        f"candidate table at batch {best.batch}")
    assert best.predicted_rows_per_s >= hand_best.predicted_rows_per_s, (
        f"search lost to the hand-picked mesh: {best} vs {hand_best}")
    print(format_candidate_table(ranked, title="plan search dryrun"),
          file=out)
    result = {"chosen": best.as_record(),
              "hand_picked": hand_best.as_record(),
              "n_devices": n_devices}
    if exec_leg:
        result["exec"] = _exec_tiny_leg(cfg, best, out)
    print(
        f"plan search dryrun OK: chose mesh dp{best.data}xpp{best.pipe}"
        f"xtp{best.model} batch {best.batch} "
        f"({best.predicted_rows_per_s:.1f} predicted rows/s) vs "
        f"hand-picked MULTICHIP_r05 dp4xtp2 "
        f"({hand_best.predicted_rows_per_s:.1f}) on {n_devices} virtual "
        f"devices" + (", exec parity checked" if exec_leg else ""),
        file=out)
    return result


def _exec_tiny_leg(cfg, best: PlanCandidate, out) -> Dict:
    """Build the chosen mesh and score a handful of prompts through the
    sharded engine with single-device parity — proof the plan runs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.quant import quantize_decoder_params_np
    from ..parallel import make_mesh, shard_params
    from ..utils.testing import build_inprocess_tokenizer
    from .engine import EngineConfig, ScoringEngine

    devices = jax.devices()[:best.data * best.pipe * best.model]
    mesh = make_mesh(data=best.data, pipe=best.pipe, model=best.model,
                     devices=devices)
    rng = np.random.default_rng(0)
    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    L, F, V = cfg.num_layers, cfg.intermediate_size, cfg.vocab_size

    def init(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    params = quantize_decoder_params_np({
        "embed": {"tokens": init(V, h)},
        "layers": {
            "ln1": {"scale": np.ones((L, h), np.float32),
                    "bias": np.zeros((L, h), np.float32)},
            "attn": {"wq": init(L, h, nd), "wk": init(L, h, kvd),
                     "wv": init(L, h, kvd), "wo": init(L, nd, h)},
            "mlp": {"wi": init(L, h, F), "wo": init(L, F, h)},
        },
        "final_ln": {"scale": np.ones(h, np.float32),
                     "bias": np.zeros(h, np.float32)},
    })
    tokenizer = build_inprocess_tokenizer()
    prompts = [f"Question: is candidate {i} a plan? Answer:"
               for i in range(4)]
    dp = best.data
    ecfg = EngineConfig(batch_size=dp * max(1, -(-4 // dp)),
                        decode_completions=False, buckets=(32, 96))
    single = ScoringEngine("falcon", cfg, jax.tree.map(jnp.asarray, params),
                           tokenizer, mesh=None, engine_config=ecfg)
    sharded = ScoringEngine("falcon", cfg, shard_params(params, mesh),
                            tokenizer, mesh=mesh, engine_config=ecfg)
    ref = single.first_token_relative_prob(prompts)
    got = sharded.first_token_relative_prob(prompts)
    np.testing.assert_allclose(got, ref, atol=5e-5, rtol=1e-4)
    print(f"# plan search exec: sharded fast-path parity on "
          f"mesh {dict(mesh.shape)} ({len(prompts)} prompts)", file=out)
    return {"mesh": dict(mesh.shape), "prompts": len(prompts),
            "parity": True}


# ---------------------------------------------------------------------------
# CLI: ``python -m llm_interpretation_replication_tpu plan search``
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="llm_interpretation_replication_tpu plan",
        description="auto-parallel plan search over mesh x batch x "
                    "kv-dtype x prefill-chunk x pool target")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("search", help="enumerate + rank candidate plans")
    p.add_argument("--model", choices=["falcon-7b", "small-1b"],
                   default="falcon-7b", help="bench geometry to price")
    p.add_argument("--quant", choices=["none", "int8"], default="int8")
    p.add_argument("--devices", type=int, default=1, metavar="N",
                   help="device count to enumerate meshes over (no JAX "
                        "init: the search is pure host arithmetic)")
    p.add_argument("--seq", type=int, default=256,
                   help="worst-bucket sequence length to budget")
    p.add_argument("--workload", choices=["full", "binary", "packed"],
                   default="full",
                   help="full: the two-leg full-study row contract; "
                        "binary: the yes/no pooled-phase-2 sweep; "
                        "packed: anchor-gathered multi-question batch "
                        "prompting (questions/s — adds the packing axis)")
    p.add_argument("--batch-max", type=int, default=512)
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="in-flight device batches to budget (default: 2 "
                        "for the full-study workload, 4 for the binary "
                        "sweep — the bench mode defaults)")
    p.add_argument("--hbm-gib", type=float, default=16.0,
                   help="per-device HBM (v5e default)")
    p.add_argument("--top", type=int, default=8,
                   help="runner-ups to print/record")
    p.add_argument("--format", choices=["table", "json"], default="table")
    p.add_argument("--dryrun", action="store_true",
                   help="the MULTICHIP acceptance leg: search the virtual "
                        "8-device mesh (tiny flagship geometry) and prove "
                        "the choice reproduces or beats the hand-picked "
                        "MULTICHIP_r05 dp4xtp2 point")
    p.add_argument("--exec", dest="exec_leg",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="with --dryrun: also build the chosen mesh and "
                        "run a tiny sharded scoring parity check "
                        "(--no-exec = prediction comparison only)")
    args = parser.parse_args(argv)

    if args.dryrun:
        if args.devices not in (1, 8):
            parser.error(f"--dryrun runs on the virtual 8-device mesh "
                         f"(the MULTICHIP_r05 comparison); drop "
                         f"--devices {args.devices} or pass 8")
        result = run_dryrun(n_devices=8, exec_leg=args.exec_leg)
        if args.format == "json":
            print(json.dumps(result))
        return 0

    from ..models.config import BENCH_GEOMETRIES, DecoderConfig

    cfg = DecoderConfig(**BENCH_GEOMETRIES[args.model])
    ranked = search_plans(
        cfg, args.quant, args.devices, seq=args.seq,
        workload=args.workload,
        batches=tuple(range(32, args.batch_max + 1, 32)),
        pipeline_depth=args.pipeline_depth
        or (2 if args.workload == "full" else 4),
        hbm_bytes=int(args.hbm_gib * 2**30))
    if args.format == "json":
        print(json.dumps(plan_search_record(ranked, top=args.top)))
    else:
        print(format_candidate_table(ranked, top=args.top))
    return 0 if chosen_plan(ranked) is not None else 1


if __name__ == "__main__":
    raise SystemExit(main())
