"""Checkpoint loading: local HF snapshots → sharded HBM-resident params.

Replaces the reference's ``AutoModelForCausalLM.from_pretrained(device_map=
"auto", load_in_8bit=True)`` (run_base_vs_instruct_100q.py:416-451): weights
stream shard-by-shard from safetensors (or torch .bin) into the converted
pytree, are cast to bf16, and are placed on the mesh with TP sharding — no
int8 workaround needed because a 2-D mesh fits 7B bf16 in per-chip HBM.

Zero-egress note: this loads from a local snapshot directory (HF cache layout
or a plain dir with config.json + weights); it never hits the network.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np

from ..models import config as mcfg
from ..models import convert as mconvert


#: Environment gate for the persistent XLA compilation cache: a path
#: enables it there; "0"/"off"/"" disables even when a caller passes a
#: default; unset defers to the caller's ``path`` argument.
COMPILE_CACHE_ENV = "LLM_INTERP_COMPILE_CACHE"


def enable_compile_cache(path: Optional[str] = None,
                         min_compile_secs: float = 5.0) -> Optional[str]:
    """Point JAX's persistent compilation cache at a directory, env-gated.

    Programs at sweep shapes take 1.5-4 min EACH to compile through the
    remote-compile helper and were recompiled per process: BENCH_r05's
    repeat 0 paid ~150 s over repeat 1 on identical code.  With the cache
    on, repeat-0 and preemption-resume runs deserialize their executables
    in seconds — combined with an explicit bucket warmup
    (ScoringEngine.warmup) the cold-start penalty disappears.

    Resolution order: ``$LLM_INTERP_COMPILE_CACHE`` wins when set (a path
    enables; ``0``/``off``/empty disables); otherwise ``path`` when given;
    otherwise no-op.  Returns the directory in effect, or None when
    disabled/unsupported (older jax without the option — compile per run,
    like before).  Records the ``compile_cache_enabled`` telemetry counter
    so benchmarks can report whether their warm numbers had it.
    """
    env = os.environ.get(COMPILE_CACHE_ENV)
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "none"):
            return None
        path = env
    if not path:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    except (AttributeError, KeyError, TypeError, ValueError) as err:
        # older jax without the option spells rejection as AttributeError/
        # KeyError from config.update (ValueError/TypeError for a bad
        # path/seconds value); anything else — e.g. RESOURCE_EXHAUSTED
        # surfacing through jax init — must propagate to faults
        # classification, not be swallowed here (graftlint G05).  A
        # silently-missing cache costs ~150 s per cold run — leave a
        # trail distinguishing "jax rejected it" from "env disabled it".
        import warnings

        warnings.warn(f"persistent compilation cache unavailable "
                      f"({err}); compiling per process")
        return None
    from ..utils.telemetry import record_counter

    record_counter("compile_cache_enabled")
    return os.path.abspath(path)


class CheckpointDir:
    """Random access over a local HF snapshot's weight files."""

    def __init__(self, path: str):
        self.path = path
        self._index = {}        # tensor name -> (file, kind)
        self._handles = {}
        st_index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(st_index):
            with open(st_index) as f:
                weight_map = json.load(f)["weight_map"]
            for name, fname in weight_map.items():
                self._index[name] = (os.path.join(path, fname), "safetensors")
        elif os.path.exists(os.path.join(path, "model.safetensors")):
            fname = os.path.join(path, "model.safetensors")
            for name in self._st_names(fname):
                self._index[name] = (fname, "safetensors")
        else:
            bin_index = os.path.join(path, "pytorch_model.bin.index.json")
            if os.path.exists(bin_index):
                with open(bin_index) as f:
                    weight_map = json.load(f)["weight_map"]
                for name, fname in weight_map.items():
                    self._index[name] = (os.path.join(path, fname), "torch")
            elif os.path.exists(os.path.join(path, "pytorch_model.bin")):
                fname = os.path.join(path, "pytorch_model.bin")
                self._index = {None: (fname, "torch")}  # lazy full load
            else:
                raise FileNotFoundError(f"no weights found under {path}")

    @staticmethod
    def _st_names(fname):
        from safetensors import safe_open

        with safe_open(fname, framework="np") as f:
            return list(f.keys())

    def get(self, name: str) -> np.ndarray:
        if None in self._index:  # single torch bin
            import torch

            fname, _ = self._index[None]
            sd = getattr(self, "_torch_sd", None)
            if sd is None:
                sd = torch.load(fname, map_location="cpu", weights_only=True)
                self._torch_sd = sd
            if name not in sd:
                raise KeyError(name)
            return sd[name].float().numpy()
        if name not in self._index:
            raise KeyError(name)
        fname, kind = self._index[name]
        if kind == "safetensors":
            from safetensors import safe_open

            h = self._handles.get(fname)
            if h is None:
                h = safe_open(fname, framework="np")
                self._handles[fname] = h
            t = h.get_tensor(name)
            if t.dtype == np.dtype("V2"):  # raw bf16 comes back as void16
                t = _bf16_to_f32(t)
            return np.asarray(t, dtype=np.float32) if t.dtype != np.float32 else t
        import torch

        sd = torch.load(fname, map_location="cpu", weights_only=True)
        return sd[name].float().numpy()


def _bf16_to_f32(raw: np.ndarray) -> np.ndarray:
    u16 = raw.view(np.uint16)
    u32 = u16.astype(np.uint32) << 16
    return u32.view(np.float32)


def load_hf_config(path: str):
    """Read a snapshot's ``config.json`` WITHOUT executing repo code.

    Families that only exist as remote code (Qwen v1, Baichuan) make
    ``AutoConfig(trust_remote_code=False)`` raise and
    ``trust_remote_code=True`` would execute arbitrary repo code just to
    build a config object.  ``models.config.from_hf_config`` reads plain
    attributes only, so a namespace over the raw JSON serves every family.
    """
    import types

    with open(os.path.join(path, "config.json")) as f:
        raw = json.load(f)
    # T5 checkpoints store only feed_forward_proj; HF derives these two
    proj = raw.get("feed_forward_proj")
    if proj and "dense_act_fn" not in raw:
        raw["dense_act_fn"] = proj.replace("gated-", "")
        raw["is_gated_act"] = proj.startswith("gated-")
    # Legacy-key aliases AutoConfig normally applies via attribute_map —
    # original Falcon snapshots (model_type 'RefinedWeb'/'RefinedWebModel')
    # and GPT-2-lineage configs use the short names.
    for legacy, canonical in (
        ("n_layer", "num_hidden_layers"),
        ("n_head", "num_attention_heads"),
        ("n_head_kv", "num_kv_heads"),
        ("n_embed", "hidden_size"),
        ("n_embd", "hidden_size"),
        ("n_positions", "max_position_embeddings"),
    ):
        if legacy in raw and canonical not in raw:
            raw[canonical] = raw[legacy]
    return types.SimpleNamespace(**raw)


def load_model(
    path: str,
    dtype=None,
    mesh=None,
    quant: str = "none",
    attention_impl: Optional[str] = None,
) -> Tuple[str, object, dict]:
    """Load (family, config, params) from a local snapshot dir.

    With ``mesh`` given, parameters are placed TP-sharded on the mesh as they
    are converted (HBM-resident from the start); otherwise they stay host-side
    jnp arrays in ``dtype`` (default bf16).

    ``quant='int8'`` quantizes the projection weights host-side (w8a8 path,
    ops/quant.py) before any device placement — the framework's answer to the
    reference's bitsandbytes ``load_in_8bit``, except on TPU it buys ~1.9x
    scoring throughput (v5e int8 MXU) on top of the 2x HBM saving.  Only
    decoder families support it (T5's scoring leg is not compute-bound).
    """
    import jax
    import jax.numpy as jnp

    hf = load_hf_config(path)
    family, cfg = mcfg.from_hf_config(hf)
    if attention_impl and family != "t5":
        import dataclasses

        if attention_impl not in ("xla", "flash", "auto"):
            # validate BEFORE the try: the fallback below is only for the
            # flash/ALiBi incompatibility, not for typo'd impl names
            raise ValueError(f"unknown attention_impl {attention_impl!r}")
        # 'auto' falls back to dense inside the config for ALiBi /
        # sliding-window models; explicit 'flash' rejects them — degrade to
        # dense with a warning so a roster-wide flag survives mixed families
        try:
            cfg = dataclasses.replace(cfg, attention_impl=attention_impl)
        except ValueError as err:
            import warnings

            warnings.warn(f"{path}: {err}; keeping attention_impl='xla'")
    ckpt = CheckpointDir(path)
    dtype = dtype or jnp.bfloat16
    params = mconvert.convert(family, ckpt.get, cfg, dtype=None)
    if quant == "int8":
        if family == "t5":
            # Enc-dec scoring is a single short decoder step — not worth the
            # int8 error budget.  Fall back so mixed sweeps (run-instruct-sweep
            # includes tk-instruct/T0) keep running under a global --quant.
            import warnings

            warnings.warn(f"int8 quantization unsupported for T5 family ({path}); loading bf16")
        else:
            from ..ops.quant import quantize_decoder_params_np

            params = quantize_decoder_params_np(params)
    elif quant != "none":
        raise ValueError(f"unknown quant mode {quant!r}")
    itemsize = jnp.dtype(dtype).itemsize
    if (quant == "none" and family != "t5"
            # 'auto' stays dense at sweep lengths too (it only flips to the
            # flash kernel past its long-context threshold), so it OOMs the
            # same way as explicit 'xla'
            and cfg.attention_impl in ("xla", "auto")
            and _param_bytes(params, itemsize) > DENSE_BF16_WARN_BYTES
            and (mesh is None or mesh.devices.size == 1)):
        import warnings

        # measured on 16 GB v5e (PARITY.md bf16 note): ~13 GB of bf16 7B
        # weights leave no HBM for the dense S×T attention scores at ANY
        # sweep batch size — the run will OOM where int8 fits comfortably
        warnings.warn(
            f"{path}: unquantized weights at this scale typically cannot "
            f"host dense attention scores on a single chip; use "
            f"quant='int8' or attention_impl='flash' (block-streamed "
            f"scores)")
    if mesh is not None:
        from ..parallel.sharding import param_specs

        import jax
        from jax.sharding import NamedSharding

        kind = "t5" if family == "t5" else "decoder"
        specs = param_specs(params, kind)

        def place(x, s, key):
            return jax.device_put(
                jnp.asarray(x, dtype=_target_dtype(key, x, dtype)),
                NamedSharding(mesh, s),
            )

        params = _walk2(params, specs, place)
    else:
        params = _cast(params, dtype)
    return family, cfg, params


# Unquantized-weight bytes above which single-chip dense attention is known
# not to fit 16 GB HBM beside the weights (bf16 7B ≈ 13 GB measured).
DENSE_BF16_WARN_BYTES = 10e9


def _param_bytes(params, bytes_per_elem: int) -> float:
    """Approximate device size of an unquantized param tree."""
    import jax

    return sum(np.prod(leaf.shape) for leaf in jax.tree_util.tree_leaves(params)
               if hasattr(leaf, "shape")) * bytes_per_elem


def _target_dtype(key, x, dtype):
    """Quantized leaves keep their dtype: int8 weights stay int8 and fp32
    quantization scales must not be squeezed into bf16."""
    if getattr(x, "dtype", None) == np.int8:
        return np.int8
    if key.endswith("_qscale"):
        return np.float32
    return dtype


def _walk2(tree, other, fn, key=""):
    if isinstance(tree, dict):
        return {k: _walk2(v, other[k], fn, k) for k, v in tree.items()}
    return fn(tree, other, key)


def _cast(tree, dtype, key=""):
    import jax.numpy as jnp

    if isinstance(tree, dict):
        return {k: _cast(v, dtype, k) for k, v in tree.items()}
    return jnp.asarray(tree, dtype=_target_dtype(key, tree, dtype))


#: families whose tokenizers only exist as repo code (the reference passes
#: trust_remote_code=True everywhere — compare_instruct_models.py:404-428)
_REMOTE_CODE_TOKENIZER_TYPES = {"qwen", "baichuan", "chatglm", "xgen"}


def load_tokenizer(path: str, trust_remote_code: bool = False):
    """Family quirks are keyed off the snapshot's ``model_type`` (never the
    filesystem path): Baichuan ships a broken fast tokenizer, so it gets the
    slow one (the reference's special case — compare_instruct_models.py:
    422-428), and Qwen v1/Baichuan tokenizers only exist as remote code."""
    from transformers import AutoTokenizer

    model_type = ""
    try:
        model_type = getattr(load_hf_config(path), "model_type", "") or ""
    except (OSError, ValueError):
        pass  # tokenizer-only directory: no family quirks to apply
    use_fast = model_type != "baichuan"
    if model_type in _REMOTE_CODE_TOKENIZER_TYPES:
        trust_remote_code = True
    tok = AutoTokenizer.from_pretrained(
        path, local_files_only=True, use_fast=use_fast,
        trust_remote_code=trust_remote_code,
    )
    if tok.pad_token_id is None:
        if tok.eos_token is not None:
            # pad positions are attention-masked, so any in-vocab id works
            tok.pad_token = tok.eos_token
        elif "<|endoftext|>" in tok.get_vocab():  # Qwen v1: no eos attr
            tok.pad_token = "<|endoftext|>"
        elif tok.unk_token is not None:
            tok.pad_token = tok.unk_token
        else:
            # last resort: reuse an existing in-vocab token.  Minting a new
            # special token would get id == len(vocab) — out of range for the
            # checkpoint's embedding table (pad positions are masked, but
            # consumers that bounds-check ids against cfg.vocab_size break).
            vocab = tok.get_vocab()
            tok.pad_token = min(vocab, key=vocab.get)
    return tok


# ---------------------------------------------------------------------------
# K-head persistence (ROADMAP item 2(c)): distilled joint-decode heads
# saved beside the snapshot, keyed on (snapshot fingerprint, decode_k)
# ---------------------------------------------------------------------------
#
# models/decoder.distill_k_head fits the head with ridge probes over the
# model's OWN greedy continuations — seconds of work, but PER PROCESS:
# every bench repeat, serve replica, and sweep shell re-paid it.  The head
# is a pure function of (weights, decode_k, distillation corpus), so it
# persists as ``k_head.npz`` next to the snapshot weights and reloads on
# engine construction.  The fingerprint ties the file to the exact weight
# files (config.json bytes + weight-file names/sizes): a retrained or
# swapped snapshot misses the key and triggers a clean re-distillation —
# and a STALE head could only cost verify-and-accept rejections anyway,
# never a wrong row (the PARITY.md K-decode fallback rule), so the
# fingerprint is a perf guard, not a correctness one.

K_HEAD_FILENAME = "k_head.npz"


def snapshot_fingerprint(path: str) -> str:
    """Cheap content key for a snapshot dir: sha256 over the config.json
    bytes plus each weight file's (name, size) — no weight reads."""
    import hashlib

    h = hashlib.sha256()
    cfg_path = os.path.join(path, "config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path, "rb") as f:
            h.update(f.read())
    for fname in sorted(os.listdir(path)):
        if fname.endswith((".safetensors", ".bin")):
            h.update(fname.encode())
            h.update(str(os.path.getsize(os.path.join(path, fname))).encode())
    return h.hexdigest()[:16]


def save_k_head(path: str, k_head, decode_k: int,
                fingerprint: Optional[str] = None) -> str:
    """Persist a distilled K-head beside the snapshot (atomic rename so a
    preempted writer never leaves a torn file).  Returns the file path."""
    import jax.numpy as jnp

    fp = fingerprint or snapshot_fingerprint(path)
    out = os.path.join(path, K_HEAD_FILENAME)
    tmp = out + ".tmp.npz"               # savez keeps names ending .npz
    w = np.asarray(jnp.asarray(k_head["w"], jnp.float32))
    np.savez(tmp, w=w, fingerprint=np.asarray(fp),
             decode_k=np.asarray(int(decode_k)))
    os.replace(tmp, out)
    return out


def load_k_head(path: str, decode_k: int, dtype=None,
                fingerprint: Optional[str] = None):
    """Load a persisted K-head if one matches (fingerprint, decode_k);
    None on any miss — the caller re-distills (load-or-redistill)."""
    import jax.numpy as jnp

    f = os.path.join(path, K_HEAD_FILENAME)
    if not os.path.exists(f):
        return None
    try:
        with np.load(f, allow_pickle=False) as z:
            if str(z["fingerprint"]) != (fingerprint
                                         or snapshot_fingerprint(path)):
                return None
            if int(z["decode_k"]) != int(decode_k):
                return None
            w = z["w"]
    except (OSError, ValueError, KeyError):
        return None                      # torn/foreign file: re-distill
    return {"w": jnp.asarray(w, dtype) if dtype is not None
            else jnp.asarray(w)}


def attach_k_head(engine, path: str) -> bool:
    """Load-or-miss on engine construction: set ``engine.k_head`` from a
    persisted file when it matches this snapshot + ``decode_k``; returns
    True on a hit.  On a miss the caller distills as before and should
    persist via :func:`save_k_head`."""
    decode_k = int(getattr(engine.ecfg, "decode_k", 1))
    if decode_k <= 1:
        return False
    head = load_k_head(path, decode_k,
                       dtype=engine.params["embed"]["tokens"].dtype)
    if head is None:
        return False
    if int(head["w"].shape[0]) != decode_k - 1 \
            or int(head["w"].shape[1]) != engine.cfg.hidden_size:
        return False
    engine.k_head = head
    from ..utils.telemetry import record_counter
    record_counter("k_head_loaded")
    return True
