"""Slot-level continuous batching: decode-then-repack (ROADMAP item 3).

The engine's three decode pools — the ``_Phase2Pool`` legs, the packed
demo decode, and the serve micro-batches — all share one failure mode:
a row that finishes early leaves its batch lane EMPTY for the rest of
the flush.  PR 7's pools compact retired rows' K/V away (the HBM win)
but never backfill the lane (the occupancy loss); PR 10's packed rows
are a static pack; serve admits only at coalescer boundaries.  This
module owns the fix: a fixed-capacity ring of decode SLOTS where a
retired slot (EOS'd completion, settled ``first_int_stable`` parse,
answered pack question) is immediately REFILLED from a pending-work
queue between decode chunks — the newcomer's prefilled cache row drops
into the vacated lane (padded with inert invalid slots to the ring's
current cache length) while live slots keep decoding.

Numerics contract (PARITY.md "Decode-then-repack"):

- A row's decode is the same per-row math whether it runs in a fresh
  batch, a refilled slot, or the legacy whole-flush path: the decode
  offset folds into the row's effective length (``positions =
  lengths + offset + i`` — the ring passes ``lengths + decoded`` and
  ``offset = 0``, the same positions the sequential path computes), the
  tail buffer's unwritten slots are masked exact zeros, and padding
  slots are inert (masked softmax terms are exact fp32 zeros).  Tokens,
  parses, retirement points and verdicts are therefore identical across
  ring compositions — the pooled-confidence bit-reproducibility rule,
  re-pinned by ``pytest -m slots``.
- Multi-chunk SCORE fields stay in the chunked-prefill fp32 tolerance
  class: fold points and slot-compaction gathers regroup reduction
  order in the last ulp, exactly like the chunk boundaries the pooled
  path already documents.  Bit-identity is promised only where the
  pooled contract already promises it (positions 0-2 of the confidence
  stats, single-chunk windows).

Fragmentation vs retirement: RETIREMENT never triggers a cache rebuild
by itself — the vacated lane is reused in place by the refill concat.
Only FRAGMENTATION does: every chunk appends ``chunk`` tail slots to
every row, so a long-lived ring accumulates dead columns; once the slot
axis outgrows ``base_len + compact_slack`` the ring compacts each row's
valid slots to the front (stable per-row gather — content and order
preserved) and truncates.  ``slot_compactions`` counts these.

Telemetry rides the PR-12 labeled convention from day one: every
``slot_*`` counter records an unlabeled fleet-wide twin AND a
``name|leg=...,workload=...`` labeled series, so the Prometheus export
(obs/metrics.split_labeled_name) never needs a second migration.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import strict
from ..models import decoder as dmod
from ..utils.telemetry import record_counter

__all__ = ["KVSlab", "OccupancyStats", "SlotRing", "SlotRow",
           "slab_from_device", "slab_to_device", "slot_counter",
           "merge_occupancy", "occupancy_block"]


def slot_counter(name: str, value: float, leg: str, workload: str) -> None:
    """Record a ``slot_*`` counter plus its ``name|k=v`` labeled twin
    (the PR-12 convention — serve/scheduler.labeled_metric's spelling,
    keys sorted), so per-leg/per-workload Prometheus series exist from
    day one next to the fleet aggregate."""
    record_counter(name, value)
    record_counter(f"{name}|leg={leg},workload={workload}", value)


@dataclasses.dataclass
class OccupancyStats:
    """Slot-occupancy accounting for one ring (or a merged fleet).

    ``capacity_steps`` counts (batch lanes x decode steps) the ring's
    chunks spent; ``live_steps`` counts the subset occupied by live REAL
    rows still inside their decode budget.  The idle fraction is the
    headline the bench ``occupancy`` block reports, next to the
    whole-flush COUNTERFACTUAL (what the same rows' retirement profile
    would have idled under the legacy flush-at-target schedule) so the
    next driver record measures the occupancy gain directly."""

    capacity: int = 0
    rows: int = 0
    capacity_steps: int = 0
    live_steps: int = 0
    refills: int = 0
    repacks: int = 0
    compactions: int = 0
    repack_stalls: int = 0
    #: per-row decode steps actually spent (chunk-aligned retirement) —
    #: the counterfactual's input.
    row_steps: List[int] = dataclasses.field(default_factory=list)

    def idle_fraction(self) -> Optional[float]:
        if not self.capacity_steps:
            return None
        return 1.0 - self.live_steps / self.capacity_steps

    def no_repack_idle_fraction(self) -> Optional[float]:
        """Counterfactual slot-idle fraction under the legacy whole-flush
        schedule: rows group into flushes of ``capacity`` in arrival
        order, each flush runs until its LAST row retires (the flush's
        lanes all spin that long), nothing refills."""
        if not self.row_steps or not self.capacity:
            return None
        total = live = 0
        cap = max(1, self.capacity)
        for i in range(0, len(self.row_steps), cap):
            flush = self.row_steps[i: i + cap]
            dur = max(flush)
            total += cap * dur
            live += sum(flush)
        if not total:
            return None
        return 1.0 - live / total

    def merged(self, other: "OccupancyStats") -> "OccupancyStats":
        return OccupancyStats(
            capacity=max(self.capacity, other.capacity),
            rows=self.rows + other.rows,
            capacity_steps=self.capacity_steps + other.capacity_steps,
            live_steps=self.live_steps + other.live_steps,
            refills=self.refills + other.refills,
            repacks=self.repacks + other.repacks,
            compactions=self.compactions + other.compactions,
            repack_stalls=self.repack_stalls + other.repack_stalls,
            row_steps=self.row_steps + other.row_steps,
        )

    def report(self) -> Dict:
        idle = self.idle_fraction()
        before = self.no_repack_idle_fraction()
        return {
            "capacity": int(self.capacity),
            "rows": int(self.rows),
            "slot_steps": int(self.capacity_steps),
            "live_steps": int(self.live_steps),
            "slot_idle_frac": None if idle is None else round(idle, 4),
            "slot_idle_frac_no_repack": (
                None if before is None else round(before, 4)),
            "refills": int(self.refills),
            "repacks": int(self.repacks),
            "compactions": int(self.compactions),
            "repack_stalls": int(self.repack_stalls),
        }


def merge_occupancy(stats) -> Optional[OccupancyStats]:
    """Fold an iterable of :class:`OccupancyStats` into one (None when
    empty) — how the engine aggregates per-ring stats per call and bench
    aggregates per-call stats into the record's ``occupancy`` block."""
    out = None
    for s in stats:
        if s is None or not s.capacity_steps and not s.rows:
            continue
        out = s if out is None else out.merged(s)
    return out


def occupancy_block(stats: Optional[OccupancyStats]) -> Optional[Dict]:
    return None if stats is None else stats.report()


class SlotRow:
    """Host-side state of one real row travelling through the ring."""

    __slots__ = ("meta", "row_ids", "toks", "vals", "ids_k", "logz", "tgt",
                 "decoded", "checked", "retire_step", "admit_chunk",
                 "natural")

    def __init__(self, meta, row_ids, steps: int, topk: int,
                 with_scores: bool):
        self.meta = meta
        self.row_ids = row_ids                      # [2] int32 target ids
        self.toks = np.zeros((steps,), np.int32)
        if with_scores:
            self.vals = np.zeros((steps, topk), np.float32)
            self.ids_k = np.zeros((steps, topk), np.int32)
            self.logz = np.zeros((steps,), np.float32)
            self.tgt = np.zeros((steps, 2), np.float32)
        else:
            self.vals = self.ids_k = self.logz = self.tgt = None
        self.decoded = 0
        self.checked = 0          # retire_fn has inspected prefixes <= this
        self.retire_step = -1     # r*: first frozen prefix (-1 = live)
        self.admit_chunk = 0
        self.natural = False      # retired by the predicate (vs budget)


class _PendingGroup:
    """One batch's gathered rows waiting for slots: device arrays shared,
    rows handed out by index as lanes free up."""

    __slots__ = ("cache", "last", "lens", "row_ids", "metas", "taken")

    def __init__(self, cache, last, lens, row_ids, metas):
        self.cache = cache
        self.last = last
        self.lens = lens
        self.row_ids = np.asarray(row_ids, np.int32)
        self.metas = list(metas)
        self.taken = 0

    def remaining(self) -> int:
        return len(self.metas) - self.taken


@dataclasses.dataclass
class KVSlab:
    """Host-side snapshot of prefilled-but-undecided cache rows — the
    cross-replica handoff unit of the disaggregated fleet.

    A prefill-specialist replica finishes chunked prefill, resolves the
    position-0 rows, and exports the survivors as one slab per prefill
    batch; a decode-specialist replica imports the slab straight into its
    ring's pending queue (:meth:`SlotRing.feed` takes exactly these
    parts).  Everything is host ``np`` arrays: the slab crosses replica
    (and eventually host) boundaries, so it must not pin the exporter's
    devices.  bf16 K/V round-trip bit-exactly through ``ml_dtypes``
    numpy; int8 slabs carry codes AND per-head scales (the
    ``cache_kv_map`` layout), so the import decodes to the identical
    values — the PARITY.md "Cross-replica KV handoff" class.

    ``metas``/``row_ids``/``last``/``lens`` ride along so the importer
    can feed the ring without re-touching the prompt text; ``length`` is
    the cache's scalar slots-filled-so-far."""

    k: np.ndarray                    # [L, m, T, Nkv, D]
    v: np.ndarray
    positions: np.ndarray            # [m, T] int32
    valid: np.ndarray                # [m, T] bool
    length: int                      # scalar slots filled (KVCache.length)
    last: np.ndarray                 # [m, ...] last-position logits/reduced
    lens: np.ndarray                 # [m] int32 real lengths
    row_ids: np.ndarray              # [m, 2] int32 yes/no target ids
    metas: List[Dict]                # per-row ring metadata
    k_scale: Optional[np.ndarray] = None   # [L, m, T, Nkv] fp32 (int8 only)
    v_scale: Optional[np.ndarray] = None

    def rows(self) -> int:
        return len(self.metas)

    def nbytes(self) -> int:
        out = 0
        for a in (self.k, self.v, self.positions, self.valid, self.last,
                  self.lens, self.row_ids, self.k_scale, self.v_scale):
            if a is not None:
                out += int(np.asarray(a).nbytes)
        return out


def slab_from_device(cache, last, lens, row_ids, metas) -> KVSlab:
    """Materialize gathered ring rows into a host :class:`KVSlab`.

    The fetch is SANCTIONED (runtime/strict.py): export is an explicit
    transfer point of the handoff protocol, not an accidental sync, so
    strict mode's ``blocked_transfers == 0`` contract holds across a
    disaggregated run."""
    with strict.sanctioned_fetch():
        fetched = jax.device_get(
            (cache.k, cache.v, cache.positions, cache.valid, cache.length,
             cache.k_scale, cache.v_scale, last, lens))
    k, v, positions, valid, length, ks, vs, last_h, lens_h = fetched
    return KVSlab(
        k=np.asarray(k), v=np.asarray(v),
        positions=np.asarray(positions, np.int32),
        valid=np.asarray(valid, bool),
        length=int(length),
        last=np.asarray(last_h),
        lens=np.asarray(lens_h, np.int32),
        row_ids=np.asarray(row_ids, np.int32),
        metas=list(metas),
        k_scale=None if ks is None else np.asarray(ks),
        v_scale=None if vs is None else np.asarray(vs),
    )


def slab_to_device(slab: KVSlab, put=jnp.asarray):
    """Rebuild ``(cache, last, lens, row_ids, metas)`` — the
    :meth:`SlotRing.feed` argument tuple — from a host slab.  ``put``
    is the importing engine's placement function (``ScoringEngine._put``
    -less sharding: the decode replica passes a closure that lands
    arrays on ITS mesh slice; the default is plain ``jnp.asarray``)."""
    cache = dmod.KVCache(
        k=put(slab.k), v=put(slab.v),
        positions=put(np.asarray(slab.positions, np.int32)),
        valid=put(np.asarray(slab.valid, bool)),
        length=jnp.asarray(slab.length, jnp.int32),
        k_scale=None if slab.k_scale is None else put(slab.k_scale),
        v_scale=None if slab.v_scale is None else put(slab.v_scale),
    )
    return (cache, put(slab.last), put(np.asarray(slab.lens, np.int32)),
            np.asarray(slab.row_ids, np.int32), list(slab.metas))


@functools.partial(jax.jit, static_argnames=("out_len",))
def _compact_cache_slots(cache, out_len: int):
    """Per-row slot compaction: stable-sort each row's slots valid-first
    (preserving the relative order of real slots, which are already
    position-ordered) and truncate the slot axis to ``out_len``.  Row
    content is exactly preserved; only the reduction grouping of the
    masked-zero terms moves (the chunked-prefill fp32 class)."""
    order = jnp.argsort(~cache.valid, axis=1, stable=True)    # [m, T]
    idx = order[:, :out_len]

    def take_kv(a):       # k/v [L, m, T, G, D]; scales [L, m, T, G]
        # broadcastable index built from STATIC rank arithmetic (a.ndim
        # is trace-time Python), one spelling for both layouts
        expand = idx.reshape((1,) + idx.shape + (1,) * (a.ndim - 3))
        return jnp.take_along_axis(a, expand, axis=2)

    return dmod.cache_kv_map(
        cache, take_kv,
        positions=jnp.take_along_axis(cache.positions, idx, axis=1),
        valid=jnp.take_along_axis(cache.valid, idx, axis=1),
    )


@functools.partial(jax.jit, static_argnames=("out_len",))
def _pad_cache_to(cache, out_len: int):
    """Append inert invalid slots up to ``out_len`` (the newcomer-into-
    vacated-lane pad: zero K/V the attention bias masks out; zero int8
    codes decode to zero under any scale)."""
    pad_t = out_len - cache.k.shape[2]

    def pad_slots(a):
        widths = ((0, 0), (0, 0), (0, pad_t)) + ((0, 0),) * (a.ndim - 3)
        return jnp.pad(a, widths)

    return dmod.cache_kv_map(
        cache, pad_slots,
        positions=jnp.pad(cache.positions, ((0, 0), (0, pad_t))),
        valid=jnp.pad(cache.valid, ((0, 0), (0, pad_t))),
    )


@jax.jit
def _gather_ring_rows(cache, idx):
    return dmod.cache_kv_map(
        cache, lambda a: a[:, idx],
        positions=cache.positions[idx], valid=cache.valid[idx],
    )


def _concat_caches(parts) -> dmod.KVCache:
    first = parts[0]
    if len(parts) == 1:
        return first
    return dmod.KVCache(
        k=jnp.concatenate([c.k for c in parts], axis=1),
        v=jnp.concatenate([c.v for c in parts], axis=1),
        positions=jnp.concatenate([c.positions for c in parts], axis=0),
        valid=jnp.concatenate([c.valid for c in parts], axis=0),
        length=first.length,
        k_scale=(jnp.concatenate([c.k_scale for c in parts], axis=1)
                 if first.k_scale is not None else None),
        v_scale=(jnp.concatenate([c.v_scale for c in parts], axis=1)
                 if first.v_scale is not None else None),
    )


def _cache_nbytes(cache) -> int:
    n = int(cache.k.size + cache.v.size) * cache.k.dtype.itemsize
    if cache.k_scale is not None:
        n += 4 * int(cache.k_scale.size + cache.v_scale.size)
    return n


def _blank_rows(template_cache, last_t, lens_dtype, rows: int,
                slot_len: int):
    """Numerically-inert filler: one valid zero-K slot per row (the
    softmax never reduces over an empty set), zero logits, length 1 —
    the _Phase2Pool blank rule, at the ring's current slot length."""
    L, _, _, G, D = template_cache.k.shape
    kv = jnp.zeros((L, rows, slot_len, G, D), template_cache.k.dtype)
    valid = jnp.zeros((rows, slot_len), bool).at[:, 0].set(True)
    scale = (jnp.ones((L, rows, slot_len, G), jnp.float32)
             if template_cache.k_scale is not None else None)
    cache = dmod.KVCache(
        k=kv, v=kv,
        positions=jnp.zeros((rows, slot_len),
                            template_cache.positions.dtype),
        valid=valid, length=template_cache.length,
        k_scale=scale, v_scale=scale,
    )
    last = jnp.zeros((rows, last_t.shape[1]), last_t.dtype)
    lens = jnp.ones((rows,), lens_dtype)
    return cache, last, lens


class SlotRing:
    """Fixed-capacity decode ring with retire-and-refill repack.

    One ring per quantized cache length (its consumers key rings the way
    the ``_Phase2Pool`` keys flushes).  Device state is a batched
    :class:`~..models.decoder.KVCache` plus per-lane logits / effective
    lengths / EOS flags; host state is one :class:`SlotRow` per occupied
    lane.  The loop is::

        feed(...) -> pending          pump() -> [repack | decode | retire]*

    ``pump(drain=False)`` decodes only while refill work exists (live
    rows freeze between cranks so lanes never spin empty waiting for
    traffic); ``pump(drain=True)`` runs everything to retirement.

    Callbacks (the consumer contract):

    - ``retire(row) -> int``: inspect ``row.toks[:row.decoded]`` from
      ``row.checked`` on; return the retirement step ``r*`` or -1.
      Called between chunks only — a pure function of the row's own
      tokens keeps results composition-independent.
    - ``batch_review(rows, stacked) -> None``: optional vectorized hook
      run before per-row ``retire`` with the live rows' stacked stats
      (the binary leg's yes/no scan runs once per chunk here instead of
      once per row).
    - ``emit(rows)``: finished rows, in retirement order, batched per
      pump.
    - ``refill_hook(n_free) -> bool``: optional starvation escape — the
      serve scheduler admits newly-queued compatible requests here,
      mid-decode, returning True when it fed new work.
    """

    def __init__(self, engine, *, steps: int, eos_id, capacity: int,
                 leg: str, workload: str,
                 retire: Callable, emit: Callable,
                 batch_review: Optional[Callable] = None,
                 refill_hook: Optional[Callable] = None,
                 refill: bool = True,
                 with_scores: bool = True,
                 min_check: int = 1,
                 chunk: Optional[int] = None,
                 compact_slack: Optional[int] = None,
                 pad_slice: Optional[Callable] = None):
        self.engine = engine
        self.steps = int(steps)
        self.eos_id = eos_id
        self.capacity = max(1, int(capacity))
        self.leg = leg
        self.workload = workload
        self.retire = retire
        self.emit = emit
        self.batch_review = batch_review
        self.refill_hook = refill_hook
        self.refill = bool(refill)
        self.with_scores = bool(with_scores)
        self.min_check = max(1, int(min_check))
        scan = max(1, int(getattr(engine.ecfg, "scan_chunk", 5)))
        # uniform chunks >= min_check: every row's first window covers the
        # positions its minimum-read contract needs inside ONE chunk (the
        # tail buffer's masked zeros make within-chunk positions exact, so
        # e.g. the confidence stats at positions 0-2 stay bit-identical
        # to the legacy 3-step opening chunk)
        self.chunk = int(chunk) if chunk else max(scan, self.min_check)
        self.chunk = min(self.chunk, self.steps)
        self.compact_slack = (int(compact_slack) if compact_slack
                              else self.steps + self.chunk)
        self._pad_slice = pad_slice or (lambda n: n)
        self.stats = OccupancyStats(capacity=self.capacity)
        self._pending: List[_PendingGroup] = []
        self._finished: List[SlotRow] = []
        # device state (None until the first repack)
        self._cache = None
        self._prev = None
        self._lens = None
        self._done = None
        self._tids = None
        self._prev_h = None           # K-decode frontier hidden
        self._slots: List[Optional[SlotRow]] = []
        self._base_len: Optional[int] = None

    # -- feeding ---------------------------------------------------------

    def feed(self, cache, last, lens, row_ids, metas) -> None:
        """Queue one gathered batch of real rows ([g] leading axes; no
        padding rows — callers gather real rows before feeding)."""
        if not len(metas):
            return
        base = int(cache.k.shape[2])
        if self._base_len is None or base > self._base_len:
            # mixed buckets share one ring on the slotted-serve and
            # grown-pack paths: the compaction target tracks the widest
            # PROMPT region fed so far (a row's valid slots never exceed
            # base + steps)
            self._base_len = base
        self._pending.append(_PendingGroup(cache, last, lens, row_ids,
                                           metas))
        self.stats.rows += len(metas)
        slot_counter("slot_rows", len(metas), self.leg, self.workload)

    def pending_rows(self) -> int:
        return sum(g.remaining() for g in self._pending)

    def live_rows(self) -> int:
        return sum(1 for s in self._slots
                   if s is not None and s.retire_step < 0)

    # -- pump ------------------------------------------------------------

    def pump(self, drain: bool = False) -> None:
        """Crank the ring: repack (drop retired lanes, refill from
        pending), decode one chunk, run retirement.  Without ``drain``
        the ring pauses as soon as no refill work remains — live rows
        freeze in place until the next feed — so lanes only ever spin
        when there is work to backfill them with."""
        while True:
            if self.refill_hook is not None and not self._pending:
                self.refill_hook(self.capacity - self.live_rows())
            live, pending = self.live_rows(), self.pending_rows()
            if not live and not pending:
                break
            if not drain and not live and pending < self.capacity:
                break      # accumulate to capacity before spinning up —
                #            the pool-at-target cadence the flush had
            if not drain and live and not pending and live < self.capacity:
                self.stats.repack_stalls += 1
                slot_counter("slot_repack_stalls", 1, self.leg,
                             self.workload)
                break
            self._repack()
            if not self.live_rows():
                break
            self._decode_chunk()
            self._retirement_scan()
            # emit PER CHUNK (not per pump): consumers that grow new work
            # out of finished rows (the packed autoregressive-demo stages)
            # feed the pending queue in time for the NEXT repack, which is
            # what lets a later-stage pack refill a lane mid-decode
            self._flush_finished()
        self._flush_finished()
        if self._cache is not None and not self.live_rows():
            # every lane retired and nothing refilled: stream the whole
            # ring's K/V back to the allocator instead of pinning it
            # until the next crank
            record_counter("completion_cache_bytes_freed",
                           _cache_nbytes(self._cache))
            self._slots = []
            self._cache = self._prev = self._lens = None
            self._done = self._tids = self._prev_h = None

    def drain(self) -> None:
        self.pump(drain=True)

    def _flush_finished(self) -> None:
        if self._finished:
            rows, self._finished = self._finished, []
            self.emit(rows)

    # -- repack ----------------------------------------------------------

    def _take_pending(self, n: int):
        """Pop up to ``n`` rows off the pending groups (FIFO): returns
        [(cache_sub, last_sub, lens_sub, ids, rows)] gathered per source
        group at its OWN slot length — :meth:`_repack` pads every part
        (live lanes and newcomers alike) to the common maximum."""
        out = []
        while n > 0 and self._pending:
            g = self._pending[0]
            take = min(n, g.remaining())
            idx = np.arange(g.taken, g.taken + take, dtype=np.int32)
            idx_dev = jnp.asarray(idx)
            sub = _gather_ring_rows(g.cache, idx_dev)
            rows = []
            for j in idx:
                rows.append(SlotRow(g.metas[j], g.row_ids[j], self.steps,
                                    dmod.REDUCED_TOPK, self.with_scores))
            out.append((sub, g.last[idx_dev], g.lens[idx_dev],
                        jnp.asarray(g.row_ids[idx]), rows))
            g.taken += take
            n -= take
            if not g.remaining():
                self._pending.pop(0)
        return out

    def _repack(self) -> None:
        """Drop retired lanes, refill from pending, re-blank the rest.

        The concat-based rebuild IS the refill: live lanes gather across
        (their decoded tails ride along), and every part — live lanes
        and newcomers alike — pads with inert invalid slots up to the
        WIDEST part's slot length before the concat.  When
        the slot axis has outgrown ``base_len + compact_slack`` the live
        rows' slots compact valid-first first (fragmentation — never
        mere retirement — pays for the rebuild)."""
        alive_idx = [i for i, s in enumerate(self._slots)
                     if s is not None and s.retire_step < 0]
        had_state = self._cache is not None
        n_free = self.capacity - len(alive_idx)
        retired_lanes = sum(1 for s in self._slots
                            if s is not None and s.retire_step >= 0)
        will_take = ((self.refill or not alive_idx) and n_free > 0
                     and self.pending_rows() > 0)
        if had_state and not retired_lanes and not will_take \
                and not self._needs_compaction():
            return                      # nothing changed: keep lanes
        parts_cache, parts_last, parts_lens, parts_ids, rows = \
            [], [], [], [], []
        old_bytes = _cache_nbytes(self._cache) if had_state else 0
        done_parts = []
        if alive_idx:
            idx_dev = jnp.asarray(np.asarray(alive_idx, np.int32))
            sub = _gather_ring_rows(self._cache, idx_dev)
            if self._needs_compaction():
                out_len = self._base_len + self.steps
                sub = _compact_cache_slots(sub, out_len)
                self.stats.compactions += 1
                slot_counter("slot_compactions", 1, self.leg, self.workload)
            parts_cache.append(sub)
            parts_last.append(self._prev[idx_dev])
            parts_lens.append(self._lens[idx_dev])
            parts_ids.append(self._tids[idx_dev])
            done_parts.append(self._done[idx_dev])
            rows.extend(self._slots[i] for i in alive_idx)
        groups = self._take_pending(n_free) \
            if (self.refill or not alive_idx) else []
        n_new = sum(len(g[4]) for g in groups)
        for sub, last, lens, tids, grows in groups:
            parts_cache.append(sub)
            parts_last.append(last)
            parts_lens.append(lens)
            parts_ids.append(tids)
            done_parts.append(jnp.zeros((len(grows),), bool))
            rows.extend(grows)
        # common slot length = the WIDEST part: newcomers from a longer
        # bucket pad the live lanes up, not only the other way around
        # (one ring serves mixed buckets in the slotted-serve and
        # grown-pack paths)
        cur_len = max((int(c.k.shape[2]) for c in parts_cache),
                      default=None)
        parts_cache = [c if int(c.k.shape[2]) == cur_len
                       else _pad_cache_to(c, cur_len)
                       for c in parts_cache]
        if not rows:
            if had_state:
                # the whole ring retired at once: every lane's K/V slice
                # streams back to the allocator
                record_counter("completion_cache_bytes_freed", old_bytes)
            self._slots = []
            self._cache = self._prev = self._lens = None
            self._done = self._tids = self._prev_h = None
            return
        m = self._pad_slice(len(rows))
        if m > len(rows):
            template = parts_cache[0]
            blank_c, blank_l, blank_n = _blank_rows(
                template, parts_last[0], parts_lens[0].dtype,
                m - len(rows), cur_len)
            parts_cache.append(blank_c)
            parts_last.append(blank_l)
            parts_lens.append(blank_n)
            parts_ids.append(jnp.zeros((m - len(rows), 2), jnp.int32))
            done_parts.append(jnp.zeros((m - len(rows),), bool))
        self._cache = _concat_caches(parts_cache)
        self._prev = (parts_last[0] if len(parts_last) == 1
                      else jnp.concatenate(parts_last, axis=0))
        self._lens = (parts_lens[0] if len(parts_lens) == 1
                      else jnp.concatenate(parts_lens, axis=0))
        self._tids = (parts_ids[0] if len(parts_ids) == 1
                      else jnp.concatenate(parts_ids, axis=0))
        self._done = (done_parts[0] if len(done_parts) == 1
                      else jnp.concatenate(done_parts, axis=0))
        # the K-decode frontier hidden is per-lane state the gather
        # cannot extend to newcomers: drop it and let the next chunk's
        # bootstrap block re-establish it (verify-and-accept keeps any
        # proposal source safe — a stale frontier costs passes, never
        # bits)
        self._prev_h = None
        self._slots = rows + [None] * (m - len(rows))
        if had_state:
            freed = old_bytes - _cache_nbytes(self._cache)
            if freed > 0:
                record_counter("completion_cache_bytes_freed", freed)
        self.stats.repacks += 1
        slot_counter("slot_repacks", 1, self.leg, self.workload)
        if n_new and had_state and alive_idx:
            self.stats.refills += n_new
            slot_counter("slot_refills", n_new, self.leg, self.workload)

    def _needs_compaction(self) -> bool:
        if self._cache is None or self._base_len is None:
            return False
        return (int(self._cache.k.shape[2])
                > self._base_len + self.compact_slack)

    # -- decode + retirement --------------------------------------------

    def _real_mask(self) -> np.ndarray:
        return np.asarray([s is not None and s.retire_step < 0
                           for s in self._slots], bool)

    def _decode_chunk(self) -> None:
        eng = self.engine
        n = self.chunk
        real = self._real_mask()
        ws = "reduced" if self.with_scores else False
        if eng._k_active():
            toks_c, sc_c, self._cache, self._prev, self._done, \
                self._prev_h, _acc = eng._k_decode_chunk(
                    self._cache, self._prev, self._lens, np.int32(0), n,
                    self.eos_id, self._done, ws,
                    self._tids if self.with_scores else None,
                    self._prev_h, real, self.leg)
        else:
            toks_c, sc_c, self._cache, self._prev, self._done = \
                dmod.decode_steps(
                    eng.params, eng.cfg, self._cache, self._prev,
                    self._lens, np.int32(0), n, self.eos_id, self._done,
                    with_scores=ws,
                    target_ids=self._tids if self.with_scores else None)
        self._lens = self._lens + n
        toks_np = np.asarray(toks_c)
        sc_np = (tuple(np.asarray(f) for f in sc_c)
                 if self.with_scores else None)
        self.stats.capacity_steps += self.capacity * n
        live_now = 0
        for i, row in enumerate(self._slots):
            if row is None or row.retire_step >= 0:
                continue
            take = min(n, self.steps - row.decoded)
            if take > 0:
                row.toks[row.decoded: row.decoded + take] = \
                    toks_np[i, :take]
                if sc_np is not None:
                    vals, ids_k, logz, tgt = sc_np
                    row.vals[row.decoded: row.decoded + take] = \
                        vals[i, :take]
                    row.ids_k[row.decoded: row.decoded + take] = \
                        ids_k[i, :take]
                    row.logz[row.decoded: row.decoded + take] = \
                        logz[i, :take]
                    row.tgt[row.decoded: row.decoded + take] = tgt[i, :take]
                self.stats.live_steps += take
                live_now += take
                slot_counter("slot_live_steps", take, self.leg,
                             self.workload)
            row.decoded += take
        # idle reconciles exactly with the occupancy block:
        # capacity_steps - live_steps, per chunk
        slot_counter("slot_idle_steps",
                     max(0, self.capacity * n - live_now), self.leg,
                     self.workload)

    def _retirement_scan(self) -> None:
        live = [s for s in self._slots
                if s is not None and s.retire_step < 0]
        if self.batch_review is not None and live:
            self.batch_review(live)
        for row in live:
            r = self.retire(row)
            if r is None:
                r = -1
            row.checked = row.decoded
            row.natural = r >= 0
            if r < 0 and row.decoded >= self.steps:
                r = row.decoded            # budget exhausted: force-retire
            if r >= 0:
                row.retire_step = int(r)
                self._finished.append(row)
                self.stats.row_steps.append(row.decoded)
                slot_counter("slot_retired", 1, self.leg, self.workload)
