"""Execution planning: will this (model, quant, batch, seq) fit the chip?

The round-3 profiling pass (PARITY.md "bf16 fallback") found exactly ONE
working bf16 configuration for a 7B on a 16 GB v5e — Pallas flash attention
at batch <= 64 — because bf16 weights (~13 GB) leave no room for the dense
S×T attention-score tensors at any sweep batch, while the flash kernel
streams scores in blocks.  That routing lived as an inline special case in
bench.py; this module makes it a library decision the sweeps, the bench,
and a regression test share, so the only-working bf16 path cannot silently
regress (round-4 verdict item 7).

The budget model is CALIBRATED against the measured v5e anchor points
rather than derived from first principles (XLA's fusion decides what
actually coexists in HBM):

- w8a8 int8, dense, batch 192, seq 432: fits (the 38 p/s headline config)
- bf16, dense, batch 64-192: OOM (measured round 3)
- bf16, flash, batch 64: fits (21.2 p/s); batch 128: OOM

Terms reproducing all five observations: bf16 score tensor (XLA keeps the
fused softmax in bf16 at sweep shapes — an fp32 [B,H,S,S] alone would
exceed what the measured-fitting int8 config leaves free), a half-live-set
activation estimate (fusion means the widest transients never fully
coexist), an fp32 output-accumulator workspace for the flash kernel, and a
fixed runtime reserve.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

HBM_BYTES_V5E = 16 << 30  # prior: v5e device spec (16 GiB HBM)
#: Head-room XLA/runtime needs beside our tensors (compiled program
#: buffers, fragmentation, transfer staging).  0.75 GiB separates the
#: measured-fitting configs from the measured-OOM ones.
RESERVE_BYTES = 3 << 28  # anchor: BENCH_r05
#: Extra head-room the FULL-STUDY (completions) path needs beyond the
#: reserve before allocator thrash sets in — see resolve_full_sweep_plan.
THRASH_HEADROOM_BYTES = 1 << 28  # anchor: BENCH_r05


def param_count(cfg) -> int:
    """Decoder parameter count from the geometry (embeddings + L blocks)."""
    h, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    nd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    per_layer = h * nd + 2 * h * kvd + nd * h + 2 * h * f
    total = v * h + cfg.num_layers * per_layer
    if not getattr(cfg, "tie_word_embeddings", False):
        total += v * h
    return total


def weight_bytes(cfg, quant: str) -> int:
    """bf16 = 2 B/param; w8a8 int8 = 1 B/param + fp32 per-channel scales
    (negligible next to the matrices, bounded here at 1%)."""
    n = param_count(cfg)
    return int(n * 1.01) if quant == "int8" else 2 * n


def dense_attention_bytes(cfg, batch: int, seq: int,
                          prefill_chunk: int = 0) -> int:
    """The bf16 [B, H, Sq, S] score tensor of one dense-attention layer.

    ``prefill_chunk`` > 0 is the chunked-prefill activation bound
    (models/decoder.chunked_prefill): the query axis of the widest
    transient is the chunk, not the bucket — the [B, S, T] blowup the long
    buckets pay under monolithic prefill shrinks to [B, chunk, T]."""
    q = min(prefill_chunk, seq) if prefill_chunk else seq
    return batch * cfg.num_heads * q * seq * 2


def activation_bytes(cfg, batch: int, seq: int,
                     prefill_chunk: int = 0) -> int:
    """Live activation set per layer step: residual stream + the widest
    transient (MLP intermediate), at half weight for fusion overlap.
    Under chunked prefill only one chunk's activations are live at a
    time, so the token axis is bounded by the chunk."""
    h, f = cfg.hidden_size, cfg.intermediate_size
    q = min(prefill_chunk, seq) if prefill_chunk else seq
    return batch * q * (h + 2 * f)


def kv_cache_bytes(cfg, batch: int, tokens: int,
                   kv_dtype: str = "bf16") -> int:
    """K+V cache bytes for ``tokens`` slots per row, dtype-aware.

    bf16 stores 2 B/element; int8 stores 1 B/element plus one fp32
    per-head scale per slot (ops/quant.quantize_kv — [L, B, T, G] scales
    beside [L, B, T, G, D] codes), i.e. ``1 + 4/head_dim`` bytes per
    element — a 1.88x cut at head_dim 64.  This is the term that makes the
    planner dtype-aware instead of discovering the int8 operating point by
    OOM (ISSUE 5 / arxiv 2204.06514's memory-planner lesson)."""
    if kv_dtype not in ("bf16", "int8"):
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
    elems = cfg.num_layers * batch * tokens * cfg.num_kv_heads * cfg.head_dim
    if kv_dtype == "int8":
        scales = cfg.num_layers * batch * tokens * cfg.num_kv_heads
        return 2 * (elems + 4 * scales)          # k+v: codes + fp32 scales
    return 2 * elems * 2                         # k+v: bf16


def flash_workspace_bytes(cfg, batch: int, seq: int) -> int:
    """fp32 output accumulator of the Pallas flash kernel."""
    return batch * seq * cfg.num_heads * cfg.head_dim * 4


def k_head_bytes(cfg, decode_k: int) -> int:
    """HBM the joint K-token decode's K-head pins: ``decode_k - 1``
    per-offset logit projections [H, V] in the weights dtype (bf16 —
    models/decoder.k_propose reads them like a second lm_head).  Full-
    vocab heads are the dominant K-decode cost at 7B scale (~0.6 GiB per
    offset on the falcon geometry), which is what prices large K out of
    small-HBM plans — the term the plan-search K axis budgets."""
    if decode_k <= 1:
        return 0
    return (decode_k - 1) * cfg.hidden_size * cfg.vocab_size * 2


# ---------------------------------------------------------------------------
# Fit-decision formatting — ONE spelling for every budget audit
# ---------------------------------------------------------------------------
# resolve_scoring_plan, resolve_full_sweep_plan, bench.py's stderr lines and
# the plan-search candidate table all print "how much of the budget does this
# configuration need".  Routing every one of them through these helpers is
# the guarantee that the JSON record's ``context`` block and the stderr
# diagnostics can never spell the same decision differently (ISSUE 8
# satellite).

def budget_audit(need_bytes: int, budget_bytes: int) -> str:
    """``"{need} GiB of {budget}"`` — the budget-audit fragment."""
    return f"{need_bytes / 2**30:.1f} GiB of {budget_bytes / 2**30:.1f}"


def budget_reject(need_bytes: int, budget_bytes: int) -> str:
    """``"{need} GiB > budget {budget}"`` — the over-budget fragment."""
    return (f"{need_bytes / 2**30:.1f} GiB > budget "
            f"{budget_bytes / 2**30:.1f}")


def pooled_conf_tag(pool_bytes: int, pool_rows: int) -> str:
    """The pooled-confidence annotation appended to full-study reasons."""
    return (f" + pooled-conf pool {pool_bytes / 2**30:.1f} GiB "
            f"({pool_rows} rows)")


def full_study_fit_reason(batch: int, kv_dtype: str, prefill_chunk: int,
                          pool_tag: str, need_bytes: int, budget_bytes: int,
                          base_reason: str) -> str:
    """Reason string for a full-study operating point that fits as asked."""
    return (f"full-study fits at batch {batch} with {kv_dtype} KV"
            + (f" + prefill chunk {prefill_chunk}" if prefill_chunk else "")
            + pool_tag
            + f": {budget_audit(need_bytes, budget_bytes)}"
            + f" [{base_reason}]")


def full_study_clamp_reason(requested_batch: int, batch: int,
                            completions_bytes: int, kv_dtype: str,
                            pipeline_depth: int, prefill_chunk: int,
                            pool_tag: str, budget_bytes: int) -> str:
    """Reason string for a full-study batch clamped to fit the budget."""
    return (f"full-study row contract pins "
            f"{completions_bytes / 2**30:.1f} GiB "
            f"of {kv_dtype} KV completion caches/scores at depth "
            f"{pipeline_depth}"
            + (f" (prefill chunk {prefill_chunk})" if prefill_chunk else "")
            + pool_tag
            + f"; batch {requested_batch} -> {batch} to fit "
              f"{budget_bytes / 2**30:.1f} GiB")


#: Quantized cache lengths for the cross-batch phase-2 pools
#: (runtime/engine._Phase2Pool): every pooled slice is padded (inert
#: invalid slots) up to the menu entry covering its cache length, so
#: slices from DIFFERENT length buckets pool and decode together.  Lives
#: HERE (not in engine) so the budget model prices the same quantized
#: shapes the engine actually pools.  TWO menus: the binary undecided-row
#: pool keeps the coarse r4 menu (coalescing 257-512-token buckets under
#: ONE key — finer entries would fragment its flushes and compile extra
#: decode-shape families for a pool that holds only ~10% of rows), while
#: the confidence pool — which holds EVERY row, so dead slots cost real
#: HBM — gets 320/384 entries covering the fused leg's prefix-bucket +
#: format-suffix cache lengths (a 256-token bucket + 16-token suffix used
#: to quantize all the way up to 512, doubling the pooled bytes).
POOL_LEN_MENU = (256, 512, 1024, 2048)
CONF_POOL_LEN_MENU = (256, 320, 384, 512, 1024, 2048)


def pool_len_for(cache_len: int, menu=POOL_LEN_MENU) -> int:
    """Smallest pool-menu cache length covering ``cache_len``."""
    for t in menu:
        if cache_len <= t:
            return t
    return cache_len


def conf_pool_len_for(cache_len: int) -> int:
    """Confidence-pool quantized cache length (the finer menu)."""
    return pool_len_for(cache_len, CONF_POOL_LEN_MENU)


def pooled_confidence_extra_bytes(cfg, target: int, seq: int,
                                  suffix_len: int = 64,
                                  score_steps: int = 10,
                                  kv_dtype: str = "bf16") -> int:
    """Peak K/V the pooled confidence decode pins beyond the per-batch
    live set (runtime/engine._Phase2Pool with ``leg="confidence"``): up to
    ``target`` gathered row slices at the pool's quantized cache length
    (prefix bucket + format suffix, :func:`pool_len_for`), grown by the
    scored-decode steps, TWICE — the source slices and the flush's
    concatenated copy coexist until the decode executes (the pool's own
    2x ``_inflight_bytes`` accounting rule).  This is a *time-varying*
    peak: early-exit retirement compacts retired rows' slices away per
    decode chunk, so the figure here is the no-retirement worst case the
    fit decision must survive."""
    pool_len = conf_pool_len_for(seq + suffix_len)
    return 2 * kv_cache_bytes(cfg, target, pool_len + score_steps, kv_dtype)


def slot_refill_pool_bytes(cfg, target: int, batch: int, seq: int,
                           suffix_len: int = 64, score_steps: int = 10,
                           kv_dtype: str = "bf16") -> int:
    """REFILL-model confidence-pool peak (decode-then-repack,
    runtime/slots.py): the slot ring holds at most ``target`` LIVE rows
    grown by the scored steps, plus one prefill batch of gathered
    slices waiting in the pending queue for lanes — NOT the 2x
    whole-accumulation worst case :func:`pooled_confidence_extra_bytes`
    prices for the all-or-nothing flush (where every gathered slice and
    its concatenated copy coexist until the flush decode executes).
    Retired lanes' K/V are dropped at the next repack, so the ring's
    steady-state residency is capacity-shaped, not accumulation-shaped.
    The legacy function (and every anchor pin built on it) is untouched;
    plan search opts in per candidate via ``slot_repack=True``."""
    pool_len = conf_pool_len_for(seq + suffix_len)
    live = kv_cache_bytes(cfg, target, pool_len + score_steps, kv_dtype)
    pending = kv_cache_bytes(cfg, min(batch, target), pool_len, kv_dtype)
    return live + pending


def completions_extra_bytes(cfg, batch: int, seq: int,
                            gen_tokens: int = 50, score_steps: int = 10,
                            pipeline_depth: int = 2,
                            reduced_scores: bool = True,
                            kv_dtype: str = "bf16") -> int:
    """Extra live set of the FULL-STUDY row contract (decode_completions +
    confidence), per in-flight pipelined batch: the prefill-output bf16 KV
    cache at the bucket length, the cache grown to seq+gen_tokens by the
    completion chunks' concats (old + new coexist transiently, so BOTH
    count twice), and the fp32 [B, V] next-token logits.  The scored chunk
    stacks only ``models.decoder.ReducedScores`` statistics (~B*steps*41
    floats — a rounding error here), NOT the fp32 [B, steps, V] buffer the
    r4 engine pinned (~580 MB per in-flight batch at sweep shapes).

    Calibrated against the measured v5e 10k-corpus anchors (reduced-score
    engine, int8 falcon-7b, 256-token worst bucket, depth 2): batch 224
    fits and is the measured optimum (31.4 rows/s warm); 240 still runs
    but thrashes near the HBM edge (14.1 rows/s warm — allocator
    pressure); 256 OOMs mid-sweep.  The terms put 240 just past the
    budget, so requests above the boundary clamp to 224.

    ``kv_dtype`` makes the pinned-cache terms dtype-aware
    (:func:`kv_cache_bytes`): int8 KV nearly halves them, which is what
    lifts the full-study batch off the 224 cliff."""
    cache_b = kv_cache_bytes(cfg, batch, seq, kv_dtype)
    cache_g = kv_cache_bytes(cfg, batch, seq + gen_tokens, kv_dtype)
    logits = batch * cfg.vocab_size * 4                      # fp32 [B, V]
    if reduced_scores:
        scores = batch * score_steps * 41 * 4                # ReducedScores
    else:
        # Engines configured with top_k beyond ReducedScores' kept
        # candidates (models.decoder.REDUCED_TOPK) fall back to stacking
        # the full fp32 [B, steps, V] tensor per in-flight batch — the r4
        # live set.  Callers must pass reduced_scores=False for that
        # configuration or the plan under-reserves by ~580 MB per batch.
        scores = batch * score_steps * cfg.vocab_size * 4
    return pipeline_depth * (2 * (cache_b + cache_g) + logits + scores)


@dataclasses.dataclass(frozen=True)
class GenerationPlan:
    """Resolved per-call generation schedule for one scoring leg.

    ``cache_key`` EXPLICITLY includes the per-call ``max_new_tokens`` cap:
    the engine keeps one plan per key (runtime/engine._gen_plan), and the
    warmup pass registers one warmed program family per key — so the
    perturbation sweep's binary leg (50-token cap, ~5 decode chunks) and
    confidence leg (10-token cap, 1 chunk) each keep their own plan and
    compiled-program family instead of a cap-blind key letting one leg
    evict/overwrite the other's warm state between chunks.
    """
    scan_steps: int             # scored look-ahead positions (MAX_LOOK_AHEAD)
    total_new_tokens: int       # completion decode length for this leg
    chunks: Tuple[int, ...]     # decode_steps chunk sizes covering the total
    cache_key: Tuple            # (scan_steps, total, decode_completions, cap)

    def __iter__(self):         # legacy (steps, total) tuple unpacking
        return iter((self.scan_steps, self.total_new_tokens))

    def __eq__(self, other):    # legacy comparisons against (steps, total)
        if isinstance(other, tuple):
            return (self.scan_steps, self.total_new_tokens) == other
        return (isinstance(other, GenerationPlan)
                and self.cache_key == other.cache_key)

    def __hash__(self):
        return hash(self.cache_key)


def plan_cache_key(score_steps: int, max_look_ahead: int, default_cap: int,
                   decode_completions: bool,
                   max_new_tokens: Optional[int] = None) -> Tuple:
    """Engine-side lookup key for a leg's :class:`GenerationPlan`.

    Lives HERE, next to the plan it keys, so the cap-sensitivity contract
    (the per-call ``max_new_tokens`` override MUST be part of the key —
    see :class:`GenerationPlan`) has exactly one spelling; the engine's
    ``_gen_plan`` and the strict-mode recompile sentry's audit trail both
    depend on distinct legs resolving to distinct keys.  The raw config
    knobs are kept (rather than the resolved ``cache_key``) so two knob
    combinations that HAPPEN to resolve identically today still map to
    one plan each if resolution ever diverges."""
    return (score_steps, max_look_ahead, default_cap,
            bool(decode_completions), max_new_tokens)


def generation_plan(score_steps: int, max_look_ahead: int, default_cap: int,
                    decode_completions: bool,
                    max_new_tokens: Optional[int] = None) -> GenerationPlan:
    """Build the generation schedule the engine's ``_gen_plan`` used to
    compute inline: scored-scan steps, the leg's total decode length (the
    per-call ``max_new_tokens`` override, never below the scored scan), and
    the decode chunk sizes (``score_steps``-sized chunks; the first doubles
    as the scored look-ahead — runtime/engine consume loop)."""
    steps = max(score_steps, max_look_ahead)
    cap = default_cap if max_new_tokens is None else max_new_tokens
    total = max(steps, cap) if decode_completions else steps
    chunks, offset = [], 0
    while offset < total:
        chunks.append(min(steps, total - offset))
        offset += chunks[-1]
    return GenerationPlan(steps, total, tuple(chunks),
                          cache_key=(steps, total, decode_completions, cap))


def prefix_cache_extra_bytes(cfg, batch: int, prefix_len: int,
                             n_legs: int = 2, suffix_len: int = 64,
                             pipeline_depth: int = 2) -> int:
    """Extra HBM the fused prefix-reuse path (engine.score_prefixed) pins
    per in-flight pipelined batch beyond the unfused full-study live set:
    the shared prefix KV cache (bf16, k+v) plus each leg's extended copy
    (prefix + suffix slots — the extend concatenates, so prefix bytes count
    once per leg again while the leg is live).  Callers sizing a fused
    sweep batch should subtract this from the budget headroom the unfused
    plan (resolve_full_sweep_plan) leaves, or simply step the batch down
    one 32-step when it OOMs — the fused path also *removes* one full
    prompt prefill per row, so in practice the measured operating point
    moves by at most one menu step."""
    per_tok = cfg.num_layers * batch * cfg.num_kv_heads * cfg.head_dim * 2 * 2
    shared = per_tok * prefix_len
    legs = n_legs * per_tok * (prefix_len + suffix_len)
    return pipeline_depth * (shared + legs)


def full_study_need_terms(cfg, weight_b: int, attention_impl: str,
                          batch: int, seq: int, gen_tokens: int = 50,
                          score_steps: int = 10, pipeline_depth: int = 2,
                          reduced_scores: bool = True,
                          kv_dtype: str = "bf16", prefill_chunk: int = 0,
                          pooled_confidence: bool = False,
                          pool_target: Optional[int] = None,
                          decode_k: int = 1,
                          slot_repack: bool = False) -> dict:
    """Per-term HBM breakdown of the full-study live set at one operating
    point — the exact terms :func:`resolve_full_sweep_plan`'s ``need()``
    sums.  Exposed as a dict so the auto-parallel search
    (runtime/plan_search.py) can divide each term across the mesh axis
    that actually shards it (weights across tp·pp, batch-leading terms
    across dp, KV terms across tp only when the kv heads divide) instead
    of re-deriving the budget model.

    Keys: ``weights``, ``attn`` (score tensor / flash workspace),
    ``act`` (activation live set), ``completions`` (pinned completion
    caches + logits/scores), ``conf_pool`` (pooled-confidence worst-case
    peak; 0 unless ``pooled_confidence`` — priced by the refill model
    :func:`slot_refill_pool_bytes` when ``slot_repack``, else the legacy
    all-or-nothing accumulation), plus ``k_head`` (the joint
    K-decode's proposal projections, :func:`k_head_bytes`) ONLY when
    ``decode_k > 1`` — absent at the default so every existing term-sum
    pin stays byte-identical."""
    attn = (flash_workspace_bytes(cfg, batch, seq)
            if attention_impl == "flash"
            else dense_attention_bytes(cfg, batch, seq, prefill_chunk))
    conf_pool = 0
    if pooled_confidence and slot_repack:
        conf_pool = slot_refill_pool_bytes(
            cfg, pool_target or batch, batch, seq,
            score_steps=score_steps, kv_dtype=kv_dtype)
    elif pooled_confidence:
        conf_pool = pooled_confidence_extra_bytes(
            cfg, pool_target or batch, seq, score_steps=score_steps,
            kv_dtype=kv_dtype)
    terms = {
        "weights": weight_b,
        "attn": attn,
        "act": activation_bytes(cfg, batch, seq, prefill_chunk),
        "completions": completions_extra_bytes(
            cfg, batch, seq, gen_tokens, score_steps, pipeline_depth,
            reduced_scores, kv_dtype),
        "conf_pool": conf_pool,
    }
    if decode_k > 1:
        terms["k_head"] = k_head_bytes(cfg, decode_k)
    return terms


def packed_need_terms(cfg, weight_b: int, attention_impl: str,
                      batch_rows: int, packed_seq: int, packing: int,
                      pipeline_depth: int = 4) -> dict:
    """Per-term HBM breakdown of the PACKED anchor-scoring sweep
    (runtime/engine.score_packed): weights, the prefill attention
    transient at the PACKED row length (Q questions + demonstrations per
    row — dense attention is quadratic in it, which is what caps the
    packing factor), activations at the packed length, and the
    [B, K, V] fp32 anchor-logit transient per in-flight pipelined batch
    riding the ``completions`` key (the batch-leading-extras slot —
    :func:`~.plan_search.sharded_need_bytes` prices both workloads
    through the same keys).  No phase-2 pool, no KV cache, no decode:
    the packed path gathers anchor logits inside one prefill program."""
    attn = (flash_workspace_bytes(cfg, batch_rows, packed_seq)
            if attention_impl == "flash"
            else dense_attention_bytes(cfg, batch_rows, packed_seq))
    return {
        "weights": weight_b,
        "attn": attn,
        "act": activation_bytes(cfg, batch_rows, packed_seq),
        "completions": pipeline_depth * batch_rows * packing
        * cfg.vocab_size * 4,
    }


@dataclasses.dataclass
class ScoringPlan:
    attention_impl: str        # "xla" (dense) or "flash"
    batch: int                 # possibly clamped from the request
    fits_dense: bool           # dense attention fits at the REQUESTED batch
    weight_bytes: int
    reason: str


def resolve_scoring_plan(cfg, quant: str, batch: int, seq: int,
                         hbm_bytes: int = HBM_BYTES_V5E,
                         requested_impl: Optional[str] = None,
                         prefill_chunk: int = 0) -> ScoringPlan:
    """Route a scoring sweep onto the chip.

    - dense (XLA) attention is the throughput default (bench.py's outcome
      table: the flash kernel loses ~12% in situ as an opaque fusion
      boundary) — kept whenever weights + dense scores + activations fit;
    - otherwise the Pallas flash kernel (block-streamed scores), with the
      batch clamped (to a power of two, largest that fits weights +
      activations + kernel workspace) — the bf16-7B escape hatch
      (PARITY.md, measured: flash batch 64 = 21.2 p/s, dense OOM).

    ``requested_impl='flash'`` skips the dense feasibility check but still
    clamps the batch.  ``prefill_chunk`` > 0 budgets the chunked-prefill
    transient bound (the widest score/activation tensors carry a
    chunk-sized query axis — see dense_attention_bytes).  Callers must
    pass it ONLY for paths that actually prefill through
    ``engine._prefill`` (the completions / fused-leg paths): the pooled
    phase-2 path's ``_prefill_select`` keeps monolithic prefill by
    design, and claiming the discount for it would predict a fit the
    real program cannot run.
    """
    wb = weight_bytes(cfg, quant)
    budget = hbm_bytes - RESERVE_BYTES
    dense_need = wb + dense_attention_bytes(cfg, batch, seq, prefill_chunk) \
        + activation_bytes(cfg, batch, seq, prefill_chunk)
    fits_dense = dense_need <= budget
    if fits_dense and requested_impl != "flash":
        return ScoringPlan("xla", batch, True, wb,
                           f"dense fits: {budget_audit(dense_need, budget)}"
                           + (f" (prefill chunk {prefill_chunk})"
                              if prefill_chunk else ""))

    def flash_need(b):
        return wb + activation_bytes(cfg, b, seq, prefill_chunk) \
            + flash_workspace_bytes(cfg, b, seq)

    if flash_need(batch) <= budget:
        clamped = batch            # requested batch fits: no clamp
    else:
        per_row = max(1, flash_need(1) - wb)
        b_max = max(1, int((budget - wb) // per_row))
        clamped = 1                # largest fitting power of two
        while clamped * 2 <= min(batch, b_max):
            clamped *= 2
    impl = "flash" if not fits_dense or requested_impl == "flash" else "xla"
    return ScoringPlan(
        impl, clamped, fits_dense, wb,
        f"dense needs {budget_reject(dense_need, budget)}; "
        f"flash at batch {clamped}"
        if not fits_dense else f"flash requested; batch {clamped}",
    )


def resolve_full_sweep_plan(cfg, quant: str, batch: int, seq: int,
                            gen_tokens: int = 50, score_steps: int = 10,
                            pipeline_depth: int = 2,
                            hbm_bytes: int = HBM_BYTES_V5E,
                            requested_impl: Optional[str] = None,
                            top_k: Optional[int] = None,
                            kv_dtype: str = "bf16",
                            prefill_chunk: int = 0,
                            pooled_confidence: bool = False,
                            pool_target: Optional[int] = None,
                            slot_repack: bool = False) -> ScoringPlan:
    """Route the FULL-STUDY sweep (binary leg with completions + confidence
    leg): resolve the attention impl like a binary sweep, then shrink the
    batch (steps of 32) until the live set INCLUDING the completion path's
    pinned caches and score buffers (completions_extra_bytes) fits.

    ``top_k``: the engine's scan top-k, when known — a value beyond
    ReducedScores' kept candidates makes the engine stack full fp32
    score tensors, which this plan must budget for (None assumes the
    default reduced path).

    ``kv_dtype``/``prefill_chunk`` are the ISSUE-5 levers: int8 KV halves
    the pinned cache terms and chunked prefill bounds the attention
    transients, so the planner PREDICTS the full-study fit back at batch
    >= 320 (int8 KV + 128-token chunks) instead of clamping to the
    measured bf16 224 cliff — with the PR-1 OOM ladder as the safety net
    if the prediction is wrong on hardware.

    ``pooled_confidence`` budgets the ISSUE-7 confidence pool: the
    engine's leg-parameterized cross-batch pool gathers every confidence
    row's cache slice and runs one pooled digit decode per
    ``pool_target`` rows (default: the batch size), so the fit decision
    must carry :func:`pooled_confidence_extra_bytes` — the no-retirement
    worst-case pool peak — on top of the per-batch live set."""
    from ..models.decoder import REDUCED_TOPK

    reduced_scores = top_k is None or top_k <= REDUCED_TOPK
    base = resolve_scoring_plan(cfg, quant, batch, seq, hbm_bytes,
                                requested_impl, prefill_chunk)
    wb = base.weight_bytes
    # The completions path churns large short-lived buffers (chunk concats,
    # per-chunk caches), so running AT the budget edge thrashes the
    # allocator instead of OOMing cleanly: batch 240 at the 256-token
    # bucket measured 14.1 rows/s warm vs 224's 31.4 on identical code —
    # slower than the smaller batch it would replace.  Keep a quarter-GiB
    # of allocator working space beyond the ordinary reserve.
    budget = hbm_bytes - RESERVE_BYTES - THRASH_HEADROOM_BYTES

    def terms(b):
        return full_study_need_terms(
            cfg, wb, base.attention_impl, b, seq, gen_tokens, score_steps,
            pipeline_depth, reduced_scores, kv_dtype, prefill_chunk,
            pooled_confidence, pool_target, slot_repack=slot_repack)

    def need(b):
        return sum(terms(b).values())

    b = min(batch, base.batch)
    if need(b) > budget:
        b = max(32, (b // 32) * 32)     # step through multiples of 32:
        while b > 32 and need(b) > budget:  # batches stay sublane-aligned
            b -= 32
    # the tag prices the pool at the FITTED batch: with no explicit
    # pool_target the engine pools at its own batch_size, which is the
    # clamped batch the caller will actually run
    fitted = terms(b)
    pool_tag = (pooled_conf_tag(fitted["conf_pool"], pool_target or b)
                if pooled_confidence else "")
    if b == base.batch:
        # no full-study clamp: still report the full-study fit decision
        # (bench records this string per operating point)
        return dataclasses.replace(base, reason=full_study_fit_reason(
            b, kv_dtype, prefill_chunk, pool_tag, need(b), budget,
            base.reason))
    return ScoringPlan(
        base.attention_impl, b, base.fits_dense, wb,
        full_study_clamp_reason(batch, b, fitted["completions"], kv_dtype,
                                pipeline_depth, prefill_chunk, pool_tag,
                                budget),
    )
