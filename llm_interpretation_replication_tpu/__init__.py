"""TPU-native framework with the capabilities of
``jonathanhchoi/llm-interpretation-replication`` (the replication package for
"Off-the-Shelf Large Language Models Are Unreliable Judges").

The reference (see /root/reference, SURVEY.md) runs three empirical studies via a
serial HuggingFace/PyTorch/CUDA logprob loop plus vendor API pipelines.  This
package re-designs that stack TPU-first:

- ``models``        Flax causal-LM zoo (Falcon, GPT-NeoX family, BLOOM, Mistral,
                    OPT, T5 enc-dec) + HF checkpoint converters.
- ``ops``           XLA/Pallas compute ops: fused attention, yes/no logprob
                    extraction, weighted-confidence digit reconstruction.
- ``parallel``      device meshes, GSPMD sharding rules (dp/tp/sp), ring
                    attention, multi-host init, collective helpers.
- ``runtime``       HBM-resident parameter loading, bucketed batching, jit'd
                    score/train steps, sweep executor.
- ``scoring``       the behavioral core replacing ``get_yes_no_logprobs``
                    (reference: analysis/run_base_vs_instruct_100q.py:279-392).
- ``sweeps``        perturbation / 100q / base-vs-instruct / 8-model sweeps with
                    manifest checkpoint-resume and schema-exact CSV/XLSX writers.
- ``stats``         normality, truncated-normal, bootstrap, kappa, correlation,
                    compliance, similarity, power engines (reference L4).
- ``survey``        human-survey pipeline (reference survey_analysis/).
- ``api_backends``  OpenAI/Anthropic/Gemini sync + batch clients (stdlib HTTP).
- ``gen``           perturbation generators (rephrasings, irrelevant insertions).
- ``utils``         xlsx IO (no openpyxl), retry, logging, caching.
- ``native``        C components (Levenshtein kernel et al.) built via cc.
"""

__version__ = "0.1.0"
