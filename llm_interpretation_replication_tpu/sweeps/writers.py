"""Schema-exact result writers (contracts in SURVEY.md §2.8).

Every downstream statistics script keys on these exact column names; rows are
built from engine result dicts so the CSV/XLSX outputs are drop-in replacements
for the reference's.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import pandas as pd

#: perturbation result workbook columns (perturb_prompts.py:966-969)
PERTURBATION_COLUMNS = [
    "Model",
    "Original Main Part",
    "Response Format",
    "Confidence Format",
    "Rephrased Main Part",
    "Full Rephrased Prompt",
    "Full Confidence Prompt",
    "Model Response",
    "Model Confidence Response",
    "Log Probabilities",
    "Token_1_Prob",
    "Token_2_Prob",
    "Odds_Ratio",
    "Confidence Value",
    "Weighted Confidence",
]

#: the Claude Message-Batches workbook adds a 'Target Tokens' column and
#: reorders (perturb_prompts_claude_batch.py:276-296; byte-identical to the
#: recorded claude_opus_batch_perturbation_results.xlsx)
CLAUDE_PERTURBATION_COLUMNS = [
    "Model", "Original Main Part", "Response Format", "Confidence Format",
    "Rephrased Main Part", "Target Tokens", "Model Confidence Response",
    "Full Confidence Prompt", "Confidence Value", "Weighted Confidence",
    "Model Response", "Full Rephrased Prompt", "Log Probabilities",
    "Token_1_Prob", "Token_2_Prob", "Odds_Ratio",
]

#: base_vs_instruct_100q_results.csv (run_base_vs_instruct_100q.py:376-382,472-476,547-567)
BASE_VS_INSTRUCT_100Q_COLUMNS = [
    "yes_prob", "no_prob", "relative_prob", "completion", "success",
    "prompt", "model", "formatted_prompt", "model_family", "base_or_instruct",
]

#: data/model_comparison_results.csv (compare_base_vs_instruct.py:90-111)
MODEL_COMPARISON_COLUMNS = [
    "prompt", "model", "model_family", "base_or_instruct", "model_output",
    "yes_prob", "no_prob", "odds_ratio",
]

#: data/instruct_model_comparison_results.csv (compare_instruct_models.py:103-121)
INSTRUCT_COMPARISON_COLUMNS = [
    "prompt", "model", "model_family", "model_output",
    "yes_prob", "no_prob", "relative_prob",
]


def model_family_from_name(model_name: str) -> str:
    """``org/model-name`` → family slug (compare_instruct_models.py:108)."""
    tail = model_name.split("/")[1] if "/" in model_name else model_name
    return tail.split("-")[0].lower()


def perturbation_row(
    model: str,
    scenario: Dict,
    rephrased_main: str,
    response_text: str = "",
    confidence_text: str = "",
    logprobs_repr: str = "",
    token_1_prob: float = 0.0,
    token_2_prob: float = 0.0,
    odds_ratio: float = 0.0,
    confidence_value: Optional[int] = None,
    weighted_confidence: Optional[float] = None,
) -> Dict:
    return {
        "Model": model,
        "Original Main Part": scenario["original_main"],
        "Response Format": scenario["response_format"],
        "Confidence Format": scenario["confidence_format"],
        "Rephrased Main Part": rephrased_main,
        "Full Rephrased Prompt": f"{rephrased_main} {scenario['response_format']}",
        "Full Confidence Prompt": f"{rephrased_main} {scenario['confidence_format']}",
        "Model Response": response_text,
        "Model Confidence Response": confidence_text,
        "Log Probabilities": logprobs_repr,
        "Token_1_Prob": token_1_prob,
        "Token_2_Prob": token_2_prob,
        "Odds_Ratio": odds_ratio,
        "Confidence Value": confidence_value,
        "Weighted Confidence": weighted_confidence,
    }


def perturbation_frame(rows: Sequence[Dict]) -> pd.DataFrame:
    return pd.DataFrame(list(rows), columns=PERTURBATION_COLUMNS)


def base_vs_instruct_100q_frame(rows: Sequence[Dict]) -> pd.DataFrame:
    return pd.DataFrame(list(rows))[BASE_VS_INSTRUCT_100Q_COLUMNS]


def model_comparison_frame(outputs: Dict[str, Dict[str, Dict]], model_pairs) -> pd.DataFrame:
    """outputs[model][prompt] -> result dict; pairs of (base, instruct)."""
    data = []
    for pair in model_pairs:
        base_name, instruct_name = pair[0], pair[1]
        for model_name in (base_name, instruct_name):
            family = model_family_from_name(model_name)
            role = "base" if model_name == base_name else "instruct"
            for prompt, result in outputs.get(model_name, {}).items():
                data.append(
                    {
                        "prompt": prompt,
                        "model": model_name,
                        "model_family": family,
                        "base_or_instruct": role,
                        "model_output": result.get("completion", "N/A"),
                        "yes_prob": result.get("yes_prob", float("nan")),
                        "no_prob": result.get("no_prob", float("nan")),
                        "odds_ratio": result.get("odds_ratio", float("nan")),
                    }
                )
    return pd.DataFrame(data, columns=MODEL_COMPARISON_COLUMNS)


def instruct_comparison_frame(outputs: Dict[str, Dict[str, Dict]], models: Sequence[str]) -> pd.DataFrame:
    data = []
    for model_name in models:
        family = model_family_from_name(model_name)
        for prompt, result in outputs.get(model_name, {}).items():
            data.append(
                {
                    "prompt": prompt,
                    "model": model_name,
                    "model_family": family,
                    "model_output": result.get("completion", "N/A"),
                    "yes_prob": result.get("yes_prob", float("nan")),
                    "no_prob": result.get("no_prob", float("nan")),
                    "relative_prob": result.get("relative_prob", float("nan")),
                }
            )
    return pd.DataFrame(data, columns=INSTRUCT_COMPARISON_COLUMNS)
