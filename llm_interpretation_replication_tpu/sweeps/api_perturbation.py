"""Frontier-API perturbation sweep — the study-1 batch orchestration.

Rebuild of perturb_prompts.py's multi-model OpenAI Batch run
(:190-269 create_batch_requests, :398-549 extract_results_from_batch,
:551-667 process_model_batch, :917-946 ThreadPoolExecutor fan-out): per
scenario x rephrasing build the binary + confidence request pair, skip
triples already in the output workbook, submit through the client's
chunked batch lifecycle (50k cap, 24h window, 60s polling), extract
first-token target probabilities and the int-token weighted confidence,
and append the 15-column workbook incrementally per model.

Reasoning models (o*/gpt-5*) follow the reference's two modes: with
``skip_reasoning_logprobs`` (the default, SKIP_REASONING_MODEL_LOGPROBS=True
:48) only the confidence leg runs; otherwise the binary leg repeats
``REASONING_MODEL_RUNS`` times and probabilities are response-frequency
approximations (:412-445).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np
import pandas as pd

from ..api_backends.openai_client import build_batch_request, is_reasoning_model
from ..scoring.confidence import (
    extract_first_int,
    weighted_confidence_digits,
    weighted_confidence_single_tokens,
)
from ..utils.logging import SessionLogger
from ..utils.xlsx import append_xlsx, read_xlsx
from .writers import (
    CLAUDE_PERTURBATION_COLUMNS,
    PERTURBATION_COLUMNS,
    perturbation_frame,
    perturbation_row,
)

REASONING_MODEL_RUNS = 10  # perturb_prompts.py:46-47


def load_processed_triples(output_xlsx: str) -> Set[Tuple[str, str, str]]:
    """(Model, Original Main Part, Rephrased Main Part) triples already in the
    output workbook (resume semantics, perturb_prompts.py:161-188)."""
    import os

    if not os.path.exists(output_xlsx):
        return set()
    df = read_xlsx(output_xlsx)
    return {
        (str(r["Model"]), str(r["Original Main Part"]), str(r["Rephrased Main Part"]))
        for _, r in df.iterrows()
    }


def create_batch_requests(
    model: str,
    scenarios: Sequence[Dict],
    processed: Optional[Set[Tuple[str, str, str]]] = None,
    skip_reasoning_logprobs: bool = True,
    max_rephrasings: Optional[int] = None,
) -> Tuple[List[Dict], Dict[str, Dict]]:
    """Request list + custom_id -> prompt-info mapping (reference :190-269).

    ``scenarios`` are perturbations.json records (original_main,
    response_format, target_tokens, confidence_format, rephrasings).
    """
    reasoning = is_reasoning_model(model)
    requests: List[Dict] = []
    id_mapping: Dict[str, Dict] = {}
    counter = 0
    for prompt_idx, scenario in enumerate(scenarios):
        rephrasings = scenario["rephrasings"]
        if max_rephrasings is not None:      # 0 means "none", not "all"
            rephrasings = rephrasings[:max_rephrasings]
        for rephrase_idx, rephrased in enumerate(rephrasings):
            if processed and (model, scenario["original_main"], rephrased) in processed:
                continue
            formats = (
                ["confidence"] if (reasoning and skip_reasoning_logprobs)
                else ["binary", "confidence"]
            )
            for format_type in formats:
                suffix = (scenario["response_format"] if format_type == "binary"
                          else scenario["confidence_format"])
                full_prompt = f"{rephrased} {suffix}"
                runs = (REASONING_MODEL_RUNS
                        if reasoning and format_type == "binary" else 1)
                for run_idx in range(runs):
                    custom_id = f"req-{counter}"
                    id_mapping[custom_id] = {
                        "prompt_idx": prompt_idx,
                        "rephrase_idx": rephrase_idx,
                        "format_type": format_type,
                        "run_idx": run_idx,
                        "original_main": scenario["original_main"],
                        "response_format": scenario["response_format"],
                        "confidence_format": scenario["confidence_format"],
                        "rephrased_main": rephrased,
                        "target_tokens": list(scenario["target_tokens"]),
                        "model": model,
                    }
                    requests.append(
                        build_batch_request(
                            custom_id, model,
                            [{"role": "user", "content": full_prompt}],
                        )
                    )
                    counter += 1
    return requests, id_mapping


def group_batch_results(raw_results: Sequence[Dict],
                        id_mapping: Dict[str, Dict]) -> Dict[Tuple[int, int], Dict]:
    """Re-pair downloaded JSONL rows into per-(prompt, rephrasing) groups
    (reference :352-396): binary runs accumulate, confidence is singular."""
    grouped: Dict[Tuple[int, int], Dict] = {}
    for row in raw_results:
        info = id_mapping.get(row.get("custom_id"))
        if info is None:
            continue
        body = (row.get("response") or {}).get("body")
        if body is None or (row.get("error") is not None):
            continue
        key = (info["prompt_idx"], info["rephrase_idx"])
        slot = grouped.setdefault(
            key, {"mapping_info": info, "binary_results": [], "confidence_result": None}
        )
        if info["format_type"] == "binary":
            slot["binary_results"].append(body)
        else:
            slot["confidence_result"] = body
    return grouped


def extract_results_from_batch(
    grouped: Dict[Tuple[int, int], Dict],
    model: str,
    skip_reasoning_logprobs: bool = True,
    log=None,
) -> List[Dict]:
    """Batch bodies -> 15-column workbook rows (reference :398-549)."""
    reasoning = is_reasoning_model(model)
    rows: List[Dict] = []
    for key in sorted(grouped):
        slot = grouped[key]
        info = slot["mapping_info"]
        binary_results = slot["binary_results"]
        confidence_result = slot["confidence_result"]
        if not binary_results and not (reasoning and skip_reasoning_logprobs):
            if log:
                log(f"Warning: no binary results for {key}")
            continue
        if confidence_result is None:
            # half-failed pair: binary succeeded but confidence errored
            # (reasoning models in frequency mode included — skip-logprobs
            # mode only ever creates slots from confidence responses).
            # Writing the row would let triple-based resume skip it forever
            # with a null confidence — leave it out so resume retries, the
            # same semantics the Claude leg adopted for failed requests.
            if log:
                log(f"Warning: no confidence result for {key} — will retry on resume")
            continue

        # past the guards above, confidence_result is always present — a
        # null-confidence row is never a representable output
        response_body = None
        skip_mode = False
        weighted_confidence = None
        confidence_answer = confidence_result["choices"][0]["message"]["content"].strip()
        confidence_value = extract_first_int(confidence_answer)
        if reasoning and not skip_reasoning_logprobs:
            # frequency-based probability approximation over the runs
            t1 = t2 = 0
            texts = []
            for body in binary_results:
                text = body["choices"][0]["message"]["content"].strip()
                texts.append(text)
                if info["target_tokens"][0] in text:
                    t1 += 1
                elif info["target_tokens"][1] in text:
                    t2 += 1
            n = len(binary_results)
            token_1_prob = t1 / n if n else 0.0
            token_2_prob = t2 / n if n else 0.0
            answer_text = max(set(texts), key=texts.count) if texts else ""
            weighted_confidence = confidence_value
        elif reasoning:
            answer_text = "N/A (skipped for reasoning model)"
            token_1_prob = token_2_prob = 0.0
            skip_mode = True
            weighted_confidence = confidence_value
        else:
            response_body = binary_results[0]
            answer_text = response_body["choices"][0]["message"]["content"].strip()
            token_1_prob = token_2_prob = 0.0
            content = ((response_body["choices"][0].get("logprobs") or {})
                       .get("content") or [])
            if content:
                for cand in content[0].get("top_logprobs", []):
                    if cand["token"] == info["target_tokens"][0]:
                        token_1_prob = float(np.exp(cand["logprob"]))
                    elif cand["token"] == info["target_tokens"][1]:
                        token_2_prob = float(np.exp(cand["logprob"]))
            # logprob-weighted expected value over int tokens 0-100
            # across ALL positions (reference :505-526 — the batch path's
            # simple int scan; scoring/confidence holds the shared impl)
            positions = [
                [(c["token"], c["logprob"])
                 for c in token_info.get("top_logprobs", [])]
                for token_info in ((confidence_result["choices"][0]
                                    .get("logprobs") or {}).get("content") or [])
            ]
            weighted_confidence = weighted_confidence_single_tokens(positions)

        # reference: skip-logprobs rows record 0.0, not inf (:455)
        odds_ratio = (0.0 if skip_mode
                      else token_1_prob / token_2_prob if token_2_prob > 0
                      else float("inf"))
        rows.append({
            "Model": model,
            "Original Main Part": info["original_main"],
            "Response Format": info["response_format"],
            "Confidence Format": info["confidence_format"],
            "Rephrased Main Part": info["rephrased_main"],
            "Full Rephrased Prompt": f"{info['rephrased_main']} {info['response_format']}",
            "Full Confidence Prompt": f"{info['rephrased_main']} {info['confidence_format']}",
            "Model Response": answer_text,
            "Model Confidence Response": confidence_answer,
            "Log Probabilities": (
                "N/A for reasoning models" if reasoning
                else str((response_body or {}).get("choices", [{}])[0].get("logprobs", {}))
            ),
            "Token_1_Prob": token_1_prob,
            "Token_2_Prob": token_2_prob,
            "Odds_Ratio": odds_ratio,
            "Confidence Value": confidence_value,
            "Weighted Confidence": weighted_confidence,
        })
    return rows


def run_api_perturbation_sweep(
    client,
    models: Sequence[str],
    scenarios: Sequence[Dict],
    output_xlsx: str,
    max_workers: int = 3,
    poll_interval: float = 60.0,
    skip_reasoning_logprobs: bool = True,
    max_rephrasings: Optional[int] = None,
    cost_tracker=None,
    sleep=time.sleep,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    """Fan ≤``max_workers`` models through the Batch API concurrently
    (reference :917-946), appending each model's rows to ``output_xlsx`` as it
    finishes so a crash keeps completed models (resume skips their triples)."""
    log = log or SessionLogger()
    processed = load_processed_triples(output_xlsx)

    def run_model(model: str) -> List[Dict]:
        requests, id_mapping = create_batch_requests(
            model, scenarios, processed=processed,
            skip_reasoning_logprobs=skip_reasoning_logprobs,
            max_rephrasings=max_rephrasings,
        )
        if not requests:
            log(f"{model}: nothing to do (all triples processed)")
            return []
        log(f"{model}: submitting {len(requests)} batch requests")
        raw = client.run_batch(requests, poll_interval=poll_interval, sleep=sleep)
        if cost_tracker is not None:
            for row in raw:
                usage = ((row.get("response") or {}).get("body") or {}).get("usage")
                if usage:
                    cost_tracker.record(
                        model,
                        usage.get("prompt_tokens", 0),
                        usage.get("completion_tokens", 0),
                    )
        grouped = group_batch_results(raw, id_mapping)
        return extract_results_from_batch(
            grouped, model, skip_reasoning_logprobs=skip_reasoning_logprobs, log=log
        )

    failures: List[Tuple[str, Exception]] = []
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = {pool.submit(run_model, model): model for model in models}
        for future in as_completed(futures):
            model = futures[future]
            try:                 # one failed batch must not lose the others
                rows = future.result()
            # graftlint: disable=G05 reference :929-946 per-model guard: one failed API batch logs and the other vendors' batches continue
            except Exception as err:
                log(f"{model}: FAILED — {err}")
                failures.append((model, err))
                continue
            if rows:
                append_xlsx(perturbation_frame(rows), output_xlsx)
                log(f"{model}: appended {len(rows)} rows to {output_xlsx}")
    if failures and len(failures) == len(models):
        raise RuntimeError(f"every model failed: {failures}")
    import os

    return read_xlsx(output_xlsx) if os.path.exists(output_xlsx) else pd.DataFrame(
        columns=PERTURBATION_COLUMNS
    )


# ---------------------------------------------------------------------------
# Claude Message-Batches leg (perturb_prompts_claude_batch.py)
# ---------------------------------------------------------------------------
#
# Claude exposes no logprobs, so the batch sweep runs CONFIDENCE-ONLY at
# temperature 1.0 (:137-147); binary fields carry the reference's literal
# N/A sentinels and zeroed probabilities (:281-296).

def create_claude_batch_requests(
    model: str,
    scenarios: Sequence[Dict],
    processed: Optional[Set[Tuple[str, str]]] = None,
    max_rephrasings: Optional[int] = None,
) -> Tuple[List[Dict], Dict[str, Dict]]:
    """Confidence-only request list + id map; ``processed`` holds
    (original_main, rephrased_main) pairs already in the workbook."""
    from ..api_backends.anthropic_client import build_batch_request

    requests: List[Dict] = []
    id_mapping: Dict[str, Dict] = {}
    counter = 0
    for prompt_idx, scenario in enumerate(scenarios):
        rephrasings = scenario["rephrasings"]
        if max_rephrasings is not None:
            rephrasings = rephrasings[:max_rephrasings]
        for rephrase_idx, rephrased in enumerate(rephrasings):
            if processed and (scenario["original_main"], rephrased) in processed:
                continue
            custom_id = f"confidence-{counter}"
            id_mapping[custom_id] = {
                "prompt_idx": prompt_idx,
                "rephrase_idx": rephrase_idx,
                "original_main": scenario["original_main"],
                "response_format": scenario["response_format"],
                "confidence_format": scenario["confidence_format"],
                "rephrased_main": rephrased,
                "target_tokens": list(scenario["target_tokens"]),
            }
            requests.append(build_batch_request(
                custom_id, model,
                [{"role": "user",
                  "content": f"{rephrased} {scenario['confidence_format']}"}],
                temperature=1.0,
            ))
            counter += 1
    return requests, id_mapping


def extract_claude_batch_rows(raw_results: Sequence[Dict], id_mapping: Dict[str, Dict],
                              model: str, log=None) -> List[Dict]:
    """Batch result JSONL -> the reference's 16-column Claude workbook rows
    (incl. the extra 'Target Tokens' column, :276-296)."""
    rows: List[Dict] = []
    for row in raw_results:
        info = id_mapping.get(row.get("custom_id"))
        if info is None:
            continue
        result = row.get("result") or {}
        if result.get("type") != "succeeded":
            # leave errored/expired pairs OUT of the workbook so resume
            # retries them (the OpenAI leg's semantics; the reference wrote
            # empty rows that its own resume then skipped forever)
            if log:
                log(f"Warning: failed request {row.get('custom_id')} — will retry on resume")
            continue
        content = (result.get("message") or {}).get("content") or []
        text = (content[0].get("text", "") if content else "").strip()
        confidence = extract_first_int(text)
        rows.append({
            "Model": model,
            "Original Main Part": info["original_main"],
            "Response Format": info["response_format"],
            "Confidence Format": info["confidence_format"],
            "Rephrased Main Part": info["rephrased_main"],
            "Target Tokens": str(info["target_tokens"]),
            "Model Confidence Response": text,
            "Full Confidence Prompt": f"{info['rephrased_main']} {info['confidence_format']}",
            "Confidence Value": confidence,
            "Weighted Confidence": confidence,
            "Model Response": "N/A (Confidence-only mode)",
            "Full Rephrased Prompt": "N/A (Confidence-only mode)",
            "Log Probabilities": "N/A (Batch processing - logprobs not available)",
            "Token_1_Prob": 0.0,
            "Token_2_Prob": 0.0,
            "Odds_Ratio": 0.0,
        })
    return rows


def run_claude_perturbation_sweep(
    client,
    model: str,
    scenarios: Sequence[Dict],
    output_xlsx: str,
    poll_interval: float = 30.0,
    max_rephrasings: Optional[int] = None,
    sleep=time.sleep,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    """Submit-or-resume the confidence-only Claude batch sweep and append the
    16-column workbook (reference main flow, 10k chunks handled by
    ``client.run_batches``)."""
    import os

    log = log or SessionLogger()
    # resume per model: another model's rows in the same workbook must not
    # mask this one (the reference script was hardcoded single-model)
    processed = {
        (orig, reph)
        for m, orig, reph in load_processed_triples(output_xlsx)
        if m == model
    }
    requests, id_mapping = create_claude_batch_requests(
        model, scenarios, processed=processed, max_rephrasings=max_rephrasings
    )
    if requests:
        log(f"{model}: submitting {len(requests)} message-batch requests")
        raw = client.run_batches(requests, poll_interval=poll_interval, sleep=sleep)
        rows = extract_claude_batch_rows(raw, id_mapping, model, log=log)
        if rows:
            append_xlsx(pd.DataFrame(rows, columns=CLAUDE_PERTURBATION_COLUMNS),
                        output_xlsx)
            log(f"{model}: appended {len(rows)} rows to {output_xlsx}")
    else:
        log(f"{model}: nothing to do (all pairs processed)")
    return read_xlsx(output_xlsx) if os.path.exists(output_xlsx) else pd.DataFrame(
        columns=CLAUDE_PERTURBATION_COLUMNS
    )


# ---------------------------------------------------------------------------
# GPT sync leg (perturb_prompts_gpt.py)
# ---------------------------------------------------------------------------
#
# The reference's non-batch OpenAI sweep (:86-233): one binary + one
# confidence chat completion per rephrasing, prompts joined with a BLANK
# LINE ("{rephrasing}\n\n{format}", :156-157 — unlike the Gemini leg's
# single space), first-token top-20 logprob scan for the target tokens,
# single-token 3-position weighted confidence (:47-85), 0.5 s rate-limit
# sleep between pairs (:190), max_tokens=10 on both calls (:118,:143).
# The reference script writes its workbook only once at the end; this leg
# adds the checkpoint-append + resume-by-(model, original, rephrased)
# discipline the Claude/Gemini legs have.  Two DELIBERATE column-content
# deviations from perturb_prompts_gpt.py: (1) Token_i_Prob records the real
# first-position probabilities of the target tokens (the reference stubbed
# them to 0, :181-185, because its extractor never parsed the binary
# logprobs); (2) 'Log Probabilities' records the BINARY response's
# top-20 first-position logprobs — the data Token_i_Prob is derived from,
# auditable per row — where the reference stored the CONFIDENCE response's
# full logprobs dict (:170) that its analysis never read.

def _gpt_perturbation_row(client, model: str, scenario: Dict,
                          rephrased: str) -> Dict:
    import json as jsonlib
    import math

    from ..api_backends.evaluators import openai_content_and_logprobs

    binary_prompt = f"{rephrased}\n\n{scenario['response_format']}"
    confidence_prompt = f"{rephrased}\n\n{scenario['confidence_format']}"
    t1, t2 = scenario["target_tokens"][0], scenario["target_tokens"][1]

    binary = client.chat_completion(
        model, [{"role": "user", "content": binary_prompt}],
        max_tokens=10)  # perturb_prompts_gpt.py:118
    text, content = openai_content_and_logprobs(binary)
    p1 = p2 = 0.0
    top0 = content[0].get("top_logprobs", []) if content else []
    for item in top0:
        tok = (item.get("token") or "").strip()
        if tok == t1:
            p1 = math.exp(item["logprob"])
        elif tok == t2:
            p2 = math.exp(item["logprob"])

    conf = client.chat_completion(
        model, [{"role": "user", "content": confidence_prompt}],
        max_tokens=10)  # perturb_prompts_gpt.py:143
    conf_text, conf_content = openai_content_and_logprobs(conf)
    positions = [
        [(i["token"], i["logprob"]) for i in tok.get("top_logprobs", [])]
        for tok in conf_content
    ]
    return perturbation_row(
        model, scenario, rephrased,
        response_text=text,
        confidence_text=conf_text,
        logprobs_repr=jsonlib.dumps(
            [{"token": i.get("token"), "logprob": i.get("logprob")}
             for i in top0]),
        token_1_prob=p1,
        token_2_prob=p2,
        odds_ratio=p1 / p2 if p2 > 0 else float("inf"),
        confidence_value=extract_first_int(conf_text),
        weighted_confidence=weighted_confidence_single_tokens(positions),
    )


def run_gpt_perturbation_sweep(
    client,
    model: str,
    scenarios: Sequence[Dict],
    output_xlsx: str,
    checkpoint_every: int = 50,
    rate_limit_sleep: float = 0.5,
    max_rephrasings: Optional[int] = None,
    sleep=time.sleep,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    """Serial checkpointed GPT sync sweep: the reference's per-rephrasing
    loop with workbook append every ``checkpoint_every`` rows and resume by
    (model, original, rephrased) triple — the 15-column schema shared with
    the OpenAI-batch and Gemini legs."""
    import os

    if is_reasoning_model(model):
        # o*/gpt-5* return no logprobs, so every row would record
        # Token_i_Prob=0 garbage; the batch pipeline has the
        # reasoning-model modes (confidence-only / frequency repeats,
        # perturb_prompts.py:46-48) — route there instead of writing junk.
        raise ValueError(
            f"{model} is a reasoning model (no logprobs on the sync API); "
            f"use run-api-perturbation, whose batch pipeline handles "
            f"reasoning models")
    log = log or SessionLogger()
    processed = load_processed_triples(output_xlsx)
    work: List[Tuple[Dict, str]] = []
    for scenario in scenarios:
        rephrasings = scenario["rephrasings"]
        if max_rephrasings is not None:
            rephrasings = rephrasings[:max_rephrasings]
        for rephrased in rephrasings:
            if (model, scenario["original_main"], rephrased) not in processed:
                work.append((scenario, rephrased))
    if not work:
        log(f"{model}: nothing to do (all triples processed)")
    else:
        log(f"{model}: evaluating {len(work)} perturbations (sync)")
        pending: List[Dict] = []
        errors = 0
        for scenario, rephrased in work:
            try:
                pending.append(
                    _gpt_perturbation_row(client, model, scenario, rephrased))
            # graftlint: disable=G05 API-side failure: count it, log it, keep the paid sweep alive (no device errors flow here)
            except Exception as err:
                errors += 1
                log(f"{model}: evaluation failed — {err}")
            if len(pending) >= checkpoint_every:
                append_xlsx(perturbation_frame(pending), output_xlsx)
                log(f"{model}: checkpointed {len(pending)} rows")
                pending.clear()
            if rate_limit_sleep:
                sleep(rate_limit_sleep)
        if pending:
            append_xlsx(perturbation_frame(pending), output_xlsx)
            log(f"{model}: checkpointed {len(pending)} rows")
        if errors:
            log(f"{model}: {errors} evaluations failed (will retry on resume)")
            if errors == len(work):
                raise RuntimeError(
                    f"{model}: every evaluation failed ({errors}/{len(work)})"
                )
    return read_xlsx(output_xlsx) if os.path.exists(output_xlsx) else pd.DataFrame(
        columns=PERTURBATION_COLUMNS
    )


# ---------------------------------------------------------------------------
# Gemini sync/threaded leg (perturb_prompts_gemini.py / _parallel.py)
# ---------------------------------------------------------------------------
#
# Gemini's sync API returns logprobs (responseLogprobs=True, top 19), so the
# sweep evaluates binary + confidence per rephrasing directly: first-position
# target-token probabilities, multi-token digit reconstruction for weighted
# confidence (:270-416), 20-thread fan-out behind the client's token-bucket
# rate limiter (:30-64), and a workbook checkpoint every ``checkpoint_every``
# completions (:33, 295-311).

def _gemini_perturbation_row(client, model: str, scenario: Dict,
                             rephrased: str) -> Dict:
    import math

    binary_prompt = f"{rephrased} {scenario['response_format']}"
    confidence_prompt = f"{rephrased} {scenario['confidence_format']}"
    t1, t2 = scenario["target_tokens"][0], scenario["target_tokens"][1]

    binary = client.generate_content(model, binary_prompt, response_logprobs=True)
    positions = client.top_candidates_of(binary)
    p1 = p2 = 0.0
    if positions:
        for token, logprob in positions[0]:
            if token.strip() == t1:
                p1 = math.exp(logprob)
            elif token.strip() == t2:
                p2 = math.exp(logprob)

    conf = client.generate_content(model, confidence_prompt, response_logprobs=True)
    conf_text = client.text_of(conf)
    return perturbation_row(
        model, scenario, rephrased,
        response_text=client.text_of(binary),
        confidence_text=conf_text,
        logprobs_repr=str(positions[:3]),
        token_1_prob=p1,
        token_2_prob=p2,
        odds_ratio=p1 / p2 if p2 > 0 else float("inf"),
        confidence_value=extract_first_int(conf_text),
        weighted_confidence=weighted_confidence_digits(client.top_candidates_of(conf)),
    )


def run_gemini_perturbation_sweep(
    client,
    model: str,
    scenarios: Sequence[Dict],
    output_xlsx: str,
    max_workers: int = 20,
    checkpoint_every: int = 50,
    max_rephrasings: Optional[int] = None,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    """Threaded sync sweep with incremental workbook checkpoints and
    (model, original, rephrased) resume — the 15-column schema shared with
    the OpenAI leg (gemini_perturbation_results.xlsx matches it exactly)."""
    import os
    import threading

    log = log or SessionLogger()
    processed = load_processed_triples(output_xlsx)
    work: List[Tuple[Dict, str]] = []
    for scenario in scenarios:
        rephrasings = scenario["rephrasings"]
        if max_rephrasings is not None:
            rephrasings = rephrasings[:max_rephrasings]
        for rephrased in rephrasings:
            if (model, scenario["original_main"], rephrased) not in processed:
                work.append((scenario, rephrased))
    if not work:
        log(f"{model}: nothing to do (all triples processed)")
    else:
        log(f"{model}: evaluating {len(work)} perturbations on {max_workers} threads")
        pending: List[Dict] = []
        lock = threading.Lock()

        def flush_locked():
            if pending:
                append_xlsx(perturbation_frame(pending), output_xlsx)
                log(f"{model}: checkpointed {len(pending)} rows")
                pending.clear()

        def flush_with_lock():
            with lock:
                flush_locked()

        def flush_for_preemption():
            # Signal handlers run in the MAIN thread.  A blocking acquire
            # on a lock the main thread itself holds (the final
            # flush_with_lock below) would deadlock inside the preemption
            # grace window.  The bounded wait covers worker-held locks
            # (short appends); if the main thread is already mid-flush,
            # those rows are being written anyway — skip.
            if lock.acquire(timeout=5.0):
                try:
                    flush_locked()
                finally:
                    lock.release()

        def run_one(item):
            scenario, rephrased = item
            row = _gemini_perturbation_row(client, model, scenario, rephrased)
            with lock:
                pending.append(row)
                if len(pending) >= checkpoint_every:
                    flush_locked()

        # Preemption safety (runtime/faults.py): a SIGTERM/SIGINT in the
        # main thread checkpoints the completed-but-unflushed rows before
        # exit; the resumed sweep's triple-keyed skip set redoes only the
        # in-flight evaluations.
        from ..runtime.faults import PreemptionGuard

        errors = 0
        with PreemptionGuard(flush_for_preemption, label="gemini_perturbation"):
            pool = ThreadPoolExecutor(max_workers=max_workers)
            try:
                futures = [pool.submit(run_one, item) for item in work]
                for future in as_completed(futures):
                    try:
                        future.result()
                    # graftlint: disable=G05 API-side failure: count it, log it, keep the paid sweep alive (no device errors flow here)
                    except Exception as err:
                        errors += 1
                        log(f"{model}: evaluation failed — {err}")
            except BaseException:
                # preemption/Ctrl-C: drop the queued work instead of the
                # context manager's shutdown(wait=True) — the grace window
                # cannot absorb thousands of queued API calls.  Only the
                # <= max_workers in-flight calls finish (joined by the
                # executor's atexit hook); their rows re-run on resume.
                pool.shutdown(wait=False, cancel_futures=True)
                raise
            pool.shutdown(wait=True)
            flush_with_lock()
        if errors:
            log(f"{model}: {errors} evaluations failed (will retry on resume)")
            if errors == len(work):
                raise RuntimeError(
                    f"{model}: every evaluation failed ({errors}/{len(work)})"
                )
    return read_xlsx(output_xlsx) if os.path.exists(output_xlsx) else pd.DataFrame(
        columns=PERTURBATION_COLUMNS
    )
