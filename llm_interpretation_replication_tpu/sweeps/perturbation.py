"""10k-perturbation sweep on local TPU models.

The reference runs this sweep only against vendor APIs (perturb_prompts.py);
the TPU build makes the same sweep run against local checkpoints: per scenario
(5 × 2000 rephrasings), a binary leg scoring the two target tokens at the
first generated position (top-20 membership semantics like the API extractor,
perturb_prompts.py:480-498) and a confidence leg (greedy continuation parsed
for the first integer + digit-reconstruction weighted confidence).

Output workbook matches the 15-column schema (SURVEY.md §2.8) so
``analyze_perturbation_results.py``-equivalent stats consume it unchanged.
Resume: rows already present in the output workbook are skipped by
(model, original_main, rephrased_main) key (ibid.:161-188).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..scoring.confidence import (
    extract_first_int,
    top_candidates_from_scores,
    weighted_confidence_digits,
)
from ..utils.logging import SessionLogger
from ..utils.xlsx import read_xlsx, write_xlsx
from .writers import PERTURBATION_COLUMNS, perturbation_frame, perturbation_row

TOP_LOGPROBS = 20  # API extractor scans top-20 of the first token


def load_existing_keys(output_xlsx: str) -> set:
    if not os.path.exists(output_xlsx):
        return set()
    df = read_xlsx(output_xlsx)
    if df.empty:
        return set()
    return {
        (row["Model"], row["Original Main Part"], row["Rephrased Main Part"])
        for _, row in df.iterrows()
    }


def run_model_perturbation_sweep(
    engine,
    model_name: str,
    scenarios: Sequence[Dict],
    output_xlsx: str,
    checkpoint_every: int = 100,
    max_rephrasings: Optional[int] = None,
    confidence: bool = True,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    log = log or SessionLogger()
    processed = load_existing_keys(output_xlsx)
    existing_df = read_xlsx(output_xlsx) if os.path.exists(output_xlsx) else perturbation_frame([])
    all_rows: List[Dict] = existing_df.to_dict("records") if len(existing_df) else []
    pending: List[Dict] = []

    def flush():
        nonlocal pending, all_rows
        if not pending:
            return
        all_rows.extend(pending)
        pending = []
        os.makedirs(os.path.dirname(os.path.abspath(output_xlsx)), exist_ok=True)
        write_xlsx(pd.DataFrame(all_rows, columns=PERTURBATION_COLUMNS), output_xlsx)

    for scenario in scenarios:
        rephrasings = scenario["rephrasings"]
        if max_rephrasings:
            rephrasings = rephrasings[:max_rephrasings]
        todo = [
            r for r in rephrasings
            if (model_name, scenario["original_main"], r) not in processed
        ]
        if not todo:
            log(f"Scenario already complete for {model_name}")
            continue
        log(f"{model_name}: scoring {len(todo)} rephrasings of scenario "
            f"{scenario['original_main'][:50]!r}...")
        targets = list(scenario["target_tokens"])
        binary_prompts = [f"{r} {scenario['response_format']}" for r in todo]
        probs = engine.first_token_relative_prob(
            binary_prompts, targets=targets, top_filter=TOP_LOGPROBS
        )
        responses = engine.score_prompts(binary_prompts, targets=targets)

        conf_values: List[Optional[int]] = [None] * len(todo)
        conf_texts = [""] * len(todo)
        weighted: List[Optional[float]] = [None] * len(todo)
        if confidence:
            conf_prompts = [f"{r} {scenario['confidence_format']}" for r in todo]
            conf_rows = engine.score_prompts(
                conf_prompts, targets=targets, with_confidence=True
            )
            for i, row in enumerate(conf_rows):
                conf_texts[i] = row["completion"]
                conf_values[i] = extract_first_int(row["completion"])
                weighted[i] = row.get("weighted_confidence")

        for i, reph in enumerate(todo):
            t1p, t2p = float(probs[i, 0]), float(probs[i, 1])
            odds = t1p / t2p if t2p > 0 else float("inf")
            pending.append(
                perturbation_row(
                    model_name,
                    scenario,
                    reph,
                    response_text=responses[i]["completion"],
                    confidence_text=conf_texts[i],
                    logprobs_repr=f"local:first_token_top{TOP_LOGPROBS}",
                    token_1_prob=t1p,
                    token_2_prob=t2p,
                    odds_ratio=odds,
                    confidence_value=conf_values[i],
                    weighted_confidence=weighted[i],
                )
            )
            processed.add((model_name, scenario["original_main"], reph))
            if len(pending) >= checkpoint_every:
                flush()
    flush()
    return pd.DataFrame(all_rows, columns=PERTURBATION_COLUMNS)
