"""10k-perturbation sweep on local TPU models.

The reference runs this sweep only against vendor APIs (perturb_prompts.py);
the TPU build makes the same sweep run against local checkpoints: per scenario
(5 × 2000 rephrasings), a binary leg scoring the two target tokens at the
first generated position (top-20 membership semantics like the API extractor,
perturb_prompts.py:480-498) and a confidence leg (greedy continuation parsed
for the first integer + digit-reconstruction weighted confidence).

Output workbook matches the 15-column schema (SURVEY.md §2.8) so
``analyze_perturbation_results.py``-equivalent stats consume it unchanged.
Resume: rows already present in the output workbook are skipped by
(model, original_main, rephrased_main) key (ibid.:161-188).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import tracer as obs
from ..runtime import faults
from ..scoring.confidence import extract_first_int
from ..utils.checkpoint import append_jsonl
from ..utils.logging import SessionLogger
from ..utils.retry import RetryPolicy
from ..utils.telemetry import record_fault
from ..utils.xlsx import read_xlsx, write_xlsx
from .writers import PERTURBATION_COLUMNS, perturbation_row

TOP_LOGPROBS = 20  # API extractor scans top-20 of the first token


def _sidelog_path(output_xlsx: str) -> str:
    return output_xlsx + ".rows.jsonl"


@contextlib.contextmanager
def _closing(prefetcher):
    """contextlib.closing that tolerates None (no prefetcher in play)."""
    try:
        yield prefetcher
    finally:
        if prefetcher is not None:
            prefetcher.close()


def _row_key(row: Dict) -> Tuple:
    return (row["Model"], row["Original Main Part"], row["Rephrased Main Part"])


def load_existing_rows(output_xlsx: str) -> Tuple[List[Dict], set]:
    """All checkpointed rows for a sweep output: the rendered workbook plus
    any side-log rows a crash left unrendered.  Returns (rows, key set).

    The side-log (``<output>.rows.jsonl``) is the sweep's append-only
    checkpoint: each flush APPENDS its new rows there in O(new) instead of
    rewriting the whole accumulating workbook (the r04 flush was O(total)
    per flush — O(n²) over a sweep, a measured 3-4 s tail at 10k rows and
    growing quadratically for two-leg or multi-model runs).  The xlsx is
    rendered from the full row list only at end of sweep, and the side-log
    is deleted once the render has landed, so a finished run looks exactly
    like before."""
    rows: List[Dict] = []
    if os.path.exists(output_xlsx):
        df = read_xlsx(output_xlsx)
        if len(df):
            rows = df.to_dict("records")
    seen = {_row_key(r) for r in rows}
    sidelog = _sidelog_path(output_xlsx)
    if os.path.exists(sidelog):
        with open(sidelog) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    # a hard kill mid-append can tear the trailing line
                    # (fsync covers completed flushes, not in-progress
                    # ones); its chunk simply re-scores on resume
                    continue
                key = _row_key(row)
                if key not in seen:
                    rows.append(row)
                    seen.add(key)
    return rows, seen


def load_existing_keys(output_xlsx: str) -> set:
    return load_existing_rows(output_xlsx)[1]


def run_model_perturbation_sweep(
    engine,
    model_name: str,
    scenarios: Sequence[Dict],
    output_xlsx: str,
    checkpoint_every: int = 100,
    max_rephrasings: Optional[int] = None,
    confidence: bool = True,
    confidence_max_new_tokens: int = 10,
    score_chunk: int = 2000,
    retry_policy: Optional[RetryPolicy] = None,
    log: Optional[SessionLogger] = None,
    fuse_prefix: bool = True,
    host_prefetch: bool = True,
) -> pd.DataFrame:
    """Local-model perturbation sweep (module docstring has the contract).

    ``fuse_prefix`` (default on, engines with ``score_prefixed`` only):
    each rephrasing tokenizes ONCE per chunk and prefills ONCE per row —
    the binary and confidence legs run as short pre-tokenized format-suffix
    extensions over the shared prefix KV cache instead of two full-prompt
    passes (the r5 full-study path tokenized and prefilled every ~100-430
    token rephrasing twice).  Suffixes tokenize as ``" " + format`` with no
    special tokens, so a leg's token stream is the split spelling of the
    reference's ``f"{rephrasing} {format}"`` prompt.  ``host_prefetch``
    tokenizes chunk N+1 on a background thread while the device scores
    chunk N (runtime/batching.HostPrefetcher; idle time the overlap fails
    to hide lands in the ``host_overlap_idle_ms`` telemetry counter).
    Engines without the fused API (older/foreign engines, API fakes) keep
    the legacy two-full-string path bit-for-bit."""
    log = log or SessionLogger()
    if getattr(engine, "plan_decision", None):
        # the operating point was chosen by the auto-parallel plan search
        # (runtime/plan_search.py) — name the decision in the sweep log so
        # the run is auditable the way bench records are
        log(f"[plan] {engine.plan_decision}")
    all_rows, processed = load_existing_rows(output_xlsx)
    pending: List[Dict] = []
    os.makedirs(os.path.dirname(os.path.abspath(output_xlsx)), exist_ok=True)
    sidelog = _sidelog_path(output_xlsx)

    in_flush = False

    def flush(final: bool = False):
        # O(new rows): append the checkpoint to the side-log, fsync'd so a
        # hard kill right after the flush cannot lose the rows it claimed
        # to checkpoint; the xlsx is rendered once, at end of sweep (resume
        # reads workbook + side-log, so durability is unchanged — see
        # load_existing_rows).  The in_flush latch makes the flush signal-
        # reentrancy-safe: the PreemptionGuard handler runs in this same
        # thread, and re-entering mid-append would write the pending rows
        # twice and interleave torn JSONL lines; the interrupted append's
        # buffer still lands when its file closes on unwind.
        nonlocal pending, all_rows, in_flush
        if in_flush:
            return
        in_flush = True
        try:
            with obs.span("checkpoint_flush", phase="host_write",
                          rows=len(pending), final=final):
                if pending:
                    append_jsonl(sidelog, pending)
                    all_rows.extend(pending)
                    pending = []
                if final:
                    write_xlsx(pd.DataFrame(all_rows,
                                            columns=PERTURBATION_COLUMNS),
                               output_xlsx)
                    if os.path.exists(sidelog):
                        os.remove(sidelog)
        finally:
            in_flush = False

    # Cross-scenario batching: the engine takes PER-PROMPT target pairs, so
    # one scoring call mixes all scenarios' rephrasings.  Per-scenario calls
    # paid a partial tail batch per (scenario, length-bucket) — ~40% of all
    # prefill rows were padding at the real corpus; batched across scenarios
    # the tails collapse to one per bucket per chunk.  ``score_chunk`` rows
    # are scored per call — it bounds CRASH LOSS (a crash during a chunk's
    # scoring calls loses that whole chunk; the workbook can only flush
    # rows whose chunk finished), so the 2000 default keeps the old
    # one-scenario durability while still merging tail batches whenever
    # scenarios have fewer rephrasings.  Raise it for maximum throughput on
    # reliable hardware.
    todo_items: List[tuple] = []
    for scenario in scenarios:
        rephrasings = scenario["rephrasings"]
        if max_rephrasings:
            rephrasings = rephrasings[:max_rephrasings]
        todo = [
            r for r in rephrasings
            if (model_name, scenario["original_main"], r) not in processed
        ]
        if not todo:
            log(f"Scenario already complete for {model_name}")
            continue
        log(f"{model_name}: scoring {len(todo)} rephrasings of scenario "
            f"{scenario['original_main'][:50]!r}...")
        todo_items.extend((scenario, r) for r in todo)

    # Foreign engines with the older score_prompts signature keep working:
    # the confidence cap kwarg is only passed when the signature names it
    # or accepts **kwargs (probed once, outside the chunk loop).
    import inspect

    try:
        params = inspect.signature(engine.score_prompts).parameters
        takes_cap = ("max_new_tokens" in params
                     or any(p.kind == p.VAR_KEYWORD for p in params.values()))
    except (TypeError, ValueError):
        takes_cap = True

    fuse = fuse_prefix and callable(getattr(engine, "score_prefixed", None))

    # Transient-retry wrappers (runtime/faults.py): an RPC hiccup or
    # connection reset from the tunneled runtime retries in place with
    # backoff instead of losing the chunk.  OOM is deliberately NOT
    # retried here — the engine's own batch-ladder back-off handles it at
    # batch granularity — and real errors propagate immediately.
    score_prompts = faults.retry_transient(
        engine.score_prompts, retry_policy, label="perturbation.score")
    first_token = faults.retry_transient(
        engine.first_token_relative_prob, retry_policy,
        label="perturbation.first_token")
    score_prefixed = (faults.retry_transient(
        engine.score_prefixed, retry_policy,
        label="perturbation.score_prefixed") if fuse else None)

    # Fused path host work, done ONCE per sweep: each scenario's format
    # suffixes pre-tokenize (leading space, no special tokens — the split
    # spelling of the reference's f"{rephrasing} {format}"), so per chunk
    # only the rephrasings themselves hit the tokenizer, once each
    # (satellite fix: the r5 path encoded BOTH full leg strings from
    # scratch — every rephrasing tokenized twice).
    if fuse:
        tok = engine.tokenizer
        suffix_ids = []
        for s in scenarios:
            texts = [" " + s["response_format"]]
            if confidence:
                texts.append(" " + s["confidence_format"])
            suffix_ids.append([
                list(ids) for ids in
                tok(texts, add_special_tokens=False)["input_ids"]])
        scenario_slot = {id(s): i for i, s in enumerate(scenarios)}

        def encode_chunk(chunk):
            """Tokenize one chunk's rephrasings (once each) and assemble
            pre-tokenized (prefix_ids, suffix_ids_per_leg) pairs — runs on
            the prefetcher's background thread, overlapped with device
            execution of the previous chunk."""
            prefix_ids = tok([r for _, r in chunk])["input_ids"]
            pairs = [
                (list(p), tuple(suffix_ids[scenario_slot[id(s)]]))
                for p, (s, _) in zip(prefix_ids, chunk)
            ]
            targets = [list(s["target_tokens"]) for s, _ in chunk]
            return chunk, pairs, targets

    def score_chunk_fused(chunk, pairs, targets):
        """One fused engine call covers BOTH legs: the rephrasing prefix
        prefills once per row and each leg extends the shared cache.  The
        confidence leg caps at ``confidence_max_new_tokens`` (default 10):
        every reference confidence contract is an API leg capped at
        max_tokens=10 (perturb_prompts_gpt.py:118,143), the parse reads
        only the first integer, and the weighted confidence reads only the
        first 3 positions; the cap keys the leg's OWN generation plan
        (runtime/plan.GenerationPlan), so it never evicts the binary
        leg's."""
        from ..runtime.engine import LegSpec

        legs = [LegSpec("binary")]
        if confidence:
            legs.append(LegSpec(
                "confidence", with_confidence=True,
                max_new_tokens=confidence_max_new_tokens or None))
        outs = score_prefixed(pairs, targets=targets, legs=legs)
        return outs[0], (outs[1] if confidence else None)

    def score_chunk_legacy(chunk, targets):
        """Engines without score_prefixed: the original two-full-string
        contract, byte-for-byte (API fakes and older engines hash/score
        the exact prompt strings)."""
        binary_prompts = [f"{r} {s['response_format']}" for s, r in chunk]
        responses = score_prompts(binary_prompts, targets=targets)
        conf_rows = None
        if confidence:
            conf_prompts = [f"{r} {s['confidence_format']}"
                            for s, r in chunk]
            cap_kw = ({"max_new_tokens": confidence_max_new_tokens}
                      if confidence_max_new_tokens and takes_cap else {})
            conf_rows = score_prompts(
                conf_prompts, targets=targets, with_confidence=True,
                **cap_kw)
        return responses, conf_rows

    chunks = [todo_items[start:start + score_chunk]
              for start in range(0, len(todo_items), score_chunk)]
    prefetcher = None
    if fuse and host_prefetch and len(chunks) > 1:
        # double-buffered host pipeline: chunk N+1 tokenizes while the
        # device scores chunk N
        from ..runtime.batching import HostPrefetcher

        prefetcher = HostPrefetcher(chunks, encode_chunk)
        chunk_iter = iter(prefetcher)
    elif fuse:
        chunk_iter = iter(map(encode_chunk, chunks))
    else:
        chunk_iter = iter((c, None, [list(s["target_tokens"]) for s, _ in c])
                          for c in chunks)

    # Preemption safety: shared/preemptible slices SIGTERM with a short
    # grace window.  The guard flushes the pending side-log rows before
    # exiting, so a preempted 10k sweep resumes losing at most the
    # in-flight score_chunk (the resume path skips every flushed row).
    from ..utils.telemetry import counters as _counters
    from ..utils.telemetry import counters_since as _counters_since

    counters_snap = _counters()
    sweep_t0 = time.perf_counter()
    done_rows, total_rows = 0, len(todo_items)
    # Run-health instrumentation (obs/flight.py): the flight recorder is
    # armed at the workbook's directory, so an OOM-ladder walk, retry
    # exhaustion, preemption, or watchdog trip leaves a flightrec-*.json
    # triage artifact next to the sweep's own outputs; the stall watchdog
    # is fed by the heartbeat below and WARNS (never kills) when no chunk
    # completes within k x the trailing median chunk time.
    obs_flight.enable(os.path.dirname(os.path.abspath(output_xlsx)))
    watchdog = obs_flight.StallWatchdog(
        label=f"perturbation:{model_name}")
    with faults.PreemptionGuard(flush, label="perturbation"), \
            _closing(prefetcher), watchdog:
        # _closing: a mid-sweep error (device OOM bubbling to the caller's
        # retry policy, preemption exit) must stop the prefetcher's worker
        # thread, or it keeps tokenizing the remaining corpus for a sweep
        # that is no longer running
        for start, (chunk, pairs, targets) in zip(
                range(0, len(todo_items), score_chunk), chunk_iter):
            if fuse:
                responses, conf_rows = score_chunk_fused(chunk, pairs,
                                                         targets)
            else:
                responses, conf_rows = score_chunk_legacy(chunk, targets)
            ecfg = getattr(engine, "ecfg", None)
            if (ecfg is not None
                    and getattr(ecfg, "first_token_top_filter", None) == TOP_LOGPROBS
                    and responses
                    and all("first_token_yes_prob" in row for row in responses)):
                # the scoring pass already computed the top-20-filtered
                # position-0 probabilities from its own prefill logits — no
                # second full forward for the binary leg.  Guarded on the
                # engine's filter matching the API extractor's top-20 contract
                # and on EVERY row carrying the fields (error rows don't).
                probs = np.asarray([
                    [row["first_token_yes_prob"], row["first_token_no_prob"],
                     row["first_token_relative_prob"]] for row in responses
                ])
            else:   # foreign/fake engines, custom filters, or error rows
                binary_prompts = (
                    [list(p) + list(s[0]) for p, s in pairs] if fuse
                    else [f"{r} {s['response_format']}" for s, r in chunk])
                probs = first_token(
                    binary_prompts, targets=targets, top_filter=TOP_LOGPROBS
                )
            n_nan = int(np.isnan(np.asarray(probs[:, :2], dtype=float))
                        .any(axis=1).sum())
            if n_nan:
                # NaN target probabilities (a numerically-broken checkpoint
                # or an injected fault) must stay auditable: the rows are
                # still written — the schema carries them and resume must
                # not rescore silently — but the event is on record.
                record_fault("nan_logits", model=model_name, rows=n_nan,
                             chunk_start=start)
                log(f"{model_name}: WARNING — {n_nan} rows carry NaN target "
                    f"probabilities (recorded in telemetry)")

            conf_values: List[Optional[int]] = [None] * len(chunk)
            conf_texts = [""] * len(chunk)
            weighted: List[Optional[float]] = [None] * len(chunk)
            if confidence:
                for i, row in enumerate(conf_rows):
                    conf_texts[i] = row["completion"]
                    conf_values[i] = extract_first_int(row["completion"])
                    weighted[i] = row.get("weighted_confidence")

            with obs.span("build_rows", phase="host_rows",
                          rows=len(chunk)):
                for i, (scenario, reph) in enumerate(chunk):
                    t1p, t2p = float(probs[i, 0]), float(probs[i, 1])
                    odds = t1p / t2p if t2p > 0 else float("inf")
                    pending.append(
                        perturbation_row(
                            model_name,
                            scenario,
                            reph,
                            response_text=responses[i]["completion"],
                            confidence_text=conf_texts[i],
                            logprobs_repr=f"local:first_token_top{TOP_LOGPROBS}",
                            token_1_prob=t1p,
                            token_2_prob=t2p,
                            odds_ratio=odds,
                            confidence_value=conf_values[i],
                            weighted_confidence=weighted[i],
                        )
                    )
                    processed.add((model_name, scenario["original_main"],
                                   reph))
                    if len(pending) >= checkpoint_every:
                        flush()
            # heartbeat: progress, achieved rate, and ETA per chunk.  ONE
            # code path (obs/metrics.heartbeat) produces the log line AND
            # the metrics-registry gauges (+ a JSONL metrics sample when
            # --metrics is armed) AND beats the stall watchdog — a
            # multi-hour sweep is observable from its log stream or from
            # the metrics surface, without scraping stderr.
            done_rows += len(chunk)
            obs_metrics.heartbeat(model_name, done_rows, total_rows,
                                  time.perf_counter() - sweep_t0, log=log)
        flush(final=True)
    delta = _counters_since(counters_snap)
    if delta.get("kv_cache_bytes_saved") or delta.get("prefill_chunks"):
        # the int8-KV / chunked-prefill operating point is auditable per
        # sweep, not just per bench run: a sweep that silently fell back
        # to the bf16 monolithic path is a different measurement
        log(f"{model_name}: kv_cache_bytes_saved="
            f"{delta.get('kv_cache_bytes_saved', 0):.0f} "
            f"prefill_chunks={delta.get('prefill_chunks', 0):.0f}")
    return pd.DataFrame(all_rows, columns=PERTURBATION_COLUMNS)


def run_packed_perturbation_sweep(
    engine,
    model_name: str,
    scenarios: Sequence[Dict],
    output_xlsx: str,
    packing: int = 4,
    drift_parity: bool = True,
    checkpoint_every: int = 100,
    max_rephrasings: Optional[int] = None,
    score_chunk: int = 2000,
    retry_policy: Optional[RetryPolicy] = None,
    log: Optional[SessionLogger] = None,
) -> Tuple[pd.DataFrame, Optional[Dict]]:
    """Packed multi-question perturbation sweep (scoring/packed.py —
    Auto-Demo batch prompting, arxiv 2410.01724): ``packing`` rephrasings
    concatenate into ONE row (each followed by its demonstration answer),
    the row prefills once, and every question's binary-leg probabilities
    read from the logits gathered at its answer anchor — one prefill
    amortized across Q questions, no decode path, no confidence leg.

    ``drift_parity`` (default on) scores the SAME rows isolated first
    (the API top-20 first-token contract — the packed rows' comparator)
    and returns a drift block (per-question |Δ relative_prob|
    distribution + flip rate, scoring/packed.drift_report) as a
    first-class result next to the DataFrame; the isolated pass also
    supplies each question's Auto-Demo demonstration (its own isolated
    answer).  With parity off, demonstrations fall back to each
    scenario's nominal yes target.

    Workbook rows keep the 15-column schema: ``Model Response`` is empty
    (nothing decodes), ``Log Probabilities`` names the packed extractor
    (``local:packed{Q}:first_token_top20``), and the confidence columns
    are None — resume keys and downstream readers are unchanged.
    Returns ``(DataFrame, drift_report | None)``."""
    from ..scoring import packed as packed_mod

    if not callable(getattr(engine, "score_packed", None)):
        raise ValueError(
            "packed sweep needs an engine with score_packed (the anchor-"
            "gather prefill path); foreign engines score isolated only")
    log = log or SessionLogger()
    if getattr(engine, "plan_decision", None):
        log(f"[plan] {engine.plan_decision}")
    all_rows, processed = load_existing_rows(output_xlsx)
    pending: List[Dict] = []
    os.makedirs(os.path.dirname(os.path.abspath(output_xlsx)), exist_ok=True)
    sidelog = _sidelog_path(output_xlsx)
    in_flush = False

    def flush(final: bool = False):
        nonlocal pending, all_rows, in_flush
        if in_flush:
            return
        in_flush = True
        try:
            with obs.span("checkpoint_flush", phase="host_write",
                          rows=len(pending), final=final):
                if pending:
                    append_jsonl(sidelog, pending)
                    all_rows.extend(pending)
                    pending = []
                if final:
                    write_xlsx(pd.DataFrame(all_rows,
                                            columns=PERTURBATION_COLUMNS),
                               output_xlsx)
                    if os.path.exists(sidelog):
                        os.remove(sidelog)
        finally:
            in_flush = False

    todo_items: List[tuple] = []
    for scenario in scenarios:
        rephrasings = scenario["rephrasings"]
        if max_rephrasings:
            rephrasings = rephrasings[:max_rephrasings]
        todo = [
            r for r in rephrasings
            if (model_name, scenario["original_main"], r) not in processed
        ]
        if not todo:
            log(f"Scenario already complete for {model_name}")
            continue
        log(f"{model_name}: packed-scoring {len(todo)} rephrasings "
            f"(Q={packing}) of scenario "
            f"{scenario['original_main'][:50]!r}...")
        todo_items.extend((scenario, r) for r in todo)

    score_packed = faults.retry_transient(
        engine.score_packed, retry_policy, label="perturbation.packed")
    first_token = faults.retry_transient(
        engine.first_token_relative_prob, retry_policy,
        label="perturbation.packed_isolated")

    sweep_t0 = time.perf_counter()
    done_rows, total_rows = 0, len(todo_items)
    drift_packed: List[float] = []
    drift_isolated: List[float] = []
    obs_flight.enable(os.path.dirname(os.path.abspath(output_xlsx)))
    watchdog = obs_flight.StallWatchdog(
        label=f"perturbation-packed:{model_name}")
    with faults.PreemptionGuard(flush, label="perturbation-packed"), \
            watchdog:
        for start in range(0, len(todo_items), score_chunk):
            chunk = todo_items[start:start + score_chunk]
            prompts = [f"{r} {s['response_format']}" for s, r in chunk]
            targets = [list(s["target_tokens"]) for s, _ in chunk]
            iso = None
            if drift_parity:
                iso = first_token(prompts, targets=targets,
                                  top_filter=TOP_LOGPROBS)
                demos = packed_mod.demos_from_relative_probs(
                    iso[:, 2], targets)
            else:
                demos = [t[0] for t in targets]
            packs = packed_mod.build_packs(prompts, packing, demos)
            rows = score_packed(packs, targets=targets)
            if iso is not None:
                drift_isolated.extend(float(v) for v in iso[:, 2])
                # engine error rows carry no first_token_* fields
                # (_error_row contract); NaN routes them into the drift
                # report's n_skipped instead of crashing the sweep
                drift_packed.extend(
                    row.get("first_token_relative_prob", float("nan"))
                    for row in rows)
            n_err = sum(1 for row in rows if not row.get("success"))
            if n_err:
                record_fault("packed_error_rows", model=model_name,
                             rows=n_err, chunk_start=start)
                log(f"{model_name}: WARNING — {n_err} packed rows are "
                    f"error rows (recorded in telemetry)")
            with obs.span("build_rows", phase="host_rows",
                          rows=len(chunk)):
                for i, (scenario, reph) in enumerate(chunk):
                    t1p = rows[i].get("first_token_yes_prob",
                                      float("nan"))
                    t2p = rows[i].get("first_token_no_prob",
                                      float("nan"))
                    odds = t1p / t2p if t2p > 0 else float("inf")
                    pending.append(
                        perturbation_row(
                            model_name, scenario, reph,
                            response_text="",
                            confidence_text="",
                            logprobs_repr=(f"local:packed{packing}:"
                                           f"first_token_top{TOP_LOGPROBS}"),
                            token_1_prob=t1p,
                            token_2_prob=t2p,
                            odds_ratio=odds,
                            confidence_value=None,
                            weighted_confidence=None,
                        )
                    )
                    processed.add((model_name, scenario["original_main"],
                                   reph))
                    if len(pending) >= checkpoint_every:
                        flush()
            done_rows += len(chunk)
            obs_metrics.heartbeat(f"{model_name}[packed{packing}]",
                                  done_rows, total_rows,
                                  time.perf_counter() - sweep_t0, log=log)
        flush(final=True)
    report = None
    if drift_parity:
        report = packed_mod.drift_report(drift_packed, drift_isolated,
                                         packing)
        log(f"{model_name}: packed drift |Δrel_prob| mean "
            f"{report['mean_abs_delta']} p90 {report['p90_abs_delta']} "
            f"flip rate {report['flip_rate']} "
            f"({report['n_questions']} questions, Q={packing})")
    return pd.DataFrame(all_rows, columns=PERTURBATION_COLUMNS), report
