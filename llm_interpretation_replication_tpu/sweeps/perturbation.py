"""10k-perturbation sweep on local TPU models.

The reference runs this sweep only against vendor APIs (perturb_prompts.py);
the TPU build makes the same sweep run against local checkpoints: per scenario
(5 × 2000 rephrasings), a binary leg scoring the two target tokens at the
first generated position (top-20 membership semantics like the API extractor,
perturb_prompts.py:480-498) and a confidence leg (greedy continuation parsed
for the first integer + digit-reconstruction weighted confidence).

Output workbook matches the 15-column schema (SURVEY.md §2.8) so
``analyze_perturbation_results.py``-equivalent stats consume it unchanged.
Resume: rows already present in the output workbook are skipped by
(model, original_main, rephrased_main) key (ibid.:161-188).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from ..scoring.confidence import (
    extract_first_int,
    top_candidates_from_scores,
    weighted_confidence_digits,
)
from ..utils.logging import SessionLogger
from ..utils.xlsx import read_xlsx, write_xlsx
from .writers import PERTURBATION_COLUMNS, perturbation_frame, perturbation_row

TOP_LOGPROBS = 20  # API extractor scans top-20 of the first token


def load_existing_keys(output_xlsx: str) -> set:
    if not os.path.exists(output_xlsx):
        return set()
    df = read_xlsx(output_xlsx)
    if df.empty:
        return set()
    return {
        (row["Model"], row["Original Main Part"], row["Rephrased Main Part"])
        for _, row in df.iterrows()
    }


def run_model_perturbation_sweep(
    engine,
    model_name: str,
    scenarios: Sequence[Dict],
    output_xlsx: str,
    checkpoint_every: int = 100,
    max_rephrasings: Optional[int] = None,
    confidence: bool = True,
    score_chunk: int = 2000,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    log = log or SessionLogger()
    processed = load_existing_keys(output_xlsx)
    existing_df = read_xlsx(output_xlsx) if os.path.exists(output_xlsx) else perturbation_frame([])
    all_rows: List[Dict] = existing_df.to_dict("records") if len(existing_df) else []
    pending: List[Dict] = []

    def flush():
        nonlocal pending, all_rows
        if not pending:
            return
        all_rows.extend(pending)
        pending = []
        os.makedirs(os.path.dirname(os.path.abspath(output_xlsx)), exist_ok=True)
        write_xlsx(pd.DataFrame(all_rows, columns=PERTURBATION_COLUMNS), output_xlsx)

    # Cross-scenario batching: the engine takes PER-PROMPT target pairs, so
    # one scoring call mixes all scenarios' rephrasings.  Per-scenario calls
    # paid a partial tail batch per (scenario, length-bucket) — ~40% of all
    # prefill rows were padding at the real corpus; batched across scenarios
    # the tails collapse to one per bucket per chunk.  ``score_chunk`` rows
    # are scored per call — it bounds CRASH LOSS (a crash during a chunk's
    # scoring calls loses that whole chunk; the workbook can only flush
    # rows whose chunk finished), so the 2000 default keeps the old
    # one-scenario durability while still merging tail batches whenever
    # scenarios have fewer rephrasings.  Raise it for maximum throughput on
    # reliable hardware.
    todo_items: List[tuple] = []
    for scenario in scenarios:
        rephrasings = scenario["rephrasings"]
        if max_rephrasings:
            rephrasings = rephrasings[:max_rephrasings]
        todo = [
            r for r in rephrasings
            if (model_name, scenario["original_main"], r) not in processed
        ]
        if not todo:
            log(f"Scenario already complete for {model_name}")
            continue
        log(f"{model_name}: scoring {len(todo)} rephrasings of scenario "
            f"{scenario['original_main'][:50]!r}...")
        todo_items.extend((scenario, r) for r in todo)

    for start in range(0, len(todo_items), score_chunk):
        chunk = todo_items[start:start + score_chunk]
        targets = [list(s["target_tokens"]) for s, _ in chunk]
        binary_prompts = [f"{r} {s['response_format']}" for s, r in chunk]
        responses = engine.score_prompts(binary_prompts, targets=targets)
        ecfg = getattr(engine, "ecfg", None)
        if (ecfg is not None
                and getattr(ecfg, "first_token_top_filter", None) == TOP_LOGPROBS
                and responses
                and all("first_token_yes_prob" in row for row in responses)):
            # the scoring pass already computed the top-20-filtered
            # position-0 probabilities from its own prefill logits — no
            # second full forward for the binary leg.  Guarded on the
            # engine's filter matching the API extractor's top-20 contract
            # and on EVERY row carrying the fields (error rows don't).
            probs = np.asarray([
                [row["first_token_yes_prob"], row["first_token_no_prob"],
                 row["first_token_relative_prob"]] for row in responses
            ])
        else:   # foreign/fake engines, custom filters, or error rows
            probs = engine.first_token_relative_prob(
                binary_prompts, targets=targets, top_filter=TOP_LOGPROBS
            )

        conf_values: List[Optional[int]] = [None] * len(chunk)
        conf_texts = [""] * len(chunk)
        weighted: List[Optional[float]] = [None] * len(chunk)
        if confidence:
            conf_prompts = [f"{r} {s['confidence_format']}" for s, r in chunk]
            conf_rows = engine.score_prompts(
                conf_prompts, targets=targets, with_confidence=True
            )
            for i, row in enumerate(conf_rows):
                conf_texts[i] = row["completion"]
                conf_values[i] = extract_first_int(row["completion"])
                weighted[i] = row.get("weighted_confidence")

        for i, (scenario, reph) in enumerate(chunk):
            t1p, t2p = float(probs[i, 0]), float(probs[i, 1])
            odds = t1p / t2p if t2p > 0 else float("inf")
            pending.append(
                perturbation_row(
                    model_name,
                    scenario,
                    reph,
                    response_text=responses[i]["completion"],
                    confidence_text=conf_texts[i],
                    logprobs_repr=f"local:first_token_top{TOP_LOGPROBS}",
                    token_1_prob=t1p,
                    token_2_prob=t2p,
                    odds_ratio=odds,
                    confidence_value=conf_values[i],
                    weighted_confidence=weighted[i],
                )
            )
            processed.add((model_name, scenario["original_main"], reph))
            if len(pending) >= checkpoint_every:
                flush()
    flush()
    return pd.DataFrame(all_rows, columns=PERTURBATION_COLUMNS)
