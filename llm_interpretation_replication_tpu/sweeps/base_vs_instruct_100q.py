"""100-question base-vs-instruct sweep (the north-star workload).

TPU-native rebuild of run_base_vs_instruct_100q.py:514-599: per (base,
instruct, family) pair, format the 100 ordinary-meaning questions (few-shot
for base, bare for instruct), score the whole batch in one jit'd sweep, and
checkpoint after every model so a preempted run resumes.  The CSV matches
``base_vs_instruct_100q_results.csv``; the statistics leg
(instruct−base MAE bootstrap) lives in stats/bootstrap.py.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import pandas as pd

from ..config import model_pairs_100q, ordinary_meaning_questions
from ..runtime import faults
from ..scoring.prompts import format_prompt, format_prompt_parts
from ..utils.checkpoint import CheckpointFile
from ..utils.logging import SessionLogger
from ..utils.retry import RetryPolicy
from .writers import base_vs_instruct_100q_frame

EngineFactory = Callable[[str], object]  # model name -> ScoringEngine


def run_model_on_prompts(engine, model_name: str, prompts: Sequence[str],
                         is_base_model: bool,
                         retry_policy: Optional[RetryPolicy] = None) -> List[Dict]:
    formatted = [format_prompt(q, is_base_model, model_name) for q in prompts]
    # Engines with the fused path get (prefix, suffix) pairs: the shared
    # few-shot preamble (identical across all 100 base-model questions)
    # tokenizes once per sweep and the question rides as a suffix
    # extension over its prefix cache; the joined parts reproduce
    # ``formatted`` byte-for-byte, so CSV columns and resume keys are
    # unchanged.  Engines without it (API fakes) score the full strings.
    # NOTE: with ONE leg there is no device-side prefill saving (the
    # engine does not dedupe identical prefixes across rows, and the
    # extend adds one program family + a KV concat per batch) — the win
    # here is host-side tokenize-once; device-side dedupe of the shared
    # preamble (prefill one row, broadcast its cache) is the natural
    # follow-up if 100q throughput ever matters.
    if callable(getattr(engine, "score_prefixed", None)):
        scored = [tuple(format_prompt_parts(q, is_base_model, model_name))
                  for q in prompts]
    else:
        scored = formatted
    try:
        # transient failures retry with backoff before the error-row
        # fallback burns the model's rows (runtime/faults.py)
        rows = faults.retry_transient(
            engine.score_prompts, retry_policy,
            label=f"100q.{model_name}")(scored)
    # graftlint: disable=G05 reference contract: a broken model emits an error row and the 100q sweep keeps moving (ref :484-496); OOM takes the engine's own back-off path before reaching here
    except Exception as err:
        return [
            {
                "prompt": q,
                "model": model_name,
                "formatted_prompt": f[:200],
                "yes_prob": float("nan"),
                "no_prob": float("nan"),
                "relative_prob": float("nan"),
                "completion": f"MODEL_ERROR: {str(err)[:50]}",
                "success": False,
            }
            for q, f in zip(prompts, formatted)
        ]
    out = []
    for q, f, row in zip(prompts, formatted, rows):
        out.append(
            {
                "yes_prob": row["yes_prob"],
                "no_prob": row["no_prob"],
                "relative_prob": row["relative_prob"],
                "completion": row["completion"],
                "success": row["success"],
                "prompt": q,
                "model": model_name,
                "formatted_prompt": f[:200],
            }
        )
    return out


def run_sweep(
    engine_factory: EngineFactory,
    model_pairs: Optional[Sequence[Dict]] = None,
    prompts: Optional[Sequence[str]] = None,
    checkpoint_path: str = "results/base_vs_instruct_100q_checkpoint.json",
    results_csv: str = "results/base_vs_instruct_100q_results.csv",
    retry_policy: Optional[RetryPolicy] = None,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    log = log or SessionLogger()
    model_pairs = model_pairs if model_pairs is not None else model_pairs_100q()
    prompts = prompts if prompts is not None else ordinary_meaning_questions()
    ck = CheckpointFile(checkpoint_path, default={"completed_models": [], "results": []})
    state = ck.load()
    completed = set(state["completed_models"])
    all_results: List[Dict] = list(state["results"])

    def save_checkpoint():
        # The guard below can fire this from the signal handler BETWEEN the
        # loop's `all_results.extend(...)` and `completed.add(...)`: unlike
        # the sibling sweeps (where the completion marker IS the stored
        # result), rows and marker are separate state here.  Keep the
        # checkpoint invariant — rows exactly for completed models — by
        # filtering, so the in-flight model re-scores on resume instead of
        # landing twice in the CSV.
        done = [r for r in all_results if r.get("model") in completed]
        ck.save({"completed_models": sorted(completed), "results": done})

    # Preemption safety: a SIGTERM mid-sweep persists the completed models
    # before exit; the resumed run redoes only the in-flight model.
    with faults.PreemptionGuard(save_checkpoint, label="100q_sweep"):
        for pair in model_pairs:
            base, instruct, family = pair["base"], pair["instruct"], pair["family"]
            for model_name, role, is_base in ((base, "base", True), (instruct, "instruct", False)):
                if model_name in completed:
                    log(f"Skipping {model_name} (already completed)")
                    continue
                log(f"Running {role.upper()} model: {model_name}")
                engine = engine_factory(model_name)
                results = run_model_on_prompts(engine, model_name, prompts,
                                               is_base, retry_policy=retry_policy)
                for r in results:
                    r["model_family"] = family
                    r["base_or_instruct"] = role
                all_results.extend(results)
                completed.add(model_name)
                save_checkpoint()
                log(f"Checkpoint saved after {model_name}")

    df = base_vs_instruct_100q_frame(all_results)
    import os

    os.makedirs(os.path.dirname(os.path.abspath(results_csv)), exist_ok=True)
    df.to_csv(results_csv, index=False)
    log(f"Saved {len(df)} rows to {results_csv}")
    return df
