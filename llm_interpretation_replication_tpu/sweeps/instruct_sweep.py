"""Instruct-model and base-vs-instruct word-meaning sweeps.

TPU rebuilds of compare_instruct_models.py (10-model instruct roster →
``instruct_model_comparison_results.csv``) and compare_base_vs_instruct.py
(base/instruct pairs → ``model_comparison_results.csv``), with per-model
checkpointing and the same CSV contracts.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional, Sequence

import pandas as pd

from ..config import instruct_sweep_models, model_pairs_word_meaning
from ..obs import metrics as obs_metrics
from ..runtime import faults
from ..scoring.prompts import format_instruct_prompt, format_prompt
from ..utils.checkpoint import CheckpointFile
from ..utils.logging import SessionLogger
from ..utils.retry import RetryPolicy
from .writers import instruct_comparison_frame, model_comparison_frame

EngineFactory = Callable[[str], object]


def _score_model(engine, model_name: str, prompts: Sequence[str], is_base: bool,
                 retry_policy: Optional[RetryPolicy] = None) -> Dict[str, Dict]:
    formatted = [format_prompt(q, is_base, model_name) for q in prompts]
    try:
        # transient errors retry with backoff (runtime/faults.py) BEFORE the
        # error-row fallback: a connection reset must not burn a whole
        # model's rows when a second attempt would have scored them
        rows = faults.retry_transient(
            engine.score_prompts, retry_policy,
            label=f"instruct.{model_name}")(formatted)
    # graftlint: disable=G05 per-model guard: one broken roster model must not sink the multi-model sweep; the engine's OOM ladder runs below this
    except Exception as err:
        rows = [
            {
                "yes_prob": float("nan"), "no_prob": float("nan"),
                "relative_prob": float("nan"), "odds_ratio": float("nan"),
                "completion": f"MODEL_ERROR: {str(err)[:50]}", "success": False,
            }
            for _ in prompts
        ]
    return {q: row for q, row in zip(prompts, rows)}


def _prompts_fingerprint(prompts: Sequence[str]) -> str:
    import hashlib

    digest = hashlib.sha256("\n".join(prompts).encode("utf-8")).hexdigest()
    return f"{len(prompts)}:{digest[:16]}"


def run_instruct_sweep(
    engine_factory: EngineFactory,
    prompts: Sequence[str],
    models: Optional[Sequence[str]] = None,
    checkpoint_path: str = "results/instruct_sweep_checkpoint.json",
    results_csv: str = "results/instruct_model_comparison_results.csv",
    retry_policy: Optional[RetryPolicy] = None,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    log = log or SessionLogger()
    models = list(models if models is not None else instruct_sweep_models())
    fp = _prompts_fingerprint(prompts)
    ck = CheckpointFile(checkpoint_path, default={"outputs": {}, "prompts": fp})
    state = ck.load()
    # Checkpoints are keyed by model name; a checkpoint from a DIFFERENT
    # question list (e.g. the 50q sweep's, when the survey-2 leg reuses its
    # output dir) would silently skip every model and republish the old rows.
    if state.get("prompts", fp) != fp:
        log(f"Checkpoint {checkpoint_path} belongs to a different prompt set "
            f"({state.get('prompts')} != {fp}); starting fresh")
        state = {"outputs": {}, "prompts": fp}
    state["prompts"] = fp
    outputs: Dict[str, Dict] = state["outputs"]
    # Preemption safety: SIGTERM/SIGINT saves the completed models'
    # checkpoint before exit, so the resumed sweep loses at most the
    # in-flight model (outputs only gains a key once a model finishes).
    sweep_t0 = time.perf_counter()
    scored = 0
    with faults.PreemptionGuard(
            lambda: ck.save({"outputs": outputs, "prompts": fp}),
            label="instruct_sweep"):
        for model_name in models:
            if model_name in outputs:
                log(f"Skipping {model_name} (checkpointed)")
                continue
            log(f"Running instruct model: {model_name}")
            engine = engine_factory(model_name)
            outputs[model_name] = _score_model(
                engine, model_name, prompts, is_base=False,
                retry_policy=retry_policy)
            ck.save({"outputs": outputs, "prompts": fp})
            # heartbeat (obs/metrics.py): progress, achieved rate, ETA —
            # the perturbation shell's per-chunk line at model
            # granularity, through the SAME code path, so the line and
            # the metrics-registry gauges agree by construction
            scored += 1
            remaining = sum(1 for m in models if m not in outputs)
            elapsed = time.perf_counter() - sweep_t0
            rate = scored * len(prompts) / elapsed if elapsed > 0 else 0.0
            obs_metrics.heartbeat(
                "instruct_sweep", len(outputs), len(models), elapsed,
                log=log, unit="models", rate=rate, rate_unit="rows",
                eta_s=(remaining * len(prompts) / rate) if rate > 0
                else 0.0)
    df = instruct_comparison_frame(outputs, models)
    os.makedirs(os.path.dirname(os.path.abspath(results_csv)), exist_ok=True)
    df.to_csv(results_csv, index=False)
    log(f"Saved {len(df)} rows to {results_csv}")
    return df


def run_base_vs_instruct_word_meaning(
    engine_factory: EngineFactory,
    prompts: Sequence[str],
    model_pairs: Optional[Sequence[Dict]] = None,
    checkpoint_path: str = "results/model_comparison_checkpoint.json",
    results_csv: str = "results/model_comparison_results.csv",
    retry_policy: Optional[RetryPolicy] = None,
    log: Optional[SessionLogger] = None,
) -> pd.DataFrame:
    log = log or SessionLogger()
    model_pairs = list(model_pairs if model_pairs is not None else model_pairs_word_meaning())
    pair_tuples = [(p["base"], p["instruct"]) for p in model_pairs]
    ck = CheckpointFile(checkpoint_path, default={"outputs": {}})
    state = ck.load()
    outputs: Dict[str, Dict] = state["outputs"]
    with faults.PreemptionGuard(lambda: ck.save({"outputs": outputs}),
                                label="base_vs_instruct_word_meaning"):
        for base, instruct in pair_tuples:
            for model_name, is_base in ((base, True), (instruct, False)):
                if model_name in outputs:
                    log(f"Skipping {model_name} (checkpointed)")
                    continue
                log(f"Running {'base' if is_base else 'instruct'} model: {model_name}")
                engine = engine_factory(model_name)
                outputs[model_name] = _score_model(
                    engine, model_name, prompts, is_base,
                    retry_policy=retry_policy)
                ck.save({"outputs": outputs})
    df = model_comparison_frame(outputs, pair_tuples)
    os.makedirs(os.path.dirname(os.path.abspath(results_csv)), exist_ok=True)
    df.to_csv(results_csv, index=False)
    log(f"Saved {len(df)} rows to {results_csv}")
    return df
