from .base_vs_instruct_100q import run_model_on_prompts, run_sweep
from .instruct_sweep import run_base_vs_instruct_word_meaning, run_instruct_sweep
from .perturbation import (
    load_existing_keys,
    run_model_perturbation_sweep,
    run_packed_perturbation_sweep,
)
from .writers import (
    BASE_VS_INSTRUCT_100Q_COLUMNS,
    INSTRUCT_COMPARISON_COLUMNS,
    MODEL_COMPARISON_COLUMNS,
    PERTURBATION_COLUMNS,
    base_vs_instruct_100q_frame,
    instruct_comparison_frame,
    model_comparison_frame,
    model_family_from_name,
    perturbation_frame,
    perturbation_row,
)
