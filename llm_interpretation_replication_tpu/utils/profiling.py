"""Tracing / profiling (aux subsystem the reference lacks — SURVEY.md §5).

``trace`` wraps ``jax.profiler`` for TensorBoard-viewable device traces;
``ThroughputMeter`` tracks prompts/sec and tokens/sec/chip for sweeps with
optional heartbeat persistence.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


@contextlib.contextmanager
def trace(log_dir: str, enabled: bool = True):
    """Capture a jax.profiler trace into ``log_dir`` (view with TensorBoard)."""
    if not enabled:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region inside a trace (shows up on the TraceViewer timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def top_device_ops(trace_dir: str, top_n: int = 25):
    """Per-op device-time totals out of a :func:`trace` capture — the
    headless answer to TensorBoard's op profile (no TB in the image).

    Parses the newest ``*.xplane.pb`` under ``trace_dir`` with the
    TensorFlow tsl proto and sums event durations per XLA op on each device
    plane.  Returns [(op_name, total_ms)] sorted descending.  This is the
    analysis that located the round-3 decode relayout loop: look for
    unexplained ``%while`` or ``%copy`` ops over large shapes between the
    compute fusions (PARITY.md bench notes).
    """
    import glob
    import os
    from collections import defaultdict

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    space = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        space.ParseFromString(f.read())
    totals: dict = defaultdict(float)
    for plane in space.planes:
        if "TPU" not in plane.name and "CPU" not in plane.name:
            continue
        # key on the authoritative map key — XEventMetadata.id is a
        # by-convention duplicate some producers leave unset
        names = {mid: m.name for mid, m in plane.event_metadata.items()}
        for line in plane.lines:
            # TPU device planes put XLA ops on "XLA Ops" lines; the CPU
            # backend logs thunk executions on its PjRt client thread line
            if "XLA Ops" not in line.name and "XLAPjRtCpuClient" not in line.name:
                continue
            for ev in line.events:
                totals[names.get(ev.metadata_id, "?")] += ev.duration_ps / 1e9
    return sorted(totals.items(), key=lambda kv: -kv[1])[:top_n]


class ThroughputMeter:
    def __init__(self, n_chips: int = 1, clock=time.perf_counter):
        self.n_chips = max(n_chips, 1)
        self._clock = clock
        self.reset()

    def reset(self):
        self._start = self._clock()
        self.prompts = 0
        self.tokens = 0

    def add(self, prompts: int, tokens: int = 0):
        self.prompts += prompts
        self.tokens += tokens

    def snapshot(self) -> dict:
        elapsed = max(self._clock() - self._start, 1e-9)
        return {
            "elapsed_sec": round(elapsed, 3),
            "prompts": self.prompts,
            "prompts_per_sec": round(self.prompts / elapsed, 4),
            "prompts_per_sec_per_chip": round(self.prompts / elapsed / self.n_chips, 4),
            "tokens_per_sec": round(self.tokens / elapsed, 2),
            "tokens_per_sec_per_chip": round(self.tokens / elapsed / self.n_chips, 2),
        }
