"""Tracing / profiling (aux subsystem the reference lacks — SURVEY.md §5).

``trace`` wraps ``jax.profiler`` for TensorBoard-viewable device traces;
``ThroughputMeter`` tracks prompts/sec and tokens/sec/chip for sweeps with
optional heartbeat persistence.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


@contextlib.contextmanager
def trace(log_dir: str, enabled: bool = True):
    """Capture a jax.profiler trace into ``log_dir`` (view with TensorBoard)."""
    if not enabled:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region inside a trace (shows up on the TraceViewer timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class ThroughputMeter:
    def __init__(self, n_chips: int = 1, clock=time.perf_counter):
        self.n_chips = max(n_chips, 1)
        self._clock = clock
        self.reset()

    def reset(self):
        self._start = self._clock()
        self.prompts = 0
        self.tokens = 0

    def add(self, prompts: int, tokens: int = 0):
        self.prompts += prompts
        self.tokens += tokens

    def snapshot(self) -> dict:
        elapsed = max(self._clock() - self._start, 1e-9)
        return {
            "elapsed_sec": round(elapsed, 3),
            "prompts": self.prompts,
            "prompts_per_sec": round(self.prompts / elapsed, 4),
            "prompts_per_sec_per_chip": round(self.prompts / elapsed / self.n_chips, 4),
            "tokens_per_sec": round(self.tokens / elapsed, 2),
            "tokens_per_sec_per_chip": round(self.tokens / elapsed / self.n_chips, 2),
        }
