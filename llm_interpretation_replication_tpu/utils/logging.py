"""Session logging + progress telemetry.

The reference logs with bare ``print`` plus a ``log_print`` that tees to a
session file (/root/reference/analysis/compare_instruct_models.py:20-40) and
writes ad-hoc progress JSON (evaluate_irrelevant_perturbations.py:111-128).
Here: one ``SessionLogger`` (stdout + optional file tee) and a ``Progress``
tracker that persists a JSON heartbeat for external monitoring.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import sys
import threading
import time
from typing import Optional


class SessionLogger:
    def __init__(self, log_file: Optional[str] = None, stream=None):
        self._stream = stream or sys.stdout
        self._file = None
        self._lock = threading.Lock()
        if log_file:
            os.makedirs(os.path.dirname(os.path.abspath(log_file)), exist_ok=True)
            self._file = open(log_file, "a", encoding="utf-8")

    def log(self, *parts, timestamp: bool = False) -> None:
        msg = " ".join(str(p) for p in parts)
        if timestamp:
            msg = f"[{_dt.datetime.now().isoformat(timespec='seconds')}] {msg}"
        with self._lock:
            print(msg, file=self._stream, flush=True)
            if self._file:
                self._file.write(msg + "\n")
                self._file.flush()

    __call__ = log

    def close(self) -> None:
        # same guard as log(): the scheduler thread may be mid-write when
        # the owning harness closes the session (G09 utils/logging.py
        # 'self._file = None' — close raced the guarded writer)
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None


class Progress:
    """Persistent progress heartbeat: counts, rate, ETA, arbitrary extras."""

    def __init__(self, total: int, path: Optional[str] = None, clock=time.monotonic):
        self.total = total
        self.done = 0
        self.path = path
        self._clock = clock
        self._start = clock()
        self._lock = threading.Lock()

    def update(self, n: int = 1, **extras) -> dict:
        with self._lock:
            self.done += n
            elapsed = max(self._clock() - self._start, 1e-9)
            rate = self.done / elapsed
            snapshot = {
                "done": self.done,
                "total": self.total,
                "elapsed_sec": round(elapsed, 3),
                "rate_per_sec": round(rate, 6),
                "eta_sec": round((self.total - self.done) / rate, 3) if rate else None,
                **extras,
            }
            if self.path:
                parent = os.path.dirname(os.path.abspath(self.path))
                os.makedirs(parent, exist_ok=True)
                tmp = self.path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(snapshot, f, indent=2)
                os.replace(tmp, self.path)
            return snapshot
