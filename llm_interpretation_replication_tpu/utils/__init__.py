from .checkpoint import CheckpointFile, ProcessedSet
from .logging import Progress, SessionLogger
from .retry import RateLimiter, RetryPolicy, retry_with_exponential_backoff
from .telemetry import clear_host_memory, device_memory_summary, get_memory_usage
from .xlsx import append_xlsx, read_xlsx, write_xlsx

__all__ = [
    "CheckpointFile",
    "ProcessedSet",
    "Progress",
    "SessionLogger",
    "RateLimiter",
    "RetryPolicy",
    "retry_with_exponential_backoff",
    "clear_host_memory",
    "device_memory_summary",
    "get_memory_usage",
    "append_xlsx",
    "read_xlsx",
    "write_xlsx",
]
