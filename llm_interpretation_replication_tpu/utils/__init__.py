from .checkpoint import CheckpointFile, ProcessedSet, append_jsonl
from .logging import Progress, SessionLogger
from .retry import RateLimiter, RetryPolicy, retry_with_exponential_backoff
from .telemetry import (
    clear_fault_events,
    clear_host_memory,
    device_memory_summary,
    fault_events,
    get_memory_usage,
    record_fault,
)
from .xlsx import append_xlsx, read_xlsx, write_xlsx

__all__ = [
    "CheckpointFile",
    "ProcessedSet",
    "append_jsonl",
    "clear_fault_events",
    "fault_events",
    "record_fault",
    "Progress",
    "SessionLogger",
    "RateLimiter",
    "RetryPolicy",
    "retry_with_exponential_backoff",
    "clear_host_memory",
    "device_memory_summary",
    "get_memory_usage",
    "append_xlsx",
    "read_xlsx",
    "write_xlsx",
]
