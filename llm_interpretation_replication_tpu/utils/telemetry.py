"""Host/device telemetry.

TPU-native replacement for the reference's GPU memory manager
(``clear_memory``/``get_memory_usage`` — compare_instruct_models.py:66-101,
run_base_vs_instruct_100q.py:245-262): JAX arrays are freed by dropping
references (no ``empty_cache`` dance), so the useful pieces are RAM/disk
telemetry, per-device HBM stats from ``device.memory_stats()``, and explicit
buffer donation in the jitted steps (handled in runtime/).
"""

from __future__ import annotations

import gc
import shutil
import threading
import time
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# Fault-event log (runtime/faults.py)
#
# Every recovery the fault-tolerance layer performs — an engine batch
# stepped down after OOM, a transient error retried, a preemption flush —
# degrades or perturbs the operating point the run reports, so it must stay
# auditable: a sweep that silently completed at batch 160 instead of 320 is
# a different measurement.  Events accumulate here (bounded ring) and are
# readable/drainable by benchmarks, tests, and reports.
# ---------------------------------------------------------------------------

_FAULT_EVENTS: List[Dict] = []
_FAULT_EVENTS_CAP = 1000


def record_fault(kind: str, **info) -> Dict:
    """Append one fault-recovery event ({kind, time, **info}); returns it."""
    event = {"kind": str(kind), "time": time.time(), **info}
    _FAULT_EVENTS.append(event)
    if len(_FAULT_EVENTS) > _FAULT_EVENTS_CAP:
        del _FAULT_EVENTS[: len(_FAULT_EVENTS) - _FAULT_EVENTS_CAP]
    return event


def fault_events(kind: Optional[str] = None) -> List[Dict]:
    """Recorded fault events, newest last (optionally filtered by kind)."""
    if kind is None:
        return list(_FAULT_EVENTS)
    return [e for e in _FAULT_EVENTS if e["kind"] == kind]


def clear_fault_events() -> None:
    _FAULT_EVENTS.clear()


# ---------------------------------------------------------------------------
# Performance counters (runtime/engine.py prefix-KV reuse, compile-cache
# warmup, host pipeline)
#
# Monotonic named counters for the hot-path reuse machinery: how many
# suffix legs rode an already-prefilled prefix cache (``prefix_hit``) vs
# paid a fresh prefix prefill (``prefix_miss``), how many warmup programs
# came out of the persistent XLA compilation cache (``compile_cache_hit`` /
# ``compile_cache_miss``), and how long the device-feed loop sat idle
# waiting for background host tokenization (``host_overlap_idle_ms`` /
# ``host_overlap_chunks``).  Benchmarks and the perf smoke test read these
# to prove the reuse paths actually engaged; a sweep that silently fell
# back to unfused scoring is a different measurement.
#
# Strict mode (runtime/strict.py, LLM_INTERP_STRICT=1) adds two more:
# ``recompile_events`` — one per XLA compilation seen by the log_compiles
# sentry (a warm repeat must hold this flat; growth means a shape or
# plan-key leak) — and ``blocked_transfers`` — one per implicit transfer
# the armed jax.transfer_guard rejected inside a scoring pipeline (a clean
# operating point is provable as blocked_transfers == 0).  bench.py
# --strict reports both in its JSON record.
# ---------------------------------------------------------------------------

_COUNTERS: Dict[str, float] = {}
_COUNTERS_LOCK = threading.Lock()  # the host prefetcher records from its
                                   # worker thread


def record_counter(name: str, value: float = 1) -> None:
    """Add ``value`` to the named monotonic counter (creates it at 0)."""
    with _COUNTERS_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + value


def counter(name: str) -> float:
    """Current value of one counter (0 when never recorded)."""
    with _COUNTERS_LOCK:
        return _COUNTERS.get(name, 0)


def counters() -> Dict[str, float]:
    """Snapshot of all counters."""
    with _COUNTERS_LOCK:
        return dict(_COUNTERS)


def clear_counters() -> None:
    with _COUNTERS_LOCK:
        _COUNTERS.clear()


def counters_since(snapshot: Dict[str, float]) -> Dict[str, float]:
    """Per-counter delta vs an earlier :func:`counters` snapshot.

    The counters are process-global monotones; callers measuring one
    phase (a bench repeat, a strict-mode sweep, a test) snapshot before,
    run, and diff — ``clear_counters`` would destroy concurrent readers'
    baselines.  Counters absent from ``snapshot`` count from 0; counters
    that only exist in ``snapshot`` are omitted (monotones cannot have
    shrunk)."""
    now = counters()
    return {name: value - snapshot.get(name, 0)
            for name, value in now.items()
            if value != snapshot.get(name, 0)}


def get_memory_usage() -> str:
    """Human-readable host RAM / disk / device HBM summary string."""
    parts = []
    try:
        import psutil

        vm = psutil.virtual_memory()
        parts.append(f"RAM: {vm.used / 1e9:.1f}/{vm.total / 1e9:.1f} GB ({vm.percent}%)")
    except Exception:
        pass
    try:
        du = shutil.disk_usage("/")
        parts.append(f"Disk: {du.used / 1e9:.1f}/{du.total / 1e9:.1f} GB")
    except Exception:
        pass
    parts.append(device_memory_summary() or "HBM: n/a")
    return " | ".join(parts)


def device_memory_summary() -> Optional[str]:
    try:
        import jax

        stats = []
        for d in jax.local_devices():
            ms = d.memory_stats() or {}
            used = ms.get("bytes_in_use")
            limit = ms.get("bytes_limit")
            if used is not None:
                lim = f"/{limit / 1e9:.1f}" if limit else ""
                stats.append(f"{d.platform}:{d.id} {used / 1e9:.2f}{lim} GB")
        return "HBM: " + ", ".join(stats) if stats else None
    except Exception:
        return None


def clear_host_memory() -> None:
    """Release python garbage; JAX device buffers free with their references."""
    for _ in range(3):
        gc.collect()
