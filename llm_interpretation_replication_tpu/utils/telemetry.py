"""Host/device telemetry.

TPU-native replacement for the reference's GPU memory manager
(``clear_memory``/``get_memory_usage`` — compare_instruct_models.py:66-101,
run_base_vs_instruct_100q.py:245-262): JAX arrays are freed by dropping
references (no ``empty_cache`` dance), so the useful pieces are RAM/disk
telemetry, per-device HBM stats from ``device.memory_stats()``, and explicit
buffer donation in the jitted steps (handled in runtime/).
"""

from __future__ import annotations

import gc
import shutil
from typing import Optional


def get_memory_usage() -> str:
    """Human-readable host RAM / disk / device HBM summary string."""
    parts = []
    try:
        import psutil

        vm = psutil.virtual_memory()
        parts.append(f"RAM: {vm.used / 1e9:.1f}/{vm.total / 1e9:.1f} GB ({vm.percent}%)")
    except Exception:
        pass
    try:
        du = shutil.disk_usage("/")
        parts.append(f"Disk: {du.used / 1e9:.1f}/{du.total / 1e9:.1f} GB")
    except Exception:
        pass
    parts.append(device_memory_summary() or "HBM: n/a")
    return " | ".join(parts)


def device_memory_summary() -> Optional[str]:
    try:
        import jax

        stats = []
        for d in jax.local_devices():
            ms = d.memory_stats() or {}
            used = ms.get("bytes_in_use")
            limit = ms.get("bytes_limit")
            if used is not None:
                lim = f"/{limit / 1e9:.1f}" if limit else ""
                stats.append(f"{d.platform}:{d.id} {used / 1e9:.2f}{lim} GB")
        return "HBM: " + ", ".join(stats) if stats else None
    except Exception:
        return None


def clear_host_memory() -> None:
    """Release python garbage; JAX device buffers free with their references."""
    for _ in range(3):
        gc.collect()
